"""Device-trace analysis (``cli obs devtrace``).

PR 8's gated capture writes raw profiler output that nothing in-repo
parsed; this module closes that gap: each per-config capture's
trace-event JSON (``perfetto_trace.json.gz``, written by
``obs/capture.py`` with ``create_perfetto_trace=True``) is parsed into a
per-op **measured** device timeline and joined against the static layer
— the committed α–β schedule baselines (``stats/analysis/baselines/``).
Three products per run directory:

- **per-op measured durations** — device events bucketed by op kind
  (collective / permute / dot / fusion / other) from the HLO instruction
  names the events carry (``args.hlo_op``), keyed by instruction name so
  rows join the ``analysis/hlo_audit`` instruction inventories;
- **measured overlap efficiency** — the wall-occupancy of each
  collective event covered by concurrently-executing compute events on
  the same device, reported NEXT TO the schedule auditor's static
  ``overlap_efficiency``, with a gate: a target whose static proof says
  a ring hop is hidden but whose measured timeline shows the hop
  serialized (zero straddling compute occupancy) is a
  ``runtime-serialized-collective`` finding.  On a runtime whose capture
  shows no inter-thunk concurrency anywhere (the cpu-sim thunk executor
  runs each device single-stream, so hop hiding is *unobservable* there,
  not disproved), the finding downgrades to a warning — the gate indicts
  schedules, never backends;
- **op-level fit samples** — per-collective rows (kind, ranks, analytic
  wire bytes, measured device µs, ``dispatches: 0`` — device time
  carries no host dispatch) appended to the ``obs/corpus.py`` sample
  table as the ``devtrace`` source, letting ``obs fit`` identify β on
  the cpu-sim tier from op-granularity data instead of pinning it from
  cm1.

Fail-closed contract: a run directory with no captures, a capture whose
trace is missing/truncated/empty, or a capture carrying zero device
events each produce an explicit error finding — never a silent empty
report.  Exit codes follow the pinned ``analysis.findings.EXIT_*``
contract (0 clean / 1 findings / 2 crash), like ``analyze`` and
``obs diff``.

Pure file processing — importable and runnable WITHOUT jax (the
committed capture corpus regression-gates this module backend-free),
mirroring ``obs/corpus.py``'s contract.
"""

from __future__ import annotations

import gzip
import json
import math
from pathlib import Path
from typing import Any, Optional

from dlbb_tpu.analysis.findings import (
    SEVERITY_ERROR,
    SEVERITY_WARNING,
    Finding,
)

DEVTRACE_SCHEMA = "dlbb_devtrace_v1"
DEFAULT_DEVTRACE_DIR = Path("stats/analysis/devtrace")

BUCKETS = ("collective", "permute", "dot", "fusion", "other")

# HLO instruction-name prefixes -> bucket.  Async ``-start``/``-done``
# suffixes are stripped before matching, so an async pair's transfer
# window and completion wait both charge the collective bucket.
_COLLECTIVE_PREFIXES = (
    "all-reduce", "all-gather", "reduce-scatter", "all-to-all",
    "collective-broadcast", "reduce_scatter", "partial-reduce",
)
_DOT_PREFIXES = ("dot", "convolution")

# container thunks whose device time is the SUM of their nested events
# (``call`` wraps a computation whose fusions appear as their own
# events; ``while``/``conditional`` likewise) — counting both the
# container and its contents would double-charge every bucket
_CONTAINER_PREFIXES = ("call", "while", "conditional", "async-start",
                      "async-done", "async-update")


def bucket_of(name: str) -> str:
    """Op-kind bucket of one device event, from its HLO instruction
    name (``fusion`` is matched as a substring: XLA names fused
    computations ``<ops>_fusion[.N]``)."""
    base = name.split(".")[0]
    for suffix in ("-start", "-done", "-update"):
        if base.endswith(suffix):
            base = base[: -len(suffix)]
    if base == "collective-permute":
        return "permute"
    if base.startswith(_COLLECTIVE_PREFIXES):
        return "collective"
    if base.startswith(_DOT_PREFIXES):
        return "dot"
    if "fusion" in base:
        return "fusion"
    return "other"


def _is_container(name: str) -> bool:
    return name.split(".")[0] in _CONTAINER_PREFIXES


def _is_async_completion(name: str) -> bool:
    """The ``-done``/``-update`` half of an async collective pair: its
    wait time still charges the collective bucket, but it is not a
    second instruction (α counts logical collectives) and its
    frequently-zero duration must not classify as a serialized hop."""
    base = name.split(".")[0]
    return base.endswith(("-done", "-update"))


class CaptureError(ValueError):
    """A capture that cannot be parsed into a device timeline (missing,
    truncated, or empty) — the caller turns this into an explicit
    finding, never a silent skip."""


# ---------------------------------------------------------------------------
# capture parsing
# ---------------------------------------------------------------------------


def load_trace_events(path: "str | Path") -> list[dict[str, Any]]:
    """The trace-event list of one capture (gz or plain JSON); raises
    :class:`CaptureError` on anything unreadable."""
    path = Path(path)
    if not path.exists():
        raise CaptureError(f"no trace file at {path}")
    try:
        raw = path.read_bytes()
        if path.name.endswith(".gz"):
            raw = gzip.decompress(raw)
        data = json.loads(raw)
    except (OSError, EOFError, gzip.BadGzipFile,
            json.JSONDecodeError) as e:
        raise CaptureError(
            f"{path}: truncated or unparseable trace ({e})"
        ) from e
    events = data.get("traceEvents") if isinstance(data, dict) else data
    if not isinstance(events, list) or not events:
        raise CaptureError(f"{path}: trace holds no events")
    return [e for e in events if isinstance(e, dict)]


def _annotation_windows(events: list[dict[str, Any]]
                        ) -> dict[str, list[tuple[float, float]]]:
    """The harness-planted annotation windows: ``profile_rep:<label>``
    (the dedicated capture reps), ``measure`` and ``warmup`` (the timing
    loops under a whole-session ``--trace``).  Annotations surface as
    host-thread X events whose full name rides ``args.long_name`` when
    the display name was truncated at the colon."""
    windows: dict[str, list[tuple[float, float]]] = {
        "profile_rep": [], "measure": [], "warmup": [],
    }
    for ev in events:
        if ev.get("ph") != "X" or "dur" not in ev:
            continue
        args = ev.get("args") or {}
        name = str(args.get("long_name") or ev.get("name") or "")
        span = (float(ev["ts"]), float(ev["ts"]) + float(ev["dur"]))
        if name.startswith("profile_rep:"):
            windows["profile_rep"].append(span)
        elif name == "measure":
            windows["measure"].append(span)
        elif name == "warmup":
            windows["warmup"].append(span)
    return windows


def _in_any(mid: float, spans: list[tuple[float, float]]) -> bool:
    return any(lo <= mid <= hi for lo, hi in spans)


def parse_capture(path: "str | Path") -> dict[str, Any]:
    """One capture's trace-event JSON -> the device timeline:

    ``{lanes: {(pid, tid) key: [event, ...]}, device_events,
    excluded_warmup, windows}`` where each event is
    ``{name, bucket, ts, dur, lane}``.  Device events are the X events
    carrying ``args.hlo_op`` (the converter stamps every thunk with its
    HLO instruction + module); container thunks (``call``/``while``)
    are dropped — their nested fusions appear as their own events, and
    counting both would double-charge the buckets.

    Warmup reps are excluded: an event whose midpoint falls inside a
    ``warmup`` annotation window is dropped; when ``profile_rep:`` /
    ``measure`` windows exist, only events inside one of them are kept.
    Raises :class:`CaptureError` when no device events survive — an
    empty timeline must fail closed, not report zeroes.
    """
    events = load_trace_events(path)
    windows = _annotation_windows(events)
    keep_windows = windows["profile_rep"] + windows["measure"]
    lanes: dict[str, list[dict[str, Any]]] = {}
    excluded = 0
    total = 0
    for ev in events:
        args = ev.get("args")
        if (ev.get("ph") != "X" or not isinstance(args, dict)
                or "hlo_op" not in args or "dur" not in ev):
            continue
        name = str(ev.get("name", args["hlo_op"]))
        if _is_container(name):
            continue
        total += 1
        ts, dur = float(ev["ts"]), float(ev["dur"])
        mid = ts + dur / 2.0
        if _in_any(mid, windows["warmup"]):
            excluded += 1
            continue
        if keep_windows and not _in_any(mid, keep_windows):
            excluded += 1
            continue
        lane = f"{ev.get('pid', 0)}/{ev.get('tid', 0)}"
        lanes.setdefault(lane, []).append({
            "name": name,
            "bucket": bucket_of(name),
            "ts": ts,
            "dur": dur,
            "lane": lane,
        })
    if not any(lanes.values()):
        raise CaptureError(
            f"{path}: no device events"
            + (f" ({excluded} excluded as warmup/out-of-window,"
               f" of {total} total)" if total else
               " — the capture carries no hlo_op-stamped thunks")
        )
    # device grouping for the overlap analysis: a multi-device trace
    # exports one perfetto process per device ("/device:TPU:0" ...), so
    # lanes group by pid; the CPU-simulated mesh exports ONE host
    # process whose per-device executor threads are the lanes, so each
    # lane is its own device there
    proc_names: dict[Any, str] = {}
    for ev in events:
        if ev.get("ph") == "M" and ev.get("name") == "process_name":
            proc_names[ev.get("pid")] = str(
                (ev.get("args") or {}).get("name", ""))
    devices: dict[str, list[dict[str, Any]]] = {}
    for lane, evs in lanes.items():
        pid = lane.split("/")[0]
        pname = proc_names.get(int(pid) if pid.isdigit() else pid, "")
        group = pid if "/device:" in pname else lane
        devices.setdefault(group, []).extend(evs)
    return {
        "lanes": lanes,
        "devices": devices,
        "excluded_warmup": excluded,
        "device_events": sum(len(v) for v in lanes.values()),
        "windows": {k: len(v) for k, v in windows.items()},
    }


# ---------------------------------------------------------------------------
# per-capture analysis
# ---------------------------------------------------------------------------


def _union_cover(span: tuple[float, float],
                 others: list[tuple[float, float]]) -> float:
    """Length of ``span`` covered by the union of ``others``."""
    lo, hi = span
    xs = sorted((max(a, lo), min(b, hi)) for a, b in others
                if b > lo and a < hi)
    covered = 0.0
    cur_lo = cur_hi = None
    for a, b in xs:
        if cur_hi is None or a > cur_hi:
            if cur_hi is not None:
                covered += cur_hi - cur_lo
            cur_lo, cur_hi = a, b
        else:
            cur_hi = max(cur_hi, b)
    if cur_hi is not None:
        covered += cur_hi - cur_lo
    return covered


def analyze_capture(timeline: dict[str, Any]) -> dict[str, Any]:
    """Bucket totals, per-op duration rows (keyed by instruction name —
    the ``hlo_audit`` inventory join key), and the measured-overlap
    numbers of one parsed capture."""
    per_op: dict[str, dict[str, Any]] = {}
    buckets = {b: 0.0 for b in BUCKETS}
    comm_total = hidden = 0.0
    comm_count = 0
    serialized: list[str] = []
    straddled = 0
    concurrent = False
    for group in sorted(timeline["devices"]):
        evs = timeline["devices"][group]
        compute = [(e["ts"], e["ts"] + e["dur"]) for e in evs
                   if e["bucket"] in ("dot", "fusion")]
        spans = sorted((e["ts"], e["ts"] + e["dur"]) for e in evs)
        for i in range(1, len(spans)):
            if spans[i][0] < spans[i - 1][1] - 1e-3:
                concurrent = True
                break
        for e in evs:
            row = per_op.setdefault(e["name"], {
                "name": e["name"], "bucket": e["bucket"], "count": 0,
                "total_us": 0.0, "durations": [],
            })
            row["count"] += 1
            row["total_us"] += e["dur"]
            row["durations"].append(e["dur"])
            buckets[e["bucket"]] += e["dur"]
            if e["bucket"] in ("collective", "permute"):
                comm_total += e["dur"]
                cover = _union_cover((e["ts"], e["ts"] + e["dur"]),
                                     compute)
                hidden += min(cover, e["dur"])
                # the -done half of an async pair is the same logical
                # collective (and often zero-length — no window for
                # compute to straddle); only the transfer-window events
                # count as hops for the serialized gate
                if _is_async_completion(e["name"]) or e["dur"] <= 0.0:
                    continue
                comm_count += 1
                if cover <= 0.0:
                    serialized.append(e["name"])
                else:
                    straddled += 1
    rows = []
    for name in sorted(per_op):
        row = per_op[name]
        ds = sorted(row.pop("durations"))
        row["median_us"] = round(ds[len(ds) // 2], 3)
        row["total_us"] = round(row["total_us"], 3)
        rows.append(row)
    return {
        "per_op": rows,
        "buckets_us": {b: round(v, 3) for b, v in buckets.items()},
        "comm_events": comm_count,
        "comm_total_us": round(comm_total, 3),
        "hidden_us": round(hidden, 3),
        "measured_overlap_efficiency": (
            round(hidden / comm_total, 6) if comm_total > 0 else None
        ),
        "comm_serialized_events": len(serialized),
        "comm_straddled_events": straddled,
        # whether THIS capture ever executed two thunks concurrently on
        # one device — the evidence the serialized-collective gate needs
        # before it may indict a schedule (vs a single-stream runtime)
        "runtime_concurrent": concurrent,
    }


def device_comm_samples(timeline: dict[str, Any],
                        profile_reps: int = 1,
                        buckets: "Optional[tuple[str, ...]]" = (
                            "collective", "permute"),
                        ) -> dict[str, Any]:
    """Per-device totals of device time for the fit sample:
    median-across-devices of each device's summed event time over
    ``buckets`` (default communication only; ``None`` = every bucket —
    the attribution device column), amortised per profile rep, plus
    the per-device instruction count."""
    totals: list[float] = []
    counts: list[int] = []
    for group in sorted(timeline["devices"]):
        evs = [e for e in timeline["devices"][group]
               if buckets is None or e["bucket"] in buckets]
        if not evs:
            continue
        totals.append(sum(e["dur"] for e in evs))
        # an async pair's -done event is the same logical collective:
        # its wait time counts, the instruction does not (α's analytic
        # convention counts one per hop, like corpus program rows)
        counts.append(sum(1 for e in evs
                          if not _is_async_completion(e["name"])))
    if not totals:
        return {}
    totals.sort()
    counts.sort()
    reps = max(1, int(profile_reps))
    return {
        "measured_device_us": totals[len(totals) // 2] / reps,
        "comm_instructions": counts[len(counts) // 2] / reps,
        "devices": len(totals),
    }


# ---------------------------------------------------------------------------
# the static join (committed schedule baselines; no jax)
# ---------------------------------------------------------------------------


def audit_target_name(op: str, variant: str) -> str:
    """The ``hlo_audit`` default-registry target a sweep config's
    (op, variant) was audited as — the key into the committed schedule
    baselines.  Mirrors the registry naming in
    ``analysis/hlo_audit.py`` (pinned by ``tests/test_devtrace.py``)."""
    if op in ("ag_matmul", "matmul_rs"):
        schedule = {"overlap_ring": "ring",
                    "overlap_bidir": "bidir"}.get(variant, "fused")
        return f"comm/ops.py::{op}[{schedule}]"
    if op.endswith("_q"):
        return f"comm/ops.py::{op}[{'fp8' if 'fp8' in variant else 'int8'}]"
    return f"comm/ops.py::{op}"


def _static_join(baselines: dict[str, dict], op: str,
                 variant: str) -> Optional[dict[str, Any]]:
    base = baselines.get(audit_target_name(op, variant))
    if base is None:
        return None
    return {
        "target": base.get("target"),
        "overlap_efficiency": base.get("overlap_efficiency"),
        "critical_path_us": base.get("critical_path_us"),
        "num_collectives": base.get("num_collectives"),
        "tier": base.get("tier"),
        "cost_model_version": base.get("cost_model_version"),
    }


# ---------------------------------------------------------------------------
# run-directory walk
# ---------------------------------------------------------------------------


def _resolve_capture_path(meta: dict[str, Any],
                          input_dir: Path) -> Optional[Path]:
    """The parseable trace file of one capture meta, tolerating
    relative ``trace_dir`` records from runs launched in another cwd."""
    from dlbb_tpu.obs.capture import perfetto_trace_files

    explicit = meta.get("perfetto_trace")
    if explicit and Path(explicit).exists():
        return Path(explicit)
    trace_dir = str(meta.get("trace_dir") or "")
    if not trace_dir:
        # Path("") is the cwd — rglobbing it would silently adopt an
        # unrelated run's trace; a dir-less meta must fail closed
        return None
    rel = Path(trace_dir)
    for root in (rel,
                 # a capture dir under the run dir keeps its last two
                 # components (<capture_subdir>/<label>) when the run
                 # was launched from another cwd
                 input_dir / rel.parent.name / rel.name,
                 input_dir / rel.name):
        if root.is_dir():
            files = perfetto_trace_files(root)
            if files:
                return files[-1]
    return None


def _sweep_captures(input_dir: Path) -> list[dict[str, Any]]:
    """Captured sweep configs: result JSONs carrying ``device_trace``
    metadata, each with the artifact fields the fit-sample extraction
    needs."""
    out: list[dict[str, Any]] = []
    for path in sorted(input_dir.glob("*.json")):
        try:
            data = json.loads(path.read_text())
        except (OSError, json.JSONDecodeError):
            continue
        if not isinstance(data, dict):
            continue
        meta = data.get("device_trace")
        if isinstance(meta, dict):
            out.append({"kind": "config", "file": path, "data": data,
                        "meta": meta})
    return out


def _serving_captures(input_dir: Path) -> list[dict[str, Any]]:
    """Captured serving phases: the ``observability.device_captures``
    metas the serving report/manifest records (one prefill + one decode
    scan per run)."""
    out: list[dict[str, Any]] = []
    for path in sorted(input_dir.glob("serving_*.json")):
        if path.name == "serving_resume.json":
            continue
        try:
            data = json.loads(path.read_text())
        except (OSError, json.JSONDecodeError):
            continue
        if not isinstance(data, dict):
            continue
        metas = (data.get("observability") or {}).get("device_captures")
        if isinstance(metas, list):
            for meta in metas:
                if isinstance(meta, dict):
                    out.append({"kind": "serving", "file": path,
                                "data": data, "meta": meta})
            break
    return out


def analyze_run(
    input_dir: "str | Path",
    baselines_dir: "Optional[str | Path]" = None,
) -> tuple[dict[str, Any], list[Finding]]:
    """Parse every capture a run directory recorded into the devtrace
    report + findings.  Fail-closed: no captures at all, or a capture
    that is missing/unparseable, is an explicit error finding."""
    from dlbb_tpu.analysis.schedule_audit import (
        DEFAULT_BASELINE_DIR,
        load_baselines,
    )

    input_dir = Path(input_dir)
    baselines_dir = Path(baselines_dir or DEFAULT_BASELINE_DIR)
    baselines = (load_baselines(baselines_dir)
                 if baselines_dir.is_dir() else {})
    findings: list[Finding] = []
    captures = _sweep_captures(input_dir) + _serving_captures(input_dir)
    report: dict[str, Any] = {
        "schema": DEVTRACE_SCHEMA,
        "input_dir": str(input_dir),
        "baselines_dir": str(baselines_dir),
        "captures": [],
        "op_samples": [],
    }
    if not captures:
        findings.append(Finding(
            pass_name="devtrace", rule="no-captures",
            severity=SEVERITY_ERROR, target=str(input_dir),
            message=(
                "no device captures recorded under this directory — run "
                "the sweep/serving benchmark with --device-trace DIR "
                "(or DLBB_DEVICE_TRACE) so there is a timeline to "
                "analyze; refusing to emit an empty report"
            ),
        ))
        return report, findings

    parsed_any = False
    for cap in captures:
        meta = cap["meta"]
        label = str(meta.get("label", cap["file"].name))
        row: dict[str, Any] = {
            "label": label,
            "source": cap["file"].name,
            "kind": cap["kind"],
            "capture": {k: meta.get(k) for k in (
                "trace_dir", "perfetto_trace", "profile_reps",
                "wall_seconds", "trace_bytes", "phase",
            ) if k in meta},
        }
        if "error" in meta:
            # contained at run time (and counted in
            # obs_device_capture_failures_total); surfaced here so the
            # report is explicit about what it does NOT cover
            findings.append(Finding(
                pass_name="devtrace", rule="capture-failed",
                severity=SEVERITY_WARNING, target=label,
                message=(f"capture failed at run time and was contained "
                         f"({meta['error']}) — no timeline to analyze"),
            ))
            row["error"] = meta["error"]
            report["captures"].append(row)
            continue
        trace_path = _resolve_capture_path(meta, input_dir)
        if trace_path is None:
            findings.append(Finding(
                pass_name="devtrace", rule="capture-missing",
                severity=SEVERITY_ERROR, target=label,
                message=(
                    f"result records a device capture under "
                    f"{meta.get('trace_dir')} but no parseable "
                    "perfetto trace-event JSON exists there — the "
                    "capture artifact was moved or deleted"
                ),
            ))
            row["error"] = "trace file missing"
            report["captures"].append(row)
            continue
        try:
            timeline = parse_capture(trace_path)
        except CaptureError as e:
            findings.append(Finding(
                pass_name="devtrace", rule="capture-unparseable",
                severity=SEVERITY_ERROR, target=label,
                message=str(e),
            ))
            row["error"] = str(e)
            report["captures"].append(row)
            continue
        parsed_any = True
        analysis = analyze_capture(timeline)
        row.update(analysis)
        row["device_events"] = timeline["device_events"]
        row["excluded_warmup"] = timeline["excluded_warmup"]
        row["devices"] = len(timeline["devices"])

        if cap["kind"] == "config":
            data = cap["data"]
            op = str(data.get("operation", ""))
            variant = str(data.get("variant", "default"))
            row["op"], row["variant"] = op, variant
            row["ranks"] = int(data.get("num_ranks", 0))
            row["static"] = _static_join(baselines, op, variant)
            _gate_overlap(row, findings)
            sample = _op_sample(cap, timeline, row)
            if sample is not None:
                report["op_samples"].append(sample)
        else:
            row["phase"] = meta.get("phase")
        report["captures"].append(row)

    if not parsed_any:
        findings.append(Finding(
            pass_name="devtrace", rule="no-captures",
            severity=SEVERITY_ERROR, target=str(input_dir),
            message=(
                f"none of the {len(captures)} recorded capture(s) "
                "yielded a parseable device timeline — see the "
                "per-capture findings above; refusing to emit an "
                "empty report"
            ),
        ))
    return report, findings


def _gate_overlap(row: dict[str, Any], findings: list[Finding]) -> None:
    """The static-vs-measured overlap gate, for configs measuring a
    ring-decomposed schedule (``overlap_*`` variants — the targets
    whose static proof claims every hop is hidden).  Quantised-ring ops
    (``*_q``) are exempt exactly as in the static auditor: their hop
    chains are deliberately sequential."""
    variant = row.get("variant", "")
    op = row.get("op", "")
    if not variant.startswith("overlap_") or op.endswith("_q"):
        return
    static = row.get("static") or {}
    static_overlap = static.get("overlap_efficiency")
    if not static_overlap or static_overlap <= 0:
        return
    hops = row.get("comm_events", 0)
    serialized = row.get("comm_serialized_events", 0)
    if not hops or not serialized:
        return
    # a single-stream runtime (no two thunks ever concurrent on one
    # device in this capture) cannot exhibit hop hiding at all — the
    # measured zero is an observability limit of the backend, not a
    # schedule regression, so it warns instead of failing CI
    severity = (SEVERITY_ERROR if row.get("runtime_concurrent")
                else SEVERITY_WARNING)
    measured = row.get("measured_overlap_efficiency")
    findings.append(Finding(
        pass_name="devtrace", rule="runtime-serialized-collective",
        severity=severity, target=row["label"],
        message=(
            f"static proof claims overlap_efficiency="
            f"{static_overlap:.2f} for {static.get('target')}, but the "
            f"measured timeline shows {serialized}/{hops} ring hop "
            f"event(s) with zero straddling compute occupancy "
            f"(measured overlap "
            f"{measured if measured is not None else 0:.2f})"
            + ("" if severity == SEVERITY_ERROR else
               " — single-stream runtime: no thunk concurrency "
               "observed anywhere in this capture, so hiding is "
               "unobservable on this backend, not disproved")
        ),
        details={
            "static_overlap_efficiency": static_overlap,
            "measured_overlap_efficiency": measured,
            "serialized_events": serialized,
            "comm_events": hops,
            "runtime_concurrent": bool(row.get("runtime_concurrent")),
        },
    ))


def _op_sample(cap: dict[str, Any], timeline: dict[str, Any],
               row: dict[str, Any]) -> Optional[dict[str, Any]]:
    """One corpus fit sample from a captured sweep config: the op's
    analytic features joined with the measured device communication
    time.  ``dispatches`` is 0 (a device-op duration carries no host
    dispatch overhead) and ``flops`` 0 (compute events are bucketed
    separately — the measured number is communication time only), so
    the row identifies α·collectives + wire/β directly."""
    from dlbb_tpu.obs.corpus import ingest_result

    sample, _reason = ingest_result(cap["file"], cap["data"])
    if sample is None:
        return None
    comm = device_comm_samples(
        timeline, int(cap["meta"].get("profile_reps", 1)))
    if not comm or comm["measured_device_us"] <= 0:
        return None
    return {
        "file": f"{cap['file']}::devtrace",
        "source": "devtrace",
        "op": sample["op"],
        "variant": sample["variant"],
        "kind": sample["kind"],
        "ranks": sample["ranks"],
        "dtype": sample["dtype"],
        "num_elements": sample["num_elements"],
        "wire_bytes": sample["wire_bytes"],
        "flops": 0,
        "collectives": float(comm["comm_instructions"]),
        "dispatches": 0.0,
        "measured_median_us": float(comm["measured_device_us"]),
        "measured_p90_us": float(comm["measured_device_us"]),
        "measured_p99_us": None,
        "iterations": int(cap["meta"].get("profile_reps", 1)),
        "tier": sample["tier"],
        "host": sample["host"],
        "timestamp": sample.get("timestamp"),
        "devices": comm["devices"],
    }


# ---------------------------------------------------------------------------
# report writers (JSON + MD + CSV via atomic_write_text)
# ---------------------------------------------------------------------------


def _fmt_us(us: Optional[float]) -> str:
    if us is None or not math.isfinite(us):
        return "-"
    if us >= 1e6:
        return f"{us / 1e6:.2f} s"
    if us >= 1e3:
        return f"{us / 1e3:.1f} ms"
    return f"{us:.0f} us"


def _fmt_eff(v: Optional[float]) -> str:
    return f"{v:.2f}" if isinstance(v, (int, float)) else "-"


def write_devtrace(report: dict[str, Any], findings: list[Finding],
                   out_dir: "str | Path",
                   name: str) -> tuple[Path, Path, Path]:
    """``<name>.json`` (the machine report + findings), ``<name>.md``
    (the human summary: measured overlap beside the static value per
    target) and ``<name>.csv`` (flat per-op rows) under ``out_dir``."""
    import csv
    import io

    from dlbb_tpu.utils.config import atomic_write_text

    out_dir = Path(out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    payload = dict(report)
    payload["findings"] = [f.to_dict() for f in findings]
    json_path = atomic_write_text(
        json.dumps(payload, indent=1, sort_keys=True),
        out_dir / f"{name}.json",
    )

    caps = report.get("captures", [])
    parsed = [c for c in caps if "error" not in c]
    lines = [
        f"# Device-trace analysis — {name}",
        "",
        f"- schema: `{DEVTRACE_SCHEMA}`",
        f"- input: `{report.get('input_dir')}`",
        f"- captures: {len(parsed)} parsed / {len(caps)} recorded",
        f"- static join: `{report.get('baselines_dir')}`",
        "",
        "## Measured vs static overlap, per capture",
        "",
        "Measured overlap is the wall-occupancy of collective/permute "
        "device events covered by concurrently-executing compute events "
        "on the same device; the static value is the schedule auditor's "
        "ASAP upper bound from the committed baseline "
        "(docs/observability.md, \"Device-trace analysis\").",
        "",
        "| capture | target | dev events | comm | measured overlap | "
        "static overlap | concurrency |",
        "|---|---|---:|---:|---:|---:|---|",
    ]
    for c in parsed:
        static = c.get("static") or {}
        lines.append(
            f"| {c['label']} | {static.get('target') or c.get('phase') or '-'} "
            f"| {c.get('device_events', 0)} "
            f"| {_fmt_us(c.get('comm_total_us'))} "
            f"| {_fmt_eff(c.get('measured_overlap_efficiency'))} "
            f"| {_fmt_eff(static.get('overlap_efficiency'))} "
            f"| {'yes' if c.get('runtime_concurrent') else 'no'} |"
        )
    lines += ["", "## Bucket totals (device µs)", "",
              "| capture | " + " | ".join(BUCKETS) + " |",
              "|---|" + "---:|" * len(BUCKETS)]
    for c in parsed:
        b = c.get("buckets_us", {})
        lines.append("| " + c["label"] + " | "
                     + " | ".join(_fmt_us(b.get(k, 0.0)) for k in BUCKETS)
                     + " |")
    if report.get("op_samples"):
        lines += [
            "",
            f"## Fit samples ({len(report['op_samples'])} op-level rows "
            "appended to the cm2 corpus as source `devtrace`)",
            "",
            "| op | variant | ranks | wire bytes | measured device µs "
            "| collectives |",
            "|---|---|---:|---:|---:|---:|",
        ]
        for s in report["op_samples"]:
            lines.append(
                f"| {s['op']} | {s['variant']} | {s['ranks']} "
                f"| {s['wire_bytes']} "
                f"| {s['measured_median_us']:.1f} "
                f"| {s['collectives']:.0f} |")
    if findings:
        lines += ["", "## Findings", ""]
        lines += [f"- `{f.rule}` ({f.severity}) @ {f.target}: {f.message}"
                  for f in findings]
    lines.append("")
    md_path = atomic_write_text("\n".join(lines), out_dir / f"{name}.md")

    buf = io.StringIO()
    writer = csv.DictWriter(buf, fieldnames=[
        "capture", "target", "phase", "name", "bucket", "count",
        "total_us", "median_us",
    ])
    writer.writeheader()
    for c in parsed:
        static = c.get("static") or {}
        for op_row in c.get("per_op", ()):
            writer.writerow({
                "capture": c["label"],
                "target": static.get("target", ""),
                "phase": c.get("phase", ""),
                **op_row,
            })
    csv_path = atomic_write_text(buf.getvalue(), out_dir / f"{name}.csv",
                                 newline="")
    return json_path, md_path, csv_path


def run_devtrace(
    input_dir: "str | Path",
    out_dir: "Optional[str | Path]" = None,
    baselines_dir: "Optional[str | Path]" = None,
    name: Optional[str] = None,
    verbose: bool = True,
) -> tuple[dict[str, Any], list[Finding]]:
    """CLI driver (``cli obs devtrace``): parse + join + write the
    report set; the caller maps findings to the pinned exit codes."""
    input_dir = Path(input_dir)
    name = name or input_dir.resolve().name
    report, findings = analyze_run(input_dir, baselines_dir)
    json_path, md_path, _csv = write_devtrace(
        report, findings, Path(out_dir or DEFAULT_DEVTRACE_DIR), name)
    if verbose:
        parsed = [c for c in report["captures"] if "error" not in c]
        n_overlap = sum(1 for c in parsed if (c.get("static") or {})
                        .get("overlap_efficiency"))
        print(f"[obs] devtrace: {len(parsed)}/{len(report['captures'])} "
              f"capture(s) parsed, {n_overlap} overlap-proof target(s), "
              f"{len(report['op_samples'])} fit sample(s) -> {md_path}")
    return report, findings
