"""Predicted-vs-measured calibration gate (the cost-model falsifier).

The α–β schedule auditor (PR 7) predicts a ``critical_path_us`` per audit
target from the versioned cost-model table and commits the predictions
under ``stats/analysis/baselines/`` — but nothing validated those numbers
against a real execution, which ROADMAP item 2 calls out: the model must
report predicted-vs-measured error as a first-class stat or it is
unfalsifiable.  This module closes the loop:

- :func:`run_calibration` rebuilds every committed baseline target's
  program through the SAME ``hlo_audit`` builder the prediction was
  lowered from (so predicted and measured are the identical compiled
  artifact by construction), measures its real median execution time on
  the current mesh (per-iteration ``block_until_ready`` timing — honest
  on the sim mesh, where the committed ``cpu-sim`` baselines live), and
  reports the **signed relative error** ``(measured - predicted) /
  predicted`` per target plus an aggregate (median signed error, geomean
  error factor).  The report lands as JSON + CSV
  (``atomic_write_text``), and the aggregate is merged into the output
  directory's ``sweep_manifest.json``.
- :func:`diff_calibration` compares a fresh report against the committed
  calibration baseline (``stats/analysis/calibration/``) and emits
  findings when the model error REGRESSES past the gate — the aggregate
  geomean error factor growing more than :data:`AGGREGATE_SLACK` over
  the committed run fails CI (``cli obs diff``, pinned
  ``findings.EXIT_*`` codes); per-target drift warns.  Aggregates are
  recomputed over the JOINED target set, so a subset run (the
  ``obs_smoke`` stage) diffs soundly against a full committed baseline.

Donating programs (train steps) are measured through a carry protocol:
when a second call on the original arguments dies on the donated buffer,
the step's own output state is fed back as the next input — the same
dataflow the real training loop executes.
"""

from __future__ import annotations

import json
import math
import time
from pathlib import Path
from typing import Any, Optional, Sequence

from dlbb_tpu.analysis.costmodel import COST_MODEL_VERSION
from dlbb_tpu.analysis.findings import (
    SEVERITY_ERROR,
    SEVERITY_WARNING,
    Finding,
)
from dlbb_tpu.analysis.schedule_audit import DEFAULT_BASELINE_DIR

CALIBRATION_SCHEMA = "dlbb_calibration_v1"

# committed calibration baseline (the diff gate's reference point)
DEFAULT_CALIBRATION_DIR = Path("stats/analysis/calibration")
# where `cli obs calibrate` writes fresh reports by default
DEFAULT_REPORT_DIR = Path("results/obs")
BASELINE_NAME = "calibration_baseline.json"
REPORT_NAME = "calibration_report.json"
CSV_NAME = "calibration_report.csv"

# diff-gate slacks: measured medians on a loaded CPU host wobble by
# small factors run to run (a process-cold subset run measured ~3.5x
# hotter than the full-surface committed baseline on this 2-core box),
# so the gate is on the ERROR FACTOR (the max/min ratio of measured vs
# predicted, always >= 1) growing by a generous multiplicative margin —
# not on absolute microseconds.  The gate exists to catch ORDER-OF-
# MAGNITUDE model regressions (a cost-table typo, a backend swap, a
# contaminated measurement path); run-to-run host noise must never trip
# it (cost-model VERSION changes are caught exactly by the version pin)
AGGREGATE_SLACK = 8.0   # geomean error factor across joined targets
TARGET_SLACK = 16.0     # per-target factor (warning only)

CSV_COLUMNS = (
    "target", "tier", "cost_model_version", "predicted_us", "measured_us",
    "signed_rel_error", "error_factor", "reps",
)


def _error_factor(measured: float, predicted: float) -> float:
    m, p = max(measured, 1e-9), max(predicted, 1e-9)
    return max(m, p) / min(m, p)


def measure_target(target: Any, warmup: int = 5,
                   reps: int = 30) -> dict[str, Any]:
    """Median (+ spread) execution time in µs of one audit target's
    program — the same ``build()`` the schedule auditor lowered, now
    actually run.  Per-iteration ``perf_counter`` + ``block_until_ready``
    brackets (honest on sync backends, i.e. the sim mesh the committed
    baselines are priced for).

    Donation-aware: when the program consumes its first argument (train
    steps), the returned state is carried into the next call."""
    import jax

    fn, args = target.build()
    jitted = fn if hasattr(fn, "lower") else jax.jit(fn)
    out = jitted(*args)
    jax.block_until_ready(out)  # absorbs compile
    cur_args = tuple(args)
    donated = False
    try:
        out = jitted(*cur_args)
        jax.block_until_ready(out)
    except Exception:  # noqa: BLE001 — donated-buffer probe
        donated = True
        cur_args = (out[0], *cur_args[1:])
        out = jitted(*cur_args)
        jax.block_until_ready(out)
        cur_args = (out[0], *cur_args[1:])
    samples: list[float] = []
    for i in range(max(0, warmup - 2) + reps):
        t0 = time.perf_counter()
        out = jitted(*cur_args)
        jax.block_until_ready(out)
        elapsed = time.perf_counter() - t0
        if donated:
            cur_args = (out[0], *cur_args[1:])
        if i >= max(0, warmup - 2):
            samples.append(elapsed)
    samples.sort()
    n = len(samples)
    return {
        "measured_us": samples[n // 2] * 1e6,
        "measured_min_us": samples[0] * 1e6,
        "measured_p90_us": samples[min(n - 1, int(n * 0.9))] * 1e6,
        "reps": n,
        "donated_carry": donated,
    }


def run_calibration(
    baselines_dir: Optional[Path] = None,
    out_dir: Optional[Path] = None,
    tier: Optional[str] = None,
    reps: int = 30,
    warmup: int = 5,
    target_filter: Optional[Sequence[str]] = None,
    verbose: bool = True,
) -> dict[str, Any]:
    """Measure every committed schedule-baseline target buildable on the
    current mesh and join against its predicted critical path.  Returns
    (and writes) the calibration report; merges the aggregate into
    ``out_dir/sweep_manifest.json``."""
    import jax

    from dlbb_tpu.analysis.hlo_audit import default_targets, default_tier
    from dlbb_tpu.analysis.schedule_audit import load_baselines
    from dlbb_tpu.obs import spans

    baselines_dir = Path(baselines_dir or DEFAULT_BASELINE_DIR)
    out_dir = Path(out_dir or DEFAULT_REPORT_DIR)
    tier = tier or default_tier()
    baselines = load_baselines(baselines_dir)
    if not baselines:
        raise FileNotFoundError(
            f"no committed schedule baselines under {baselines_dir} — "
            "run `python -m dlbb_tpu.cli analyze snapshot --simulate 8` "
            "first (the calibration joins against them)"
        )
    builders = {t.name: t for t in default_targets()}
    n_devices = len(jax.devices())

    rows: list[dict[str, Any]] = []
    skipped: list[dict[str, str]] = []
    for name in sorted(baselines):
        base = baselines[name]
        if target_filter and not any(s in name for s in target_filter):
            skipped.append({"target": name, "reason": "filtered"})
            continue
        target = builders.get(name)
        if target is None:
            skipped.append({"target": name,
                            "reason": "no registry builder for target"})
            continue
        if target.min_devices > n_devices:
            skipped.append({
                "target": name,
                "reason": (f"needs {target.min_devices} devices, "
                           f"{n_devices} available"),
            })
            continue
        if base.get("tier") != tier:
            skipped.append({
                "target": name,
                "reason": (f"baseline priced for tier "
                           f"{base.get('tier')!r}, measuring on {tier!r}"),
            })
            continue
        predicted = base.get("critical_path_us")
        if not predicted:
            skipped.append({"target": name,
                            "reason": "baseline has no critical_path_us"})
            continue
        try:
            with spans.span(f"calibrate:{name}", cat="calibration"):
                measured = measure_target(target, warmup=warmup, reps=reps)
        except Exception as e:  # noqa: BLE001 — per-target containment
            skipped.append({
                "target": name,
                "reason": f"measurement crashed: {type(e).__name__}: {e}",
            })
            if verbose:
                print(f"[obs] {name}: CRASH ({type(e).__name__}: {e})")
            continue
        m_us = measured["measured_us"]
        row = {
            "target": name,
            "tier": tier,
            "cost_model_version": base.get("cost_model_version"),
            "predicted_us": float(predicted),
            "signed_rel_error": (m_us - predicted) / predicted,
            "error_factor": _error_factor(m_us, predicted),
            **measured,
        }
        rows.append(row)
        if verbose:
            print(f"[obs] {name}: predicted {predicted:.1f}us, measured "
                  f"{m_us:.1f}us (err {row['signed_rel_error']:+.1f}x, "
                  f"factor {row['error_factor']:.1f}x)")

    report = {
        "schema": CALIBRATION_SCHEMA,
        "tier": tier,
        "cost_model_version": COST_MODEL_VERSION,
        "baselines_dir": str(baselines_dir),
        "aggregate": aggregate_errors(rows, skipped),
        "targets": rows,
        "skipped": skipped,
        "timestamp": time.time(),
    }
    write_report(report, out_dir)
    return report


def aggregate_errors(rows: list[dict[str, Any]],
                     skipped: Sequence[dict] = ()) -> dict[str, Any]:
    """The first-class predicted-vs-measured error stat: median signed
    relative error (bias direction), median absolute relative error, and
    the geometric-mean / max error factors (scale-free accuracy)."""
    if not rows:
        return {
            "targets_measured": 0,
            "targets_skipped": len(skipped),
            "median_signed_rel_error": None,
            "median_abs_rel_error": None,
            "geomean_error_factor": None,
            "max_error_factor": None,
        }
    signed = sorted(r["signed_rel_error"] for r in rows)
    abs_err = sorted(abs(e) for e in signed)
    factors = [r["error_factor"] for r in rows]
    return {
        "targets_measured": len(rows),
        "targets_skipped": len(skipped),
        "median_signed_rel_error": signed[len(signed) // 2],
        "median_abs_rel_error": abs_err[len(abs_err) // 2],
        "geomean_error_factor": math.exp(
            sum(math.log(f) for f in factors) / len(factors)
        ),
        "max_error_factor": max(factors),
    }


def write_report(report: dict[str, Any], out_dir: Path) -> Path:
    """JSON + CSV, atomically; the aggregate also lands in the output
    directory's ``sweep_manifest.json`` (created if absent, merged if a
    sweep already wrote one) so manifest consumers see the calibration
    state next to the compile/cache accounting."""
    import csv
    import io

    from dlbb_tpu.bench.schedule import MANIFEST_NAME, MANIFEST_SCHEMA
    from dlbb_tpu.utils.config import atomic_write_text, save_json

    out_dir = Path(out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    path = atomic_write_text(
        json.dumps(report, indent=2, sort_keys=True), out_dir / REPORT_NAME
    )
    buf = io.StringIO()
    writer = csv.DictWriter(buf, fieldnames=list(CSV_COLUMNS),
                            extrasaction="ignore")
    writer.writeheader()
    for row in report["targets"]:
        writer.writerow(row)
    atomic_write_text(buf.getvalue(), out_dir / CSV_NAME, newline="")

    manifest_path = out_dir / MANIFEST_NAME
    manifest: dict[str, Any] = {"schema": MANIFEST_SCHEMA,
                                "kind": "calibration"}
    if manifest_path.exists():
        try:
            manifest = json.loads(manifest_path.read_text())
        except (OSError, json.JSONDecodeError):
            pass  # torn/legacy manifest: rewrite with the calibration only
    manifest["calibration"] = {
        "tier": report["tier"],
        "cost_model_version": report["cost_model_version"],
        **report["aggregate"],
    }
    manifest.setdefault("timestamp", time.time())
    save_json(manifest, manifest_path)
    return path


def save_calibration_baseline(report: dict[str, Any],
                              directory: Optional[Path] = None) -> Path:
    """Commit a calibration report as the diff gate's reference point."""
    from dlbb_tpu.utils.config import atomic_write_text

    directory = Path(directory or DEFAULT_CALIBRATION_DIR)
    directory.mkdir(parents=True, exist_ok=True)
    path = directory / BASELINE_NAME
    atomic_write_text(
        json.dumps(report, indent=2, sort_keys=True) + "\n", path
    )
    return path


def load_calibration_baseline(directory: "Path | str") -> dict[str, Any]:
    directory = Path(directory)
    path = directory / BASELINE_NAME if directory.is_dir() else directory
    return json.loads(path.read_text())


def diff_calibration(report: dict[str, Any],
                     baseline_dir: "Path | str") -> list[Finding]:
    """Findings when the fresh calibration regresses past the committed
    baseline.  The CI-gating (error) rules: no/unreadable baseline,
    cost-model version or tier skew, and the joined-aggregate geomean
    error factor growing more than :data:`AGGREGATE_SLACK`.  Per-target
    drift and improvements warn."""
    findings: list[Finding] = []
    try:
        base = load_calibration_baseline(baseline_dir)
    except (OSError, json.JSONDecodeError) as e:
        findings.append(Finding(
            pass_name="obs", rule="missing-calibration-baseline",
            severity=SEVERITY_ERROR, target=str(baseline_dir),
            message=(
                f"no committed calibration baseline ({e}) — run "
                "`python -m dlbb_tpu.cli obs calibrate --simulate 8` and "
                f"commit {Path(baseline_dir) / BASELINE_NAME}"
            ),
        ))
        return findings
    if (base.get("cost_model_version") != report.get("cost_model_version")
            or base.get("tier") != report.get("tier")):
        findings.append(Finding(
            pass_name="obs", rule="cost-model-mismatch",
            severity=SEVERITY_ERROR, target=BASELINE_NAME,
            message=(
                f"calibration baseline is {base.get('cost_model_version')}"
                f"/{base.get('tier')} but this run is "
                f"{report.get('cost_model_version')}/{report.get('tier')} "
                "— errors are not comparable; re-run `obs calibrate` and "
                "commit the new baseline after a cost-model change"
            ),
        ))
        return findings

    base_rows = {r["target"]: r for r in base.get("targets", ())}
    cur_rows = {r["target"]: r for r in report.get("targets", ())}
    joined = sorted(set(base_rows) & set(cur_rows))
    if not joined:
        findings.append(Finding(
            pass_name="obs", rule="no-joined-targets",
            severity=SEVERITY_ERROR, target=BASELINE_NAME,
            message=(
                "the fresh calibration shares no measured target with the "
                "committed baseline — nothing to gate on; check the "
                "--targets filter / the baselines directory"
            ),
        ))
        return findings

    # aggregate over the JOINED set on both sides, so a subset run (the
    # obs_smoke stage) compares like with like
    base_join = [base_rows[t] for t in joined]
    cur_join = [cur_rows[t] for t in joined]
    base_geo = aggregate_errors(base_join)["geomean_error_factor"]
    cur_geo = aggregate_errors(cur_join)["geomean_error_factor"]
    if cur_geo > base_geo * AGGREGATE_SLACK:
        findings.append(Finding(
            pass_name="obs", rule="calibration-regression",
            severity=SEVERITY_ERROR, target=BASELINE_NAME,
            message=(
                f"aggregate cost-model error regressed: geomean error "
                f"factor {cur_geo:.1f}x vs committed {base_geo:.1f}x over "
                f"{len(joined)} joined target(s) (gate at "
                f"{AGGREGATE_SLACK:.1f}x growth) — the α–β model got "
                "WORSE at predicting this mesh; investigate (cost-model "
                "drift, backend change, measurement contamination), then "
                "re-commit the calibration baseline if the change is "
                "intended"
            ),
            details={"baseline_geomean": base_geo, "current_geomean": cur_geo,
                     "joined_targets": len(joined)},
        ))
    elif base_geo > cur_geo * AGGREGATE_SLACK:
        findings.append(Finding(
            pass_name="obs", rule="calibration-improved",
            severity=SEVERITY_WARNING, target=BASELINE_NAME,
            message=(
                f"aggregate error factor improved {base_geo / cur_geo:.1f}x "
                "under the committed baseline — re-run `obs calibrate` and "
                "commit to tighten the gate"
            ),
            details={"baseline_geomean": base_geo,
                     "current_geomean": cur_geo},
        ))
    for t in joined:
        b, c = base_rows[t]["error_factor"], cur_rows[t]["error_factor"]
        if c > b * TARGET_SLACK:
            findings.append(Finding(
                pass_name="obs", rule="target-calibration-drift",
                severity=SEVERITY_WARNING, target=t,
                message=(
                    f"per-target error factor {c:.1f}x vs committed "
                    f"{b:.1f}x (> {TARGET_SLACK:.0f}x growth) — this "
                    "target's prediction drifted; aggregate gate decides "
                    "CI, but check this one first"
                ),
                details={"baseline_factor": b, "current_factor": c},
            ))
    for t in sorted(set(cur_rows) - set(base_rows)):
        findings.append(Finding(
            pass_name="obs", rule="uncalibrated-target",
            severity=SEVERITY_WARNING, target=t,
            message=(
                "measured target has no entry in the committed "
                "calibration baseline — re-run `obs calibrate` over the "
                "full surface and commit, so the new target is gated too"
            ),
        ))
    return findings
