"""Predicted-vs-measured calibration gate (the cost-model falsifier).

The α–β schedule auditor (PR 7) predicts a ``critical_path_us`` per audit
target from the versioned cost-model table and commits the predictions
under ``stats/analysis/baselines/`` — but nothing validated those numbers
against a real execution, which ROADMAP item 2 calls out: the model must
report predicted-vs-measured error as a first-class stat or it is
unfalsifiable.  This module closes the loop:

- :func:`run_calibration` rebuilds every committed baseline target's
  program through the SAME ``hlo_audit`` builder the prediction was
  lowered from (so predicted and measured are the identical compiled
  artifact by construction), measures its real median execution time on
  the current mesh (per-iteration ``block_until_ready`` timing — honest
  on the sim mesh, where the committed ``cpu-sim`` baselines live), and
  reports the **signed relative error** ``(measured - predicted) /
  predicted`` per target plus an aggregate (median signed error, geomean
  error factor).  The report lands as JSON + CSV
  (``atomic_write_text``), and the aggregate is merged into the output
  directory's ``sweep_manifest.json``.
- :func:`diff_calibration` compares a fresh report against the committed
  calibration baseline (``stats/analysis/calibration/``) and emits
  findings when the model error REGRESSES past the gate — the aggregate
  geomean error factor growing more than :data:`AGGREGATE_SLACK` over
  the committed run fails CI (``cli obs diff``, pinned
  ``findings.EXIT_*`` codes); per-target drift warns.  Aggregates are
  recomputed over the JOINED target set, so a subset run (the
  ``obs_smoke`` stage) diffs soundly against a full committed baseline.

Donating programs (train steps) are measured through a carry protocol:
when a second call on the original arguments dies on the donated buffer,
the step's own output state is fed back as the next input — the same
dataflow the real training loop executes.
"""

from __future__ import annotations

import json
import math
import time
from pathlib import Path
from typing import Any, Optional, Sequence

from dlbb_tpu.analysis.costmodel import (
    COST_MODEL_VERSION,
    resolve_tier,
)
from dlbb_tpu.analysis.findings import (
    SEVERITY_ERROR,
    SEVERITY_WARNING,
    Finding,
)
from dlbb_tpu.analysis.schedule_audit import DEFAULT_BASELINE_DIR

CALIBRATION_SCHEMA = "dlbb_calibration_v1"

# committed calibration baseline (the diff gate's reference point)
DEFAULT_CALIBRATION_DIR = Path("stats/analysis/calibration")
# where `cli obs calibrate` writes fresh reports by default
DEFAULT_REPORT_DIR = Path("results/obs")
BASELINE_NAME = "calibration_baseline.json"
REPORT_NAME = "calibration_report.json"
CSV_NAME = "calibration_report.csv"
METRICS_NAME = "metrics.prom"


def baseline_name(model: str = COST_MODEL_VERSION) -> str:
    """Each cost model gets its own committed baseline file (cm1 keeps
    the historical name): the error factors of different models are not
    comparable, so the diff gate never joins across them."""
    if model in (None, COST_MODEL_VERSION):
        return BASELINE_NAME
    return f"calibration_baseline_{model}.json"

# diff-gate slacks: measured medians on a loaded CPU host wobble by
# small factors run to run (a process-cold subset run measured ~3.5x
# hotter than the full-surface committed baseline on this 2-core box),
# so the gate is on the ERROR FACTOR (the max/min ratio of measured vs
# predicted, always >= 1) growing by a generous multiplicative margin —
# not on absolute microseconds.  The gate exists to catch ORDER-OF-
# MAGNITUDE model regressions (a cost-table typo, a backend swap, a
# contaminated measurement path); run-to-run host noise must never trip
# it (cost-model VERSION changes are caught exactly by the version pin)
AGGREGATE_SLACK = 8.0   # geomean error factor across joined targets
TARGET_SLACK = 16.0     # per-target factor (warning only)

CSV_COLUMNS = (
    "target", "tier", "cost_model_version", "predicted_us",
    "dispatch_count", "predicted_dispatch_overhead_us", "measured_us",
    "signed_rel_error", "error_factor", "reps",
)


def _error_factor(measured: float, predicted: float) -> float:
    m, p = max(measured, 1e-9), max(predicted, 1e-9)
    return max(m, p) / min(m, p)


def measure_target(target: Any, warmup: int = 5,
                   reps: int = 30) -> dict[str, Any]:
    """Median (+ spread) execution time in µs of one audit target's
    program — the same ``build()`` the schedule auditor lowered, now
    actually run.  Per-iteration ``perf_counter`` + ``block_until_ready``
    brackets (honest on sync backends, i.e. the sim mesh the committed
    baselines are priced for).

    Donation-aware: when the program consumes its first argument (train
    steps), the returned state is carried into the next call."""
    import jax

    fn, args = target.build()
    jitted = fn if hasattr(fn, "lower") else jax.jit(fn)
    out = jitted(*args)
    jax.block_until_ready(out)  # absorbs compile
    cur_args = tuple(args)
    # carry protocols, probed in order: "head" feeds out[0] back as the
    # next first argument (train steps returning (state, metrics)),
    # "whole" feeds the entire output back (programs whose output IS the
    # donated carry, e.g. the serving compaction scatter)
    carry = None
    try:
        out = jitted(*cur_args)
        jax.block_until_ready(out)
    except Exception:  # noqa: BLE001 — donated-buffer probe
        probe_err: Optional[Exception] = None
        for mode in ("head", "whole"):
            try:
                fed = out[0] if mode == "head" else out
                trial = (fed, *cur_args[1:])
                out = jitted(*trial)
                jax.block_until_ready(out)
                carry = mode
                cur_args = ((out[0] if mode == "head" else out),
                            *cur_args[1:])
                break
            except Exception as e:  # noqa: BLE001 — try the next protocol
                probe_err = e
        if carry is None:
            raise probe_err
    samples: list[float] = []
    for i in range(max(0, warmup - 2) + reps):
        t0 = time.perf_counter()
        out = jitted(*cur_args)
        jax.block_until_ready(out)
        elapsed = time.perf_counter() - t0
        if carry is not None:
            cur_args = ((out[0] if carry == "head" else out),
                        *cur_args[1:])
        if i >= max(0, warmup - 2):
            samples.append(elapsed)
    samples.sort()
    n = len(samples)
    return {
        "measured_us": samples[n // 2] * 1e6,
        "measured_min_us": samples[0] * 1e6,
        "measured_p90_us": samples[min(n - 1, int(n * 0.9))] * 1e6,
        "reps": n,
        "donated_carry": carry is not None,
        **({"carry_protocol": carry} if carry else {}),
    }


def run_calibration(
    baselines_dir: Optional[Path] = None,
    out_dir: Optional[Path] = None,
    tier: Optional[str] = None,
    reps: int = 30,
    warmup: int = 5,
    target_filter: Optional[Sequence[str]] = None,
    verbose: bool = True,
    model: str = COST_MODEL_VERSION,
    fit_dir: "Optional[str | Path]" = None,
) -> dict[str, Any]:
    """Measure every committed schedule-baseline target buildable on the
    current mesh and join against its predicted wall time.  Returns
    (and writes) the calibration report; merges the aggregate into
    ``out_dir/sweep_manifest.json``.

    ``model`` selects the pricing: cm1 reads each committed baseline's
    ``critical_path_us`` (γ = 0, the historical behaviour); cm2 resolves
    the fitted tier (``stats/analysis/costmodel_fit/``) and re-prices
    every target's schedule with the fitted α/β/peak plus the
    per-dispatch γ — falling back to cm1 with a loud ``fit-missing``
    warning when no DB is committed (the report records the model that
    actually priced it)."""
    import jax

    from dlbb_tpu.analysis.hlo_audit import default_targets, default_tier
    from dlbb_tpu.analysis.schedule_audit import load_baselines
    from dlbb_tpu.obs import spans

    baselines_dir = Path(baselines_dir or DEFAULT_BASELINE_DIR)
    out_dir = Path(out_dir or DEFAULT_REPORT_DIR)
    tier = tier or default_tier()
    cost_tier = resolve_tier(tier, model=model, fit_dir=fit_dir)
    baselines = load_baselines(baselines_dir)
    if not baselines:
        raise FileNotFoundError(
            f"no committed schedule baselines under {baselines_dir} — "
            "run `python -m dlbb_tpu.cli analyze snapshot --simulate 8` "
            "first (the calibration joins against them)"
        )
    builders = {t.name: t for t in default_targets()}
    n_devices = len(jax.devices())

    rows: list[dict[str, Any]] = []
    skipped: list[dict[str, str]] = []
    for name in sorted(baselines):
        base = baselines[name]
        if target_filter and not any(s in name for s in target_filter):
            skipped.append({"target": name, "reason": "filtered"})
            continue
        target = builders.get(name)
        if target is None:
            skipped.append({"target": name,
                            "reason": "no registry builder for target"})
            continue
        if target.min_devices > n_devices:
            skipped.append({
                "target": name,
                "reason": (f"needs {target.min_devices} devices, "
                           f"{n_devices} available"),
            })
            continue
        if base.get("tier") != tier:
            skipped.append({
                "target": name,
                "reason": (f"baseline priced for tier "
                           f"{base.get('tier')!r}, measuring on {tier!r}"),
            })
            continue
        overhead = cost_tier.gamma_dispatch_us
        if cost_tier.version == COST_MODEL_VERSION:
            cp = base.get("critical_path_us")
            if not cp:
                # cm1 prices this program at zero (no collectives, no
                # dots — e.g. the serving compaction jits): nothing to
                # compare, BUT its measured time is the purest
                # per-dispatch-γ sample the fit corpus can get, so
                # measure it and carry the number on the skip record
                # (excluded from every aggregate)
                entry = {
                    "target": name,
                    "reason": ("baseline has no critical_path_us "
                               "(measured for the fit corpus only)"),
                }
                try:
                    m = measure_target(target, warmup=warmup, reps=reps)
                    entry["measured_us"] = m["measured_us"]
                    entry["reps"] = m["reps"]
                except Exception as e:  # noqa: BLE001 — containment
                    entry["reason"] += (f"; measurement crashed: "
                                        f"{type(e).__name__}: {e}")
                skipped.append(entry)
                continue
            predicted = float(cp) + overhead  # γ = 0 under cm1
        else:
            # fitted model: re-price this target's schedule with the
            # fitted tier (the committed baselines are cm1-priced, so
            # their numbers cannot serve a cm2 prediction)
            from dlbb_tpu.analysis.hlo_audit import audit_target

            try:
                _f, meta = audit_target(target, passes=("schedule",),
                                        tier=cost_tier)
                predicted = float(meta["schedule"]["predicted_wall_us"])
            except Exception as e:  # noqa: BLE001 — per-target containment
                skipped.append({
                    "target": name,
                    "reason": (f"cm2 re-pricing crashed: "
                               f"{type(e).__name__}: {e}"),
                })
                if verbose:
                    print(f"[obs] {name}: CRASH ({type(e).__name__}: {e})")
                continue
        try:
            with spans.span(f"calibrate:{name}", cat="calibration"):
                measured = measure_target(target, warmup=warmup, reps=reps)
        except Exception as e:  # noqa: BLE001 — per-target containment
            skipped.append({
                "target": name,
                "reason": f"measurement crashed: {type(e).__name__}: {e}",
            })
            if verbose:
                print(f"[obs] {name}: CRASH ({type(e).__name__}: {e})")
            continue
        m_us = measured["measured_us"]
        row = {
            "target": name,
            "tier": tier,
            "cost_model_version": cost_tier.version,
            "predicted_us": float(predicted),
            "dispatch_count": 1,
            "predicted_dispatch_overhead_us": overhead,
            "signed_rel_error": (m_us - predicted) / max(predicted, 1e-9),
            "error_factor": _error_factor(m_us, predicted),
            **measured,
        }
        rows.append(row)
        if verbose:
            print(f"[obs] {name}: predicted {predicted:.1f}us, measured "
                  f"{m_us:.1f}us (err {row['signed_rel_error']:+.1f}x, "
                  f"factor {row['error_factor']:.1f}x)")

    report = {
        "schema": CALIBRATION_SCHEMA,
        "tier": tier,
        "cost_model_version": cost_tier.version,
        "baselines_dir": str(baselines_dir),
        "aggregate": aggregate_errors(rows, skipped),
        "targets": rows,
        "skipped": skipped,
        "timestamp": time.time(),
    }
    if cost_tier.fit is not None:
        report["fit"] = {
            k: cost_tier.fit.get(k)
            for k in ("fit_version", "db_path", "samples_used",
                      "residuals")
        }
    write_report(report, out_dir)
    return report


def aggregate_errors(rows: list[dict[str, Any]],
                     skipped: Sequence[dict] = ()) -> dict[str, Any]:
    """The first-class predicted-vs-measured error stat: median signed
    relative error (bias direction), median absolute relative error, and
    the geometric-mean / max error factors (scale-free accuracy)."""
    if not rows:
        return {
            "targets_measured": 0,
            "targets_skipped": len(skipped),
            "median_signed_rel_error": None,
            "median_abs_rel_error": None,
            "geomean_error_factor": None,
            "max_error_factor": None,
        }
    signed = sorted(r["signed_rel_error"] for r in rows)
    abs_err = sorted(abs(e) for e in signed)
    factors = [r["error_factor"] for r in rows]
    return {
        "targets_measured": len(rows),
        "targets_skipped": len(skipped),
        "median_signed_rel_error": signed[len(signed) // 2],
        "median_abs_rel_error": abs_err[len(abs_err) // 2],
        "geomean_error_factor": math.exp(
            sum(math.log(f) for f in factors) / len(factors)
        ),
        "max_error_factor": max(factors),
    }


def write_report(report: dict[str, Any], out_dir: Path) -> Path:
    """JSON + CSV, atomically; the aggregate also lands in the output
    directory's ``sweep_manifest.json`` (created if absent, merged if a
    sweep already wrote one) so manifest consumers see the calibration
    state next to the compile/cache accounting."""
    import csv
    import io

    from dlbb_tpu.bench.schedule import MANIFEST_NAME, MANIFEST_SCHEMA
    from dlbb_tpu.utils.config import atomic_write_text, save_json

    out_dir = Path(out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    path = atomic_write_text(
        json.dumps(report, indent=2, sort_keys=True), out_dir / REPORT_NAME
    )
    buf = io.StringIO()
    writer = csv.DictWriter(buf, fieldnames=list(CSV_COLUMNS),
                            extrasaction="ignore")
    writer.writeheader()
    for row in report["targets"]:
        writer.writerow(row)
    atomic_write_text(buf.getvalue(), out_dir / CSV_NAME, newline="")

    manifest_path = out_dir / MANIFEST_NAME
    manifest: dict[str, Any] = {"schema": MANIFEST_SCHEMA,
                                "kind": "calibration"}
    if manifest_path.exists():
        try:
            manifest = json.loads(manifest_path.read_text())
        except (OSError, json.JSONDecodeError):
            pass  # torn/legacy manifest: rewrite with the calibration only
    manifest["calibration"] = {
        "tier": report["tier"],
        "cost_model_version": report["cost_model_version"],
        **report["aggregate"],
    }
    if "fit" in report:
        # the fitted-DB version this calibration was priced with — the
        # manifest-side record the fit_smoke CI stage pins
        manifest["calibration"]["fit_version"] = report["fit"].get(
            "fit_version")
        manifest["calibration"]["fitted_db"] = report["fit"].get("db_path")
    manifest.setdefault("timestamp", time.time())
    save_json(manifest, manifest_path)
    _fold_metrics(calibration_metrics(report), out_dir / METRICS_NAME)
    return path


def _metric_family(line: str) -> Optional[str]:
    if line.startswith("# HELP ") or line.startswith("# TYPE "):
        parts = line.split()
        return parts[2] if len(parts) > 2 else None
    if not line or line.startswith("#"):
        return None
    return line.split("{", 1)[0].split(" ", 1)[0]


def _fold_metrics(registry, path: Path) -> Path:
    """Fold the calibration gauges into an existing ``metrics.prom`` —
    calibrating into a sweep/serving output directory must not clobber
    that run's own export (every ``sweep_*``/``serve_*`` series would
    vanish from the scrape target, while the manifest path carefully
    merges).  Existing lines of families the calibration does not own
    are kept verbatim; re-runs replace only their own families."""
    from dlbb_tpu.obs.export import PROM_PREFIX
    from dlbb_tpu.utils.config import atomic_write_text

    own = {PROM_PREFIX + name for name in registry.as_dict()}
    kept: list[str] = []
    try:
        for line in Path(path).read_text().splitlines():
            fam = _metric_family(line)
            if fam is None or fam not in own:
                kept.append(line)
    except OSError:
        pass
    text = ("\n".join(kept) + "\n" if kept else "") \
        + registry.to_prometheus()
    return atomic_write_text(text, Path(path))


def calibration_metrics(report: dict[str, Any], registry=None):
    """Calibration / fit health as Prometheus gauges
    (``metrics.prom`` next to every calibration report): a drifting cost
    model shows up on a scrape dashboard, not only in ``obs diff`` CI."""
    from dlbb_tpu.obs.export import MetricsRegistry

    registry = registry or MetricsRegistry()
    labels = {"tier": report.get("tier"),
              "model": report.get("cost_model_version")}
    agg = report.get("aggregate", {})
    for key, metric, hlp in (
        ("geomean_error_factor", "obs_calibration_error_factor",
         "geomean predicted-vs-measured error factor across targets"),
        ("max_error_factor", "obs_calibration_max_error_factor",
         "worst per-target error factor"),
        ("median_signed_rel_error",
         "obs_calibration_median_signed_rel_error",
         "median signed relative error (bias direction)"),
    ):
        if agg.get(key) is not None:
            registry.set_gauge(metric, agg[key], help=hlp, **labels)
    registry.set_gauge("obs_calibration_targets",
                       agg.get("targets_measured", 0),
                       help="targets measured this calibration",
                       outcome="measured", **labels)
    registry.set_gauge("obs_calibration_targets",
                       agg.get("targets_skipped", 0),
                       outcome="skipped", **labels)
    fit = report.get("fit")
    if fit:
        registry.set_gauge("obs_fit_version", fit.get("fit_version") or 0,
                           help="fitted-DB version this run priced with",
                           **labels)
        if fit.get("samples_used") is not None:
            registry.set_gauge("obs_fit_samples", fit["samples_used"],
                               help="corpus samples the fit kept",
                               **labels)
        res = fit.get("residuals") or {}
        for key, metric, hlp in (
            ("geomean_error_factor", "obs_fit_residual_error_factor",
             "geomean fit residual factor over the corpus"),
            ("rms_log_error", "obs_fit_rms_log_error",
             "rms log-space fit residual"),
        ):
            if res.get(key) is not None:
                registry.set_gauge(metric, res[key], help=hlp, **labels)
    return registry


def save_calibration_baseline(report: dict[str, Any],
                              directory: Optional[Path] = None) -> Path:
    """Commit a calibration report as the diff gate's reference point —
    one file per cost model (``calibration_baseline.json`` for cm1,
    ``calibration_baseline_cm2.json`` for cm2)."""
    from dlbb_tpu.utils.config import atomic_write_text

    directory = Path(directory or DEFAULT_CALIBRATION_DIR)
    directory.mkdir(parents=True, exist_ok=True)
    path = directory / baseline_name(report.get("cost_model_version"))
    atomic_write_text(
        json.dumps(report, indent=2, sort_keys=True) + "\n", path
    )
    return path


def load_calibration_baseline(directory: "Path | str",
                              model: str = COST_MODEL_VERSION
                              ) -> dict[str, Any]:
    directory = Path(directory)
    path = (directory / baseline_name(model) if directory.is_dir()
            else directory)
    return json.loads(path.read_text())


def diff_calibration(report: dict[str, Any],
                     baseline_dir: "Path | str",
                     requested_model: Optional[str] = None
                     ) -> list[Finding]:
    """Findings when the fresh calibration regresses past the committed
    baseline.  The CI-gating (error) rules: no/unreadable baseline,
    cost-model version or tier skew, the report having been priced with
    a DIFFERENT model than ``requested_model`` (the cm2 fit DB fell back
    to cm1 — gating cm1 against its own baseline would silently pass the
    cm2 gate), and the joined-aggregate geomean error factor growing
    more than :data:`AGGREGATE_SLACK`.  Per-target drift and
    improvements warn."""
    findings: list[Finding] = []
    model = report.get("cost_model_version", COST_MODEL_VERSION)
    if requested_model and requested_model != model:
        findings.append(Finding(
            pass_name="obs", rule="cost-model-mismatch",
            severity=SEVERITY_ERROR, target=str(baseline_dir),
            message=(
                f"--model {requested_model} was requested but the "
                f"calibration was priced with {model} (missing fitted "
                "DB? run `python -m dlbb_tpu.cli obs fit` and commit "
                f"stats/analysis/costmodel_fit/) — refusing to gate "
                f"{model} in its place"
            ),
        ))
        return findings
    try:
        base = load_calibration_baseline(baseline_dir, model=model)
    except (OSError, json.JSONDecodeError) as e:
        findings.append(Finding(
            pass_name="obs", rule="missing-calibration-baseline",
            severity=SEVERITY_ERROR, target=str(baseline_dir),
            message=(
                f"no committed {model} calibration baseline ({e}) — run "
                f"`python -m dlbb_tpu.cli obs calibrate --model {model} "
                "--simulate 8` and commit "
                f"{Path(baseline_dir) / baseline_name(model)}"
            ),
        ))
        return findings
    if (base.get("cost_model_version") != report.get("cost_model_version")
            or base.get("tier") != report.get("tier")):
        findings.append(Finding(
            pass_name="obs", rule="cost-model-mismatch",
            severity=SEVERITY_ERROR, target=BASELINE_NAME,
            message=(
                f"calibration baseline is {base.get('cost_model_version')}"
                f"/{base.get('tier')} but this run is "
                f"{report.get('cost_model_version')}/{report.get('tier')} "
                "— errors are not comparable; re-run `obs calibrate` and "
                "commit the new baseline after a cost-model change"
            ),
        ))
        return findings

    base_rows = {r["target"]: r for r in base.get("targets", ())}
    cur_rows = {r["target"]: r for r in report.get("targets", ())}
    joined = sorted(set(base_rows) & set(cur_rows))
    if not joined:
        findings.append(Finding(
            pass_name="obs", rule="no-joined-targets",
            severity=SEVERITY_ERROR, target=BASELINE_NAME,
            message=(
                "the fresh calibration shares no measured target with the "
                "committed baseline — nothing to gate on; check the "
                "--targets filter / the baselines directory"
            ),
        ))
        return findings

    # aggregate over the JOINED set on both sides, so a subset run (the
    # obs_smoke stage) compares like with like
    base_join = [base_rows[t] for t in joined]
    cur_join = [cur_rows[t] for t in joined]
    base_geo = aggregate_errors(base_join)["geomean_error_factor"]
    cur_geo = aggregate_errors(cur_join)["geomean_error_factor"]
    if cur_geo > base_geo * AGGREGATE_SLACK:
        findings.append(Finding(
            pass_name="obs", rule="calibration-regression",
            severity=SEVERITY_ERROR, target=BASELINE_NAME,
            message=(
                f"aggregate cost-model error regressed: geomean error "
                f"factor {cur_geo:.1f}x vs committed {base_geo:.1f}x over "
                f"{len(joined)} joined target(s) (gate at "
                f"{AGGREGATE_SLACK:.1f}x growth) — the α–β model got "
                "WORSE at predicting this mesh; investigate (cost-model "
                "drift, backend change, measurement contamination), then "
                "re-commit the calibration baseline if the change is "
                "intended"
            ),
            details={"baseline_geomean": base_geo, "current_geomean": cur_geo,
                     "joined_targets": len(joined)},
        ))
    elif base_geo > cur_geo * AGGREGATE_SLACK:
        findings.append(Finding(
            pass_name="obs", rule="calibration-improved",
            severity=SEVERITY_WARNING, target=BASELINE_NAME,
            message=(
                f"aggregate error factor improved {base_geo / cur_geo:.1f}x "
                "under the committed baseline — re-run `obs calibrate` and "
                "commit to tighten the gate"
            ),
            details={"baseline_geomean": base_geo,
                     "current_geomean": cur_geo},
        ))
    for t in joined:
        b, c = base_rows[t]["error_factor"], cur_rows[t]["error_factor"]
        if c > b * TARGET_SLACK:
            findings.append(Finding(
                pass_name="obs", rule="target-calibration-drift",
                severity=SEVERITY_WARNING, target=t,
                message=(
                    f"per-target error factor {c:.1f}x vs committed "
                    f"{b:.1f}x (> {TARGET_SLACK:.0f}x growth) — this "
                    "target's prediction drifted; aggregate gate decides "
                    "CI, but check this one first"
                ),
                details={"baseline_factor": b, "current_factor": c},
            ))
    for t in sorted(set(cur_rows) - set(base_rows)):
        findings.append(Finding(
            pass_name="obs", rule="uncalibrated-target",
            severity=SEVERITY_WARNING, target=t,
            message=(
                "measured target has no entry in the committed "
                "calibration baseline — re-run `obs calibrate` over the "
                "full surface and commit, so the new target is gated too"
            ),
        ))
    return findings
