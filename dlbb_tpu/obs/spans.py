"""Unified host-side span tracing (Chrome trace-event JSON).

One process-wide :class:`SpanTracer` collects begin/end span pairs and
instant events from every layer of the harness — sweep planning /
compile / measure / write phases (``bench/schedule.py``,
``bench/runner.py``), train-loop steps and checkpoint saves
(``train/loop.py``), and every resilience-journal event (the journal's
pluggable sink forwards each fsync'd line as a trace instant, so a
crashed sweep's timeline is reconstructable from either artifact).  The
output is the Chrome trace-event format, loadable directly in Perfetto
(https://ui.perfetto.dev) or ``chrome://tracing``.

Zero-overhead contract (same shape as ``resilience/inject.py``): with no
tracer active, :func:`span` returns one shared ``nullcontext`` singleton
and :func:`instant` is a module-global load plus an ``is None`` test —
and ``utils/timing.py`` (the only module that brackets device work with
clocks) never imports this package at all, pinned statically by
``tests/test_obs.py``.  Spans wrap timed regions from the OUTSIDE only;
the ``profiler-in-timed-region`` comm-lint rule polices the device-side
(``jax.profiler``) half of that contract.

Timestamps are ``time.perf_counter`` relative to tracer start (the
monotonic clock — wall-clock timestamps live in the ``otherData``
metadata block, outside every event), in microseconds as the trace-event
spec requires.  Thread ids are real ``threading.get_ident`` values, so
the compile-ahead worker renders as its own track.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from pathlib import Path
from typing import Any, Iterator, Optional

SPAN_SCHEMA = "dlbb_span_trace_v1"

# shared disabled-path singleton: ``span()`` with no tracer active returns
# THIS object every time (one allocation for the whole process)
_NULL_SPAN = contextlib.nullcontext()

_TRACER: Optional["SpanTracer"] = None
_LOCK = threading.Lock()

ENV_VAR = "DLBB_SPANS"


def default_span_path() -> Optional[str]:
    """The env-switched default (``DLBB_SPANS=trace.json``), or None —
    the span-tracing analogue of ``DLBB_TRACE_DIR``."""
    return os.environ.get(ENV_VAR) or None


class SpanTracer:
    """Thread-safe in-memory trace-event collector for one session.

    Events are appended under a lock (µs-scale cost, only while tracing
    is on); :meth:`finish` writes the whole trace atomically
    (``utils/config.atomic_write_text``) so a crash mid-write can never
    leave a torn JSON behind.
    """

    def __init__(self, path: "str | Path",
                 meta: Optional[dict[str, Any]] = None) -> None:
        self.path = Path(path)
        self.meta = dict(meta or {})
        self._events: list[dict[str, Any]] = []
        self._elock = threading.Lock()
        self._t0 = time.perf_counter()
        self._pid = os.getpid()
        # wall-clock anchor for humans correlating with the journal;
        # lives in otherData, never in an event timestamp
        self.started_at = time.time()

    # -- event emission ----------------------------------------------------

    def _ts_us(self) -> float:
        return (time.perf_counter() - self._t0) * 1e6

    def _emit(self, ev: dict[str, Any]) -> None:
        with self._elock:
            self._events.append(ev)

    def begin(self, name: str, cat: str = "harness",
              args: Optional[dict[str, Any]] = None) -> None:
        self._emit({"name": name, "cat": cat, "ph": "B",
                    "ts": self._ts_us(), "pid": self._pid,
                    "tid": threading.get_ident(),
                    **({"args": args} if args else {})})

    def end(self, name: str, cat: str = "harness") -> None:
        self._emit({"name": name, "cat": cat, "ph": "E",
                    "ts": self._ts_us(), "pid": self._pid,
                    "tid": threading.get_ident()})

    @contextlib.contextmanager
    def span(self, name: str, cat: str = "harness",
             **args: Any) -> Iterator[None]:
        self.begin(name, cat, args=_jsonable(args))
        try:
            yield
        finally:
            self.end(name, cat)

    def instant(self, name: str, cat: str = "event",
                args: Optional[dict[str, Any]] = None) -> None:
        """A zero-duration marker (journal events, retries, preemptions).
        Scope "t" (thread) keeps concurrent instants on their own
        tracks."""
        self._emit({"name": name, "cat": cat, "ph": "i", "s": "t",
                    "ts": self._ts_us(), "pid": self._pid,
                    "tid": threading.get_ident(),
                    **({"args": _jsonable(args)} if args else {})})

    def events(self) -> list[dict[str, Any]]:
        with self._elock:
            return list(self._events)

    # -- output ------------------------------------------------------------

    def finish(self) -> Path:
        """Write the trace JSON atomically and return its path.  The
        tracer stays usable (a later finish rewrites with more events),
        so crash paths can checkpoint the trace early."""
        from dlbb_tpu.utils.config import atomic_write_text

        payload = {
            "traceEvents": self.events(),
            "displayTimeUnit": "ms",
            "otherData": {
                "schema": SPAN_SCHEMA,
                "pid": self._pid,
                "started_at": self.started_at,
                **self.meta,
            },
        }
        return atomic_write_text(json.dumps(payload), self.path)


def _jsonable(args: dict[str, Any]) -> dict[str, Any]:
    """Trace args must be JSON-serialisable; coerce the stragglers
    (paths, numpy scalars) to strings rather than crash the harness."""
    out: dict[str, Any] = {}
    for k, v in args.items():
        if isinstance(v, (str, int, float, bool)) or v is None:
            out[k] = v
        else:
            out[k] = str(v)
    return out


# ---------------------------------------------------------------------------
# module-level (zero-overhead) surface
# ---------------------------------------------------------------------------


def active() -> Optional[SpanTracer]:
    return _TRACER


def start(path: "str | Path",
          meta: Optional[dict[str, Any]] = None) -> SpanTracer:
    """Install the process-wide tracer.  A tracer that is already active
    WINS (first-starter owns the output file): nested activations — the
    CLI wrapping ``run_sweep`` which opens its own tracing scope — merge
    their events into the outer trace instead of fighting over files."""
    global _TRACER
    with _LOCK:
        if _TRACER is None:
            _TRACER = SpanTracer(path, meta=meta)
        return _TRACER


def stop() -> Optional[Path]:
    """Finish + uninstall the process-wide tracer; returns the written
    path (None when no tracer was active)."""
    global _TRACER
    with _LOCK:
        tracer, _TRACER = _TRACER, None
    if tracer is None:
        return None
    return tracer.finish()


@contextlib.contextmanager
def tracing(path: "Optional[str | Path]",
            meta: Optional[dict[str, Any]] = None
            ) -> Iterator[Optional[SpanTracer]]:
    """Scope-based activation: no-op when ``path`` is falsy, and a pure
    pass-through (no second tracer, no double write) when a tracer is
    already active — the inner scope's events land in the outer trace."""
    if not path:
        yield _TRACER
        return
    if _TRACER is not None:
        yield _TRACER
        return
    tracer = start(path, meta=meta)
    try:
        yield tracer
    finally:
        stop()


def span(name: str, cat: str = "harness", **args: Any):
    """A context manager tracing one named region — THE instrumentation
    entry point.  Disabled = the shared nullcontext singleton (no
    allocation, no clock read)."""
    tracer = _TRACER
    if tracer is None:
        return _NULL_SPAN
    return tracer.span(name, cat, **args)


def instant(name: str, cat: str = "event", **args: Any) -> None:
    tracer = _TRACER
    if tracer is not None:
        tracer.instant(name, cat, args=args or None)


def journal_sink(event: str, record: dict[str, Any]) -> None:
    """The resilience-journal sink: forwards one journal record as a
    trace instant (``resilience/journal.py`` takes this as its ``sink``
    parameter — the journal module itself never imports obs).  No-op
    with no tracer active; never raises into the journal."""
    tracer = _TRACER
    if tracer is None:
        return
    try:
        args = {k: v for k, v in record.items() if k not in ("ts", "event")}
        tracer.instant(event, cat="journal", args=args or None)
    except Exception:  # noqa: BLE001 — observability must not kill sweeps
        pass


# ---------------------------------------------------------------------------
# trace validation + journal -> trace reconstruction
# ---------------------------------------------------------------------------

_REQUIRED_EVENT_KEYS = ("name", "ph", "ts", "pid", "tid")


def validate_trace_events(events: list[dict[str, Any]]) -> list[str]:
    """Schema check for a trace-event list: required keys present, known
    phases only, and B/E pairs properly nested per (pid, tid) — the
    invariant Perfetto needs to build flame graphs.  Returns problem
    descriptions (empty = valid)."""
    problems: list[str] = []
    stacks: dict[tuple, list[str]] = {}
    for n, ev in enumerate(events):
        missing = [k for k in _REQUIRED_EVENT_KEYS if k not in ev]
        if missing:
            problems.append(f"event {n}: missing keys {missing}")
            continue
        ph = ev["ph"]
        if ph not in ("B", "E", "X", "i", "I", "M", "C"):
            problems.append(f"event {n}: unknown phase {ph!r}")
            continue
        key = (ev["pid"], ev["tid"])
        if ph == "B":
            stacks.setdefault(key, []).append(ev["name"])
        elif ph == "E":
            stack = stacks.setdefault(key, [])
            if not stack:
                problems.append(
                    f"event {n}: E {ev['name']!r} with empty stack on "
                    f"tid {ev['tid']}"
                )
            elif stack[-1] != ev["name"]:
                problems.append(
                    f"event {n}: E {ev['name']!r} does not close "
                    f"B {stack[-1]!r} on tid {ev['tid']} (misnested)"
                )
            else:
                stack.pop()
        elif ph == "X" and "dur" not in ev:
            problems.append(f"event {n}: X event without dur")
    for key, stack in sorted(stacks.items()):
        if stack:
            problems.append(f"tid {key[1]}: unclosed span(s) {stack}")
    return problems


def load_trace(path: "str | Path") -> dict[str, Any]:
    return json.loads(Path(path).read_text())


# the journal event streams a directory can hold (a sweep and a
# serving run may share an output dir — and one append-only journal
# file): each gets its own Perfetto track group (pid + process_name).
# Fleet runs (``serve/fleet.py``) add one track group PER REPLICA —
# every engine-side journal line carries ``replica=N`` through the
# replica journal proxy — plus a supervisor group for the fleet-level
# control events (failover, hedging, the degradation ladder), so a
# crashed fleet run reconstructs replica-by-replica from the journal
# alone (the PR-8 contract).
_SWEEP_PID, _SERVE_PID, _FLEET_PID = 1, 2, 3
_REPLICA_PID_BASE = 10

# supervisor-side fleet lifecycle events rendered as process-scoped
# instants (full-height markers): each one changes how every later
# request span on the affected tracks must be read
_FLEET_LIFECYCLE = ("replica-up", "replica-fenced", "replica-failed",
                    "request-failover", "request-hedged",
                    "degrade-transition", "failover-torn", "fleet-stall")


def _pid_name(pid: int) -> str:
    if pid >= _REPLICA_PID_BASE:
        return f"replica-{pid - _REPLICA_PID_BASE}"
    return {_SWEEP_PID: "sweep", _SERVE_PID: "serving",
            _FLEET_PID: "fleet"}[pid]


def _classify_stream(records: list[dict[str, Any]]) -> list[int]:
    """Per-record stream id: events carrying ``replica=N`` (a fleet
    replica's engine lifecycle) go to that replica's track group;
    other serving events (request lifecycle, and any event inside a
    ``mode: serve`` session) go to the serving track group; fleet
    supervisor events (inside a ``mode: fleet`` session) to the fleet
    group; everything else to the sweep one.  Session markers
    (``sweep-start``) switch the ambient mode for the events that
    follow them in file order — the streams interleaved in ONE
    append-only journal split cleanly, instead of the whole file being
    rendered as whichever kind came first."""
    pids: list[int] = []
    ambient = _SWEEP_PID
    for rec in records:
        ev = str(rec.get("event", ""))
        replica = rec.get("replica")
        if ev == "sweep-start":
            mode = rec.get("mode")
            ambient = (_SERVE_PID if mode == "serve"
                       else _FLEET_PID if mode == "fleet"
                       else _SWEEP_PID)
            pids.append(ambient)
        elif isinstance(replica, int):
            pids.append(_REPLICA_PID_BASE + replica)
        elif ev in _FLEET_LIFECYCLE or ambient == _FLEET_PID and (
                ev.startswith("request-") or ev.startswith("serve")
                or ev.startswith("spec-")):
            pids.append(_FLEET_PID)
        elif (ev.startswith("request-") or ev.startswith("serve")
              or ev.startswith("spec-")):
            pids.append(_SERVE_PID)
        else:
            pids.append(ambient)
    return pids


def journal_to_trace(journal_dir: "str | Path",
                     out_path: "str | Path") -> tuple[Path, int, int]:
    """Reconstruct a run timeline from the fsync'd journal(s) alone
    (``cli obs trace``): every journal event becomes a trace instant, and
    each config's ``started`` -> ``completed``/``failed`` pair becomes a
    complete ("X") span — so even a sweep that crashed before writing its
    span trace yields a loadable Perfetto timeline from the fsync'd
    journal.  Serving journals (``serve/engine.py``) pair the same way:
    ``request-arrived`` -> ``request-completed``/``request-rejected``/
    ``request-failed``/``request-preempted`` becomes each request's
    end-to-end span (queueing included) — failed and preempted
    lifecycles stay debuggable from the journal alone, exactly as
    completed ones do.

    A directory holding BOTH a sweep and a serving event stream —
    interleaved in the append-only ``sweep_journal.jsonl``, or split
    across ``*journal*.jsonl`` files — yields ONE merged timeline with
    two labelled track groups (``sweep`` / ``serving``), config and
    request spans each pairing within their own stream.
    Returns ``(path, events_converted, torn_lines)``."""
    from dlbb_tpu.resilience.journal import read_journal_file
    from dlbb_tpu.utils.config import atomic_write_text

    journal_dir = Path(journal_dir)
    records: list[dict[str, Any]] = []
    torn = 0
    sources: list[str] = []
    if journal_dir.is_dir():
        files = sorted(journal_dir.glob("*journal*.jsonl"))
    else:
        files = [journal_dir]
    for path in files:
        recs, t = read_journal_file(path)
        if recs:
            records.extend(recs)
            sources.append(path.name)
        torn += t
    if not records:
        raise FileNotFoundError(
            f"no parseable journal events under {journal_dir} "
            "(is this a sweep output directory?)"
        )
    pids = _classify_stream(records)
    order = sorted(range(len(records)),
                   key=lambda i: float(records[i].get("ts", 0.0)))
    t0 = min(float(r["ts"]) for r in records if "ts" in r)
    events: list[dict[str, Any]] = []
    seen_pids = sorted(set(pids))
    for pid in seen_pids:
        events.append({
            "name": "process_name", "ph": "M", "ts": 0.0,
            "pid": pid, "tid": 0,
            "args": {"name": _pid_name(pid)},
        })
    open_configs: dict[tuple[int, str], float] = {}
    for i in order:
        rec, pid = records[i], pids[i]
        ts_us = (float(rec.get("ts", t0)) - t0) * 1e6
        name = rec.get("event", "?")
        config = rec.get("config")
        args = {k: v for k, v in rec.items() if k != "ts"}
        if name in ("started", "request-arrived") and config:
            open_configs[(pid, config)] = ts_us
        elif (name in ("completed", "failed", "request-completed",
                       "request-rejected", "request-infeasible",
                       "request-failed", "request-preempted",
                       "request-canceled")
              and (pid, config) in open_configs):
            start_us = open_configs.pop((pid, config))
            kind = name[len("request-"):] if name.startswith(
                "request-") else name
            events.append({
                "name": config, "cat": f"config-{kind}", "ph": "X",
                "ts": start_us, "dur": max(ts_us - start_us, 0.0),
                "pid": pid, "tid": 1, "args": _jsonable(args),
            })
        if name in _FLEET_LIFECYCLE:
            # fleet lifecycle: full-height, own category — a fence or a
            # ladder transition recolours every later request span on
            # the affected tracks, so it must not drown among the
            # per-request ticks
            label = name
            if isinstance(rec.get("replica"), int):
                label = f"{name}[replica-{rec['replica']}]"
            elif config:
                label = f"{name}[{config}]"
            events.append({
                "name": label, "cat": "fleet", "ph": "i", "s": "p",
                "ts": ts_us, "pid": pid, "tid": 1,
                "args": _jsonable(args),
            })
            continue
        if name == "degraded":
            # a degraded-probe fallback (PR 11) changes how EVERY later
            # number in the run must be read — render it as a labelled,
            # process-scoped instant (full-height marker in Perfetto)
            # instead of a thread-local tick lost among the lifecycle
            # events
            reason = rec.get("reason") or "unknown"
            events.append({
                "name": f"degraded[{reason}]", "cat": "degraded",
                "ph": "i", "s": "p", "ts": ts_us, "pid": pid, "tid": 1,
                "args": _jsonable(args),
            })
            continue
        if name in ("prefix-attach", "prefix-cow"):
            # the prefix-cache pair: an attach instant labelled with its
            # donor/reuse (the TTFT story of that admission) and its CoW
            # sibling when the trie matched past the attach cap — own
            # category so a Perfetto query can line hit rate up against
            # the prefill spans
            label = f"{name}[{config}]" if config else name
            events.append({
                "name": label, "cat": "prefix-cache", "ph": "i",
                "s": "p", "ts": ts_us, "pid": pid, "tid": 1,
                "args": _jsonable(args),
            })
            continue
        events.append({
            "name": name, "cat": "journal", "ph": "i", "s": "t",
            "ts": ts_us, "pid": pid, "tid": 1, "args": _jsonable(args),
        })
    payload = {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "schema": SPAN_SCHEMA,
            "source": ",".join(sources),
            "journal_dir": str(journal_dir),
            "wall_t0": t0,
            "torn_lines": torn,
            "streams": {str(pid): _pid_name(pid)
                        for pid in seen_pids},
        },
    }
    path = atomic_write_text(json.dumps(payload), Path(out_path))
    return path, len(events), torn
