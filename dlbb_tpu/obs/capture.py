"""Gated per-config device-trace capture (``jax.profiler``).

The capture contract that keeps published numbers honest:

- captures run on DEDICATED profile reps — separate invocations of the
  work unit's program, never appended to the timing series the stats
  pipeline summarises (a traced sweep's artifacts are byte-identical in
  every stats field to an untraced run, asserted by the ``obs_smoke``
  gate);
- captures are scheduled strictly OUTSIDE the timed region and outside
  the PR-3/PR-5 measurement gate — after ``time_collective`` has
  returned and the gate has been released, so profiler overhead can
  never contend with a measurement (and a background compile is free to
  proceed during the capture: the capture is not a measurement);
- the ``profiler-in-timed-region`` comm-lint rule
  (``analysis/source_lint.py``) statically rejects any
  ``jax.profiler``/capture call inside a ``Timer`` block or
  ``perf_counter`` span anywhere in the repo, so the contract cannot rot
  by accident.  This file is the sanctioned capture API and is exempt
  (like ``utils/timing.py`` for host syncs).

Every capture is written as a PARSEABLE artifact: ``jax.profiler.trace``
runs with ``create_perfetto_trace=True``, so the capture directory holds
a trace-event JSON (``perfetto_trace.json.gz`` — the input of
``dlbb_tpu.obs.devtrace``) next to the raw ``.xplane.pb`` files (kept
for external profilers).  The metadata records the parseable trace path,
the capture's wall seconds and its on-disk byte size, so the sweep
manifest / devtrace report can account for capture cost.

Capture failures are contained: a broken profiler (e.g. an outer
``--trace`` session already holding the singleton profiler state) lands
as an ``error`` field in the capture metadata, never as a failed config
— and the sweep driver counts it in the
``obs_device_capture_failures_total`` labelled counter exported to
``metrics.prom``.
"""

from __future__ import annotations

import re
import time
from pathlib import Path
from typing import Any, Callable, Optional

CAPTURE_META_SCHEMA = "dlbb_device_capture_v1"

ENV_VAR = "DLBB_DEVICE_TRACE"


def default_capture_dir() -> Optional[str]:
    """Env-switched default (``DLBB_DEVICE_TRACE=dir``), or None."""
    import os

    return os.environ.get(ENV_VAR) or None


def _slug(label: str) -> str:
    return re.sub(r"[^\w.+-]+", "_", label).strip("_") or "capture"


def _dir_bytes(path: Path) -> int:
    return sum(p.stat().st_size for p in path.rglob("*") if p.is_file())


def capture_device_trace(
    fn: Callable,
    payload_builder: Callable[[], Any],
    trace_root: "str | Path",
    label: str,
    profile_reps: int = 1,
) -> dict[str, Any]:
    """Run ``profile_reps`` dedicated executions of ``fn`` on a freshly
    built payload under ``jax.profiler.trace``, writing both the xplane
    trace and the parseable perfetto trace-event JSON to
    ``trace_root/<label>/``.  Returns capture metadata for the result
    JSON / sweep manifest; the reps' timings are deliberately NOT
    returned — profile reps never enter a stats series."""
    import jax

    trace_dir = Path(trace_root) / _slug(label)
    meta: dict[str, Any] = {
        "schema": CAPTURE_META_SCHEMA,
        "label": label,
        "trace_dir": str(trace_dir),
        "profile_reps": int(profile_reps),
        # the honesty marker consumers key on: these reps are outside
        # the measurement series by construction
        "excluded_from_stats": True,
    }
    t0 = time.perf_counter()
    try:
        # a fresh payload: the measured payload may be cached (shared
        # with later configs) or donated (chained timing) — the capture
        # must never consume either
        x = payload_builder()
        trace_dir.mkdir(parents=True, exist_ok=True)
        with jax.profiler.trace(str(trace_dir),
                                create_perfetto_trace=True):
            with jax.profiler.TraceAnnotation(f"profile_rep:{label}"):
                for _ in range(max(1, int(profile_reps))):
                    jax.block_until_ready(fn(x))
    except Exception as e:  # noqa: BLE001 — capture must not fail a config
        meta["error"] = f"{type(e).__name__}: {e}"
        meta["error_kind"] = type(e).__name__
    meta["wall_seconds"] = time.perf_counter() - t0
    if trace_dir.is_dir():
        meta["trace_bytes"] = _dir_bytes(trace_dir)
        traces = perfetto_trace_files(trace_dir)
        if traces:
            meta["perfetto_trace"] = str(traces[-1])
        elif "error" not in meta:
            # the profiler ran but produced nothing parseable — record
            # it so the devtrace gate can fail closed with a clear
            # finding instead of a silent empty report
            meta["error"] = (
                "capture produced no perfetto trace-event JSON under "
                f"{trace_dir}"
            )
            meta["error_kind"] = "NoPerfettoTrace"
    return meta


def xplane_files(trace_root: "str | Path") -> list[Path]:
    """The ``.xplane.pb`` files under a capture directory — the raw
    profiler output kept alongside the parseable trace."""
    return sorted(Path(trace_root).rglob("*.xplane.pb"))


def perfetto_trace_files(trace_root: "str | Path") -> list[Path]:
    """The parseable trace-event JSON file(s) under a capture directory
    — what ``obs devtrace`` parses.  ``jax.profiler`` writes
    ``perfetto_trace.json.gz``; the per-host ``*.trace.json.gz`` trace
    (same event content, trace-viewer flavoured) is accepted as a
    fallback for captures taken by external tooling."""
    root = Path(trace_root)
    primary = sorted(root.rglob("perfetto_trace.json.gz"))
    if primary:
        return primary
    return sorted(root.rglob("*.trace.json.gz"))
