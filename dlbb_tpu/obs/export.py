"""Metrics registry: labelled counters/gauges + Prometheus-textfile export.

One :class:`MetricsRegistry` per sweep backs the ``sweep_manifest.json``
aggregates (the config-outcome counters are registry-backed through
:class:`LabeledCounter`, so the manifest and the export can never
disagree) and renders to the Prometheus textfile exposition format —
``metrics.prom`` next to the manifest, ready for a node-exporter
textfile collector on a TPU host.

Deliberately tiny and dependency-free (importable without jax/numpy):
counters and gauges with string labels, deterministic output order
(insertion order for metrics, sorted label sets within one), atomic
writes through ``utils/config.atomic_write_text``.
"""

from __future__ import annotations

import threading
from pathlib import Path
from typing import Any, Iterator, Mapping, Optional

PROM_PREFIX = "dlbb_"

_KINDS = ("counter", "gauge")


def _label_key(labels: dict[str, Any]) -> tuple[tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class _Metric:
    __slots__ = ("name", "kind", "help", "values")

    def __init__(self, name: str, kind: str, help: str = "") -> None:
        self.name = name
        self.kind = kind
        self.help = help
        self.values: dict[tuple[tuple[str, str], ...], float] = {}


class MetricsRegistry:
    """Thread-safe registry of named counters/gauges with labels."""

    def __init__(self) -> None:
        self._metrics: dict[str, _Metric] = {}
        self._lock = threading.Lock()

    def _metric(self, name: str, kind: str, help: str = "") -> _Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = _Metric(name, kind, help)
                self._metrics[name] = m
            elif m.kind != kind:
                raise ValueError(
                    f"metric {name!r} already registered as {m.kind}, "
                    f"not {kind}"
                )
            return m

    def inc(self, name: str, value: float = 1.0, help: str = "",
            **labels: Any) -> float:
        """Increment a counter; negative increments are rejected (that is
        what gauges are for)."""
        if value < 0:
            raise ValueError(f"counter {name!r} cannot decrease ({value})")
        m = self._metric(name, "counter", help)
        key = _label_key(labels)
        with self._lock:
            m.values[key] = m.values.get(key, 0.0) + value
            return m.values[key]

    def set_gauge(self, name: str, value: float, help: str = "",
                  **labels: Any) -> None:
        m = self._metric(name, "gauge", help)
        with self._lock:
            m.values[_label_key(labels)] = float(value)

    def get(self, name: str, **labels: Any) -> float:
        m = self._metrics.get(name)
        if m is None:
            return 0.0
        return m.values.get(_label_key(labels), 0.0)

    def labeled_counter(self, name: str, label: str,
                        initial: tuple[str, ...] = (),
                        help: str = "") -> "LabeledCounter":
        """A dict-like view over one counter's ``label`` axis — the sweep
        engine's config-outcome counters use this so the SAME registry
        entries feed the manifest dict and the textfile export."""
        counter = LabeledCounter(self, name, label, help=help)
        for key in initial:
            counter.ensure(key)
        return counter

    # -- rendering ---------------------------------------------------------

    def as_dict(self) -> dict[str, dict[str, Any]]:
        out: dict[str, dict[str, Any]] = {}
        with self._lock:
            for name, m in self._metrics.items():
                out[name] = {
                    "kind": m.kind,
                    "values": [
                        {"labels": dict(k), "value": v}
                        for k, v in sorted(m.values.items())
                    ],
                }
        return out

    def to_prometheus(self) -> str:
        """Prometheus textfile exposition format.  Counter names get the
        conventional ``_total`` suffix appended when missing."""
        lines: list[str] = []
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            name = PROM_PREFIX + m.name
            if m.kind == "counter" and not name.endswith("_total"):
                name += "_total"
            if m.help:
                lines.append(f"# HELP {name} {m.help}")
            lines.append(f"# TYPE {name} {m.kind}")
            for key, value in sorted(m.values.items()):
                if key:
                    rendered = ",".join(
                        f'{k}="{_escape(v)}"' for k, v in key
                    )
                    lines.append(f"{name}{{{rendered}}} {_num(value)}")
                else:
                    lines.append(f"{name} {_num(value)}")
        return "\n".join(lines) + "\n"

    def write_textfile(self, path: "str | Path") -> Path:
        from dlbb_tpu.utils.config import atomic_write_text

        return atomic_write_text(self.to_prometheus(), Path(path))


def _escape(v: str) -> str:
    return v.replace("\\", r"\\").replace('"', r"\"").replace("\n", r"\n")


def _num(v: float) -> str:
    return str(int(v)) if float(v).is_integer() else repr(float(v))


class LabeledCounter(Mapping):
    """Mapping view of one counter metric keyed by a single label.

    Supports the sweep driver's existing idiom (``counts["measured"] +=
    1``, ``dict(counts)`` for the manifest) while every mutation lands in
    the backing :class:`MetricsRegistry` — the "metrics back the manifest
    aggregates" contract."""

    def __init__(self, registry: MetricsRegistry, name: str, label: str,
                 help: str = "") -> None:
        self._registry = registry
        self._name = name
        self._label = label
        self._keys: list[str] = []
        self._help = help

    def ensure(self, key: str) -> None:
        if key not in self._keys:
            self._keys.append(key)
            self._registry.inc(self._name, 0, help=self._help,
                               **{self._label: key})

    def __getitem__(self, key: str) -> int:
        return int(self._registry.get(self._name, **{self._label: key}))

    def __setitem__(self, key: str, value: int) -> None:
        self.ensure(key)
        current = self[key]
        delta = int(value) - current
        if delta < 0:
            raise ValueError(
                f"counter {self._name}[{key}] cannot decrease "
                f"({current} -> {value})"
            )
        if delta:
            self._registry.inc(self._name, delta, **{self._label: key})

    def __iter__(self) -> Iterator[str]:
        return iter(self._keys)

    def __len__(self) -> int:
        return len(self._keys)


def serving_metrics(report: dict[str, Any],
                    registry: Optional[MetricsRegistry] = None
                    ) -> MetricsRegistry:
    """Fold a serving report (``serve/engine.py``) into gauges on top of
    the live counters/gauges the engine already registered — the serving
    analogue of :func:`sweep_metrics`, written as ``metrics.prom`` next
    to every serving run's manifest.

    The request-outcome counters (arrived/admitted/rejected/completed)
    are registry-backed during the run (``serve_requests``), so the
    report and the export share one source; this adds the derived
    summary numbers (goodput, tail latencies, cache peaks)."""
    registry = registry or MetricsRegistry()
    registry.set_gauge("serve_goodput_tokens_per_second",
                       report.get("goodput_tokens_per_s", 0.0),
                       help="completed-request output tokens per second")
    registry.set_gauge("serve_throughput_tokens_per_second",
                       report.get("throughput_tokens_per_s", 0.0),
                       help="all generated tokens per second")
    registry.set_gauge("serve_wall_seconds",
                       report.get("wall_seconds", 0.0),
                       help="trace wall-clock time")
    # serve_decode_steps is a live engine COUNTER (each fused-scan trip
    # counts once); when folding a bare report into a fresh registry,
    # seed it from the report so the export is self-contained either way
    if registry.get("serve_decode_steps") == 0:
        registry.inc("serve_decode_steps", report.get("decode_steps", 0),
                     help="decode steps executed (each fused-scan trip "
                          "counts once)")
    registry.set_gauge("serve_decode_units",
                       report.get("decode_units",
                                  report.get("decode_steps", 0)),
                       help="decode host dispatches (a fused scan is one)")
    fast = report.get("fast_path", {})
    for key, hlp in (
        ("fused_scans", "fused decode scans dispatched"),
        ("fused_steps", "decode steps executed inside fused scans"),
        ("prefill_chunks", "prefill chunks processed"),
        ("compacted_scans", "fused scans run on a compacted batch"),
    ):
        if key in fast:
            registry.set_gauge(f"serve_fastpath_{key}", fast[key])
    shed = report.get("requests", {}).get("shed_rate")
    if shed is not None:
        registry.set_gauge("serve_shed_rate", shed,
                           help="rejected / arrived requests this run")
    req = report.get("requests", {})
    for key, metric, hlp in (
        ("deadline_shed", "serve_deadline_shed",
         "queued requests shed because their SLO deadline passed"),
        ("completed_past_deadline", "serve_completed_past_deadline",
         "requests served to completion but past their SLO deadline"),
        ("failed", "serve_failed_requests",
         "requests failed closed (dispatch failure / hung dispatch)"),
        ("preempted", "serve_preempted_requests",
         "in-flight requests preempted by a graceful drain"),
    ):
        if key in req:
            registry.set_gauge(metric, req[key], help=hlp)
    # resilience counters live in the engine registry during the run
    # (serve_request_retries / serve_hung_dispatches /
    # serve_deadline_exceeded); when folding a bare report into a
    # fresh registry, seed the totals so the export is self-contained
    res = report.get("resilience", {})
    if res and all(registry.get("serve_request_retries", phase=p) == 0
                   for p in ("decode", "prefill", "bookkeeping")):
        registry.inc("serve_request_retries", res.get("retries", 0),
                     phase="decode",
                     help="transient dispatch/bookkeeping retries, "
                          "by phase")
    if res and registry.get("serve_hung_dispatches") == 0:
        registry.inc("serve_hung_dispatches",
                     res.get("hung_dispatches", 0),
                     help="decode units abandoned by the dispatch "
                          "watchdog")
    # speculative decoding: the per-drafter proposed/accepted counters
    # (serve_spec_proposed_total / serve_spec_accepted_total) and the
    # acceptance-EMA gauge are live ENGINE metrics; when folding a bare
    # report into a fresh registry, seed the totals from the report's
    # speculation sub-dict so the export is self-contained either way
    spec = report.get("speculation", {})
    if spec and spec.get("mode") not in (None, "off"):
        drafter = spec["mode"]
        if registry.get("serve_spec_proposed_total", drafter=drafter) == 0:
            registry.inc("serve_spec_proposed_total",
                         spec.get("proposed_tokens", 0), drafter=drafter,
                         help="draft tokens proposed to the verify step, "
                              "by drafter")
            registry.inc("serve_spec_accepted_total",
                         spec.get("accepted_tokens", 0), drafter=drafter,
                         help="draft tokens the target verify accepted, "
                              "by drafter")
        if spec.get("acceptance_rate") is not None:
            registry.set_gauge("serve_spec_acceptance_ema",
                               spec["acceptance_rate"],
                               help="run-level draft acceptance EMA")
        if spec.get("mean_accepted_len") is not None:
            registry.set_gauge("serve_spec_mean_accepted_len",
                               spec["mean_accepted_len"],
                               help="mean tokens committed per verify "
                                    "unit slot (accepted + bonus)")
    for metric, key in (("serve_ttft_seconds", "ttft"),
                        ("serve_per_token_seconds", "per_token_latency")):
        summary = report.get(key, {})
        for q in ("median", "p95", "p99", "p999"):
            if q in summary:
                registry.set_gauge(metric, summary[q], quantile=q)
    cache = report.get("cache", {})
    for k in ("blocks_in_use", "peak_blocks_in_use",
              "peak_blocks_reserved", "total_blocks", "shared_blocks",
              "peak_shared_blocks", "cow_blocks", "prefix_refs"):
        if k in cache:
            registry.set_gauge("serve_cache_blocks", cache[k], stat=k)
    # prefix cache: the hit/reuse counters (serve_prefix_hits_total /
    # serve_prefix_tokens_reused_total) are live ENGINE metrics; when
    # folding a bare report into a fresh registry, seed the totals from
    # the report's prefix sub-dict so the export is self-contained
    pre = report.get("prefix", {})
    if pre.get("enabled"):
        if registry.get("serve_prefix_hits") == 0:
            registry.inc("serve_prefix_hits", pre.get("hits", 0),
                         help="admissions that attached to a trie-matched "
                              "shared prefix")
            registry.inc("serve_prefix_tokens_reused",
                         pre.get("tokens_reused", 0),
                         help="prompt tokens served from shared blocks "
                              "instead of prefill compute")
        if pre.get("hit_rate") is not None:
            registry.set_gauge("serve_prefix_hit_rate", pre["hit_rate"],
                               help="prefix-attached fraction of "
                                    "prefills this run")
    return registry


def fleet_metrics(report: dict[str, Any],
                  registry: Optional[MetricsRegistry] = None
                  ) -> MetricsRegistry:
    """Fold a fleet report (``serve/fleet.py``) into the supervisor's
    live registry — the fleet analogue of :func:`serving_metrics`,
    written as ``metrics.prom`` next to the fleet manifest.

    The failover/hedge/degrade counters and the per-replica resident
    gauges are registry-backed DURING the run (``serve_failovers`` /
    ``serve_hedges`` / ``serve_degrade_transitions`` /
    ``serve_replica_resident_requests``), so report and export share
    one source; folding a bare report into a fresh registry seeds the
    totals so the export is self-contained either way — and never
    clobbers live counters that already carry the run's increments."""
    registry = registry or MetricsRegistry()
    registry.set_gauge("serve_goodput_tokens_per_second",
                       report.get("goodput_tokens_per_s", 0.0),
                       help="completed-request output tokens per second")
    registry.set_gauge("serve_wall_seconds",
                       report.get("wall_seconds", 0.0),
                       help="trace wall-clock time")
    fleet = report.get("fleet", {})
    registry.set_gauge("serve_fleet_replicas",
                       fleet.get("replicas", 0),
                       help="configured replica count (failure domains)")
    fo = report.get("failovers", {})
    if fo and all(registry.get("serve_failovers", reason=r) == 0
                  for r in fo.get("by_reason", {})):
        for reason, n in sorted(fo.get("by_reason", {}).items()):
            registry.inc("serve_failovers", n, reason=reason,
                         help="requests failed over off a fenced "
                              "replica, by fence reason")
    hedges = report.get("hedges", {})
    if hedges and all(registry.get("serve_hedges", outcome=o) == 0
                      for o in hedges):
        for outcome, n in sorted(hedges.items()):
            registry.inc("serve_hedges", n, outcome=outcome,
                         help="hedged requests by outcome")
    degrade = report.get("degrade", {})
    registry.set_gauge("serve_fleet_degrade_level",
                       degrade.get("level", 0),
                       help="final degradation-ladder level "
                            "(0 = full service)")
    if degrade.get("transitions") and registry.get(
            "serve_degrade_transitions",
            level=degrade["transitions"][0]["name"]) == 0:
        for rec in degrade["transitions"]:
            registry.inc("serve_degrade_transitions", 1,
                         level=rec["name"],
                         help="degradation-ladder escalations, by "
                              "level entered")
    routing = report.get("routing", {})
    for key, metric, hlp in (
        ("prefix_affinity_hits", "serve_fleet_affinity_hits",
         "admissions routed by prefix affinity"),
        ("prefix_affinity_misses", "serve_fleet_affinity_misses",
         "prefix-bearing admissions routed least-loaded instead"),
    ):
        if key in routing:
            registry.set_gauge(metric, routing[key], help=hlp)
    req = report.get("requests", {})
    for key in ("completed", "failed", "rejected", "canceled", "shed"):
        if key in req:
            registry.set_gauge("serve_fleet_requests", req[key],
                               outcome=key,
                               help="fleet-terminal request outcomes")
    ttft = report.get("ttft", {})
    for q in ("median", "p95", "p99", "p999"):
        if q in ttft:
            registry.set_gauge("serve_ttft_seconds", ttft[q], quantile=q)
    penalty = report.get("failover_ttft_penalty_s")
    if penalty is not None:
        registry.set_gauge("serve_failover_ttft_penalty_seconds", penalty,
                           help="mean TTFT of failed-over requests minus "
                                "mean TTFT of cleanly-routed ones")
    return registry


ANALYSIS_PASSES = ("hlo", "lint", "schedule", "memory", "numerics")


def analysis_metrics(report: Any,
                     registry: Optional[MetricsRegistry] = None
                     ) -> MetricsRegistry:
    """Fold a comm-lint :class:`~dlbb_tpu.analysis.findings.AnalysisReport`
    into per-pass finding-count gauges — the static-verification analogue
    of :func:`sweep_metrics`, folded into ``metrics.prom`` by ``analyze
    --output`` so suppression/violation drift is observable across PRs.

    Every known pass gets a sample at both severities even when clean
    (zeros are the signal: a pass that stops reporting is a silently
    dropped gate, which a dashboard can only see if the series exists)."""
    registry = registry or MetricsRegistry()
    counts: dict[tuple[str, str], int] = {
        (p, sev): 0
        for p in ANALYSIS_PASSES
        for sev in ("error", "warning")
    }
    for f in getattr(report, "findings", ()):
        key = (f.pass_name, f.severity)
        counts[key] = counts.get(key, 0) + 1
    for (pass_name, severity), n in sorted(counts.items()):
        registry.set_gauge(
            "analysis_findings", n,
            help="comm-lint findings by static pass and severity",
            severity=severity, **{"pass": pass_name},
        )
    registry.set_gauge(
        "analysis_suppressed", getattr(report, "suppressed", 0),
        help="comm-lint findings silenced by inline suppressions",
    )
    return registry


def sweep_metrics(manifest: dict[str, Any],
                  registry: Optional[MetricsRegistry] = None
                  ) -> MetricsRegistry:
    """Fold a sweep manifest's aggregate sections into gauges (wall and
    compile seconds, cache hits/misses, payload-cache stats, watchdog
    state) on top of the live counters the sweep already registered."""
    registry = registry or MetricsRegistry()
    registry.set_gauge("sweep_wall_seconds", manifest.get("wall_seconds", 0.0),
                       help="sweep wall-clock time")
    registry.set_gauge("sweep_compile_seconds",
                       manifest.get("compile_seconds_total", 0.0),
                       help="summed compile time across work units")
    cache = manifest.get("compile_cache", {})
    for k in ("persistent_hits", "persistent_misses"):
        registry.set_gauge("sweep_compile_cache", cache.get(k, 0),
                           outcome=k.replace("persistent_", ""))
    payload = manifest.get("payload_cache", {})
    for k, v in sorted(payload.items()):
        registry.set_gauge("sweep_payload_cache", v, stat=k)
    res = manifest.get("resilience", {})
    registry.set_gauge("sweep_retries", res.get("retries_total", 0),
                       help="transient-failure retries burned")
    registry.set_gauge("sweep_quarantined", len(res.get("quarantined", ())),
                       help="configs quarantined with exception chains")
    return registry
