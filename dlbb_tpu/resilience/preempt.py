"""Graceful preemption (SIGTERM) handling.

TPU fleets preempt routinely (maintenance events, spot reclaims) and the
runtime's notice is a SIGTERM with a short grace window.  The default
Python behaviour — ``SIGTERM`` kills the process wherever it is — can
land mid-measurement or mid-checkpoint-save.  :class:`PreemptionGuard`
turns the signal into a *flag* the harness polls at safe points:

- ``run_sweep`` checks between configs → journals ``preempted``, writes
  the manifest, and stops (the remaining grid is journaled ``planned``
  and a ``--resume`` run completes it exactly);
- ``run_train`` checks between steps → breaks the loop and falls through
  to the forced final checkpoint save (+ integrity manifest), so the
  restore after preemption starts from the last finished step.

Signal handlers can only be installed on the main thread; elsewhere
(e.g. a harness embedded in a worker thread) the guard degrades to an
inert flag that injection (``preempt`` site) and tests can still set.
"""

from __future__ import annotations

import signal
import threading
from typing import Any, Optional

__all__ = ["PreemptionGuard"]


class PreemptionGuard:
    """Scoped SIGTERM-to-flag handler (re-entrant safe, restores the
    previous handler on exit)::

        with PreemptionGuard() as guard:
            for config in plan:
                if guard.requested:
                    ...journal + flush + stop...
                    break
    """

    def __init__(self, signals: tuple[int, ...] = (signal.SIGTERM,)) -> None:
        self._signals = signals
        self._previous: dict[int, Any] = {}
        self._event = threading.Event()
        self.installed = False
        self.signal_received: Optional[int] = None

    @property
    def requested(self) -> bool:
        return self._event.is_set()

    def request(self) -> None:
        """Set the flag programmatically (tests, embedding harnesses)."""
        self._event.set()

    def _handler(self, signum, frame) -> None:
        self.signal_received = signum
        self._event.set()

    def __enter__(self) -> "PreemptionGuard":
        try:
            for sig in self._signals:
                self._previous[sig] = signal.signal(sig, self._handler)
            self.installed = True
        except ValueError:
            # not the main thread: signal.signal refuses — degrade to an
            # inert flag (restore nothing on exit)
            self._previous.clear()
            self.installed = False
        return self

    def __exit__(self, *exc) -> None:
        for sig, prev in self._previous.items():
            try:
                signal.signal(sig, prev)
            except (ValueError, TypeError):
                pass
        self._previous.clear()
        self.installed = False
