"""Resilience subsystem: deterministic fault injection, crash-safe sweep
journaling, artifact validation, graceful preemption, and the chaos gate.

The hardened execution paths live in the layers they harden
(``bench/runner`` retry/quarantine/watchdog, ``bench/schedule`` gate and
compile deadlines, ``train/checkpoint`` integrity manifests); this
package holds the shared machinery:

- :mod:`~dlbb_tpu.resilience.inject` — seedable fault-injection registry
  (``DLBB_FAULT_PLAN`` / ``--fault-plan``), zero instructions in timed
  regions when inactive;
- :mod:`~dlbb_tpu.resilience.journal` — append-only fsync'd
  ``sweep_journal.jsonl``;
- :mod:`~dlbb_tpu.resilience.validate` — artifact/timing validation
  (what resume trusts);
- :mod:`~dlbb_tpu.resilience.preempt` — SIGTERM → graceful-stop flag;
- :mod:`~dlbb_tpu.resilience.errors` — failure taxonomy (transient vs
  permanent, deadline, checkpoint corruption);
- :mod:`~dlbb_tpu.resilience.chaos` — the ``cli chaos`` gate asserting
  the invariants under every fault class (imported lazily: it pulls in
  the whole bench stack).

See ``docs/resilience.md`` for the contracts.
"""

from dlbb_tpu.resilience.errors import (
    CheckpointCorruption,
    CorruptStats,
    DeadlineExceeded,
    InjectedFault,
    TornWrite,
    TransientFault,
    exception_chain,
    is_transient,
)
from dlbb_tpu.resilience.journal import SweepJournal, read_journal
from dlbb_tpu.resilience.preempt import PreemptionGuard
from dlbb_tpu.resilience.validate import (
    validate_result_json,
    validate_timings,
)

__all__ = [
    "CheckpointCorruption",
    "CorruptStats",
    "DeadlineExceeded",
    "InjectedFault",
    "PreemptionGuard",
    "SweepJournal",
    "TornWrite",
    "TransientFault",
    "exception_chain",
    "is_transient",
    "read_journal",
    "validate_result_json",
    "validate_timings",
]
