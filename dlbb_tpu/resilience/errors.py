"""Failure taxonomy for the resilience subsystem.

Every hardened execution path (``bench/runner``, ``bench/schedule``,
``train/checkpoint``) classifies exceptions against these types:

- **transient** faults (:class:`TransientFault`, :class:`CorruptStats`)
  are retried with exponential backoff — the retry recomputes from
  scratch (fresh payload, fresh measurement) so a retried config's
  published stats contain nothing from the failed attempt;
- everything else is **permanent**: the config is quarantined (journaled
  ``failed`` with its exception chain in ``sweep_manifest.json``), never
  silently skipped.
"""

from __future__ import annotations

import traceback


class InjectedFault(RuntimeError):
    """Base class for faults raised by the injection registry
    (``dlbb_tpu.resilience.inject``) — never raised in production runs."""


class TransientFault(InjectedFault):
    """An injected retryable runtime error (models a flaky runtime /
    transport hiccup a production fleet retries through)."""


class TornWrite(InjectedFault):
    """An injected torn artifact write: a truncated JSON was left at the
    FINAL path (modelling the legacy non-atomic writer dying mid-dump)
    and the process 'crashed' before completing the config."""


class CorruptStats(RuntimeError):
    """Measured timings contain NaN/Inf — whether injected
    (``stats-nan`` site) or real (device fault), the stats must never
    reach an artifact; classified transient so the config re-measures
    from scratch."""


class DeadlineExceeded(RuntimeError):
    """A work unit overran its wall-clock deadline (hung compile or hung
    measurement) and was abandoned by the watchdog."""

    def __init__(self, label: str, deadline_seconds: float,
                 phase: str = "measure") -> None:
        super().__init__(
            f"{phase} of {label} exceeded the {deadline_seconds:g}s "
            "unit deadline; abandoned and quarantined"
        )
        self.label = label
        self.deadline_seconds = deadline_seconds
        self.phase = phase


class CheckpointCorruption(RuntimeError):
    """A checkpoint failed its integrity manifest (checksum mismatch /
    missing file) — an explicit ``restore(step=...)`` refuses it;
    ``restore_or`` falls back to the newest intact step instead."""


_TRANSIENT_TYPES = (TransientFault, CorruptStats)


def is_transient(exc: BaseException) -> bool:
    """Whether the bounded-retry loop should re-attempt after ``exc``."""
    return isinstance(exc, _TRANSIENT_TYPES)


def exception_chain(exc: BaseException) -> dict:
    """JSON-able record of an exception and its ``__cause__``/
    ``__context__`` chain — what the quarantine record carries instead of
    a silent skip."""
    chain = []
    seen: set[int] = set()
    cur: BaseException | None = exc
    while cur is not None and id(cur) not in seen:
        seen.add(id(cur))
        chain.append({"type": type(cur).__name__, "message": str(cur)})
        cur = cur.__cause__ or cur.__context__
    return {
        "error": f"{type(exc).__name__}: {exc}",
        "chain": chain,
        "traceback": "".join(
            traceback.format_exception(type(exc), exc, exc.__traceback__)
        ),
    }
