"""Artifact validation — what resume (and the chaos gate) trusts.

Before this module, ``run_sweep``'s resume path trusted file EXISTENCE
(``runner.py`` pre-PR5): a process killed mid-``json.dump`` of a
non-atomic writer left a truncated result that resume skipped forever,
leaking into the committed corpus.  Resume now trusts an artifact only if
it passes :func:`validate_result_json` — parses, carries the result
schema, and every timing sample is finite — and re-runs it (with a
warning + journal record) otherwise.
"""

from __future__ import annotations

import json
from pathlib import Path

import numpy as np

# The fields every sweep result JSON carries (reference-compatible schema,
# ``bench/runner._run_one``) that downstream stats readers index on.
REQUIRED_RESULT_FIELDS = (
    "implementation",
    "operation",
    "num_ranks",
    "num_elements",
    "timings",
)


def validate_result_json(path: "str | Path") -> tuple[bool, str]:
    """Is the artifact at ``path`` a complete, sane sweep result?

    Returns ``(ok, reason)``; ``reason`` is ``"ok"`` or why the artifact
    must not be trusted (truncated/torn JSON, missing schema fields,
    empty or non-finite timings)."""
    path = Path(path)
    if not path.exists():
        return False, "missing"
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, json.JSONDecodeError, UnicodeDecodeError) as e:
        return False, f"unparseable ({type(e).__name__}: {e})"
    if not isinstance(data, dict):
        return False, "not a JSON object"
    missing = [k for k in REQUIRED_RESULT_FIELDS if k not in data]
    if missing:
        return False, f"missing fields {missing}"
    try:
        arr = np.asarray(data["timings"], dtype=np.float64)
    except (TypeError, ValueError) as e:
        return False, f"non-numeric timings ({e})"
    if arr.size == 0:
        return False, "empty timings"
    if not np.isfinite(arr).all():
        bad = int(np.size(arr) - np.isfinite(arr).sum())
        return False, f"non-finite timings ({bad}/{arr.size} samples)"
    if not np.isfinite(np.median(arr)):
        return False, "non-finite median"
    return True, "ok"


def validate_timings(timings) -> tuple[bool, str]:
    """Pre-write check on a just-measured timing matrix (the writer-side
    twin of :func:`validate_result_json`): a NaN/Inf sample — injected or
    real — must never reach an artifact."""
    arr = np.asarray(timings, dtype=np.float64)
    if arr.size == 0:
        return False, "empty timings"
    if not np.isfinite(arr).all():
        bad = int(np.size(arr) - np.isfinite(arr).sum())
        return False, f"non-finite timings ({bad}/{arr.size} samples)"
    return True, "ok"
