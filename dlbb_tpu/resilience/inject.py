"""Deterministic, seedable fault-injection registry (chaos harness core).

Production TPU fleets treat preemption, flaky runtimes, torn writes and
corrupt artifacts as routine (Varuna, EuroSys'21; CheckFreq, FAST'21); the
benchmark harness must fail closed, retry transients, and resume exactly.
This module provides the *injection* half: named fault sites threaded
through the execution layers (never through timed regions — see below),
activated by a compact plan string.

Plan grammar (``DLBB_FAULT_PLAN`` env / ``--fault-plan`` CLI)::

    plan    := entry ("," entry)*
    entry   := SITE [":" trigger] | NAME "=" VALUE
    trigger := INT        fire on the first N hits of the site
             | "@" INT    fire only on the Nth hit (1-based)
             | "p" FLOAT  fire each hit with probability FLOAT (seeded)
             | "*"        fire on every hit

    examples:  "exec-transient"            first hit only
               "exec-transient:2"          first two hits
               "stats-nan:@2"              second hit only
               "exec-transient:p0.5,seed=7"  seeded coin per hit
               "exec-hang:@1,hang_seconds=5" site parameter

``NAME=VALUE`` entries are plan-level parameters: ``seed`` (default 0)
drives the probabilistic triggers through a per-site ``random.Random``
seeded by ``crc32(site) ^ seed`` — stable across processes and hash
randomisation — and sites read behaviour knobs (``hang_seconds``,
``torn_fraction``) via :func:`param`.

Zero-overhead contract: fault sites live strictly OUTSIDE timed regions —
around compiles, before/after (never inside) ``time_collective``, in
artifact writers and checkpoint save paths.  ``utils/timing.py`` (the only
module that brackets device work with clocks) never imports this module,
so an inactive plan adds zero instructions to any timed region; with no
plan active :func:`fire` is one module-global load and an ``is None``
test.  ``tests/test_resilience.py`` pins both properties.

Known sites (each raises/acts at its caller, listed with the layer that
hosts it):

==================  =====================================================
``compile-fail``    ``bench/schedule._compile_unit`` — build raises
``compile-hang``    ``bench/schedule._compile_unit`` — sleeps
                    ``hang_seconds`` (default 30) before building
``exec-transient``  ``bench/runner._run_one`` pre-measurement — raises
                    :class:`~dlbb_tpu.resilience.errors.TransientFault`
``exec-hang``       ``bench/runner._run_one`` pre-measurement — sleeps
                    ``hang_seconds``
``stats-nan``       ``bench/runner._run_one`` post-measurement — poisons
                    the timing vector with NaN/Inf
``torn-write``      ``utils/config.save_json`` — leaves a truncated JSON
                    at the final path (first ``torn_fraction``, default
                    0.3, of the payload) and raises
                    :class:`~dlbb_tpu.resilience.errors.TornWrite`
``kill-mid-write``  ``utils/config.save_json`` — SIGKILLs the process
                    between the tmp write and ``os.replace`` (died
                    mid-write with the atomic writer: tmp file only)
``ckpt-corrupt``    ``train/checkpoint.Checkpointer.maybe_save`` —
                    flips bytes in a just-saved checkpoint file (after
                    its integrity manifest was written, so verification
                    must catch it)
``preempt``         ``bench/runner`` between configs / ``train/loop``
                    between steps — SIGTERMs own process (the graceful
                    preemption path; the installed handler must turn it
                    into a journaled stop + final save)
==================  =====================================================

Serving sites (``serve/engine.py``; all fire strictly on the HOST side
of a dispatch boundary — the jitted prefill/decode programs are
byte-identical with or without a plan, pinned statically by
``tests/test_serve_resilience.py``):

=====================  ==================================================
``serve-prefill-fail`` prefill dispatch boundary — raises
                       :class:`TransientFault` BEFORE the jit is
                       invoked (retry re-dispatches; the donated cache
                       was never consumed)
``serve-decode-fail``  decode-unit dispatch boundary — same contract
``serve-decode-hang``  decode-unit dispatch — sleeps ``hang_seconds``
                       (the in-flight-window watchdog must abandon it)
``serve-cache-torn``   host ledger/slot bookkeeping after a decode
                       unit — raises mid-loop, leaving the accounting
                       torn (rollback to the pre-dispatch snapshot must
                       recover; the device result is unaffected)
``serve-trace-corrupt`` ``serve/traffic.TrafficTrace.load`` — truncates
                       the trace text before parsing (load must fail
                       closed with a clear chained error)
``serve-preempt``      serving scheduler loop boundary — SIGTERMs own
                       process (graceful drain + checkpoint +
                       ``cli serve --resume``)
=====================  ==================================================

Fleet sites (``serve/fleet.py`` + the replica control plane checked at
the engine's scheduler-loop boundary; all strictly host-side — the
static zero-injection pin extends to ``fleet.py`` via
``tests/test_fleet.py``):

========================  ===============================================
``serve-replica-kill``    replica loop boundary — raises
                          :class:`~dlbb_tpu.serve.fleet.ReplicaKilled`
                          out of the engine (simulated replica SIGKILL:
                          no report, no cleanup; the supervisor fences
                          the replica and fails its residents over)
``serve-replica-hang``    replica loop boundary — sleeps
                          ``hang_seconds`` (the per-replica heartbeat
                          watchdog must fence the silent replica)
``serve-failover-torn``   supervisor routing-table update mid-failover —
                          raises :class:`TornWrite` after the mutation,
                          before any feed push (the snapshot/restore
                          discipline must roll back and retry without
                          double-routing a request)
========================  ===============================================
"""

from __future__ import annotations

import os
import random
import threading
import zlib
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Optional

from dlbb_tpu.resilience.errors import InjectedFault, TornWrite, TransientFault

__all__ = [
    "SITES",
    "FaultPlan",
    "activate",
    "active",
    "deactivate",
    "fire",
    "from_env",
    "param",
    "plan_scope",
    "InjectedFault",
    "TransientFault",
    "TornWrite",
]

ENV_VAR = "DLBB_FAULT_PLAN"

SITES: tuple[str, ...] = (
    "compile-fail",
    "compile-hang",
    "exec-transient",
    "exec-hang",
    "stats-nan",
    "torn-write",
    "kill-mid-write",
    "ckpt-corrupt",
    "preempt",
    "serve-prefill-fail",
    "serve-decode-fail",
    "serve-decode-hang",
    "serve-cache-torn",
    "serve-trace-corrupt",
    "serve-preempt",
    "serve-replica-kill",
    "serve-replica-hang",
    "serve-failover-torn",
)

_DEFAULT_PARAMS = {
    "seed": 0.0,
    "hang_seconds": 30.0,
    "torn_fraction": 0.3,
}


@dataclass(frozen=True)
class _SiteSpec:
    """Trigger rule for one site (exactly one field set; all None =
    first-hit-only default)."""

    count: Optional[int] = None   # fire on hits 1..count
    nth: Optional[int] = None     # fire only on hit == nth
    prob: Optional[float] = None  # seeded coin per hit
    always: bool = False


def _parse_trigger(site: str, trig: str) -> _SiteSpec:
    if trig == "*":
        return _SiteSpec(always=True)
    if trig.startswith("@"):
        return _SiteSpec(nth=int(trig[1:]))
    if trig.startswith("p"):
        p = float(trig[1:])
        if not 0.0 <= p <= 1.0:
            raise ValueError(f"site {site!r}: probability {p} not in [0,1]")
        return _SiteSpec(prob=p)
    return _SiteSpec(count=int(trig))


@dataclass
class FaultPlan:
    """Parsed fault plan: per-site triggers, plan parameters, and the
    deterministic hit/fire bookkeeping chaos assertions read back."""

    sites: dict[str, _SiteSpec] = field(default_factory=dict)
    params: dict[str, float] = field(default_factory=dict)
    spec: str = ""
    hits: dict[str, int] = field(default_factory=dict)
    fired: list[tuple[str, int]] = field(default_factory=list)
    _lock: threading.Lock = field(default_factory=threading.Lock,
                                  repr=False)
    _rngs: dict[str, random.Random] = field(default_factory=dict,
                                            repr=False)

    @classmethod
    def parse(cls, spec: str) -> "FaultPlan":
        plan = cls(spec=spec)
        for raw in spec.split(","):
            entry = raw.strip()
            if not entry:
                continue
            if "=" in entry:
                name, _, value = entry.partition("=")
                name = name.strip()
                if name not in _DEFAULT_PARAMS:
                    raise ValueError(
                        f"unknown fault-plan parameter {name!r} "
                        f"(known: {sorted(_DEFAULT_PARAMS)})"
                    )
                plan.params[name] = float(value)
                continue
            site, _, trig = entry.partition(":")
            site = site.strip()
            if site not in SITES:
                raise ValueError(
                    f"unknown fault site {site!r} (known: {list(SITES)})"
                )
            plan.sites[site] = (_parse_trigger(site, trig.strip())
                                if trig else _SiteSpec(count=1))
        return plan

    def param(self, name: str) -> float:
        return self.params.get(name, _DEFAULT_PARAMS[name])

    def _rng(self, site: str) -> random.Random:
        rng = self._rngs.get(site)
        if rng is None:
            # crc32, not hash(): stable under PYTHONHASHSEED randomisation
            seed = zlib.crc32(site.encode()) ^ int(self.param("seed"))
            rng = self._rngs[site] = random.Random(seed)
        return rng

    def fire(self, site: str) -> bool:
        spec = self.sites.get(site)
        if spec is None:
            return False
        with self._lock:
            n = self.hits[site] = self.hits.get(site, 0) + 1
            if spec.always:
                hit = True
            elif spec.prob is not None:
                hit = self._rng(site).random() < spec.prob
            elif spec.nth is not None:
                hit = n == spec.nth
            else:
                hit = n <= (spec.count or 1)
            if hit:
                self.fired.append((site, n))
            return hit


# The one module-global the (inactive) fast path touches.
_ACTIVE: Optional[FaultPlan] = None


def active() -> Optional[FaultPlan]:
    return _ACTIVE


def activate(plan: "FaultPlan | str") -> FaultPlan:
    """Install ``plan`` (a :class:`FaultPlan` or spec string) process-wide;
    returns the installed plan.  Callers own the scope — pair with
    :func:`deactivate` (or use :func:`plan_scope`)."""
    global _ACTIVE
    if isinstance(plan, str):
        plan = FaultPlan.parse(plan)
    _ACTIVE = plan
    return plan


def deactivate() -> None:
    global _ACTIVE
    _ACTIVE = None


@contextmanager
def plan_scope(plan: "FaultPlan | str | None"):
    """Scoped activation; ``None`` is a no-op scope (so callers can write
    ``with plan_scope(sweep.fault_plan):`` unconditionally)."""
    global _ACTIVE
    if plan is None:
        yield None
        return
    prev = _ACTIVE
    installed = activate(plan)
    try:
        yield installed
    finally:
        _ACTIVE = prev


def from_env() -> Optional[FaultPlan]:
    """Parse ``DLBB_FAULT_PLAN`` (None when unset/empty)."""
    spec = os.environ.get(ENV_VAR, "").strip()
    return FaultPlan.parse(spec) if spec else None


def fire(site: str) -> bool:
    """Should ``site`` fault now?  One global load + ``is None`` test when
    no plan is active — and every call site lives outside timed regions."""
    plan = _ACTIVE
    if plan is None:
        return False
    return plan.fire(site)


def param(name: str) -> float:
    """Active plan's parameter (module default when inactive — callers
    only consult parameters after :func:`fire` returned True)."""
    plan = _ACTIVE
    if plan is None:
        return _DEFAULT_PARAMS[name]
    return plan.param(name)
