"""Chaos gate: run a mini-sweep / mini-train under each fault class and
assert the resilience invariants (``python -m dlbb_tpu.cli chaos``).

Each class activates a deterministic fault plan
(:mod:`dlbb_tpu.resilience.inject`), drives the real execution path (the
PR-3 pipelined sweep engine on the simulated mesh; the orbax
checkpointer), and asserts:

- **no corrupt artifact survives** where resume or the stats pipeline
  would trust it — every surviving result JSON passes
  :func:`~dlbb_tpu.resilience.validate.validate_result_json`;
- **transients recover**: retried configs complete with ``retries >= 1``
  and finite stats;
- **permanent faults fail closed**: the config lands in
  ``sweep_manifest.json`` as quarantined with its exception chain, and
  the journal records ``failed``;
- **resume completes the grid exactly**: after a torn write, a SIGTERM,
  or a SIGKILL mid-write, a ``--resume`` run produces the same artifact
  set — same filenames, same schema keys, finite stats — as an
  uninterrupted run of the same grid.

The ``kill`` class SIGKILLs a real subprocess sweep (the
``kill-mid-write`` site fires between the tmp write and ``os.replace``),
because a same-process SIGKILL would take the gate down with it.

The ``serve`` class (``cli chaos --plan serve``) runs the serving-path
fault matrix through the continuous-batching engine: transient
prefill/decode dispatch failures retry after rolling the host
ledger/slot state back to the pre-dispatch snapshot; exhausted retries
fail only the affected requests with journaled exception chains; a
hung dispatch is abandoned by the EMA-scaled watchdog while the engine
continues; torn host bookkeeping rolls back and replays; a corrupt
trace file fails closed at load; blown-SLO queue heads shed with
``reason=deadline``; and SIGTERM mid-trace + ``cli serve --resume``
reproduces an uninterrupted run's artifact set (names + schema +
per-request outcomes for non-preempted requests).

The ``fleet`` class (``cli chaos --plan fleet``) runs the replica-level
fault matrix through the PR-20 fleet supervisor: a replica SIGKILLed
mid-trace is fenced and its residents fail over with tokens identical
to an unfaulted single-replica run and zero leaked ledger blocks; a
torn failover rolls back its routing mutation and retries without
double-routing; a hung replica is fenced by the heartbeat watchdog
long before the hang expires; straggling residents are hedged, first
completion wins, and the losing copy is canceled cleanly; and prefix
affinity survives the loss of a prefix group's home replica.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
from pathlib import Path
from typing import Callable, Optional

from dlbb_tpu.resilience.journal import read_journal
from dlbb_tpu.resilience.validate import validate_result_json

# Mini-grid shared by every class: 2 ops x 1 size x 4 ranks on the
# simulated mesh — two configs, two work units, seconds per class.
_MINI = dict(
    implementation="chaos",
    operations=("allreduce", "broadcast"),
    data_sizes=(("1KB", 256),),
    rank_counts=(4,),
    dtype="float32",
    warmup_iterations=1,
    measurement_iterations=3,
    compile_cache="off",
    pipeline=True,
)
_GRID_FILES = sorted(
    f"chaos_{op}_ranks4_1KB_fp32.json" for op in _MINI["operations"]
)


class ChaosFailure(AssertionError):
    """An invariant did not hold under an injected fault."""


def _sweep(out_dir: str, **kw):
    from dlbb_tpu.bench import Sweep1D, run_sweep

    cfg = dict(_MINI)
    cfg.update(kw)
    return run_sweep(Sweep1D(output_dir=out_dir, **cfg), verbose=False)


def _manifest(out_dir: str) -> dict:
    with open(Path(out_dir) / "sweep_manifest.json") as f:
        return json.load(f)


def _check(cond: bool, msg: str) -> None:
    if not cond:
        raise ChaosFailure(msg)


def _assert_all_valid(paths) -> None:
    for p in paths:
        ok, why = validate_result_json(p)
        _check(ok, f"corrupt artifact survived: {p} ({why})")


def _assert_grid_equivalent(out_dir: str, reference_dir: str) -> None:
    """Same artifact set as an uninterrupted run: same filenames, same
    schema keys, finite stats (values differ — they are measurements)."""
    got = sorted(p.name for p in Path(out_dir).glob("chaos_*.json"))
    ref = sorted(p.name for p in Path(reference_dir).glob("chaos_*.json"))
    _check(got == ref, f"artifact sets differ: {got} != {ref}")
    for name in got:
        a = json.loads((Path(out_dir) / name).read_text())
        b = json.loads((Path(reference_dir) / name).read_text())
        _check(sorted(a) == sorted(b),
               f"{name}: schema keys differ after recovery")
        ok, why = validate_result_json(Path(out_dir) / name)
        _check(ok, f"{name}: invalid after recovery ({why})")


# ---------------------------------------------------------------------------
# fault classes
# ---------------------------------------------------------------------------


def _class_compile(work: Path, log: Callable[[str], None]) -> None:
    out = str(work / "compile")
    files = _sweep(out, fault_plan="compile-fail:@1", max_retries=0)
    man = _manifest(out)
    _check(man["configs"]["failed"] == 1,
           f"compile failure not quarantined: {man['configs']}")
    q = man["resilience"]["quarantined"]
    _check(len(q) == 1 and "InjectedFault" in q[0]["error"]
           and q[0]["traceback"],
           "quarantine record lacks the exception chain")
    _check(len(files) == len(_GRID_FILES) - 1,
           "surviving configs did not all measure")
    _assert_all_valid(files)
    ev, _ = read_journal(out)
    _check(any(e["event"] == "failed" for e in ev),
           "journal has no failed record for the poisoned config")
    log("compile-fail: quarantined with exception chain; grid drained")


def _class_transient(work: Path, log: Callable[[str], None]) -> None:
    out = str(work / "transient")
    files = _sweep(out, fault_plan="exec-transient:1", max_retries=2)
    _check(len(files) == len(_GRID_FILES),
           "transient fault was not retried to completion")
    _assert_all_valid(files)
    retries = [json.loads(Path(p).read_text())["retries"] for p in files]
    _check(sum(retries) == 1,
           f"expected exactly one retried config, got retries={retries}")
    _check(_manifest(out)["resilience"]["retries_total"] == 1,
           "manifest retries_total wrong")
    log("transient: retried with backoff, artifact flags retries=1")


def _class_nan(work: Path, log: Callable[[str], None]) -> None:
    out = str(work / "nan")
    files = _sweep(out, fault_plan="stats-nan:1", max_retries=2)
    _check(len(files) == len(_GRID_FILES),
           "NaN-corrupted config did not re-measure")
    _assert_all_valid(files)  # finite medians everywhere
    retries = [json.loads(Path(p).read_text())["retries"] for p in files]
    _check(sum(retries) >= 1, "NaN corruption was not detected pre-write")
    log("stats-nan: corrupt stats never written; re-measured from scratch")


def _class_torn(work: Path, log: Callable[[str], None]) -> None:
    out = str(work / "torn")
    _sweep(out, fault_plan="torn-write:@1", max_retries=0)
    man = _manifest(out)
    _check(man["configs"]["failed"] == 1, "torn write not failed closed")
    torn = [p for p in Path(out).glob("chaos_*.json")
            if not validate_result_json(p)[0]]
    _check(len(torn) == 1, "expected exactly one torn artifact on disk")
    # resume must re-validate, refuse the torn file, and re-measure it
    files = _sweep(out, resume=True)
    _check(len(files) == len(_GRID_FILES), "resume did not complete grid")
    _assert_all_valid(files)
    ev, _ = read_journal(out)
    _check(any(e["event"] == "resume-invalid" for e in ev),
           "journal has no resume-invalid record for the torn artifact")
    log("torn-write: resume re-validated, re-measured; no corrupt artifact "
        "trusted")


def _class_hang(work: Path, log: Callable[[str], None]) -> None:
    # 120s hang vs a 60s wall budget: wide enough that a loaded host's
    # own compile+measure time can never trip the assertion, narrow
    # enough that blocking behind the hang always does (the same
    # margin fix as the tier-1 watchdog test, PR 11)
    out = str(work / "hang")
    t0 = time.perf_counter()
    files = _sweep(out, fault_plan="exec-hang:@1,hang_seconds=120",
                   unit_deadline_seconds=1.0, max_retries=0)
    wall = time.perf_counter() - t0
    man = _manifest(out)
    _check(man["resilience"]["watchdog"]["abandoned_measurements"] == 1,
           "watchdog did not abandon the hung measurement")
    _check(man["configs"]["failed"] == 1, "hung unit not quarantined")
    _check(len(files) == len(_GRID_FILES) - 1,
           "pipeline did not drain past the hung unit")
    _check(wall < 60.0,
           f"sweep blocked behind the hang ({wall:.1f}s vs 120s sleep)")
    _assert_all_valid(files)
    log(f"exec-hang: abandoned at deadline, drained in {wall:.1f}s "
        "(hang was 120s)")


def _class_ckpt(work: Path, log: Callable[[str], None]) -> None:
    import jax.numpy as jnp

    from dlbb_tpu.resilience import inject
    from dlbb_tpu.train.checkpoint import CheckpointConfig, Checkpointer
    from dlbb_tpu.train.loop import TrainState

    def state(step: int) -> TrainState:
        return TrainState({"w": jnp.full((8, 8), float(step))},
                          {"m": jnp.zeros((8,))},
                          jnp.asarray(step, jnp.int32))

    d = str(work / "ckpt")
    with inject.plan_scope("ckpt-corrupt:@3"):
        with Checkpointer(CheckpointConfig(d, max_to_keep=5)) as ckpt:
            for s in (1, 2, 3):
                _check(ckpt.maybe_save(state(s), force=True),
                       f"save of step {s} failed")
            ok, why = ckpt.verify_step(3)
            _check(not ok, "corrupted step 3 passed verification")
            _check(ckpt.latest_intact_step() == 2,
                   "latest intact step should be 2")
            restored = ckpt.restore_or(state(0))
            _check(int(restored.step) == 2
                   and float(restored.params["w"][0, 0]) == 2.0,
                   "restore_or did not fall back to the intact step")
    log(f"ckpt-corrupt: step 3 refused ({why.split('(')[0].strip()}); "
        "fell back to intact step 2")


def _class_preempt(work: Path, log: Callable[[str], None]) -> None:
    out = str(work / "preempt")
    clean = str(work / "preempt_reference")
    _sweep(clean)
    files = _sweep(out, fault_plan="preempt:@2")
    man = _manifest(out)
    _check(man["resilience"]["preempted"], "SIGTERM did not journal a stop")
    _check(len(files) == 1, "preemption should stop before config 2")
    ev, _ = read_journal(out)
    _check(any(e["event"] == "preempted" for e in ev),
           "journal has no preempted record")
    files = _sweep(out, resume=True)
    _check(len(files) == len(_GRID_FILES),
           "resume after preemption did not complete the grid")
    _assert_grid_equivalent(out, clean)
    log("preempt: SIGTERM -> journaled stop; resume completed the grid "
        "equivalently")


def _class_kill(work: Path, log: Callable[[str], None]) -> None:
    """SIGKILL mid-write (subprocess): the atomic writer must leave no
    destination artifact; resume completes the grid equivalently."""
    out = work / "kill"
    clean = work / "kill_reference"
    script = (
        "from dlbb_tpu.utils.simulate import force_cpu_simulation\n"
        "force_cpu_simulation(8)\n"
        "from dlbb_tpu.bench import Sweep1D, run_sweep\n"
        "import sys, json\n"
        "cfg = json.loads(sys.argv[1])\n"
        "run_sweep(Sweep1D(**cfg), verbose=False)\n"
    )

    def run_child(out_dir: str, **kw) -> int:
        cfg = dict(_MINI)
        cfg["output_dir"] = out_dir
        cfg.update(kw)
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.pop("DLBB_FAULT_PLAN", None)
        proc = subprocess.run(
            [sys.executable, "-c", script, json.dumps(cfg)],
            env=env, capture_output=True, text=True, timeout=600,
        )
        if proc.returncode not in (0, -9):
            raise ChaosFailure(
                f"chaos child failed unexpectedly (rc={proc.returncode}):\n"
                f"{proc.stderr[-2000:]}"
            )
        return proc.returncode

    rc = run_child(str(clean))
    _check(rc == 0, "reference child sweep failed")
    rc = run_child(str(out), fault_plan="kill-mid-write:@1")
    _check(rc == -9, f"kill-mid-write child should die by SIGKILL, rc={rc}")
    survivors = list(out.glob("chaos_*.json"))
    _check(not survivors,
           f"SIGKILL mid-write left destination artifacts: {survivors}")
    # (uniquely-named *.tmp litter from the killed write is permitted —
    # nothing ever trusts or collides with it)
    ev, _ = read_journal(out)
    _check(any(e["event"] == "started" for e in ev)
           and not any(e["event"] == "completed" for e in ev),
           "journal should show started-but-not-completed after SIGKILL")
    rc = run_child(str(out), resume=True)
    _check(rc == 0, "resume child sweep failed")
    _assert_grid_equivalent(str(out), str(clean))
    log("kill: SIGKILL mid-write left no trusted artifact; resume "
        "re-measured to an equivalent grid")


def _class_serve(work: Path, log: Callable[[str], None]) -> None:
    """The serving fault matrix (``cli chaos --plan serve``): every
    serving fault class either recovers or fails closed with journaled
    reasons, and SIGTERM-mid-trace + ``--resume`` yields an artifact
    set equivalent (names + schema + per-request outcomes for
    non-preempted requests) to an uninterrupted run."""
    from dlbb_tpu.obs.spans import journal_to_trace, load_trace
    from dlbb_tpu.resilience import inject
    from dlbb_tpu.serve.bench import (
        RESUME_CHECKPOINT,
        resume_serving,
        run_serving,
    )
    from dlbb_tpu.serve.traffic import TrafficTrace, generate_trace

    model = dict(hidden_size=64, num_layers=2, num_heads=4,
                 num_kv_heads=4, ffn_intermediate=128, dtype="float32",
                 attention="full")

    def cfg(name: str, **serving) -> dict:
        base = {"max_batch": 8, "block_size": 8, "max_seq": 64,
                "queue_capacity": 64, "hbm_budget_gb": None}
        base.update(serving)
        return {"experiment": {"name": name}, "model": dict(model),
                "parallelism": {"data_parallel": 2, "world_size": 4},
                "serving": base}

    trace = generate_trace("poisson", 10, seed=5, rate=200.0,
                           prompt_range=(4, 12), output_range=(3, 6))

    # -- transient prefill/decode dispatch failures: retried, recovered
    out = work / "serve_transient"
    rep = run_serving(
        cfg("t"), trace, str(out), verbose=False,
        fault_plan="serve-prefill-fail:1,serve-decode-fail:1")
    _check(rep["resilience"]["retries"] >= 2,
           f"transient serve faults not retried: {rep['resilience']}")
    _check(rep["requests"]["completed"] == len(trace),
           "transient serve faults did not recover to full completion")
    _check(all(v == "completed"
               for v in rep["requests"]["outcomes"].values()),
           f"unexpected outcomes: {rep['requests']['outcomes']}")
    ev, _ = read_journal(out)
    _check(any(e["event"] == "dispatch-retry" for e in ev),
           "journal has no dispatch-retry record")
    _check(json.loads((out / "serving_t.json").read_text())["schema"]
           == "dlbb_serving_report_v1", "result artifact invalid")
    log("serve transient: prefill+decode dispatch faults retried with "
        "rollback; all requests completed")

    # -- torn ledger/slot bookkeeping: rolled back + replayed
    out = work / "serve_torn"
    rep = run_serving(cfg("c"), trace, str(out), verbose=False,
                      fault_plan="serve-cache-torn:1")
    _check(rep["requests"]["completed"] == len(trace),
           "torn bookkeeping did not recover")
    _check(rep["resilience"]["retries"] >= 1,
           "torn bookkeeping was not replayed")
    _check(rep["cache"]["blocks_reserved"] == 0,
           "ledger left dangling reservations after rollback")
    log("serve cache-torn: half-applied accounting rolled back to the "
        "pre-dispatch snapshot and replayed; ledger consistent")

    # -- torn accounting over REFCOUNTED shared blocks: the rollback
    #    snapshot covers the prefix trie and its refcounts too, so a
    #    replayed decode unit neither double-frees a shared block (a
    #    torn release re-applied) nor leaks one (a torn attach dropped)
    out = work / "serve_torn_prefix"
    ptrace = generate_trace("poisson", 10, seed=5, rate=200.0,
                            prompt_range=(17, 28), output_range=(3, 6),
                            prefix_groups=2, prefix_len=16)
    pcfg = cfg("cp", prefill_chunk=8, prefix_caching=True)
    # prefix caching is a dp=1 feature (every slot's blocks live on one
    # dp shard, so a donor copy is shard-local)
    pcfg["parallelism"] = {"data_parallel": 1, "world_size": 4}
    prep = run_serving(pcfg, ptrace, str(out), verbose=False,
                       fault_plan="serve-cache-torn:1")
    _check(prep["requests"]["completed"] == len(ptrace),
           "torn refcount bookkeeping did not recover")
    _check(prep["resilience"]["retries"] >= 1,
           "torn refcount bookkeeping was not replayed")
    _check(prep["prefix"]["hits"] >= 1,
           "prefix trace produced no shared-prefix attach")
    _check(prep["cache"]["blocks_reserved"] == 0,
           "refcounted ledger left dangling reservations after rollback")
    _check(prep["cache"]["shared_blocks"] == 0,
           "prefix trie leaked shared blocks after drain "
           f"({prep['cache']})")
    _check(prep["cache"]["prefix_refs"] == 0,
           f"prefix trie leaked refcounts after drain ({prep['cache']})")
    log("serve cache-torn (prefix): refcounts + trie rolled back with "
        "the ledger; no double-free, no leaked shared block")

    # -- permanent decode failure: affected requests fail CLOSED with
    #    chains; the run itself survives
    out = work / "serve_perm"
    rep = run_serving(cfg("p", max_dispatch_retries=0), trace, str(out),
                      verbose=False, fault_plan="serve-decode-fail:*")
    _check(rep["requests"]["failed"] > 0,
           "permanent decode failure failed no requests")
    _check(rep["resilience"]["failed"]
           and rep["resilience"]["failed"][0]["traceback"],
           "failure record lacks the exception chain")
    _check(len(rep["requests"]["outcomes"]) == len(trace),
           "some requests have no terminal outcome")
    ev, _ = read_journal(out)
    _check(any(e["event"] == "request-failed" for e in ev),
           "journal has no request-failed record")
    log("serve permanent: exhausted retries failed only the affected "
        "requests, chains journaled; run drained")

    # -- hung dispatch: the watchdog abandons it, the engine continues
    out = work / "serve_hang"
    t0 = time.perf_counter()
    rep = run_serving(
        cfg("h", dispatch_deadline_factor=50.0,
            dispatch_deadline_min_s=0.5),
        trace, str(out), verbose=False,
        fault_plan="serve-decode-hang:@1,hang_seconds=120")
    wall = time.perf_counter() - t0
    _check(wall < 60.0,
           f"serve blocked behind the hung dispatch ({wall:.1f}s vs "
           "120s hang)")
    _check(rep["resilience"]["hung_dispatches"] == 1,
           "watchdog did not abandon the hung dispatch")
    _check(any(v == "failed[hung-dispatch]"
               for v in rep["requests"]["outcomes"].values()),
           "hung unit's requests not journaled failed[hung-dispatch]")
    _check(rep["requests"]["completed"] >= 1,
           "engine did not continue past the hung dispatch")
    log(f"serve hang: watchdog abandoned at deadline, engine continued "
        f"on a fresh carry ({wall:.1f}s wall vs 120s hang)")

    # -- corrupt trace load: fails closed, publishes nothing
    path = work / "trace_corrupt.json"
    trace.save(path)
    with inject.plan_scope("serve-trace-corrupt:@1"):
        try:
            TrafficTrace.load(path)
        except ValueError as e:
            _check("corrupt or truncated" in str(e)
                   and e.__cause__ is not None,
                   f"corrupt-trace error lacks cause/chain: {e}")
        else:
            raise ChaosFailure("corrupt trace loaded without error")
    log("serve trace-corrupt: load failed closed with a chained error")

    # -- per-request deadlines: shed distinct from queue-full.  A t=0
    #    burst with a 20ms SLO is deterministic on any host speed: the
    #    first 8 requests are admitted within microseconds (wait <<
    #    SLO) and complete LATE (8 serial prefills alone exceed 20ms),
    #    while the queue heads left behind are re-examined only after
    #    those prefills and shed
    from dlbb_tpu.serve.traffic import Request

    dtrace = TrafficTrace(
        kind="poisson", seed=0, params={"deadline_s": 0.02},
        requests=tuple(
            Request(rid=i, arrival_s=0.0, prompt_len=8, output_len=4,
                    seed=100 + i, deadline_s=0.02)
            for i in range(12)
        ),
    )
    out = work / "serve_deadline"
    rep = run_serving(cfg("d"), dtrace, str(out), verbose=False)
    _check(rep["requests"]["deadline_shed"] >= 1,
           "no queued request was shed by deadline under a 20ms SLO")
    _check(rep["requests"]["completed_past_deadline"] >= 1,
           "no completion was counted past its deadline")
    _check(rep["requests"]["shed_rate"] == 0.0,
           "deadline sheds leaked into the queue-full shed rate")
    ev, _ = read_journal(out)
    _check(any(e.get("reason") == "deadline" for e in ev
               if e["event"] == "request-rejected"),
           "journal has no deadline rejection record")
    log("serve deadline: blown-SLO queue heads shed "
        "(reason=deadline, distinct from queue-full); late "
        "completions counted")

    # -- SIGTERM mid-trace -> drain + checkpoint; --resume merges to an
    #    artifact set equivalent to an uninterrupted run
    ref = work / "serve_ref"
    run_serving(cfg("x"), trace, str(ref), verbose=False)
    out = work / "serve_preempt"
    rep = run_serving(cfg("x"), trace, str(out), verbose=False,
                      fault_plan="serve-preempt:@3")
    _check(rep["preempted"], "serve-preempt did not drain gracefully")
    _check((out / RESUME_CHECKPOINT).exists(),
           "preempted session wrote no resume checkpoint")
    _check(not (out / "serving_x.json").exists(),
           "preempted session wrote a result artifact")
    preempted_rids = {rid for rid, o in rep["requests"]["outcomes"]
                      .items() if o == "preempted"}
    ev, _ = read_journal(out)
    _check(any(e["event"] == "preempted" for e in ev),
           "journal has no preempted record")
    merged = resume_serving(str(out), verbose=False)
    _check(not (out / RESUME_CHECKPOINT).exists(),
           "resume left the checkpoint behind")
    names_ref = sorted(p.name for p in ref.iterdir())
    names_out = sorted(p.name for p in out.iterdir())
    _check(names_ref == names_out,
           f"artifact sets differ: {names_out} != {names_ref}")
    a = json.loads((ref / "serving_x.json").read_text())
    b = json.loads((out / "serving_x.json").read_text())
    _check(sorted(a) == sorted(b),
           "serving report schema keys differ after resume")
    oa, ob = a["requests"]["outcomes"], b["requests"]["outcomes"]
    for rid in oa:
        if rid in preempted_rids:
            continue
        _check(oa[rid] == ob[rid],
               f"request {rid} outcome differs after resume: "
               f"{ob[rid]} != {oa[rid]}")
    _check(merged["requests"]["sessions"] == 2,
           "merged report does not record both sessions")
    # the journal alone reconstructs the preempted lifecycle
    timeline, _n, _torn = journal_to_trace(out, out / "timeline.json")
    cats = {e.get("cat") for e in load_trace(timeline)["traceEvents"]}
    _check("config-preempted" in cats,
           "journal timeline has no preempted request span")
    (out / "timeline.json").unlink()
    log("serve preempt: SIGTERM drained + checkpointed; --resume "
        "merged to an equivalent artifact set (outcomes pinned for "
        "non-preempted requests)")


def _class_fleet(work: Path, log: Callable[[str], None]) -> None:
    """Replica-level fault tolerance (``cli chaos --plan fleet``): a
    2-replica fleet on the simulated mesh survives a replica SIGKILL
    mid-trace (residents failed over, every surviving request's tokens
    identical to an unfaulted single-replica run, zero leaked ledger
    blocks), a torn failover rolls back and retries without
    double-routing, a hung replica is fenced by the heartbeat watchdog
    long before the hang expires, and a straggler is hedged — first
    completion wins, the loser is canceled without corrupting the
    ledger."""
    import jax

    from dlbb_tpu.obs.spans import journal_to_trace, load_trace
    from dlbb_tpu.serve.bench import run_serving
    from dlbb_tpu.serve.fleet import run_fleet
    from dlbb_tpu.serve.traffic import Request, TrafficTrace, generate_trace

    model = dict(hidden_size=64, num_layers=2, num_heads=4,
                 num_kv_heads=4, ffn_intermediate=128, dtype="float32",
                 attention="full")

    def cfg(name: str, fleet: Optional[dict] = None, **serving) -> dict:
        base = {"max_batch": 8, "block_size": 8, "max_seq": 64,
                "queue_capacity": 64, "hbm_budget_gb": None}
        base.update(serving)
        # per-replica parallelism: 2 replicas x (dp=2 x tp=2) on the
        # 8-device simulated mesh
        return {"experiment": {"name": name}, "model": dict(model),
                "parallelism": {"data_parallel": 2, "world_size": 2},
                "serving": base, "fleet": {"replicas": 2, **(fleet or {})}}

    def ref_cfg(name: str, **serving) -> dict:
        c = cfg(name, **serving)
        del c["fleet"]
        return c

    def _tokens_match(rep: dict, ref: dict, what: str) -> None:
        """Greedy tokens depend only on (params seed, request), so a
        fleet run on device subsets must reproduce the single-replica
        reference exactly — including for failed-over / hedged rids."""
        got, want = rep["completed_tokens"], ref["completed_tokens"]
        _check(sorted(got) == sorted(want),
               f"{what}: completed-token rid sets differ")
        for rid in want:
            _check(got[rid] == want[rid],
                   f"{what}: request {rid} tokens diverged after fleet "
                   f"recovery: {got[rid]} != {want[rid]}")

    def _no_leak(replica: dict, what: str) -> None:
        cache = replica["report"]["cache"]
        _check(cache["blocks_reserved"] == 0,
               f"{what}: replica {replica['replica']} leaked ledger "
               f"blocks after drain ({cache})")
        _check(cache.get("shared_blocks", 0) == 0,
               f"{what}: replica {replica['replica']} leaked shared "
               f"blocks ({cache})")
        _check(cache.get("prefix_refs", 0) == 0,
               f"{what}: replica {replica['replica']} leaked prefix "
               f"refcounts ({cache})")

    ktrace = generate_trace("poisson", 16, seed=5, rate=60.0,
                            prompt_range=(4, 12), output_range=(4, 8))
    ref = run_serving(ref_cfg("flr"), ktrace, verbose=False,
                      devices=jax.devices()[:4], journal=False,
                      capture_tokens=True)

    # -- replica SIGKILL mid-trace: fence + failover re-prefill; every
    #    request completes with tokens identical to the unfaulted
    #    single-replica reference; the survivor's ledger drains to zero
    out = work / "fleet_kill"
    rep = run_fleet(cfg("fk"), ktrace, str(out), verbose=False,
                    fault_plan="serve-replica-kill:@8")
    dead = [r for r in rep["replicas"]
            if r["fence_reason"] == "replica-killed"]
    _check(len(dead) == 1, f"expected one killed replica, got "
           f"{[r['fence_reason'] for r in rep['replicas']]}")
    _check(all(v == "completed"
               for v in rep["requests"]["outcomes"].values()),
           f"kill: not all requests recovered: "
           f"{rep['requests']['outcomes']}")
    _check(rep["failovers"]["total"] >= 1,
           "kill fired but no resident was failed over")
    _check(rep["failovers"]["by_reason"]["replica-killed"]
           == rep["failovers"]["total"],
           f"failover reasons inconsistent: {rep['failovers']}")
    _tokens_match(rep, ref, "kill")
    survivor = [r for r in rep["replicas"] if r["status"] == "ok"]
    _check(len(survivor) == 1, "kill: no surviving replica")
    _no_leak(survivor[0], "kill")
    _check(rep["failover_ttft_penalty_s"] is not None,
           "failover TTFT penalty not measured")
    ev, torn = read_journal(out)
    _check(torn == 0, f"kill: journal has {torn} torn lines")
    fo = [e for e in ev if e["event"] == "request-failover"]
    _check(len(fo) == rep["failovers"]["total"],
           "failover count diverges from the journal")
    _check(all(e.get("reason") == "replica-killed" and e.get("error")
               for e in fo),
           "request-failover records lack reason + exception chain")
    _check(any(e["event"] == "replica-fenced"
               and e.get("reason") == "replica-killed" for e in ev),
           "journal has no replica-fenced record")
    # the journal alone reconstructs the fleet lifecycle, one Perfetto
    # track group per replica
    timeline, _n, _t = journal_to_trace(out, out / "timeline.json")
    tl = load_trace(timeline)
    names = {e["args"]["name"] for e in tl["traceEvents"]
             if e.get("name") == "process_name"}
    _check({"fleet", "replica-0", "replica-1"} <= names,
           f"timeline lacks per-replica track groups: {names}")
    _check(any(e.get("cat") == "fleet" for e in tl["traceEvents"]),
           "timeline has no fleet lifecycle instants")
    (out / "timeline.json").unlink()
    log(f"fleet kill: replica fenced mid-trace, "
        f"{rep['failovers']['total']} residents failed over and "
        f"completed with reference-identical tokens; survivor ledger "
        f"drained (TTFT penalty "
        f"{rep['failover_ttft_penalty_s'] * 1e3:.1f}ms)")

    # -- torn failover: the routing mutation rolls back to its snapshot
    #    and retries; no request is double-routed or lost
    out = work / "fleet_torn"
    rep = run_fleet(cfg("ft"), ktrace, str(out), verbose=False,
                    fault_plan="serve-replica-kill:@8,"
                               "serve-failover-torn:1")
    _check(rep["failovers"]["total"] >= 1,
           "torn: kill fired but no resident was failed over")
    fo_rids = [r["rid"] for r in rep["failovers"]["requests"]]
    _check(len(fo_rids) == len(set(fo_rids)),
           f"torn failover double-routed a request: {fo_rids}")
    _check(all(v == "completed"
               for v in rep["requests"]["outcomes"].values()),
           f"torn: not all requests recovered: "
           f"{rep['requests']['outcomes']}")
    _tokens_match(rep, ref, "torn")
    ev, _ = read_journal(out)
    _check(any(e["event"] == "failover-torn" for e in ev),
           "journal has no failover-torn rollback record")
    log("fleet torn: torn routing table rolled back + retried; "
        "no double-routed request, tokens pinned")

    # -- replica hang: the heartbeat watchdog (the dispatch-EMA
    #    watchdog generalized to replica granularity) fences the
    #    replica long before the 120s hang expires
    out = work / "fleet_hang"
    t0 = time.perf_counter()
    rep = run_fleet(
        cfg("fh", fleet={"heartbeat_min_s": 1.0,
                         "heartbeat_factor": 4.0}),
        ktrace, str(out), verbose=False,
        fault_plan="serve-replica-hang:@8,hang_seconds=120")
    wall = time.perf_counter() - t0
    _check(wall < 60.0,
           f"fleet blocked behind the hung replica ({wall:.1f}s vs "
           "120s hang)")
    _check(any(r["fence_reason"] == "replica-hung"
               for r in rep["replicas"]),
           f"hung replica not fenced: "
           f"{[r['fence_reason'] for r in rep['replicas']]}")
    _check(all(v == "completed"
               for v in rep["requests"]["outcomes"].values()),
           f"hang: not all requests recovered: "
           f"{rep['requests']['outcomes']}")
    _tokens_match(rep, ref, "hang")
    ev, _ = read_journal(out)
    _check(any(e["event"] == "replica-fenced"
               and e.get("reason") == "replica-hung" and e.get("error")
               for e in ev),
           "replica-hung fence lacks a journaled heartbeat chain")
    log(f"fleet hang: heartbeat fenced the silent replica, residents "
        f"failed over ({wall:.1f}s wall vs 120s hang)")

    # -- hedge-cancel race: a burst pins residents on a replica that
    #    then hangs briefly; past p99 x hedge_factor the supervisor
    #    duplicates them onto the survivor, first completion wins, and
    #    the losing copy is canceled without corrupting either ledger
    btrace = TrafficTrace(
        kind="poisson", seed=0, params={},
        requests=tuple(
            Request(rid=i, arrival_s=0.0, prompt_len=8, output_len=6,
                    seed=300 + i)
            for i in range(16)
        ),
    )
    bref = run_serving(ref_cfg("fbr"), btrace, verbose=False,
                       devices=jax.devices()[:4], journal=False,
                       capture_tokens=True)
    out = work / "fleet_hedge"
    rep = run_fleet(
        cfg("fg", fleet={"heartbeat_min_s": 30.0,
                         "hedge_min_completions": 4},
            hedge_factor=1.25),
        btrace, str(out), verbose=False,
        fault_plan="serve-replica-hang:@6,hang_seconds=4.0")
    _check(rep["hedges"]["issued"] >= 1,
           f"straggling residents were never hedged: {rep['hedges']}")
    _check(rep["hedges"]["won"] >= 1,
           f"no hedge duplicate won the race: {rep['hedges']}")
    _check(rep["hedges"]["won"] + rep["hedges"]["lost"]
           <= rep["hedges"]["issued"],
           f"hedge accounting inconsistent: {rep['hedges']}")
    _check(all(v == "completed"
               for v in rep["requests"]["outcomes"].values()),
           f"hedge: not all requests completed: "
           f"{rep['requests']['outcomes']}")
    _tokens_match(rep, bref, "hedge")
    # the brief hang recovered — neither replica fenced, both ledgers
    # drained (the canceled losing copies released their blocks)
    _check(all(r["status"] == "ok" for r in rep["replicas"]),
           f"hedge: replica fenced unexpectedly: "
           f"{[(r['status'], r['fence_reason']) for r in rep['replicas']]}")
    for r in rep["replicas"]:
        _no_leak(r, "hedge")
    ev, _ = read_journal(out)
    _check(any(e["event"] == "request-hedged" for e in ev),
           "journal has no request-hedged record")
    _check(any(e["event"] == "request-canceled"
               and e.get("reason") == "hedge-lost" for e in ev),
           "losing hedge copy was never canceled")
    log(f"fleet hedge: {rep['hedges']['issued']} hedges issued, "
        f"{rep['hedges']['won']} won; losers canceled, both ledgers "
        "drained, tokens pinned")

    # -- prefix affinity under fire: a shared-prefix trace routes
    #    sticky, the kill re-homes the dead replica's prefix group, and
    #    the survivor's trie refcounts still drain to zero
    ptrace = generate_trace("poisson", 10, seed=5, rate=200.0,
                            prompt_range=(17, 28), output_range=(3, 6),
                            prefix_groups=2, prefix_len=16)
    pcfg = cfg("fp", prefill_chunk=8, prefix_caching=True)
    # prefix caching is a dp=1 feature; 2 replicas x (dp=1 x tp=4)
    pcfg["parallelism"] = {"data_parallel": 1, "world_size": 4}
    out = work / "fleet_prefix"
    rep = run_fleet(pcfg, ptrace, str(out), verbose=False,
                    fault_plan="serve-replica-kill:@10")
    _check(rep["routing"]["prefix_affinity_hits"] >= 1,
           "prefix trace produced no affinity-routed request")
    _check(all(v == "completed"
               for v in rep["requests"]["outcomes"].values()),
           f"prefix: not all requests recovered: "
           f"{rep['requests']['outcomes']}")
    survivor = [r for r in rep["replicas"] if r["status"] == "ok"]
    _check(len(survivor) == 1, "prefix: no surviving replica")
    _no_leak(survivor[0], "prefix kill")
    log(f"fleet prefix: affinity routing held "
        f"({rep['routing']['prefix_affinity_hits']} hits), killed "
        "replica's prefix group re-homed, survivor trie drained")


CHAOS_CLASSES: dict[str, Callable[[Path, Callable[[str], None]], None]] = {
    "compile": _class_compile,
    "transient": _class_transient,
    "nan": _class_nan,
    "torn": _class_torn,
    "hang": _class_hang,
    "ckpt": _class_ckpt,
    "preempt": _class_preempt,
    "kill": _class_kill,
    "serve": _class_serve,
    "fleet": _class_fleet,
}


def run_chaos(plan: str = "all", output: Optional[str] = None,
              verbose: bool = True) -> int:
    """Run the chaos gate; returns a process exit code (0 = every
    invariant held)."""
    import tempfile

    def log(msg: str) -> None:
        if verbose:
            print(f"[chaos] {msg}")

    names = list(CHAOS_CLASSES) if plan == "all" else [plan]
    unknown = [n for n in names if n not in CHAOS_CLASSES]
    if unknown:
        print(f"[chaos] unknown class(es) {unknown}; "
              f"known: {list(CHAOS_CLASSES)} + 'all'")
        return 2
    workroot = Path(output) if output else Path(tempfile.mkdtemp(
        prefix="dlbb_chaos_"))
    workroot.mkdir(parents=True, exist_ok=True)
    failures = []
    for name in names:
        t0 = time.perf_counter()
        try:
            CHAOS_CLASSES[name](workroot, log)
        except ChaosFailure as e:
            failures.append((name, str(e)))
            print(f"[chaos] FAIL {name}: {e}")
        except Exception as e:  # noqa: BLE001 — gate must report, not die
            failures.append((name, f"{type(e).__name__}: {e}"))
            print(f"[chaos] ERROR {name}: {type(e).__name__}: {e}")
        else:
            log(f"{name} ok ({time.perf_counter() - t0:.1f}s)")
    if failures:
        print(f"[chaos] {len(failures)}/{len(names)} class(es) FAILED "
              f"(workdir kept: {workroot})")
        return 1
    print(f"[chaos] all {len(names)} fault class(es) green "
          f"(workdir: {workroot})")
    return 0
