"""Append-only crash-safe sweep journal (``sweep_journal.jsonl``).

One JSON line per lifecycle event of each sweep config — ``planned``,
``started``, ``completed``, ``failed``, ``resume-valid``,
``resume-invalid``, ``skipped``, ``preempted`` — fsync'd per line, so a
process killed at ANY instant leaves at most one torn trailing line
(tolerated by :func:`read_journal`).  Together with atomic artifact
writes (``utils/config.save_json``) this lets resume distinguish
"completed" from "died mid-write": an artifact is trusted only if it
exists, parses, and carries finite stats
(``dlbb_tpu.resilience.validate``); the journal is the audit trail the
chaos gate (and an operator) reads to see exactly what a crashed sweep
did and what a resumed one re-ran.

The journal is append-only across runs: a resumed sweep appends a new
``sweep-start`` session marker and its own events after the crashed
session's, preserving the full history of the grid.

A pluggable ``sink`` (``sink(event, record)``) mirrors every journal
event into another observer — the sweep driver passes
``dlbb_tpu.obs.spans.journal_sink`` so each journal line doubles as a
span-trace instant and a crashed sweep's timeline is reconstructable
from either artifact (``docs/observability.md``).  The sink fires even
when file journaling is disabled (non-coordinator hosts on a pod), and
sink exceptions are swallowed: observability must never kill a sweep.
"""

from __future__ import annotations

import json
import os
import time
from pathlib import Path
from typing import Any, Callable, Optional

JOURNAL_NAME = "sweep_journal.jsonl"
JOURNAL_SCHEMA = "dlbb_sweep_journal_v1"


class SweepJournal:
    """Append-only journal writer for one sweep session.

    Every :meth:`event` is one line: ``json.dumps`` + newline, flushed and
    fsync'd before returning — after a crash, every event the sweep
    *reported* is durably on disk.  Events never raise into the sweep
    (a full disk must not kill a measurement that already succeeded);
    write failures flip :attr:`degraded` and are reported once.
    """

    def __init__(self, out_dir: "str | Path", meta: Optional[dict] = None,
                 enabled: bool = True,
                 sink: Optional[Callable[[str, dict], None]] = None) -> None:
        self.path = Path(out_dir) / JOURNAL_NAME
        self.enabled = enabled
        self.degraded = False
        self._fh = None
        self._sink = sink
        if not enabled:
            return
        self.path.parent.mkdir(parents=True, exist_ok=True)
        try:
            # a crash mid-append leaves a torn tail WITHOUT a newline —
            # terminate it first so this session's events stay
            # line-delimited (the torn fragment stays visible to
            # read_journal as exactly one unparseable line)
            if self.path.exists():
                with open(self.path, "rb") as f:
                    f.seek(0, os.SEEK_END)
                    if f.tell() > 0:
                        f.seek(-1, os.SEEK_END)
                        needs_newline = f.read(1) != b"\n"
                    else:
                        needs_newline = False
            else:
                needs_newline = False
            self._fh = open(self.path, "a")
            if needs_newline:
                self._fh.write("\n")
        except OSError:
            self.degraded = True
            self._fh = None
            return
        self.event("sweep-start",
                   schema=JOURNAL_SCHEMA, pid=os.getpid(), **(meta or {}))

    def event(self, event: str, config: Optional[str] = None,
              **extra: Any) -> None:
        if self._fh is None and self._sink is None:
            return
        record = {"ts": time.time(), "event": event}
        if config is not None:
            record["config"] = config
        record.update(extra)
        if self._sink is not None:
            # the sink observes every event, file journaling enabled or
            # not (a non-coordinator pod host still traces locally); it
            # must never raise into the sweep
            try:
                self._sink(event, record)
            except Exception:  # noqa: BLE001 — observer isolation
                pass
        if self._fh is None:
            return
        try:
            self._fh.write(json.dumps(record, default=str) + "\n")
            self._fh.flush()
            os.fsync(self._fh.fileno())
        except (OSError, ValueError):
            if not self.degraded:
                self.degraded = True
                print(f"[journal] WARNING: cannot append to {self.path}; "
                      "journaling disabled for this session")
            self.close()

    def close(self) -> None:
        if self._fh is not None:
            try:
                self._fh.close()
            except OSError:
                pass
            self._fh = None

    def __enter__(self) -> "SweepJournal":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def read_journal(out_dir: "str | Path") -> tuple[list[dict], int]:
    """Parse ``sweep_journal.jsonl`` under ``out_dir``.

    Returns ``(events, torn_lines)`` — a line that does not parse (the
    torn tail of a killed process) is counted, not fatal; a torn line
    anywhere else is counted the same way (it can only mean a crashed
    writer, and every parseable event remains trustworthy because each
    was fsync'd before the next was attempted)."""
    return read_journal_file(Path(out_dir) / JOURNAL_NAME)


def read_journal_file(path: "str | Path") -> tuple[list[dict], int]:
    """Parse one journal JSONL file (torn-line semantics of
    :func:`read_journal`; a missing/unreadable file is an empty
    journal, not an error — obs reads non-canonical ``*journal*.jsonl``
    names through this too)."""
    events: list[dict] = []
    torn = 0
    try:
        with open(path) as f:
            lines = list(f)
    except OSError:
        return events, torn
    for line in lines:
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            torn += 1
            continue
        if isinstance(rec, dict):
            events.append(rec)
        else:
            torn += 1
    return events, torn


def completed_configs(events: list[dict]) -> set[str]:
    """Config ids with a durable ``completed`` record."""
    return {e["config"] for e in events
            if e.get("event") == "completed" and "config" in e}


def started_not_completed(events: list[dict]) -> set[str]:
    """Config ids that started but never completed/failed — the set a
    crash interrupted (resume must re-validate, never trust)."""
    done = {e["config"] for e in events
            if e.get("event") in ("completed", "failed") and "config" in e}
    return {e["config"] for e in events
            if e.get("event") == "started" and "config" in e} - done
