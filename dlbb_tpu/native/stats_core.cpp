// Native statistics core for the metrics / stats pipelines.
//
// The reference keeps all statistics in Python/numpy
// (collectives/1d/stats.py:26-129, utils.py:43-66); its native code lives
// entirely in external comm libraries (SURVEY §2.4).  This framework's
// runtime-side native component accelerates the one hot CPU loop the
// harness owns — aggregating per-rank x per-iteration timing arrays into
// summary statistics when sweeps produce thousands of result files.
//
// Semantics match numpy exactly where exactness is testable:
//  - percentile: numpy's default "linear" interpolation on sorted data
//  - std: population (ddof=0), like numpy's default
// Exposed with a C ABI for ctypes (no pybind11 in this image).

#include <algorithm>
#include <cmath>
#include <vector>

namespace {

double percentile_sorted(const std::vector<double>& s, double q) {
    const long n = static_cast<long>(s.size());
    if (n == 1) return s[0];
    const double pos = q / 100.0 * static_cast<double>(n - 1);
    const long lo = static_cast<long>(pos);
    const long hi = std::min(lo + 1, n - 1);
    const double frac = pos - static_cast<double>(lo);
    return s[lo] + (s[hi] - s[lo]) * frac;
}

}  // namespace

extern "C" {

// out[9] = mean, std, min, max, median, p95, p99, p999, count
// v2 of dlbb_summarize: adds the p99.9 tail (serving-path metrics key on
// it).  This is THE summary implementation; v1 below wraps it so the two
// ABI entry points can never drift numerically.
int dlbb_summarize2(const double* xs, long n, double* out) {
    if (xs == nullptr || out == nullptr || n <= 0) return -1;
    double sum = 0.0;
    for (long i = 0; i < n; ++i) sum += xs[i];
    const double mean = sum / static_cast<double>(n);
    double ss = 0.0;
    for (long i = 0; i < n; ++i) {
        const double d = xs[i] - mean;
        ss += d * d;
    }
    std::vector<double> s(xs, xs + n);
    std::sort(s.begin(), s.end());
    out[0] = mean;
    out[1] = std::sqrt(ss / static_cast<double>(n));
    out[2] = s.front();
    out[3] = s.back();
    out[4] = percentile_sorted(s, 50.0);
    out[5] = percentile_sorted(s, 95.0);
    out[6] = percentile_sorted(s, 99.0);
    out[7] = percentile_sorted(s, 99.9);
    out[8] = static_cast<double>(n);
    return 0;
}

// out[8] = mean, std, min, max, median, p95, p99, count
// Legacy ABI (pre-p999 consumers); thin shim over the v2 core.
int dlbb_summarize(const double* xs, long n, double* out) {
    if (out == nullptr) return -1;
    double tmp[9];
    const int rc = dlbb_summarize2(xs, n, tmp);
    if (rc != 0) return rc;
    for (int i = 0; i < 7; ++i) out[i] = tmp[i];
    out[7] = tmp[8];  // count (v1 has no p999 slot)
    return 0;
}

// Load imbalance % over per-rank mean timings:
// (max(rank_means) - mean(rank_means)) / mean(rank_means) * 100
// (reference formula, collectives/1d/stats.py:54-61).
double dlbb_load_imbalance(const double* rank_means, long n) {
    if (rank_means == nullptr || n <= 0) return 0.0;
    double sum = 0.0, maxv = rank_means[0];
    for (long i = 0; i < n; ++i) {
        sum += rank_means[i];
        if (rank_means[i] > maxv) maxv = rank_means[i];
    }
    const double mean = sum / static_cast<double>(n);
    if (mean <= 0.0) return 0.0;
    return (maxv - mean) / mean * 100.0;
}

// Row-mean reduction for [ranks][iters] timing matrices (the stats
// pipeline's inner loop over thousands of result files).
int dlbb_row_means(const double* xs, long rows, long cols, double* out) {
    if (xs == nullptr || out == nullptr || rows <= 0 || cols <= 0) return -1;
    for (long r = 0; r < rows; ++r) {
        double sum = 0.0;
        const double* row = xs + r * cols;
        for (long c = 0; c < cols; ++c) sum += row[c];
        out[r] = sum / static_cast<double>(cols);
    }
    return 0;
}

}  // extern "C"
