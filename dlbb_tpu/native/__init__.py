"""ctypes bindings for the native statistics core.

The reference has no in-repo native code (SURVEY §2.4 — its native layer is
the external MPI/oneCCL/Gloo libraries); this framework's runtime-side
native component is ``stats_core.cpp``, compiled on first use with the
in-image g++ (no pybind11 in this image, hence the C ABI + ctypes).

Graceful degradation by design: if the toolchain or the build is
unavailable the callers fall back to numpy, and ``DLBB_NATIVE=0`` disables
the native path outright.  Numerics are asserted equal to numpy in
``tests/test_native.py``.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import uuid
from pathlib import Path
from typing import Any, Optional

import numpy as np

_DIR = Path(__file__).resolve().parent
_SO = _DIR / "libdlbb_stats.so"

_lib: Any = None
_tried = False

SUMMARY_FIELDS = ("mean", "std", "min", "max", "median", "p95", "p99",
                  "p999", "count")


def _build() -> bool:
    """Compile to a globally-unique temp file, then atomically rename into
    place: concurrent builders (parallel pytest, multi-host launch on a
    shared FS) each produce a complete .so and the rename is last-writer-
    wins — no process can ever dlopen a torn file.  The build recipe lives
    only in the Makefile (``OUT=`` selects the temp output name)."""
    tmp = _DIR / f".libdlbb_stats.{uuid.uuid4().hex}.so"
    try:
        proc = subprocess.run(
            ["make", "-s", "-C", str(_DIR), f"OUT={tmp.name}"],
            capture_output=True, text=True, timeout=120,
        )
        if proc.returncode != 0 or not tmp.exists():
            return False
        os.replace(tmp, _SO)  # atomic on the same filesystem
        return True
    except (OSError, subprocess.SubprocessError):
        return False
    finally:
        tmp.unlink(missing_ok=True)


def _load() -> Any:
    """Load (building if needed) the shared library; None when
    unavailable."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    if os.environ.get("DLBB_NATIVE", "1") == "0":
        return None
    if not _SO.exists() and not _build():
        return None
    try:
        lib = ctypes.CDLL(str(_SO))
    except OSError:
        return None
    dbl_p = ctypes.POINTER(ctypes.c_double)
    lib.dlbb_summarize.argtypes = [dbl_p, ctypes.c_long, dbl_p]
    lib.dlbb_summarize.restype = ctypes.c_int
    # v2 adds p999; a stale pre-v2 .so (built from an older checkout)
    # lacks the symbol — summarize_native then computes p999 in numpy on
    # top of the v1 result instead of failing the whole native path
    try:
        lib.dlbb_summarize2.argtypes = [dbl_p, ctypes.c_long, dbl_p]
        lib.dlbb_summarize2.restype = ctypes.c_int
        lib._dlbb_has_v2 = True
    except AttributeError:
        lib._dlbb_has_v2 = False
    lib.dlbb_load_imbalance.argtypes = [dbl_p, ctypes.c_long]
    lib.dlbb_load_imbalance.restype = ctypes.c_double
    lib.dlbb_row_means.argtypes = [dbl_p, ctypes.c_long, ctypes.c_long,
                                   dbl_p]
    lib.dlbb_row_means.restype = ctypes.c_int
    _lib = lib
    return _lib


def native_available() -> bool:
    return _load() is not None


def _as_c_array(values) -> tuple[Any, np.ndarray]:
    arr = np.ascontiguousarray(np.asarray(values, dtype=np.float64))
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_double)), arr


def summarize_native(values) -> Optional[dict[str, float]]:
    """Summary statistics with the metric names of
    ``utils/metrics.summarize``; None when the native core is
    unavailable or the input is empty."""
    lib = _load()
    if lib is None:
        return None
    ptr, arr = _as_c_array(values)
    if arr.size == 0:
        return None
    if lib._dlbb_has_v2:
        out = np.empty(9, dtype=np.float64)
        rc = lib.dlbb_summarize2(
            ptr, arr.size,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_double))
        )
        if rc != 0:
            return None
        result = dict(zip(SUMMARY_FIELDS, (float(v) for v in out)))
    else:
        out = np.empty(8, dtype=np.float64)
        rc = lib.dlbb_summarize(
            ptr, arr.size,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_double))
        )
        if rc != 0:
            return None
        v1_fields = tuple(f for f in SUMMARY_FIELDS if f != "p999")
        result = dict(zip(v1_fields, (float(v) for v in out)))
        result["p999"] = float(np.percentile(arr, 99.9))
    result["count"] = int(result["count"])
    return result


def load_imbalance_native(rank_means) -> Optional[float]:
    """Reference load-imbalance %% (``collectives/1d/stats.py:54-61``);
    None when the native core is unavailable."""
    lib = _load()
    if lib is None:
        return None
    ptr, arr = _as_c_array(rank_means)
    if arr.size == 0:
        return 0.0
    return float(lib.dlbb_load_imbalance(ptr, arr.size))


def row_means_native(matrix) -> Optional[np.ndarray]:
    """Per-rank means of a [ranks][iters] timing matrix; None when the
    native core is unavailable."""
    lib = _load()
    if lib is None:
        return None
    arr = np.ascontiguousarray(np.asarray(matrix, dtype=np.float64))
    if arr.ndim != 2 or arr.size == 0:
        return None
    out = np.empty(arr.shape[0], dtype=np.float64)
    rc = lib.dlbb_row_means(
        arr.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        arr.shape[0], arr.shape[1],
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
    )
    return out if rc == 0 else None
