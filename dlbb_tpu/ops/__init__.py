"""Pallas TPU kernels for the hot ops.

The compute path of the framework is XLA (which fuses elementwise chains
into the matmuls on its own); these kernels cover the ops where explicit
VMEM blocking beats XLA's default lowering — above all attention, whose
materialised ``[S, S]`` score matrix is the canonical HBM-bandwidth trap.
"""

from dlbb_tpu.ops.flash_attention import flash_attention

__all__ = ["flash_attention"]
