"""Pallas TPU flash attention (causal or full), online-softmax, O(S) memory.

Replaces the dense path (``models/attention.py``) for long sequences: dense
attention materialises the ``[B, N, S, S]`` score matrix in HBM — at
S=8192 that is 4 GiB per head-batch in fp32 — while this kernel streams
K/V blocks through VMEM and keeps only the ``[block_q, head_dim]``
accumulator plus running max/sum on chip (the online-softmax recurrence).

Design notes (standard blocked-attention scheme: Dao et al., FlashAttention-2):

- grid ``(B*N, S/block_q, S/block_k)`` — the K dimension is innermost, so
  the VMEM scratch accumulator persists across K iterations of one Q row;
- QK^T and PV ride the MXU via ``dot_general`` with
  ``preferred_element_type=float32``; probabilities are cast back to the
  value dtype for the PV matmul (bf16 MXU passes);
- causal masking uses a 2-D ``broadcasted_iota`` of *global* positions
  with the diagonal anchored at the END of the key axis (``offset =
  sk - s``), so kv-cache decode (``sk > s``) masks correctly; fully-masked
  K blocks are skipped with ``pl.when`` — for causal attention this halves
  the FLOPs;
- the log-sum-exp per query row is emitted as a second output (needed by
  the custom-VJP backward, and useful for numerics debugging);
- block sizes auto-fit to the sequence length (largest divisor ≤ the
  requested block, preferring lane-aligned multiples of 128);
- off-TPU (the CPU-simulated test mesh) the kernel runs in interpret mode.

Reference parity note: the reference has no attention kernel at all — its
benchmark model skips attention entirely (``models.py:162-167``).  This is
capability the TPU framework adds for the long-context configs
(SURVEY §5.7).

Measured on a v5e chip (B=4, N=16, D=128, bf16, causal, chained
device-honest timing): 0.52 / 1.51 / 10.8 ms at S=2048/4096/8192 with
1024x1024 blocks — 102-182 causal-TFLOP/s vs the dense path's ~16, an
8-11x speedup; the dense path OOMs outright at S=8192 (16 GiB score
tensor).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Best of the measured {256,512,1024}^2 sweep at S in 2048..8192, D=128:
# ~10 MB VMEM working set, comfortably under the 16 MB budget.
DEFAULT_BLOCK_Q = 1024
DEFAULT_BLOCK_K = 1024
# Finite stand-in for -inf: exp(NEG_INF - m) underflows to 0 without
# generating nans in the m_prev - m_new subtraction on fully-masked rows.
NEG_INF = -1e30

_LANES = 128  # TPU vector lane count — row-stat arrays carry this axis


def _fit_block(n: int, requested: int) -> int:
    """Largest divisor of ``n`` that is <= ``requested``, preferring
    lane-aligned (multiple-of-128) divisors."""
    cap = min(requested, n)
    divisors = [d for d in range(1, cap + 1) if n % d == 0]
    aligned = [d for d in divisors if d % _LANES == 0]
    return max(aligned) if aligned else max(divisors)


def _masked_scores(q, k, qi, ki, *, sm_scale, block_q, block_k, causal,
                   offset):
    """fp32 ``[block_q, block_k]`` scores for Q block ``qi`` x K block
    ``ki``, causal-masked on global positions (query row r attends to key
    columns c with ``c <= r + offset``; ``offset = sk - s`` anchors the
    diagonal at the end of the key axis).  Shared by the forward and both
    backward kernels so the mask convention cannot diverge."""
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * sm_scale
    if causal:
        rows = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        )
        cols = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        s = jnp.where(rows + offset >= cols, s, NEG_INF)
    return s


def _block_visible(qi, ki, *, block_q, block_k, causal, offset):
    """Whether K block ``ki`` intersects the visible region of Q block
    ``qi`` (max global row + offset >= min global col)."""
    if not causal:
        return qi >= 0  # always true, as a traced bool
    return (qi + 1) * block_q - 1 + offset >= ki * block_k


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref,
                *, sm_scale: float, block_q: int, block_k: int, causal: bool,
                offset: int):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    @pl.when(_block_visible(qi, ki, block_q=block_q, block_k=block_k,
                            causal=causal, offset=offset))
    def _compute():
        v = v_ref[0]
        s = _masked_scores(q_ref[0], k_ref[0], qi, ki, sm_scale=sm_scale,
                           block_q=block_q, block_k=block_k, causal=causal,
                           offset=offset)
        m_prev = m_ref[:, :1]                                   # [bq, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)                         # [bq, 1]
        p = jnp.exp(s - m_new)                                  # [bq, bk]
        l_ref[:, :1] = l_ref[:, :1] * alpha + jnp.sum(p, axis=1,
                                                      keepdims=True)
        m_ref[:, :1] = m_new
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(ki == nk - 1)
    def _finalize():
        l = l_ref[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)  # fully-masked rows -> zeros
        o_ref[0] = (acc_ref[...] / l_safe).astype(o_ref.dtype)
        lse_ref[0] = jnp.broadcast_to(
            m_ref[:, :1] + jnp.log(l_safe), lse_ref.shape[1:]
        )


def _fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret):
    """q: [BN, S, D]; k, v: [BKV, Sk, D] with BN % BKV == 0 (grouped-query
    attention folds kv_heads into BKV; the group size ``g = BN // BKV``
    makes ``g`` consecutive Q rows of the grid share one K/V row via the
    ``b // g`` index map — K/V stay at kv_heads width in HBM and VMEM).
    Returns (o [BN, S, D], lse [BN, S, LANES] fp32).

    The row-stat (lse) output carries a broadcast 128-lane axis: TPU vector
    memory is (sublane, lane)-tiled, so a dense [BN, S] layout would be
    written through a transposed 1-lane path; the lane-replicated form keeps
    the store vectorised.  It is transient for inference (freed after the
    pallas_call) and live only across the backward for training.
    """
    bn, s, d = q.shape
    bkv, sk, _ = k.shape
    g = bn // bkv
    block_q = _fit_block(s, block_q)
    block_k = _fit_block(sk, block_k)
    offset = sk - s
    grid = (bn, s // block_q, sk // block_k)
    kernel = functools.partial(
        _fwd_kernel, sm_scale=sm_scale, block_q=block_q, block_k=block_k,
        causal=causal, offset=offset,
    )
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b // g, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b // g, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, _LANES), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bn, s, d), q.dtype),
            jax.ShapeDtypeStruct((bn, s, _LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)


def _p_from_lse(s, lse_row):
    """Recompute probabilities ``exp(s - lse)`` for the backward kernels.

    Fully-masked query rows (causal with ``offset < 0``, i.e. ``sk < s``)
    carry ``lse = NEG_INF``; there ``s - lse = NEG_INF - NEG_INF = 0`` would
    yield p = 1 across the whole block and inject garbage into dq/dk/dv.
    Such rows produced o = 0 in the forward, so their true gradient
    contribution is 0 — force p to 0.
    """
    p = jnp.exp(s - lse_row)
    return jnp.where(lse_row <= NEG_INF / 2, 0.0, p)


# ---------------------------------------------------------------------------
# backward — recompute p blockwise from (q, k, lse); two passes:
#   dq kernel:  grid over Q blocks (outer), K blocks inner — accumulates dq;
#   dkv kernel: grid over K blocks (outer), Q blocks inner — accumulates
#               dk, dv for one K block across all visible Q blocks.
# delta = rowsum(do * o) is precomputed outside (one fused XLA reduction).
# ---------------------------------------------------------------------------


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               acc_ref, *, sm_scale, block_q, block_k, causal, offset):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    @pl.when(_block_visible(qi, ki, block_q=block_q, block_k=block_k,
                            causal=causal, offset=offset))
    def _compute():
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        s = _masked_scores(q_ref[0], k, qi, ki, sm_scale=sm_scale,
                           block_q=block_q, block_k=block_k, causal=causal,
                           offset=offset)
        p = _p_from_lse(s, lse_ref[0][:, :1])                   # [bq, bk]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                                       # [bq, bk]
        ds = p * (dp - delta_ref[0][:, :1]) * sm_scale
        acc_ref[...] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(ki == nk - 1)
    def _finalize():
        dq_ref[0] = acc_ref[...].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_acc, dv_acc,
                *, sm_scale, block_q, block_k, causal, offset, q_blocks):
    """Accumulates dk, dv for one K/V block.  The inner grid dim flattens
    (query-head group, Q block) — ``q_blocks`` Q blocks per group — so
    under grouped-query attention one K/V block accumulates gradient from
    every query head that shares it."""
    ki = pl.program_id(1)
    it = pl.program_id(2)       # flattened (group, q-block) index
    qi = it % q_blocks          # Q block index within the group
    nit = pl.num_programs(2)

    @pl.when(it == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    @pl.when(_block_visible(qi, ki, block_q=block_q, block_k=block_k,
                            causal=causal, offset=offset))
    def _compute():
        q = q_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        s = _masked_scores(q, k_ref[0], qi, ki, sm_scale=sm_scale,
                           block_q=block_q, block_k=block_k, causal=causal,
                           offset=offset)
        p = _p_from_lse(s, lse_ref[0][:, :1])                   # [bq, bk]
        dv_acc[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                                       # [bk, d]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta_ref[0][:, :1]) * sm_scale          # [bq, bk]
        dk_acc[...] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                                       # [bk, d]

    @pl.when(it == nit - 1)
    def _finalize():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _bwd(sm_scale, causal, block_q, block_k, interpret, res, do):
    q, k, v, o, lse = res
    bn, s, d = q.shape
    bkv, sk, _ = k.shape
    g = bn // bkv
    block_q = _fit_block(s, block_q)
    block_k = _fit_block(sk, block_k)
    offset = sk - s
    nq = s // block_q

    delta = jnp.sum(
        o.astype(jnp.float32) * do.astype(jnp.float32), axis=-1,
        keepdims=True,
    )  # [bn, s, 1]
    delta = jnp.broadcast_to(delta, (bn, s, _LANES))

    q_spec = pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0))
    k_spec = pl.BlockSpec((1, block_k, d), lambda b, i, j: (b // g, j, 0))
    row_spec = pl.BlockSpec((1, block_q, _LANES), lambda b, i, j: (b, i, 0))

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, sm_scale=sm_scale, block_q=block_q,
                          block_k=block_k, causal=causal, offset=offset),
        grid=(bn, s // block_q, sk // block_k),
        in_specs=[q_spec, k_spec, k_spec, q_spec, row_spec, row_spec],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    # dkv: swap loop order — K blocks outer; the inner dim flattens
    # (query-head group, Q block) so each of the bkv K/V rows accumulates
    # over its g sharing query heads (grid row b serves Q rows b*g..b*g+g-1)
    q_spec_t = pl.BlockSpec(
        (1, block_q, d), lambda b, j, i: (b * g + i // nq, i % nq, 0)
    )
    k_spec_t = pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0))
    row_spec_t = pl.BlockSpec(
        (1, block_q, _LANES), lambda b, j, i: (b * g + i // nq, i % nq, 0)
    )
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, sm_scale=sm_scale, block_q=block_q,
                          block_k=block_k, causal=causal, offset=offset,
                          q_blocks=nq),
        grid=(bkv, sk // block_k, g * nq),
        in_specs=[q_spec_t, k_spec_t, k_spec_t, q_spec_t, row_spec_t,
                  row_spec_t],
        out_specs=[k_spec_t, k_spec_t],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, sm_scale, causal, block_q, block_k, interpret):
    o, _ = _fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret)
    return o


def _flash_fwd_rule(q, k, v, sm_scale, causal, block_q, block_k, interpret):
    o, lse = _fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret)
    return o, (q, k, v, o, lse)


_flash.defvjp(_flash_fwd_rule, _bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    sm_scale: float | None = None,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
    interpret: bool | None = None,
) -> jax.Array:
    """Blocked attention, ``q: [B, num_heads, S, head_dim] -> same``.

    ``k, v`` may be full ``[B, num_heads, Sk, head_dim]`` or grouped-query
    ``[B, kv_heads, Sk, head_dim]`` with ``num_heads % kv_heads == 0`` —
    query-head groups share K/V blocks inside the kernel (``b // g`` index
    maps), so grouped K/V stay at kv_heads width in HBM and VMEM: the
    KV-bandwidth saving GQA exists for, not just a smaller projection.

    Differentiable (custom VJP with blockwise recompute — no [S, S]
    residuals; dk/dv accumulate over the sharing query heads).  ``sk != s``
    is supported; with ``causal=True`` the diagonal anchors at the end of
    the key axis (kv-cache decode convention).  ``interpret=None``
    auto-selects pallas interpret mode off TPU so the same model code runs
    on the CPU-simulated dev mesh.
    """
    if q.ndim != 4:
        raise ValueError(f"expected [B, N, S, D], got {q.shape}")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    b, n, s, d = q.shape
    kvh, sk = k.shape[1], k.shape[2]
    if n % kvh != 0:
        raise ValueError(
            f"num_heads {n} not divisible by kv_heads {kvh}"
        )
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    fold = lambda t, nh, sl: t.reshape(b * nh, sl, d)  # noqa: E731
    o = _flash(
        fold(q, n, s), fold(k, kvh, sk), fold(v, kvh, sk),
        sm_scale, causal, block_q, block_k, interpret,
    )
    return o.reshape(b, n, s, d)
