"""Pass 2 — AST source lint.

Custom rules over ``dlbb_tpu/`` and ``scripts/`` for the failure modes a
distributed benchmark repo cares about and generic linters do not:

- ``host-sync-in-timed-region``: ``block_until_ready`` / ``device_get`` /
  ``float(...)`` / ``np.asarray(...)`` inside a timed region, except
  through the ``utils/timing.py`` API or as the region's final bracketing
  sync.  A mid-region host sync serialises the device pipeline into the
  measurement and corrupts the number being published.
- ``missing-donation``: a train-step jit (``jax.jit(step)`` /
  ``jax.jit(train_step)`` — any traced function whose name contains
  "step" or "train") without ``donate_argnums``/``donate_argnames``;
  without donation XLA keeps input and output state simultaneously
  resident.
- ``jit-in-loop``: ``jax.jit`` of a lambda or in-loop ``def`` closing over
  the loop variable — every iteration creates a fresh callable and
  therefore a fresh trace + compile (the Python-scalar-capture recompile
  hazard).  Warning severity (a name-resolution heuristic); CI runs with
  ``--strict-warnings`` so it still gates.
- ``host-transfer-in-loop``: ``np.asarray(...)`` / ``jax.device_get`` /
  ``.block_until_ready`` inside a Python loop body — the host-side twin
  of ``jit-in-loop``: a per-iteration device->host transfer (or full
  pipeline sync) serialises dispatch into every trip and scales with the
  loop, exactly the round-trip the fused-decode fast path exists to
  eliminate.  Warning severity (argument size is not statically
  knowable); CI runs ``--strict-warnings`` so it still gates.  Exempt:
  the measurement API homes (``TIMING_API_FILES`` +
  ``PROFILER_API_FILES`` — bracketed syncs around measurement are their
  whole purpose), calls inside a *timed region* (the timed-region rules
  own that domain and its bracketing-sync convention), loops over a
  constant literal tuple/list (a bounded probe ladder, not a data
  loop), and calls on a loop-exit path (an ``if`` body ending in
  ``break``/``return``/``raise`` executes at most once).  Only the loop
  BODY is walked (the iter expression evaluates once, a ``for/else``
  clause runs once) and nested function/lambda definitions are skipped
  (defined inside the loop is not executed per iteration).
- ``unsorted-set-iteration``: a ``for`` statement iterating directly over
  a set literal / ``set(...)`` call — hash-order dependent, so publish
  scripts reprocess artifacts in a different order run to run (the
  round-5 ADVICE nondeterminism finding, generalised).
- ``wallclock-in-timed-region``: ``time.time()`` / ``datetime.now()`` /
  ``datetime.utcnow()`` inside a timed region.  The wall clock is
  non-monotonic — NTP can step it mid-measurement — so a benchmark
  number derived from it is unfalsifiable; timed regions must read
  ``time.perf_counter()`` only (wall-clock *timestamps* belong outside
  the region).  Unlike host syncs there is no bracketing exemption: a
  wall-clock read is wrong anywhere inside the region.
- ``profiler-in-timed-region``: a profiler/tracing call —
  ``jax.profiler.*`` (``trace``, ``start_trace``, ``TraceAnnotation``,
  ``StepTraceAnnotation``), the ``utils/profiling.py`` wrappers
  (``maybe_trace`` / ``annotate`` / ``step_annotation``), or the obs
  device capture (``obs.capture.capture_device_trace``) — inside a timed
  region.  Profiler instrumentation perturbs the region it observes
  (xplane capture serialises device work and burns host cycles), so
  device traces must come from DEDICATED profile reps outside every
  timed region (``docs/observability.md``); no bracketing exemption.
  The sanctioned API homes (``utils/profiling.py``, ``obs/capture.py``)
  are exempt, like ``utils/timing.py`` is for host syncs.
- ``float64-literal-in-jit``: a float64 value materialised inside a
  jitted function (decorated ``@jax.jit`` / ``@partial(jax.jit, ...)``
  or passed by name to ``jax.jit`` in the same file) or a timed region —
  ``np.float64(...)``, ``.astype(np.float64 / "float64" / float)``,
  ``dtype=float64`` keywords, or a dtype-free host-numpy constructor
  (``np.array`` of float literals, ``np.ones``/``np.zeros``/
  ``np.linspace``) whose default dtype is float64.  With x64 disabled
  JAX silently demotes these to f32 (the literal lies about the math
  that runs); with x64 enabled they double the bytes of everything they
  touch — wire, HBM, and the number being timed.  The numerics HLO pass
  (``numerics_audit``) catches f64 that survives to the lowered module;
  this rule catches it at the source, where the fix belongs.
- ``non-atomic-artifact-write``: a bare ``json.dump(...)`` (in-place
  write of the destination file) or ``*.write_text(json.dumps(...))``
  outside the sanctioned atomic helper (``utils/config.py``:
  ``save_json`` / ``atomic_write_text``, tmp + fsync + ``os.replace``).
  A process killed mid-dump leaves a truncated JSON at the final path —
  which resume-mode sweeps and the stats pipeline would then trust
  (the PR-5 robustness hazard, ``docs/resilience.md``).

Timed regions are detected syntactically: the body of ``with Timer()``
(also ``with Timer() as t``), and statements strictly between
``<var> = time.perf_counter()`` and the statement consuming
``time.perf_counter() - <var>`` in the same block.

Suppression: ``# comm-lint: disable=rule[,rule2]`` trailing on the line
(or on the line directly above), ``# comm-lint: disable-file=rule`` near
the top of the file.
"""

from __future__ import annotations

import ast
import io
import tokenize
from pathlib import Path
from typing import Iterable, Optional

from dlbb_tpu.analysis.findings import (
    SEVERITY_ERROR,
    SEVERITY_WARNING,
    AnalysisReport,
    Finding,
)

LINT_RULES = (
    "host-sync-in-timed-region",
    "wallclock-in-timed-region",
    "profiler-in-timed-region",
    "missing-donation",
    "jit-in-loop",
    "host-transfer-in-loop",
    "unsorted-set-iteration",
    "non-atomic-artifact-write",
    "float64-literal-in-jit",
)

# Files whose whole purpose is host synchronisation around measurement.
TIMING_API_FILES = ("utils/timing.py",)
# The sanctioned profiler/capture API homes: the only files allowed to
# bracket a profiler session with a wall timer (they report the capture's
# own cost, never a published benchmark number).
PROFILER_API_FILES = ("utils/profiling.py", "obs/capture.py")
# The one sanctioned in-place writer: the atomic helper itself (its
# json.dump-to-tmp is the mechanism every other writer must go through).
ATOMIC_API_FILES = ("utils/config.py",)
# Calls through the sanctioned timing API are never host-sync findings.
TIMING_API_NAMES = {
    "force_completion", "calibrate_fetch_overhead",
    "single_iteration_estimate", "time_fn_per_iter", "time_fn_chained",
    "time_collective",
}
_SYNC_CALL_NAMES = {"block_until_ready", "device_get"}
_SYNC_WRAPPERS = {"float", "int"}
_NP_SYNC_ATTRS = {"asarray", "array"}
# wall-clock reads (non-monotonic) that must never supply a timed-region
# measurement; perf_counter/monotonic are the sanctioned clocks
_WALLCLOCK_NAMES = {
    "time.time", "datetime.now", "datetime.utcnow",
    "datetime.datetime.now", "datetime.datetime.utcnow",
    "date.today", "datetime.date.today",
}
# profiler entry points that must never run inside a timed region: the
# wrapper API (utils/profiling.py + obs/capture.py) by short name, plus
# anything reached through a `...profiler...` attribute chain
# (jax.profiler.trace / start_trace / TraceAnnotation / ...)
_PROFILER_CALL_NAMES = {
    "maybe_trace", "annotate", "step_annotation", "capture_device_trace",
}
# per-iteration device->host transfers the in-loop rule flags: the
# named trio only (float()/int() scalarisation of a device scalar moves
# 4 bytes and is the sanctioned way OUT of this finding; jnp.asarray is
# device-side and exempt by the np/numpy prefix check)
_HOST_TRANSFER_CALLS = {"block_until_ready", "device_get"}


def _is_profiler_call(name: str) -> bool:
    short = name.rsplit(".", 1)[-1]
    return short in _PROFILER_CALL_NAMES or "profiler" in name


# ---------------------------------------------------------------------------
# suppression comments
# ---------------------------------------------------------------------------


class Suppressions:
    def __init__(self, source: str):
        self.line_rules: dict[int, set[str]] = {}
        self.file_rules: set[str] = set()
        self.hits = 0
        try:
            tokens = tokenize.generate_tokens(io.StringIO(source).readline)
            for tok in tokens:
                if tok.type != tokenize.COMMENT:
                    continue
                text = tok.string.lstrip("# ").strip()
                if not text.startswith("comm-lint:"):
                    continue
                directive = text[len("comm-lint:"):].strip()
                if directive.startswith("disable-file="):
                    rules = directive[len("disable-file="):]
                    self.file_rules |= {r.strip() for r in rules.split(",")}
                elif directive.startswith("disable="):
                    rules = directive[len("disable="):]
                    self.line_rules.setdefault(tok.start[0], set()).update(
                        r.strip() for r in rules.split(",")
                    )
        except tokenize.TokenError:
            pass

    def suppressed(self, rule: str, line: int) -> bool:
        if rule in self.file_rules:
            self.hits += 1
            return True
        for ln in (line, line - 1):
            if rule in self.line_rules.get(ln, set()):
                self.hits += 1
                return True
        return False


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------


def _call_name(node: ast.Call) -> str:
    """Dotted name of a call's function, e.g. "jax.jit" or "Timer"."""
    parts = []
    f = node.func
    while isinstance(f, ast.Attribute):
        parts.append(f.attr)
        f = f.value
    if isinstance(f, ast.Name):
        parts.append(f.id)
    return ".".join(reversed(parts))


def _is_perf_counter_call(node: ast.AST) -> bool:
    return (isinstance(node, ast.Call)
            and _call_name(node).endswith("perf_counter"))


def _free_names(node: ast.AST) -> set[str]:
    return {n.id for n in ast.walk(node)
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)}


def _sync_calls(stmt: ast.stmt) -> Iterable[tuple[ast.Call, str]]:
    """(call, description) for every host-sync call inside ``stmt``."""
    for node in ast.walk(stmt):
        if not isinstance(node, ast.Call):
            continue
        name = _call_name(node)
        short = name.rsplit(".", 1)[-1]
        if short in TIMING_API_NAMES:
            continue  # sanctioned timing API
        if short in _SYNC_CALL_NAMES:
            yield node, name
        elif name in _SYNC_WRAPPERS and node.args and not isinstance(
                node.args[0], ast.Constant):
            # float(x)/int(x) on a non-literal forces the value to host
            yield node, f"{name}() on a device value"
        elif short in _NP_SYNC_ATTRS and name.split(".")[0] in ("np",
                                                               "numpy"):
            yield node, name
        elif short == "item" and isinstance(node.func, ast.Attribute):
            yield node, ".item()"


def _wallclock_calls(stmt: ast.stmt) -> Iterable[tuple[ast.Call, str]]:
    """(call, description) for every wall-clock read inside ``stmt``."""
    for node in ast.walk(stmt):
        if isinstance(node, ast.Call) and _call_name(
                node) in _WALLCLOCK_NAMES:
            yield node, f"{_call_name(node)}()"


def _profiler_calls(stmt: ast.stmt) -> Iterable[tuple[ast.Call, str]]:
    """(call, description) for every profiler/tracing call inside
    ``stmt``."""
    for node in ast.walk(stmt):
        if isinstance(node, ast.Call) and _is_profiler_call(
                _call_name(node)):
            yield node, f"{_call_name(node)}()"


def _walk_skip_defs(node: ast.AST) -> Iterable[ast.AST]:
    """``ast.walk`` that does not descend into nested function/lambda
    definitions — code *defined* inside a loop body is not necessarily
    *executed* per iteration."""
    stack = [node]
    while stack:
        n = stack.pop()
        yield n
        for child in ast.iter_child_nodes(n):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            stack.append(child)


def _host_transfer_calls(node: ast.AST) -> Iterable[tuple[ast.Call, str]]:
    """(call, description) for every device->host transfer/sync call
    inside ``node`` (nested defs excluded): ``*.block_until_ready`` /
    ``jax.device_get`` / ``np.asarray`` (numpy's ``asarray`` on a
    device array pulls the whole buffer to host; ``jnp.asarray`` stays
    on device and is not matched)."""
    for n in _walk_skip_defs(node):
        if not isinstance(n, ast.Call):
            continue
        name = _call_name(n)
        short = name.rsplit(".", 1)[-1]
        if short in _HOST_TRANSFER_CALLS:
            yield n, name
        elif short == "asarray" and name.split(".")[0] in ("np", "numpy"):
            yield n, name


def _timed_line_spans(tree: ast.AST) -> list[tuple[int, int]]:
    """Line spans of every syntactic timed region — Timer with-blocks
    and ``t = perf_counter()`` ... ``perf_counter() - t`` spans — so
    rules that defer to the timed-region rules (their bracketing-sync
    convention is policed there) can skip them."""
    spans: list[tuple[int, int]] = []
    for node in _timed_with_blocks(tree):
        spans.append((node.lineno, node.end_lineno or node.lineno))
    for scope in ast.walk(tree):
        body = getattr(scope, "body", None)
        if not isinstance(body, list):
            continue
        for blk in (body, getattr(scope, "orelse", None),
                    getattr(scope, "finalbody", None)):
            if not isinstance(blk, list):
                continue
            svars: dict[str, int] = {}
            for idx, stmt in enumerate(blk):
                if (isinstance(stmt, ast.Assign)
                        and len(stmt.targets) == 1
                        and isinstance(stmt.targets[0], ast.Name)
                        and _is_perf_counter_call(stmt.value)):
                    svars[stmt.targets[0].id] = idx
                    continue
                closed = set()
                for node in ast.walk(stmt):
                    if (isinstance(node, ast.BinOp)
                            and isinstance(node.op, ast.Sub)
                            and _is_perf_counter_call(node.left)
                            and isinstance(node.right, ast.Name)
                            and node.right.id in svars):
                        closed.add(node.right.id)
                for var in closed:
                    start = svars.pop(var)
                    spans.append((blk[start].lineno,
                                  stmt.end_lineno or stmt.lineno))
    return spans


# ---------------------------------------------------------------------------
# rule implementations
# ---------------------------------------------------------------------------


def _timed_with_blocks(tree: ast.AST) -> Iterable[ast.With]:
    for node in ast.walk(tree):
        if not isinstance(node, ast.With):
            continue
        for item in node.items:
            ctx = item.context_expr
            if isinstance(ctx, ast.Call) and _call_name(ctx).rsplit(
                    ".", 1)[-1] == "Timer":
                yield node
                break


def _check_timed_with(node: ast.With, path: str, findings: list[Finding],
                      check_profiler: bool = True):
    last = node.body[-1]
    for stmt in node.body:
        for call, desc in _sync_calls(stmt):
            if stmt is last:
                continue  # bracketing sync closing the measurement
            findings.append(Finding(
                pass_name="lint",
                rule="host-sync-in-timed-region",
                severity=SEVERITY_ERROR,
                target=path,
                message=(
                    f"{desc} inside a Timer block (before its final "
                    "statement) serialises device work into the "
                    "measurement; use the utils/timing.py API or move the "
                    "sync to the region boundary"
                ),
                location=f"{path}:{call.lineno}",
                details={"sync": desc, "region": f"with Timer() at line "
                                                 f"{node.lineno}"},
            ))
        # no bracketing exemption: a wall-clock read is wrong anywhere
        # inside the region, last statement included
        for call, desc in _wallclock_calls(stmt):
            findings.append(Finding(
                pass_name="lint",
                rule="wallclock-in-timed-region",
                severity=SEVERITY_ERROR,
                target=path,
                message=(
                    f"{desc} inside a Timer block reads the wall clock — "
                    "non-monotonic (NTP can step it mid-measurement), so "
                    "any duration derived from it is unfalsifiable; use "
                    "time.perf_counter(), and take wall-clock timestamps "
                    "outside the timed region"
                ),
                location=f"{path}:{call.lineno}",
                details={"clock": desc, "region": f"with Timer() at line "
                                                  f"{node.lineno}"},
            ))
        if not check_profiler:
            continue
        # like the wall clock, no bracketing exemption: a profiler call
        # perturbs the region wherever it sits
        for call, desc in _profiler_calls(stmt):
            findings.append(Finding(
                pass_name="lint",
                rule="profiler-in-timed-region",
                severity=SEVERITY_ERROR,
                target=path,
                message=(
                    f"{desc} inside a Timer block starts/annotates a "
                    "profiler session in the measured region — capture "
                    "overhead lands in the published number; trace on "
                    "DEDICATED profile reps outside the timed region "
                    "(dlbb_tpu.obs.capture, docs/observability.md)"
                ),
                location=f"{path}:{call.lineno}",
                details={"call": desc, "region": f"with Timer() at line "
                                                 f"{node.lineno}"},
            ))


def _check_perf_counter_regions(tree: ast.AST, path: str,
                                findings: list[Finding],
                                check_profiler: bool = True):
    """Statements strictly between ``t = time.perf_counter()`` and the
    statement consuming ``perf_counter() - t`` are a timed region."""
    for scope in ast.walk(tree):
        body = getattr(scope, "body", None)
        if not isinstance(body, list):
            continue
        for blk in (body, getattr(scope, "orelse", None),
                    getattr(scope, "finalbody", None)):
            if not isinstance(blk, list):
                continue
            self_vars: dict[str, int] = {}  # var -> index of t0 assignment
            for idx, stmt in enumerate(blk):
                if (isinstance(stmt, ast.Assign)
                        and len(stmt.targets) == 1
                        and isinstance(stmt.targets[0], ast.Name)
                        and _is_perf_counter_call(stmt.value)):
                    self_vars[stmt.targets[0].id] = idx
                    continue
                # does this statement close a region? (perf_counter() - t)
                closed = set()
                for node in ast.walk(stmt):
                    if (isinstance(node, ast.BinOp)
                            and isinstance(node.op, ast.Sub)
                            and _is_perf_counter_call(node.left)
                            and isinstance(node.right, ast.Name)
                            and node.right.id in self_vars):
                        closed.add(node.right.id)
                for var in closed:
                    start = self_vars.pop(var)
                    # the statement directly before the delta is the
                    # bracketing sync closing the measurement (e.g.
                    # ``float(loss)`` then ``t = perf_counter() - t0``) —
                    # same exemption as a Timer block's final statement
                    for mid in blk[start + 1: idx - 1]:
                        for call, desc in _sync_calls(mid):
                            findings.append(Finding(
                                pass_name="lint",
                                rule="host-sync-in-timed-region",
                                severity=SEVERITY_ERROR,
                                target=path,
                                message=(
                                    f"{desc} between "
                                    f"{var} = time.perf_counter() and its "
                                    "delta serialises device work into "
                                    "the measurement; use the "
                                    "utils/timing.py API"
                                ),
                                location=f"{path}:{call.lineno}",
                                details={"sync": desc,
                                         "region": f"perf_counter span "
                                                   f"'{var}'"},
                            ))
                    # wall-clock reads get no bracketing exemption (the
                    # statement before the delta included)
                    for mid in blk[start + 1: idx]:
                        for call, desc in _wallclock_calls(mid):
                            findings.append(Finding(
                                pass_name="lint",
                                rule="wallclock-in-timed-region",
                                severity=SEVERITY_ERROR,
                                target=path,
                                message=(
                                    f"{desc} between "
                                    f"{var} = time.perf_counter() and its "
                                    "delta reads the non-monotonic wall "
                                    "clock; use time.perf_counter() and "
                                    "timestamp outside the region"
                                ),
                                location=f"{path}:{call.lineno}",
                                details={"clock": desc,
                                         "region": f"perf_counter span "
                                                   f"'{var}'"},
                            ))
                        if not check_profiler:
                            continue
                        for call, desc in _profiler_calls(mid):
                            findings.append(Finding(
                                pass_name="lint",
                                rule="profiler-in-timed-region",
                                severity=SEVERITY_ERROR,
                                target=path,
                                message=(
                                    f"{desc} between "
                                    f"{var} = time.perf_counter() and its "
                                    "delta runs a profiler session inside "
                                    "the measured region — capture "
                                    "overhead lands in the published "
                                    "number; move the capture to a "
                                    "dedicated profile rep outside the "
                                    "region (dlbb_tpu.obs.capture)"
                                ),
                                location=f"{path}:{call.lineno}",
                                details={"call": desc,
                                         "region": f"perf_counter span "
                                                   f"'{var}'"},
                            ))


def _check_donation(tree: ast.AST, path: str, findings: list[Finding]):
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or _call_name(node) not in (
                "jax.jit", "jit"):
            continue
        if not node.args:
            continue
        fn = node.args[0]
        fn_name = fn.id if isinstance(fn, ast.Name) else None
        if fn_name is None or not ("step" in fn_name or "train" in fn_name):
            continue
        kwargs = {kw.arg for kw in node.keywords}
        if not kwargs & {"donate_argnums", "donate_argnames"}:
            findings.append(Finding(
                pass_name="lint",
                rule="missing-donation",
                severity=SEVERITY_ERROR,
                target=path,
                message=(
                    f"jax.jit({fn_name}) looks like a train-step jit but "
                    "donates no arguments — without donate_argnums the "
                    "input and output state are simultaneously resident "
                    "(2x state HBM)"
                ),
                location=f"{path}:{node.lineno}",
                details={"function": fn_name},
            ))


def _check_jit_in_loop(tree: ast.AST, path: str, findings: list[Finding]):
    for loop in ast.walk(tree):
        if not isinstance(loop, (ast.For, ast.While)):
            continue
        loop_vars: set[str] = set()
        if isinstance(loop, ast.For):
            loop_vars = {n.id for n in ast.walk(loop.target)
                         if isinstance(n, ast.Name)}
        in_loop_defs = {
            d.name: d for d in ast.walk(loop)
            if isinstance(d, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        for node in ast.walk(loop):
            if not isinstance(node, ast.Call) or _call_name(node) not in (
                    "jax.jit", "jit", "jax.pmap", "pmap"):
                continue
            if not node.args:
                continue
            fn = node.args[0]
            if isinstance(fn, ast.Lambda):
                traced, what = fn.body, "lambda ..."
            elif isinstance(fn, ast.Name) and fn.id in in_loop_defs:
                # a def in the loop body is a fresh function object per
                # iteration, exactly like an inline lambda
                traced, what = in_loop_defs[fn.id], fn.id
            else:
                continue
            if not loop_vars or _free_names(traced) & loop_vars:
                findings.append(Finding(
                    pass_name="lint",
                    rule="jit-in-loop",
                    severity=SEVERITY_WARNING,
                    target=path,
                    message=(
                        f"jax.jit({what}) inside a loop creates a "
                        "fresh callable — and a fresh trace + XLA compile "
                        "— every iteration (Python-scalar capture "
                        "recompile hazard); hoist the jit and pass the "
                        "varying value as an argument"
                    ),
                    location=f"{path}:{node.lineno}",
                    details={"loop_line": loop.lineno},
                ))


def _is_constant_iterable(node: ast.AST) -> bool:
    """A literal tuple/list of constants — a bounded probe ladder
    (``for mode in ("head", "whole")``), not a data loop."""
    return (isinstance(node, (ast.Tuple, ast.List))
            and all(isinstance(e, ast.Constant) for e in node.elts))


def _check_host_transfer_in_loop(tree: ast.AST, path: str,
                                 findings: list[Finding]):
    """``host-transfer-in-loop``: a device->host transfer repeated every
    iteration of a Python loop (the host-side twin of jit-in-loop).
    Exempt spans: timed regions (the timed-region rules own those and
    their bracketing-sync convention), constant-literal probe loops, and
    loop-exit ``if`` bodies (break/return/raise — at most one
    execution)."""
    exempt = _timed_line_spans(tree)
    for node in ast.walk(tree):
        if isinstance(node, ast.For) and _is_constant_iterable(node.iter):
            exempt.append((node.lineno, node.end_lineno or node.lineno))
        elif (isinstance(node, ast.If) and node.body
                and isinstance(node.body[-1],
                               (ast.Break, ast.Return, ast.Raise))):
            last = node.body[-1]
            exempt.append((node.body[0].lineno,
                           last.end_lineno or last.lineno))
    seen: set[tuple[int, int]] = set()
    for loop in ast.walk(tree):
        if not isinstance(loop, (ast.For, ast.While)):
            continue
        if isinstance(loop, ast.For) and _is_constant_iterable(loop.iter):
            continue
        # the loop BODY only: the iter expression evaluates once, and a
        # for/else clause runs once after the loop
        for stmt in loop.body:
            for call, desc in _host_transfer_calls(stmt):
                key = (call.lineno, call.col_offset)
                if key in seen:
                    continue  # nested loops re-discover the same call
                if any(lo <= call.lineno <= hi for lo, hi in exempt):
                    continue
                seen.add(key)
                findings.append(Finding(
                    pass_name="lint",
                    rule="host-transfer-in-loop",
                    severity=SEVERITY_WARNING,
                    target=path,
                    message=(
                        f"{desc}() inside a loop body forces a "
                        "device->host round trip (or full pipeline "
                        "sync) EVERY iteration — dispatch serialises "
                        "into each trip and the cost scales with the "
                        "loop; batch the transfer outside the loop, "
                        "keep the reduction on device (e.g. jnp.argmax "
                        "+ a scalar int()), or fuse the steps into one "
                        "dispatch (docs/serving.md fast path)"
                    ),
                    location=f"{path}:{call.lineno}",
                    details={"call": desc, "loop_line": loop.lineno},
                ))


def _check_atomic_writes(tree: ast.AST, path: str, findings: list[Finding]):
    """``non-atomic-artifact-write``: JSON artifacts must go through the
    atomic helper (tmp + fsync + ``os.replace``), never be written
    in-place at their final path."""

    def is_dumps(e: ast.AST) -> bool:
        if isinstance(e, ast.Call) and _call_name(e).rsplit(
                ".", 1)[-1] == "dumps" and _call_name(e).startswith("json"):
            return True
        if isinstance(e, ast.BinOp) and isinstance(e.op, ast.Add):
            # json.dumps(...) + "\n" and friends
            return is_dumps(e.left) or is_dumps(e.right)
        return False

    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = _call_name(node)
        if name == "json.dump":
            findings.append(Finding(
                pass_name="lint",
                rule="non-atomic-artifact-write",
                severity=SEVERITY_ERROR,
                target=path,
                message=(
                    "bare json.dump writes the destination in-place — a "
                    "process killed mid-dump leaves a truncated artifact "
                    "that resume-mode sweeps / the stats pipeline would "
                    "trust; use dlbb_tpu.utils.config.save_json (tmp + "
                    "fsync + os.replace)"
                ),
                location=f"{path}:{node.lineno}",
                details={"call": "json.dump"},
            ))
        elif (name.rsplit(".", 1)[-1] == "write_text" and node.args
                and is_dumps(node.args[0])):
            findings.append(Finding(
                pass_name="lint",
                rule="non-atomic-artifact-write",
                severity=SEVERITY_ERROR,
                target=path,
                message=(
                    "write_text(json.dumps(...)) truncates the "
                    "destination before writing — a kill mid-write tears "
                    "the artifact; use dlbb_tpu.utils.config.save_json / "
                    "atomic_write_text (tmp + fsync + os.replace)"
                ),
                location=f"{path}:{node.lineno}",
                details={"call": "write_text(json.dumps)"},
            ))


_JIT_NAMES = ("jax.jit", "jit", "jax.pmap", "pmap")


def _dotted(node: ast.AST) -> str:
    """Dotted name of an Attribute/Name expression ("" when neither)."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return ".".join(reversed(parts))


def _is_jit_decorator(dec: ast.AST) -> bool:
    """``@jax.jit`` / ``@jit`` / ``@partial(jax.jit, ...)`` (functools
    spelling included)."""
    if isinstance(dec, ast.Call):
        name = _call_name(dec)
        if name in _JIT_NAMES:
            return True  # @jax.jit(donate_argnums=...)
        return (name.rsplit(".", 1)[-1] == "partial" and dec.args
                and _dotted(dec.args[0]) in _JIT_NAMES)
    return _dotted(dec) in _JIT_NAMES


def _jitted_spans(tree: ast.AST) -> list[tuple[int, int]]:
    """Line spans of every function the file jits: decorated defs plus
    defs whose NAME is passed to ``jax.jit``/``pmap`` anywhere in the
    file (the ``step_fn = jax.jit(step_fn, ...)`` idiom)."""
    defs: dict[str, ast.AST] = {}
    spans: list[tuple[int, int]] = []
    jit_arg_names: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs.setdefault(node.name, node)
            if any(_is_jit_decorator(d) for d in node.decorator_list):
                spans.append((node.lineno, node.end_lineno or node.lineno))
        elif (isinstance(node, ast.Call) and _call_name(node) in _JIT_NAMES
                and node.args and isinstance(node.args[0], ast.Name)):
            jit_arg_names.add(node.args[0].id)
    for name in sorted(jit_arg_names):
        d = defs.get(name)
        if d is not None:
            spans.append((d.lineno, d.end_lineno or d.lineno))
    return spans


def _f64_dtype_desc(e: ast.AST) -> Optional[str]:
    """Description when ``e`` denotes the float64 dtype: the
    ``np.float64``/``jnp.float64`` attribute, the ``"float64"``/
    ``"double"`` string, or the Python ``float`` builtin (float64 by
    definition)."""
    name = _dotted(e)
    if name and name.rsplit(".", 1)[-1] in ("float64", "double"):
        return name
    if isinstance(e, ast.Constant) and e.value in ("float64", "double"):
        return repr(e.value)
    if isinstance(e, ast.Name) and e.id == "float":
        return "float (the Python builtin is float64)"
    return None


# dtype-free host-numpy constructors whose default result dtype is
# float64 regardless of argument dtypes
_NP_F64_DEFAULT_CTORS = {"ones", "zeros", "linspace", "full"}


def _float64_sites(tree: ast.AST) -> Iterable[tuple[ast.AST, str]]:
    """(node, description) for every expression that materialises a
    float64 value: ``np.float64(x)`` casts, ``.astype`` upcasts,
    ``dtype=float64`` keywords, and dtype-free host-numpy constructors
    (``np.array`` of float literals; ``np.ones``/``zeros``/``linspace``/
    ``full`` always)."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = _call_name(node)
        short = name.rsplit(".", 1)[-1]
        if short in ("float64", "double") and "." in name:
            yield node, f"{name}(...) cast"
            continue
        if short == "astype" and node.args:
            desc = _f64_dtype_desc(node.args[0])
            if desc:
                yield node, f".astype({desc})"
                continue
        for kw in node.keywords:
            if kw.arg == "dtype":
                desc = _f64_dtype_desc(kw.value)
                if desc:
                    yield node, f"{name}(dtype={desc})"
                break
        else:
            if name.split(".")[0] not in ("np", "numpy"):
                continue
            if short in _NP_F64_DEFAULT_CTORS:
                yield node, (f"{name}(...) without dtype= "
                             "(host numpy defaults to float64)")
            elif short in ("array", "asarray") and node.args and any(
                    isinstance(c, ast.Constant) and isinstance(c.value, float)
                    for c in ast.walk(node.args[0])):
                yield node, (f"{name}(...) of float literals without "
                             "dtype= (host numpy defaults to float64)")


def _check_float64(tree: ast.AST, path: str, findings: list[Finding],
                   include_timed: bool = True):
    """``float64-literal-in-jit``: float64 materialised inside a jitted
    function or a timed region.  With jax x64 disabled the value is
    silently demoted to f32 (the source lies about the math that runs);
    with x64 enabled it doubles the bytes of everything downstream."""
    spans = _jitted_spans(tree)
    if include_timed:
        spans += _timed_line_spans(tree)
    if not spans:
        return
    for node, desc in _float64_sites(tree):
        line = node.lineno
        if not any(lo <= line <= hi for lo, hi in spans):
            continue
        findings.append(Finding(
            pass_name="lint",
            rule="float64-literal-in-jit",
            severity=SEVERITY_ERROR,
            target=path,
            message=(
                f"{desc} inside a jitted function or timed region "
                "materialises float64 — silently demoted to f32 when "
                "jax x64 is off (the literal lies about the math that "
                "runs), and doubled wire/HBM bytes when it is on; pin "
                "an explicit 32-bit dtype (jnp.float32 / the model's "
                "policy dtype)"
            ),
            location=f"{path}:{line}",
            details={"expression": desc},
        ))


def _check_set_iteration(tree: ast.AST, path: str, findings: list[Finding]):
    def is_set_expr(e: ast.AST) -> bool:
        if isinstance(e, ast.Set):
            return True
        if isinstance(e, ast.Call) and _call_name(e) == "set":
            return True
        if isinstance(e, ast.BinOp) and isinstance(e.op, ast.BitOr):
            return is_set_expr(e.left) or is_set_expr(e.right)
        return False

    for node in ast.walk(tree):
        if isinstance(node, ast.For) and is_set_expr(node.iter):
            findings.append(Finding(
                pass_name="lint",
                rule="unsorted-set-iteration",
                severity=SEVERITY_ERROR,
                target=path,
                message=(
                    "iterating directly over a set is hash-order "
                    "dependent — artifact/publishing order changes run to "
                    "run; wrap the set in sorted(...)"
                ),
                location=f"{path}:{node.iter.lineno}",
                details={},
            ))


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------


def lint_source(source: str, path: str) -> tuple[list[Finding], int]:
    """Lint one file's source text; returns (findings, suppressed_count)."""
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        return [Finding(
            pass_name="lint", rule="syntax-error", severity=SEVERITY_ERROR,
            target=path, message=f"file does not parse: {e}",
            location=f"{path}:{e.lineno or 0}",
        )], 0

    findings: list[Finding] = []
    norm = path.replace("\\", "/")
    if not norm.endswith(TIMING_API_FILES):
        check_prof = not norm.endswith(PROFILER_API_FILES)
        for block in _timed_with_blocks(tree):
            _check_timed_with(block, path, findings,
                              check_profiler=check_prof)
        _check_perf_counter_regions(tree, path, findings,
                                    check_profiler=check_prof)
        if check_prof:
            # the measurement/capture API homes drive the device in
            # loops on purpose (timing reps, profile reps) — same
            # exemption set as the profiler rule
            _check_host_transfer_in_loop(tree, path, findings)
    _check_donation(tree, path, findings)
    _check_jit_in_loop(tree, path, findings)
    _check_set_iteration(tree, path, findings)
    # the timing API computes host-side stats inside its own perf_counter
    # spans by design — its timed regions are exempt (jitted fns are not)
    _check_float64(tree, path, findings,
                   include_timed=not norm.endswith(TIMING_API_FILES))
    if not norm.endswith(ATOMIC_API_FILES):
        _check_atomic_writes(tree, path, findings)

    sup = Suppressions(source)
    kept = []
    for f in findings:
        line = int(f.location.rsplit(":", 1)[1]) if f.location else 0
        if not sup.suppressed(f.rule, line):
            kept.append(f)
    return kept, sup.hits


DEFAULT_LINT_DIRS = ("dlbb_tpu", "scripts")


def run_source_lint(
    root: Optional[str] = None,
    paths: Optional[Iterable[str]] = None,
    verbose: bool = False,
) -> AnalysisReport:
    """Lint every ``*.py`` under ``root``'s default dirs (or explicit
    ``paths``)."""
    report = AnalysisReport()
    if paths is None:
        base = Path(root or ".")
        files = sorted(
            p for d in DEFAULT_LINT_DIRS
            for p in (base / d).rglob("*.py") if p.is_file()
        )
        if not files:
            # a typo'd --root (or wrong cwd) must not read as a clean gate
            report.findings.append(Finding(
                pass_name="lint", rule="no-files-linted",
                severity=SEVERITY_ERROR, target=str(base),
                message=(
                    f"no Python files under {'/'.join(DEFAULT_LINT_DIRS)} "
                    f"of {base.resolve()}; is --root the repo root?"
                ),
            ))
            return report
    else:
        files = [Path(p) for p in paths]
    for p in files:
        rel = str(p)
        try:
            source = p.read_text()
        except OSError as e:
            report.findings.append(Finding(
                pass_name="lint", rule="io-error",
                severity=SEVERITY_ERROR, target=rel,
                message=f"cannot read: {e}",
            ))
            continue
        findings, suppressed = lint_source(source, rel)
        report.findings.extend(findings)
        report.suppressed += suppressed
        report.files_linted += 1
        if verbose and findings:
            print(f"[lint] {rel}: {len(findings)} finding(s)")
    return report
