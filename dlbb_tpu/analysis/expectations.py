"""Analytic expected-collective model.

Maps what a benchmark *claims* to do — a registry collective from
``comm/ops.py`` or a ``ParallelismPlan`` axis assignment — to the HLO
collective kinds the lowered program is allowed to contain and the byte
volume each instruction may carry.  The HLO auditor compares the compiled
module against this; anything outside the envelope is a finding.

Two layers:

- ``OP_EXPECTED_KINDS`` — per registry op, the HLO kinds its SPMD encoding
  lowers to (documented next to each entry; see also docs/analysis.md).
- ``plan_expected_kinds`` — per parallelism axis, the kinds the axis is
  allowed to introduce into a model/train computation (Megatron TP =>
  all-reduce, ring sp => collective-permute, Ulysses sp => all-to-all,
  pp => collective-permute, ZeRO dp => reduce-scatter/all-gather, ...).

``wire_bytes`` converts an instruction's per-device result bytes into the
analytic wire volume of the standard ring algorithm for its kind — the
"plan-derived expected volume" attached to every finding.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

# Registry op -> allowed HLO collective kinds, and the kind that MUST
# appear at least once (the op's defining primitive).
#
# The SPMD encodings (comm/ops.py) compose every root-rooted MPI op from
# symmetric collectives, so e.g. broadcast/scatter/reduce legitimately
# lower to all-reduce (psum of a masked contribution), and gather (like
# allgather) to all-gather.  "prod" allreduce is the one all-gather-based
# reduction (no pprod primitive) — the registry default is "sum" so the
# audit pins all-reduce.
OP_EXPECTED_KINDS: dict[str, dict] = {
    "allreduce": {"required": "all-reduce", "allowed": {"all-reduce"}},
    "allreduce_hierarchical": {
        # one psum per mesh axis: >= 2 all-reduce instructions on a
        # multi-axis mesh
        "required": "all-reduce", "allowed": {"all-reduce"},
        "min_required": 2,
    },
    "allgather": {"required": "all-gather", "allowed": {"all-gather"}},
    "broadcast": {"required": "all-reduce", "allowed": {"all-reduce"}},
    "gather": {"required": "all-gather", "allowed": {"all-gather"}},
    "scatter": {"required": "all-reduce", "allowed": {"all-reduce"}},
    "reduce": {"required": "all-reduce", "allowed": {"all-reduce"}},
    "alltoall": {"required": "all-to-all", "allowed": {"all-to-all"}},
    "sendrecv": {
        "required": "collective-permute", "allowed": {"collective-permute"},
    },
    "reducescatter": {
        "required": "reduce-scatter",
        # XLA CPU sometimes legalises psum_scatter to all-reduce + slice
        # (semantically identical, 2x wire volume); accept either lowering
        # but require one of the two.
        "allowed": {"reduce-scatter", "all-reduce"},
        "required_any": {"reduce-scatter", "all-reduce"},
    },
    "barrier": {"required": "all-reduce", "allowed": {"all-reduce"}},
    # Collective-matmul micro-ops, FUSED schedule (the registry default).
    # The decomposed ring/bidir schedules are audited via
    # ``overlap_op_expectation`` below — they must contain the
    # collective-permute chain and NOTHING else.
    "ag_matmul": {"required": "all-gather", "allowed": {"all-gather"}},
    "matmul_rs": {
        "required": "reduce-scatter",
        # same CPU legalisation latitude as `reducescatter`: psum_scatter
        # may lower to all-reduce + slice
        "allowed": {"reduce-scatter", "all-reduce"},
        "required_any": {"reduce-scatter", "all-reduce"},
    },
}

# Parallelism axis -> collective kinds that axis may introduce.
#
# tp additionally allows collective-permute: the fused-QKV kernel shards
# its packed [H + 2*kv*d] output dim over tp, and the q/k/v (and
# simplified-attention) slice boundaries do not align with the shard
# boundaries, so GSPMD realigns with neighbour collective-permutes of
# activation size (verified against the compiled HLO of the tiny TP
# forward; an audit finding only if they exceed the activation-byte
# ceiling).  The tripwire for TP mis-sharding remains all-gather: a
# weight-sized gather means the Megatron layout collapsed to replication.
AXIS_EXPECTED_KINDS: dict[str, set[str]] = {
    "dp": {"all-reduce", "reduce-scatter", "all-gather"},  # DDP / ZeRO
    "tp": {"all-reduce", "collective-permute"},  # row psum + QKV realign
    # tp with the overlapped collective-matmul schedule
    # (model.tp_overlap = ring|bidir): every projection's collective is a
    # ppermute chain; the ONLY legitimate all-gather is the single
    # activation-sized reshard back to the caller's batch layout after the
    # final layernorm.  all-reduce is deliberately absent — a surviving
    # all-reduce means the decomposition collapsed back to the fused
    # lowering.
    "tp_overlap": {"collective-permute", "all-gather"},
    "sp_ring": {"collective-permute"},                      # ring attention
    "sp_ulysses": {"all-to-all"},                           # Ulysses resharding
    "pp": {"collective-permute", "all-reduce"},             # hops + masked psum
    "ep": {"all-reduce"},                                   # expert combine psum
}


def plan_expected_kinds(dp: int = 1, tp: int = 1, sp: int = 1, pp: int = 1,
                        ep: int = 1, attention: str = "full",
                        zero_stage: int = 0,
                        tp_overlap: str = "off") -> set[str]:
    """The union of collective kinds a (plan, attention, ZeRO stage,
    tp_overlap schedule) combination is allowed to lower to.  Anything
    else in the compiled module — most importantly an all-gather in a
    plain TP forward, or a surviving all-reduce in an overlapped one — is
    a sharding mismatch."""
    kinds: set[str] = set()
    if dp > 1:
        kinds |= ({"all-reduce"} if zero_stage == 0
                  else AXIS_EXPECTED_KINDS["dp"])
    if tp > 1:
        kinds |= AXIS_EXPECTED_KINDS[
            "tp_overlap" if tp_overlap != "off" else "tp"
        ]
    if sp > 1:
        kinds |= AXIS_EXPECTED_KINDS[
            "sp_ring" if attention == "ring" else "sp_ulysses"
        ]
    if pp > 1:
        kinds |= AXIS_EXPECTED_KINDS["pp"]
    if ep > 1:
        kinds |= AXIS_EXPECTED_KINDS["ep"]
    return kinds


def wire_bytes(kind: str, result_bytes: int, group_size: Optional[int]) -> int:
    """Analytic per-device wire volume of the standard ring algorithm for
    ``kind``, given the instruction's per-device result bytes.

    all-reduce: 2(P-1)/P x buffer (reduce-scatter + all-gather phases);
    all-gather: result is the gathered buffer, each device receives the
    (P-1)/P of it produced elsewhere; reduce-scatter: mirrors all-gather
    with the roles of operand/result swapped — the wire carries (P-1) x
    the scattered shard; all-to-all: (P-1)/P of the slab changes device;
    collective-permute: the whole buffer moves once.
    """
    p = group_size or 1
    if p <= 1:
        return 0
    if kind == "all-reduce":
        return int(2 * (p - 1) / p * result_bytes)
    if kind == "all-gather":
        return int((p - 1) / p * result_bytes)
    if kind == "reduce-scatter":
        return int((p - 1) * result_bytes)
    if kind == "all-to-all":
        return int((p - 1) / p * result_bytes)
    if kind == "collective-permute":
        return int(result_bytes)
    return int(result_bytes)


@dataclass
class TargetExpectation:
    """The audit contract for one lowered computation.

    allowed:            collective kinds that may appear.
    required_any:       at least one instruction of one of these kinds must
                        appear (None = nothing required, e.g. a pure-local
                        computation that must stay communication-free).
    min_required:       minimum number of instructions among required_any.
    max_bytes_per_instr: per-device result-byte ceiling per instruction
                        (None = unchecked); catches "oversized" collectives
                        such as a full-parameter all-gather where only an
                        activation-sized transfer is planned.
    expect_donation:    the computation must donate at least one input
                        buffer (train-step convention — without it XLA
                        keeps input and output state resident).
    """

    allowed: set[str] = field(default_factory=set)
    required_any: Optional[set[str]] = None
    min_required: int = 1
    max_bytes_per_instr: Optional[int] = None
    expect_donation: bool = False


def op_expectation(op_name: str, payload_bytes_per_rank: int,
                   slack: float = 1.25) -> TargetExpectation:
    """Expectation for one ``comm/ops.py`` registry op.

    ``payload_bytes_per_rank`` is the per-rank buffer size; the byte
    ceiling allows ``slack`` headroom over the worst-case legitimate
    instruction (the gathered [P, n] result for gather-family ops is
    handled by callers passing the global payload size).
    """
    spec = OP_EXPECTED_KINDS[op_name]
    required_any = spec.get("required_any")
    if required_any is None:
        required_any = {spec["required"]}
    return TargetExpectation(
        allowed=set(spec["allowed"]),
        required_any=set(required_any),
        min_required=spec.get("min_required", 1),
        max_bytes_per_instr=int(payload_bytes_per_rank * slack),
    )


def overlap_op_expectation(p: int, chunk_bytes: int,
                           slack: float = 1.25) -> TargetExpectation:
    """Expectation for a RING-DECOMPOSED collective matmul (either op,
    either direction): the lowered program must be a pure
    collective-permute chain — at least ``p - 1`` hops (the unidirectional
    ring's count; the bidirectional all-gather ring splits the same count
    across two directions, the bidirectional reduce-scatter doubles it
    with half-sized messages), each carrying at most one travelling chunk
    (``chunk_bytes``) — and no fused collective may survive: an
    all-gather or reduce-scatter here means XLA undid the decomposition
    and the overlap claim is void."""
    return TargetExpectation(
        allowed={"collective-permute"},
        required_any={"collective-permute"},
        min_required=p - 1,
        max_bytes_per_instr=int(chunk_bytes * slack),
    )
