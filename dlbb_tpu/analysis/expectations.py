"""Analytic expected-collective model.

Maps what a benchmark *claims* to do — a registry collective from
``comm/ops.py`` or a ``ParallelismPlan`` axis assignment — to the HLO
collective kinds the lowered program is allowed to contain and the byte
volume each instruction may carry.  The HLO auditor compares the compiled
module against this; anything outside the envelope is a finding.

Two layers:

- ``OP_EXPECTED_KINDS`` — per registry op, the HLO kinds its SPMD encoding
  lowers to (documented next to each entry; see also docs/analysis.md).
- ``plan_expected_kinds`` — per parallelism axis, the kinds the axis is
  allowed to introduce into a model/train computation (Megatron TP =>
  all-reduce, ring sp => collective-permute, Ulysses sp => all-to-all,
  pp => collective-permute, ZeRO dp => reduce-scatter/all-gather, ...).

``wire_bytes`` converts an instruction's per-device result bytes into the
analytic wire volume of the standard ring algorithm for its kind — the
"plan-derived expected volume" attached to every finding.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

# --- compressed-collective wire model (docs/compression.md) ---------------
#
# These constants are the single source of truth for the quantised wire
# format: dlbb_tpu/comm/compression.py imports them (this module must stay
# importable WITHOUT jax — the source lint runs backend-free — so the
# dependency points this way, not comm -> analysis -> comm).
COMPRESSIONS = ("int8", "fp8")
# payload bytes per element on the wire (int8 and fp8 e4m3 are both 1 B)
COMPRESSED_WIRE_ITEM_BYTES = {"int8": 1, "fp8": 1}
# one fp32 scale per chunk of this many elements — the scale-tensor side
# channel, charged to every byte ceiling below
SCALE_CHUNK_ELEMS = 256
SCALE_ITEM_BYTES = 4


def scale_bytes(num_elements: int) -> int:
    """Bytes of the fp32 scale side channel for a quantised payload of
    ``num_elements`` (one scale per SCALE_CHUNK_ELEMS-element chunk)."""
    return -(-num_elements // SCALE_CHUNK_ELEMS) * SCALE_ITEM_BYTES


def padded_elems(num_elements: int) -> int:
    """Elements actually on the wire for a quantised payload of
    ``num_elements``: quantize_chunked zero-pads each payload to a
    SCALE_CHUNK_ELEMS multiple, and the padding travels — an analytic
    model that ignored it would undercount small/misaligned payloads
    and reject correct implementations against their own ceiling."""
    return -(-num_elements // SCALE_CHUNK_ELEMS) * SCALE_CHUNK_ELEMS

# Registry op -> allowed HLO collective kinds, and the kind that MUST
# appear at least once (the op's defining primitive).
#
# The SPMD encodings (comm/ops.py) compose every root-rooted MPI op from
# symmetric collectives, so e.g. broadcast/scatter/reduce legitimately
# lower to all-reduce (psum of a masked contribution), and gather (like
# allgather) to all-gather.  "prod" allreduce is the one all-gather-based
# reduction (no pprod primitive) — the registry default is "sum" so the
# audit pins all-reduce.
OP_EXPECTED_KINDS: dict[str, dict] = {
    "allreduce": {"required": "all-reduce", "allowed": {"all-reduce"}},
    "allreduce_hierarchical": {
        # one psum per mesh axis: >= 2 all-reduce instructions on a
        # multi-axis mesh
        "required": "all-reduce", "allowed": {"all-reduce"},
        "min_required": 2,
    },
    "allgather": {"required": "all-gather", "allowed": {"all-gather"}},
    "broadcast": {"required": "all-reduce", "allowed": {"all-reduce"}},
    "gather": {"required": "all-gather", "allowed": {"all-gather"}},
    "scatter": {"required": "all-reduce", "allowed": {"all-reduce"}},
    "reduce": {"required": "all-reduce", "allowed": {"all-reduce"}},
    "alltoall": {"required": "all-to-all", "allowed": {"all-to-all"}},
    "sendrecv": {
        "required": "collective-permute", "allowed": {"collective-permute"},
    },
    "reducescatter": {
        "required": "reduce-scatter",
        # XLA CPU sometimes legalises psum_scatter to all-reduce + slice
        # (semantically identical, 2x wire volume); accept either lowering
        # but require one of the two.
        "allowed": {"reduce-scatter", "all-reduce"},
        "required_any": {"reduce-scatter", "all-reduce"},
    },
    "barrier": {"required": "all-reduce", "allowed": {"all-reduce"}},
    # Collective-matmul micro-ops, FUSED schedule (the registry default).
    # The decomposed ring/bidir schedules are audited via
    # ``overlap_op_expectation`` below — they must contain the
    # collective-permute chain and NOTHING else.
    "ag_matmul": {"required": "all-gather", "allowed": {"all-gather"}},
    "matmul_rs": {
        "required": "reduce-scatter",
        # same CPU legalisation latitude as `reducescatter`: psum_scatter
        # may lower to all-reduce + slice
        "allowed": {"reduce-scatter", "all-reduce"},
        "required_any": {"reduce-scatter", "all-reduce"},
    },
}

# Parallelism axis -> collective kinds that axis may introduce.
#
# tp additionally allows collective-permute: the fused-QKV kernel shards
# its packed [H + 2*kv*d] output dim over tp, and the q/k/v (and
# simplified-attention) slice boundaries do not align with the shard
# boundaries, so GSPMD realigns with neighbour collective-permutes of
# activation size (verified against the compiled HLO of the tiny TP
# forward; an audit finding only if they exceed the activation-byte
# ceiling).  The tripwire for TP mis-sharding remains all-gather: a
# weight-sized gather means the Megatron layout collapsed to replication.
AXIS_EXPECTED_KINDS: dict[str, set[str]] = {
    "dp": {"all-reduce", "reduce-scatter", "all-gather"},  # DDP / ZeRO
    "tp": {"all-reduce", "collective-permute"},  # row psum + QKV realign
    # tp with the overlapped collective-matmul schedule
    # (model.tp_overlap = ring|bidir): every projection's collective is a
    # ppermute chain; the ONLY legitimate all-gather is the single
    # activation-sized reshard back to the caller's batch layout after the
    # final layernorm.  all-reduce is deliberately absent — a surviving
    # all-reduce means the decomposition collapsed back to the fused
    # lowering.
    "tp_overlap": {"collective-permute", "all-gather"},
    "sp_ring": {"collective-permute"},                      # ring attention
    "sp_ulysses": {"all-to-all"},                           # Ulysses resharding
    "pp": {"collective-permute", "all-reduce"},             # hops + masked psum
    "ep": {"all-reduce"},                                   # expert combine psum
    # dp with quantised gradient reduction (training.grad_compression):
    # ppermute ring + wire-dtype all-gather; all-reduce only for the
    # scalar loss mean (byte-bounded by the total-wire ceiling)
    "dp_compressed": {"collective-permute", "all-gather", "all-reduce"},
}


def plan_expected_kinds(dp: int = 1, tp: int = 1, sp: int = 1, pp: int = 1,
                        ep: int = 1, attention: str = "full",
                        zero_stage: int = 0,
                        tp_overlap: str = "off",
                        compression: str = "none",
                        decode: bool = False) -> set[str]:
    """The union of collective kinds a (plan, attention, ZeRO stage,
    tp_overlap schedule, grad-compression mode) combination is allowed to
    lower to.  Anything else in the compiled module — most importantly an
    all-gather in a plain TP forward, or a surviving all-reduce in an
    overlapped one — is a sharding mismatch.

    ``decode=True`` is the serving inference step (decode AND the prefill
    cache-append step, ``dlbb_tpu/serve/engine.py``): there is no
    gradient reduction, so dp — pure batch parallelism over the cache
    slots — contributes NOTHING, and the only legal collectives are tp's
    tiny per-token row-parallel psums + QKV realignment permutes.  The
    KV-cache itself must never reach the wire; the serving audit targets
    pair this set with an activation-sized byte ceiling, so a cache
    regather (slot-cache-sized all-gather) fails on BOTH axes."""
    if decode:
        if sp > 1 or pp > 1 or ep > 1:
            raise ValueError(
                "decode=True models the serving step, which runs on "
                f"(dp, tp) meshes only (got sp={sp}, pp={pp}, ep={ep})"
            )
        return set(AXIS_EXPECTED_KINDS["tp"]) if tp > 1 else set()
    kinds: set[str] = set()
    if dp > 1:
        if compression not in (None, "none"):
            # quantised gradient reduction (docs/compression.md): the dp
            # reduction is a collective-permute ring + a wire-dtype
            # all-gather.  all-reduce stays allowed for the scalar loss
            # mean ONLY — a gradient-sized all-reduce surviving here blows
            # the total-wire ceiling (max_total_wire_bytes), which is the
            # gate proving XLA did not dequantise before the collective.
            kinds |= AXIS_EXPECTED_KINDS["dp_compressed"]
        else:
            kinds |= ({"all-reduce"} if zero_stage == 0
                      else AXIS_EXPECTED_KINDS["dp"])
    if tp > 1:
        kinds |= AXIS_EXPECTED_KINDS[
            "tp_overlap" if tp_overlap != "off" else "tp"
        ]
    if sp > 1:
        kinds |= AXIS_EXPECTED_KINDS[
            "sp_ring" if attention == "ring" else "sp_ulysses"
        ]
    if pp > 1:
        kinds |= AXIS_EXPECTED_KINDS["pp"]
    if ep > 1:
        kinds |= AXIS_EXPECTED_KINDS["ep"]
    return kinds


def wire_bytes(kind: str, result_bytes: int, group_size: Optional[int]) -> int:
    """Analytic per-device wire volume of the standard ring algorithm for
    ``kind``, given the instruction's per-device result bytes.

    all-reduce: 2(P-1)/P x buffer (reduce-scatter + all-gather phases);
    all-gather: result is the gathered buffer, each device receives the
    (P-1)/P of it produced elsewhere; reduce-scatter: mirrors all-gather
    with the roles of operand/result swapped — the wire carries (P-1) x
    the scattered shard; all-to-all: (P-1)/P of the slab changes device;
    collective-permute: the whole buffer moves once.
    """
    p = group_size or 1
    if p <= 1:
        return 0
    if kind == "all-reduce":
        return int(2 * (p - 1) / p * result_bytes)
    if kind == "all-gather":
        return int((p - 1) / p * result_bytes)
    if kind == "reduce-scatter":
        return int((p - 1) * result_bytes)
    if kind == "all-to-all":
        return int((p - 1) / p * result_bytes)
    if kind == "collective-permute":
        return int(result_bytes)
    return int(result_bytes)


@dataclass
class TargetExpectation:
    """The audit contract for one lowered computation.

    allowed:            collective kinds that may appear.
    required_any:       at least one instruction of one of these kinds must
                        appear (None = nothing required, e.g. a pure-local
                        computation that must stay communication-free).
    min_required:       minimum number of instructions among required_any.
    max_bytes_per_instr: per-device result-byte ceiling per instruction
                        (None = unchecked); catches "oversized" collectives
                        such as a full-parameter all-gather where only an
                        activation-sized transfer is planned.
    max_total_wire_bytes: ceiling on the SUM of analytic per-device wire
                        bytes (``wire_bytes``) over every collective in the
                        module (None = unchecked).  The compressed-
                        collective gate: a quantised reduction that XLA
                        secretly dequantised back to bf16 moves ~2x the
                        wire and blows this ceiling even when every
                        individual instruction looks plausible.
    expect_donation:    the computation must donate at least one input
                        buffer (train-step convention — without it XLA
                        keeps input and output state resident).
    expect_overlap:     the target claims its collectives are hidden
                        behind compute (the ring-decomposed collective-
                        matmul schedules): the schedule auditor emits a
                        ``serialized-collective`` error for every ring
                        hop with no straddling matmul
                        (``schedule_audit.analyze_schedule``).
    max_peak_bytes:     per-device ceiling on the program's audited
                        ``peak_live_bytes`` (the buffer-liveness pass,
                        ``memory_audit.py``; None = unchecked).  Seeded
                        from analytic model/cache sizes with slack —
                        the byte-ceiling's whole-program twin: a
                        replicated state pytree or an undonated carry
                        blows it even when every wire instruction looks
                        right.
    policy_dtype:       the target's declared compute/storage dtype in
                        HLO terms ("f32" / "bf16" / "f16"; None = no
                        declared policy).  The numerics auditor's
                        anchor (``numerics_audit.py``): under a low
                        policy, sizeable f32 collectives / while
                        carries are ``silent-upcast``; params or
                        accumulators BELOW policy precision (or any
                        f64) are ``policy-conformance``.  Derive it
                        from ``ModelConfig.dtype`` with
                        :func:`policy_dtype_for` so the declared policy
                        can never drift from the model config the
                        target actually built.
    expect_bitwise_reproducible: the target claims bitwise-identical
                        results across runs/topologies.  Any fp
                        add-reduction on the wire (all-reduce /
                        reduce-scatter) makes that claim unsound —
                        the reduction order is backend-scheduled —
                        so the numerics auditor errors
                        (``nondeterministic-reduction``).  Off by
                        default: no benchmark target claims it; the
                        count is still recorded per target.
    donated_bytes_expected: analytic per-device bytes the program's
                        donated input buffers must sum to, within
                        ``donated_bytes_tolerance`` (relative).  The
                        serving cross-check: the decode step's donated
                        cache carry must agree with
                        ``models.configs.kv_cache_bytes_per_device`` —
                        the same number ``validate_serving``'s HBM
                        budget gate prices — so the build-time
                        rejection can never drift from what XLA
                        actually allocates (``serving-cache-drift``).
    """

    allowed: set[str] = field(default_factory=set)
    required_any: Optional[set[str]] = None
    min_required: int = 1
    max_bytes_per_instr: Optional[int] = None
    max_total_wire_bytes: Optional[int] = None
    expect_donation: bool = False
    expect_overlap: bool = False
    max_peak_bytes: Optional[int] = None
    donated_bytes_expected: Optional[int] = None
    donated_bytes_tolerance: float = 0.10
    policy_dtype: Optional[str] = None
    expect_bitwise_reproducible: bool = False


# ``ModelConfig.dtype`` / numpy-style dtype name -> HLO element type, the
# translation every audit target uses to declare its precision policy
_HLO_POLICY_DTYPE = {
    "float32": "f32", "bfloat16": "bf16", "float16": "f16",
    "float64": "f64",
    "f32": "f32", "bf16": "bf16", "f16": "f16", "f64": "f64",
}


def policy_dtype_for(dtype: str) -> str:
    """The HLO element type a ``ModelConfig.dtype`` string declares —
    the single translation point between model configs and the numerics
    auditor's ``policy_dtype``."""
    try:
        return _HLO_POLICY_DTYPE[dtype]
    except KeyError:
        raise ValueError(
            f"no HLO policy dtype for {dtype!r}; known: "
            f"{sorted(_HLO_POLICY_DTYPE)}"
        ) from None


def op_expectation(op_name: str, payload_bytes_per_rank: int,
                   slack: float = 1.25) -> TargetExpectation:
    """Expectation for one ``comm/ops.py`` registry op.

    ``payload_bytes_per_rank`` is the per-rank buffer size; the byte
    ceiling allows ``slack`` headroom over the worst-case legitimate
    instruction (the gathered [P, n] result for gather-family ops is
    handled by callers passing the global payload size).
    """
    spec = OP_EXPECTED_KINDS[op_name]
    required_any = spec.get("required_any")
    if required_any is None:
        required_any = {spec["required"]}
    return TargetExpectation(
        allowed=set(spec["allowed"]),
        required_any=set(required_any),
        min_required=spec.get("min_required", 1),
        max_bytes_per_instr=int(payload_bytes_per_rank * slack),
        # registry micro-op payloads are f32 (comm/ops.py make_payload)
        policy_dtype="f32",
    )


# Analytic per-device wire bytes of each registry op's IMPLEMENTATION
# (comm/ops.py SPMD encodings — e.g. broadcast is a psum of a masked
# contribution, so its wire is an all-reduce's, not a tree broadcast's).
# ``n`` is the op's per-rank element count (the [P, n] row / the [P, n]
# slab row for per_peer ops), ``p`` the rank count, ``b`` the payload
# element bytes.  Pinned against the registry by tests/test_compression.py.
def op_wire_bytes(op_name: str, num_elements: int, num_ranks: int,
                  elem_bytes: int,
                  compression: Optional[str] = None) -> Optional[int]:
    """Per-device analytic wire bytes for one registry op, or None for
    ops without a wire model (the collective-matmul micro-ops, whose
    wire depends on the schedule).  For the compressed ops the model
    includes the fp32 scale side channel; ``compression`` defaults to
    the op's default (int8)."""
    n, p, b = num_elements, num_ranks, elem_bytes
    if p <= 1:
        return 0
    if op_name in ("allreduce", "allreduce_hierarchical", "broadcast",
                   "reduce", "barrier"):
        return int(2 * (p - 1) / p * n * b)
    if op_name in ("allgather", "gather", "alltoall"):
        return int((p - 1) * n * b)
    if op_name == "scatter":
        # psum-broadcast of the root's whole [P, n] slab, then local slice
        return int(2 * (p - 1) / p * p * n * b)
    if op_name == "sendrecv":
        return int(n * b)
    if op_name == "reducescatter":
        return int((p - 1) * n * b)
    if op_name in ("allreduce_q", "reducescatter_q"):
        # quantised payloads travel chunk-padded (padded_elems), scale
        # side channel included
        w = COMPRESSED_WIRE_ITEM_BYTES[compression or "int8"]
        if op_name == "reducescatter_q":
            # ring phase only: (P-1) hops of one quantised row + scales
            return (p - 1) * (padded_elems(n) * w + scale_bytes(n))
        # ring reduce-scatter of ceil(n/P)-element chunks, then the
        # all-gather of the quantised reduced chunks (+ scale gathers)
        c = -(-n // p)
        ring = (p - 1) * (padded_elems(c) * w + scale_bytes(c))
        gather = int(
            (p - 1) / p * p * (padded_elems(c) * w + scale_bytes(c)))
        return ring + gather
    return None


def compression_wire_ceiling(baseline_bytes: int, analytic_bytes: int,
                             ratio: float = 0.55,
                             slack: float = 1.1) -> int:
    """The one compression total-wire ceiling, shared by every compressed
    audit target (micro-ops AND the compressed train step — a contract
    change here moves all of them together): the ``ratio`` x uncompressed
    baseline contract, OR ``slack`` x the op's own padding-included
    analytic wire where compression cannot pay (small/misaligned
    payloads), whichever is larger."""
    return max(int(ratio * baseline_bytes), int(slack * analytic_bytes))


def compressed_op_expectation(op_name: str, p: int, num_elements: int,
                              compression: str = "int8",
                              baseline_elem_bytes: int = 2,
                              ratio: float = 0.55) -> TargetExpectation:
    """Expectation for a compressed registry op (``allreduce_q`` /
    ``reducescatter_q``): the lowered module must be the quantised ring —
    collective-permutes (plus, for allreduce_q, the wire-dtype all-gather
    phase) — and its TOTAL analytic wire volume, scale side channel
    included, must stay under ``ratio`` x the uncompressed bf16 wire of
    the op it replaces.  The total ceiling is what proves XLA did not
    dequantise before the collective: a bf16-wire ring moves ~2x and
    fails it even though its instruction kinds look right.

    At small/misaligned payloads the chunk padding + scale overhead can
    legitimately exceed ``ratio`` x baseline (compression only pays above
    ~SCALE_CHUNK_ELEMS elements per ring chunk), so the ceiling is the
    MAX of the ratio contract and 1.1x the op's own analytic wire
    (``op_wire_bytes``, padding included) — strict where compression is
    meaningful, never rejecting a correct ring where it is not."""
    w = COMPRESSED_WIRE_ITEM_BYTES[compression]
    if op_name == "allreduce_q":
        baseline = wire_bytes(
            "all-reduce", num_elements * baseline_elem_bytes, p)
        allowed = {"collective-permute", "all-gather"}
        # largest legitimate instruction: the quantised all-gather result
        # — P chunk-padded ring chunks
        max_instr = p * padded_elems(-(-num_elements // p)) * w
    elif op_name == "reducescatter_q":
        baseline = wire_bytes(
            "reduce-scatter", num_elements * baseline_elem_bytes, p)
        allowed = {"collective-permute"}
        max_instr = padded_elems(num_elements) * w
    else:
        raise ValueError(f"not a compressed registry op: {op_name!r}")
    analytic = op_wire_bytes(op_name, num_elements, p, baseline_elem_bytes,
                             compression=compression)
    return TargetExpectation(
        allowed=allowed,
        required_any={"collective-permute"},
        min_required=p - 1,
        # a dequantised bf16 instruction would be 2x the wire width and
        # trip this even before the total ceiling
        max_bytes_per_instr=int(
            max_instr * 1.25 + scale_bytes(num_elements) * p
        ),
        max_total_wire_bytes=compression_wire_ceiling(
            baseline, analytic, ratio=ratio),
        # the compressed micro-ops carry bf16 payloads (the baseline the
        # ratio contract is priced against) — the numerics pass verifies
        # nothing f32-sized crosses the quantised ring (the scale side
        # channel stays under its byte floor)
        policy_dtype="bf16",
    )


def decode_scan_expectation(dp: int, tp: int, k: int,
                            act_bytes: int,
                            slack: float = 1.25,
                            policy_dtype: Optional[str] = "f32",
                            ) -> TargetExpectation:
    """Expectation for the FUSED multi-step decode scan
    (``serve/engine.py::build_decode_fused``): the scan body may contain
    only the per-token tp collectives (``plan_expected_kinds(decode=
    True)``), and — execution-weighted through the scan's
    ``known_trip_count`` (the while-body pricing the schedule auditor
    already does) — the row-parallel psum must fire at least once per
    trip: ``min_required = k``.

    all-gather is additionally allowed for ONE structural artifact:
    XLA hoists the loop-invariant slot-lengths vector into the while
    carry, GSPMD shards the hoisted copy over dp, and the final
    lengths computation re-gathers it at the loop BOUNDARY — a single
    ``4 B x max_batch`` instruction, executed once per scan (verified
    against the compiled HLO; the engine already keeps lengths out of
    the live carry, which removed the per-trip gathers).  The ceiling
    still prices every instruction at ONE step's activation bytes, so
    a cache regather — ~8x the ceiling for even one layer's plane —
    fails the byte axis outright, and its trip-count-weighted wire
    lands far past the committed baseline's 1.10x ``analyze diff``
    gate."""
    return TargetExpectation(
        allowed=plan_expected_kinds(dp=dp, tp=tp, decode=True)
        | {"all-gather"},
        required_any={"all-reduce"},
        min_required=k,
        max_bytes_per_instr=int(act_bytes * slack),
        expect_donation=True,
        policy_dtype=policy_dtype,
    )


def verify_step_expectation(dp: int, tp: int, gamma: int,
                            act_bytes: int,
                            slack: float = 1.25,
                            policy_dtype: Optional[str] = "f32",
                            ) -> TargetExpectation:
    """Expectation for the speculative-decoding verify step
    (``serve/engine.py::build_verify_step``): the γ drafted tokens plus
    the carry token run through ONE batched ``[max_batch, γ+1, H]``
    target forward — so the lowered program is shaped exactly like a
    decode step whose activations are (γ+1) wide, NOT like γ+1
    sequential decode steps.

    Concretely: the kind set stays the per-token decode set (tp psums +
    QKV realign permutes; the same single boundary all-gather artifact
    the fused scan carries), ``min_required = 1`` — the row-parallel
    psum fires once per scanned layer, with NO per-draft-token trip
    weighting (a per-token re-verify loop would show up as a γ+1-trip
    while body, and its trip-weighted wire lands past the committed
    baseline's ``analyze diff`` gate) — and every instruction is capped
    at (γ+1) x one step's activation bytes.  The γ+1 one-hot cache
    appends must lower to collective-free elementwise selects, exactly
    like the decode step's single append: ``act_bytes`` is the ONE-step
    ceiling, so a cache regather trips the byte axis identically."""
    return TargetExpectation(
        allowed=plan_expected_kinds(dp=dp, tp=tp, decode=True)
        | {"all-gather"},
        required_any={"all-reduce"},
        min_required=1,
        max_bytes_per_instr=int(act_bytes * (gamma + 1) * slack),
        expect_donation=True,
        policy_dtype=policy_dtype,
    )


def compact_expectation() -> TargetExpectation:
    """Expectation for the slot-compaction gather/scatter jits
    (``serve/engine.py``): pure LOCAL data movement — the slot dim is
    unsharded (dp=1 is enforced at config validation), so the lowered
    program must contain ZERO collectives.  Any collective here means
    the repack crossed the wire and compaction cannot win."""
    return TargetExpectation(allowed=set(), required_any=None)


def overlap_op_expectation(p: int, chunk_bytes: int,
                           slack: float = 1.25) -> TargetExpectation:
    """Expectation for a RING-DECOMPOSED collective matmul (either op,
    either direction): the lowered program must be a pure
    collective-permute chain — at least ``p - 1`` hops (the unidirectional
    ring's count; the bidirectional all-gather ring splits the same count
    across two directions, the bidirectional reduce-scatter doubles it
    with half-sized messages), each carrying at most one travelling chunk
    (``chunk_bytes``) — and no fused collective may survive: an
    all-gather or reduce-scatter here means XLA undid the decomposition
    and the overlap claim is void."""
    return TargetExpectation(
        allowed={"collective-permute"},
        required_any={"collective-permute"},
        min_required=p - 1,
        max_bytes_per_instr=int(chunk_bytes * slack),
        expect_overlap=True,
        policy_dtype="f32",
    )
