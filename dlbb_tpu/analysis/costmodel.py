"""Versioned α–β / peak-FLOPs cost-model table.

The static schedule auditor (``schedule_audit.py``) prices every HLO
instruction with the classic Hockney α–β model: a collective moving ``w``
analytic wire bytes on link tier ``t`` costs ``α(t) + w / β(t)``
microseconds, a dense-compute instruction doing ``f`` FLOPs costs
``f / peak(t)``.  The table is deliberately small and **versioned**: the
numbers are seeds (they make the *relative* structure of a schedule —
what serialises with what — falsifiable, not the absolute walls), and
ROADMAP item 2 replaces them with coefficients fitted from sweep
artifacts.  Any change to the numbers must bump ``COST_MODEL_VERSION``:
committed schedule baselines (``stats/analysis/baselines/``) record the
version they were priced with, and ``analyze diff`` refuses to compare
across versions (re-snapshot instead).

Tier provenance:

- ``cpu-sim`` — the ``--simulate N`` host-process mesh.  "Links" are
  shared-memory copies (~10 GB/s sustained, ~1 µs wakeup); peak compute
  is a conservative single-core ~50 GFLOP/s.  This is the tier every CI
  baseline is priced with.
- ``tpu-v5lite`` — TPU v5e: ICI ~45 GB/s/direction per link, ~1 µs hop
  latency; bf16 peak 197 TFLOP/s (the round-1..3 chip rows measured
  ~175 TFLOP/s sustained on the 1B forward, consistent with this peak).
- ``tpu-v5lite-dcn`` — inter-slice data-center network, ~100 Gb/s and
  ~10 µs latency: the tier a multi-host pod's cross-slice collectives
  are priced with once the backend-matrix refactor (ROADMAP item 5)
  lands per-tier topology fingerprints.

This module must stay importable WITHOUT jax — the schedule auditor's
unit tests and the sweep manifest writer run backend-free.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Optional

COST_MODEL_VERSION = "cm1"

# the fitted model: coefficients regressed from the measured sweep
# corpus (dlbb_tpu/obs/{corpus,fit}.py) into a versioned DB under
# stats/analysis/costmodel_fit/ — resolve_tier("...", model=CM2_VERSION)
# loads the latest fit and prices with it
CM2_VERSION = "cm2"
KNOWN_MODELS = (COST_MODEL_VERSION, CM2_VERSION)

FIT_SCHEMA = "dlbb_costmodel_fit_v1"
DEFAULT_FIT_DIR = Path("stats/analysis/costmodel_fit")


@dataclass(frozen=True)
class CostTier:
    """One link + compute tier of the α–β table.

    alpha_us:           per-collective fixed latency (hop setup) in µs.
    beta_bytes_per_us:  sustained link bandwidth (bytes per µs == MB/s
                        divided by ~1.05; 1 GB/s == 1000 bytes/µs).
    peak_flops_per_us:  dense-compute peak (FLOPs per µs; 1 TFLOP/s ==
                        1e6 FLOPs/µs).
    gamma_dispatch_us:  per-dispatch host overhead (trace/launch/sync of
                        one jitted program) — 0 in cm1 (un-modelled, the
                        committed ~289x cpu-sim gap), fitted in cm2.
    hbm_bytes:          per-device memory capacity the tier's programs
                        must fit in (HBM on a real chip; a notional
                        per-fake-device share of host RAM on the sim
                        mesh).  0 = unknown/unchecked.  This is a
                        CAPACITY record, not a priced coefficient — it
                        feeds the memory auditor's ``hbm_headroom`` /
                        feasibility term (``memory_audit.py``, the
                        ``cli plan --auto`` pruning input), never a
                        µs prediction, so changing it does not bump
                        COST_MODEL_VERSION.
    version:            the cost model the numbers came from ("cm1"
                        analytic seeds, "cm2" fitted) — reports and
                        baselines record this, and diff gates refuse to
                        compare across it.
    fit:                fit metadata (coefficient CIs, residuals, sample
                        counts, fit_version) when version == "cm2".
    """

    name: str
    alpha_us: float
    beta_bytes_per_us: float
    peak_flops_per_us: float
    description: str = ""
    gamma_dispatch_us: float = 0.0
    hbm_bytes: float = 0.0
    version: str = COST_MODEL_VERSION
    fit: Optional[dict] = field(default=None, compare=False)


# version -> tier name -> CostTier.  Append-only: old versions stay so a
# baseline priced with them remains interpretable.
COST_MODELS: dict[str, dict[str, CostTier]] = {
    "cm1": {
        "cpu-sim": CostTier(
            name="cpu-sim",
            alpha_us=1.0,
            beta_bytes_per_us=10_000.0,      # ~10 GB/s shared-memory copy
            peak_flops_per_us=50_000.0,      # ~50 GFLOP/s single core
            hbm_bytes=2.0 * 2**30,           # ~2 GiB host-RAM share/device
            description="--simulate N host-process mesh (CI baseline tier)",
        ),
        "tpu-v5lite": CostTier(
            name="tpu-v5lite",
            alpha_us=1.0,
            beta_bytes_per_us=45_000.0,      # ~45 GB/s/dir ICI link
            peak_flops_per_us=197_000_000.0,  # 197 TFLOP/s bf16 peak
            hbm_bytes=16.0 * 2**30,          # 16 GiB HBM per v5e chip
            description="TPU v5e single slice, ICI ring",
        ),
        "tpu-v5lite-dcn": CostTier(
            name="tpu-v5lite-dcn",
            alpha_us=10.0,
            beta_bytes_per_us=12_500.0,      # ~100 Gb/s DCN
            peak_flops_per_us=197_000_000.0,
            hbm_bytes=16.0 * 2**30,
            description="TPU v5e cross-slice data-center network",
        ),
    },
}

DEFAULT_TIER = "cpu-sim"


def get_tier(name: Optional[str] = None,
             version: str = COST_MODEL_VERSION) -> CostTier:
    """Look up a tier in one model version; raises KeyError with the
    known names on a typo so the CLI error is actionable."""
    table = COST_MODELS.get(version)
    if table is None:
        raise KeyError(
            f"unknown cost-model version {version!r}; "
            f"known: {sorted(COST_MODELS)}"
        )
    tier = table.get(name or DEFAULT_TIER)
    if tier is None:
        raise KeyError(
            f"unknown cost-model tier {name!r}; known: {sorted(table)}"
        )
    return tier


def collective_cost_us(wire_bytes: int, tier: CostTier) -> float:
    """α + bytes/β: the Hockney cost of moving ``wire_bytes`` analytic
    wire bytes (``expectations.wire_bytes`` — per-device, the ring
    algorithm's multiplier already factored in) over one tier."""
    return tier.alpha_us + wire_bytes / tier.beta_bytes_per_us


def compute_cost_us(flops: int, tier: CostTier) -> float:
    """FLOPs / peak: dense-compute time at the tier's peak throughput."""
    return flops / tier.peak_flops_per_us


def dispatch_cost_us(dispatch_count: int, tier: CostTier) -> float:
    """γ x dispatches: the host-side cost of launching ``dispatch_count``
    jitted programs — the term cm1 omits (γ = 0) and cm2 fits.  A wall
    prediction for one program execution is ``critical_path_us +
    dispatch_cost_us(1, tier)``."""
    return dispatch_count * tier.gamma_dispatch_us


def hbm_headroom_bytes(peak_bytes: int, tier: CostTier) -> Optional[int]:
    """Per-device memory headroom of a program whose audited
    ``peak_live_bytes`` is ``peak_bytes`` on ``tier`` — the feasibility
    term of the target report (``memory_audit.py``): a plan point with
    negative headroom OOMs before its α–β time matters, so the future
    ``cli plan --auto`` search prunes it statically instead of
    measuring through the failure.  None when the tier records no
    capacity."""
    if not tier.hbm_bytes:
        return None
    return int(tier.hbm_bytes) - int(peak_bytes)


def memory_feasible(peak_bytes: int, tier: CostTier) -> Optional[bool]:
    """Whether a program with the given audited peak fits the tier's
    per-device memory (None = the tier records no capacity)."""
    headroom = hbm_headroom_bytes(peak_bytes, tier)
    return None if headroom is None else headroom >= 0


# ---------------------------------------------------------------------------
# cm2: the fitted model (DB under stats/analysis/costmodel_fit/)
# ---------------------------------------------------------------------------


class FitMissingError(FileNotFoundError):
    """cm2 was requested but no fitted DB exists for the tier."""


def fit_db_path(tier: str,
                directory: "Optional[str | Path]" = None) -> Path:
    return Path(directory or DEFAULT_FIT_DIR) / f"{CM2_VERSION}_{tier}.json"


def load_fitted_tier(
    name: str,
    directory: "Optional[str | Path]" = None,
    fit_version: Optional[int] = None,
) -> CostTier:
    """The cm2 pricing tier: cm1's analytic seed overlaid with the
    latest (or a pinned ``fit_version``) coefficients from the fitted
    DB.  Raises :class:`FitMissingError` when no DB exists — callers
    decide whether that falls back (``resolve_tier``) or fails."""
    cm1 = get_tier(name)  # validates the tier name first
    path = fit_db_path(name, directory)
    if not path.exists():
        raise FitMissingError(
            f"no fitted cm2 DB for tier {name!r} at {path} — run "
            "`python -m dlbb_tpu.cli obs fit --results results` and "
            "commit the DB (docs/observability.md)"
        )
    db = json.loads(path.read_text())
    versions = db.get("versions") or []
    if not versions:
        raise FitMissingError(f"fitted DB {path} holds no versions")
    if fit_version is not None:
        matches = [v for v in versions
                   if v.get("fit_version") == fit_version]
        if not matches:
            raise FitMissingError(
                f"fitted DB {path} has no fit_version {fit_version} "
                f"(latest: {versions[-1].get('fit_version')})"
            )
        entry = matches[0]
    else:
        entry = versions[-1]
    coeff = entry["coefficients"]

    def _v(key: str, fallback: float) -> float:
        c = coeff.get(key)
        if isinstance(c, dict) and isinstance(c.get("value"), (int, float)):
            return float(c["value"])
        return fallback

    meta: dict[str, Any] = {
        "fit_version": entry.get("fit_version"),
        "fitted_at": entry.get("fitted_at"),
        "db_path": str(path),
        "samples_used": entry.get("samples_used"),
        "residuals": entry.get("residuals"),
        "coefficients": coeff,
        "alpha_pinned": entry.get("alpha_pinned"),
        "peak_pinned": entry.get("peak_pinned"),
    }
    return CostTier(
        name=name,
        alpha_us=_v("alpha_us", cm1.alpha_us),
        beta_bytes_per_us=_v("beta_bytes_per_us", cm1.beta_bytes_per_us),
        peak_flops_per_us=_v("peak_flops_per_us", cm1.peak_flops_per_us),
        gamma_dispatch_us=_v("gamma_dispatch_us", 0.0),
        hbm_bytes=cm1.hbm_bytes,  # capacity record, never fitted
        description=(f"fitted from the sweep corpus "
                     f"(fit v{entry.get('fit_version')}); "
                     f"seed: {cm1.description}"),
        version=CM2_VERSION,
        fit=meta,
    )


def resolve_tier(
    name: Optional[str] = None,
    model: str = COST_MODEL_VERSION,
    fit_dir: "Optional[str | Path]" = None,
    warn: bool = True,
) -> CostTier:
    """The one model-selection entry point (``--model cm1|cm2`` flows
    here from the schedule auditor, ``obs calibrate`` and ``obs
    attribute``).  cm2 with no committed fit falls back to the cm1
    analytic constants with a LOUD warning — the returned tier's
    ``version`` stays "cm1" so every report records which model actually
    priced it."""
    if model not in KNOWN_MODELS:
        raise KeyError(
            f"unknown cost model {model!r}; known: {list(KNOWN_MODELS)}"
        )
    if model == COST_MODEL_VERSION:
        return get_tier(name)
    try:
        return load_fitted_tier(name or DEFAULT_TIER, fit_dir)
    except FitMissingError as e:
        if warn:
            print(f"[costmodel] WARNING: fit-missing — {e}; "
                  "falling back to cm1 analytic constants")
        return get_tier(name)
