"""Versioned α–β / peak-FLOPs cost-model table.

The static schedule auditor (``schedule_audit.py``) prices every HLO
instruction with the classic Hockney α–β model: a collective moving ``w``
analytic wire bytes on link tier ``t`` costs ``α(t) + w / β(t)``
microseconds, a dense-compute instruction doing ``f`` FLOPs costs
``f / peak(t)``.  The table is deliberately small and **versioned**: the
numbers are seeds (they make the *relative* structure of a schedule —
what serialises with what — falsifiable, not the absolute walls), and
ROADMAP item 2 replaces them with coefficients fitted from sweep
artifacts.  Any change to the numbers must bump ``COST_MODEL_VERSION``:
committed schedule baselines (``stats/analysis/baselines/``) record the
version they were priced with, and ``analyze diff`` refuses to compare
across versions (re-snapshot instead).

Tier provenance:

- ``cpu-sim`` — the ``--simulate N`` host-process mesh.  "Links" are
  shared-memory copies (~10 GB/s sustained, ~1 µs wakeup); peak compute
  is a conservative single-core ~50 GFLOP/s.  This is the tier every CI
  baseline is priced with.
- ``tpu-v5lite`` — TPU v5e: ICI ~45 GB/s/direction per link, ~1 µs hop
  latency; bf16 peak 197 TFLOP/s (the round-1..3 chip rows measured
  ~175 TFLOP/s sustained on the 1B forward, consistent with this peak).
- ``tpu-v5lite-dcn`` — inter-slice data-center network, ~100 Gb/s and
  ~10 µs latency: the tier a multi-host pod's cross-slice collectives
  are priced with once the backend-matrix refactor (ROADMAP item 5)
  lands per-tier topology fingerprints.

This module must stay importable WITHOUT jax — the schedule auditor's
unit tests and the sweep manifest writer run backend-free.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

COST_MODEL_VERSION = "cm1"


@dataclass(frozen=True)
class CostTier:
    """One link + compute tier of the α–β table.

    alpha_us:           per-collective fixed latency (hop setup) in µs.
    beta_bytes_per_us:  sustained link bandwidth (bytes per µs == MB/s
                        divided by ~1.05; 1 GB/s == 1000 bytes/µs).
    peak_flops_per_us:  dense-compute peak (FLOPs per µs; 1 TFLOP/s ==
                        1e6 FLOPs/µs).
    """

    name: str
    alpha_us: float
    beta_bytes_per_us: float
    peak_flops_per_us: float
    description: str = ""


# version -> tier name -> CostTier.  Append-only: old versions stay so a
# baseline priced with them remains interpretable.
COST_MODELS: dict[str, dict[str, CostTier]] = {
    "cm1": {
        "cpu-sim": CostTier(
            name="cpu-sim",
            alpha_us=1.0,
            beta_bytes_per_us=10_000.0,      # ~10 GB/s shared-memory copy
            peak_flops_per_us=50_000.0,      # ~50 GFLOP/s single core
            description="--simulate N host-process mesh (CI baseline tier)",
        ),
        "tpu-v5lite": CostTier(
            name="tpu-v5lite",
            alpha_us=1.0,
            beta_bytes_per_us=45_000.0,      # ~45 GB/s/dir ICI link
            peak_flops_per_us=197_000_000.0,  # 197 TFLOP/s bf16 peak
            description="TPU v5e single slice, ICI ring",
        ),
        "tpu-v5lite-dcn": CostTier(
            name="tpu-v5lite-dcn",
            alpha_us=10.0,
            beta_bytes_per_us=12_500.0,      # ~100 Gb/s DCN
            peak_flops_per_us=197_000_000.0,
            description="TPU v5e cross-slice data-center network",
        ),
    },
}

DEFAULT_TIER = "cpu-sim"


def get_tier(name: Optional[str] = None,
             version: str = COST_MODEL_VERSION) -> CostTier:
    """Look up a tier in one model version; raises KeyError with the
    known names on a typo so the CLI error is actionable."""
    table = COST_MODELS.get(version)
    if table is None:
        raise KeyError(
            f"unknown cost-model version {version!r}; "
            f"known: {sorted(COST_MODELS)}"
        )
    tier = table.get(name or DEFAULT_TIER)
    if tier is None:
        raise KeyError(
            f"unknown cost-model tier {name!r}; known: {sorted(table)}"
        )
    return tier


def collective_cost_us(wire_bytes: int, tier: CostTier) -> float:
    """α + bytes/β: the Hockney cost of moving ``wire_bytes`` analytic
    wire bytes (``expectations.wire_bytes`` — per-device, the ring
    algorithm's multiplier already factored in) over one tier."""
    return tier.alpha_us + wire_bytes / tier.beta_bytes_per_us


def compute_cost_us(flops: int, tier: CostTier) -> float:
    """FLOPs / peak: dense-compute time at the tier's peak throughput."""
    return flops / tier.peak_flops_per_us
