"""Pass 5 — static numerics auditor (dtype flow / precision policy).

The byte auditor proves *what* a lowered program sends, the schedule
auditor *when*, the memory auditor *how much HBM* — this pass proves the
program computes in the *precision* its target declares.  Over the same
parsed post-SPMD module (``hlo_parse.parse_module``) it runs a dtype-flow
analysis: every accumulation site (``dot`` contractions, add-combiner
``reduce``), every collective payload, every ``convert``, every while
carry — including the instructions XLA moved into fusion bodies, reached
through ``hlo_parse.resolve_producers`` (bf16 accumulator arithmetic and
convert chains live almost exclusively there).

Error-bound model (docs/numerics.md): summing ``n`` terms in a dtype with
unit roundoff ``u`` (``u = 2^-p``, ``p`` = significand bits incl. the
hidden bit: f32 24, bf16 8, f16 11) bounds the result's relative error —
against ``sum(|x_i|)`` — by ``(n-1)·u`` for sequential accumulation and
``ceil(log2 n)·u`` for the tree order XLA actually emits.  A bf16
accumulator over n=4096 elements is therefore up to ``4095·2^-8 ≈ 16``
relative — total loss — where f32 stays ``< 2.5e-4``; the fp64 shadow
cross-check (``numerics_shadow.py``) replays flagged shapes empirically
against a float64 reference to confirm the bound is real, not
theoretical.

Rules (all findings carry the analytic details):

- ``low-precision-accumulation`` — a bf16/f16 accumulator on a dot or
  add-reduce over ``>= LOW_PRECISION_ACCUM_FLOOR`` elements, with the
  sequential and tree bounds per reduction shape.
- ``silent-upcast``       — under a declared bf16/f16 policy
  (``TargetExpectation.policy_dtype``), an f32/f64 tensor crossing a
  collective or resident in a while carry: doubled wire / HBM the plan
  never priced, reported in extra bytes against the memory auditor's
  ``peak_live_bytes`` when available.
- ``quantise-roundtrip``  — a dequantise (narrow->fp convert) feeding
  straight back into a quantise (fp->narrow convert) through nothing but
  scaling/layout ops: the roundtrip did no arithmetic work and only
  re-rounded.  The compression kernels' legitimate requantise always
  accumulates between the two (``comm/compression.py`` ring hops), and
  a select that merges another *live* data stream into the window (the
  int8 decode append overwriting the fresh token's K/V) is likewise
  real work — both abort the trace, so they stay clean.  A masking
  select against a constant fill is layout-only and keeps tracing.
- ``nondeterministic-reduction`` — an fp all-reduce / reduce-scatter
  whose replica-group reduction order is backend-scheduled: counted per
  target always (meta), an error only when the target claims bitwise
  reproducibility (``expect_bitwise_reproducible``).
- ``policy-conformance``  — params / activations / accumulators disagree
  with the declared ``ModelConfig`` precision policy: any f64 in the
  module, a sizeable parameter stored below policy precision, or a
  small accumulator below policy (large ones are
  ``low-precision-accumulation``'s job — f32 master copies / moments
  ABOVE a low policy are always legal mixed-precision practice and are
  priced by ``silent-upcast`` instead).
- ``convert-churn``       — redundant convert chains XLA failed to fold:
  an identity convert, or an ``A -> wider -> A`` roundtrip whose
  intermediate has no other consumer (a *narrowing* middle —
  ``f32 -> bf16 -> f32``, ``f32 -> s8 -> f32`` — is a deliberate
  precision clamp / quantisation-error probe and is never flagged).

Per-target meta feeds the committed baseline gate exactly like the
memory pass: ``numerics_low_precision_sites`` /
``numerics_convert_count`` / ``numerics_max_rel_error_bound`` fold into
the ``stats/analysis/baselines`` snapshots and ``analyze diff`` errors
on drift (``schedule_audit.diff_baselines``).

Pure text/graph analysis — importable WITHOUT jax (only the lowering in
``hlo_audit`` and the shadow cross-check need a backend).
"""

from __future__ import annotations

import json
import time
from math import ceil, log2, prod
from pathlib import Path
from typing import Any, Optional, Union

from dlbb_tpu.analysis.expectations import TargetExpectation
from dlbb_tpu.analysis.findings import (
    SEVERITY_ERROR,
    Finding,
)
from dlbb_tpu.analysis.hlo_parse import (
    _DTYPE_BYTES,
    _array_bytes,
    HloComputation,
    HloInstruction,
    HloModule,
    call_sites,
    parse_module,
    resolve_producers,
)

NUMERICS_REPORT_SCHEMA = "dlbb_numerics_audit_v1"
NUMERICS_REPORT_NAME = "numerics_audit.json"

# significand precision in bits, hidden bit included — unit roundoff is
# 2^-p (f32: 2^-24, bf16: 2^-8, f16: 2^-11)
SIGNIFICAND_BITS = {
    "f64": 53, "f32": 24, "f16": 11, "bf16": 8,
    "f8e4m3fn": 4, "f8e4m3": 4, "f8e5m2": 3,
}
LOW_PRECISION_DTYPES = ("bf16", "f16")
# wire dtypes of the quantised collectives (plus the fp8 arithmetic types
# before _to_wire's bitcast) — a convert to/from one of these is a
# quantise/dequantise edge for the roundtrip rule
QUANT_DTYPES = ("s8", "u8", "f8e4m3fn", "f8e4m3", "f8e5m2")

# an accumulation shorter than this is not worth a finding even in bf16
# (error bound < ~2 ulp of the result); every seeded fixture sits far
# above, every real add-reduce in the repo far below
LOW_PRECISION_ACCUM_FLOOR = 512
# f32 payloads under a bf16 policy smaller than this are side channels
# (quantisation scales, loss scalars) — legal mixed-precision practice
UPCAST_BYTES_FLOOR = 4096
# parameters below policy precision smaller than this are ignored
# (scalar epsilons, counters)
POLICY_BYTES_FLOOR = 1024

# ops a value passes through unchanged-enough for roundtrip tracing:
# unary layout/rounding ops follow their single operand; clamp follows
# its middle (data) operand; select follows both branches; binary
# arithmetic follows the strictly-larger operand (the smaller one is a
# broadcast scale/bias).  On an EQUAL-size pair, multiply/divide still
# pass (an elementwise scale — broadcast scales arrive full-size in
# optimised HLO) but add/subtract/max/min ABORT: an equal-size combine
# is real accumulation, the thing that makes a requantise legitimate
_PASS_UNARY = frozenset((
    "broadcast", "reshape", "bitcast", "bitcast-convert", "copy",
    "transpose", "slice", "pad", "negate", "abs", "floor", "ceil",
    "round-nearest-even", "round-nearest-afz",
))
_BIN_SCALE = frozenset((
    "multiply", "divide", "add", "subtract", "maximum", "minimum",
))
_BIN_PASS_EQUAL = frozenset(("multiply", "divide"))


def unit_roundoff(dtype: str) -> Optional[float]:
    """``2^-p`` for a known fp dtype, None otherwise."""
    bits = SIGNIFICAND_BITS.get(dtype)
    return 2.0 ** -bits if bits else None


def accumulation_error_bounds(n: int, dtype: str) -> tuple[float, float]:
    """(sequential, tree) worst-case relative error bounds — against
    ``sum(|x_i|)`` — for summing ``n`` terms in ``dtype``: ``(n-1)·u``
    and ``ceil(log2 n)·u`` (standard first-order floating summation
    analysis; Higham 2002 §4.2)."""
    u = unit_roundoff(dtype) or 0.0
    if n <= 1:
        return 0.0, 0.0
    return (n - 1) * u, ceil(log2(n)) * u


def _is_fp(dtype: Optional[str]) -> bool:
    return dtype in SIGNIFICAND_BITS


def _precision(dtype: str) -> int:
    return SIGNIFICAND_BITS.get(dtype, 0)


def _elems(shape: tuple[int, ...]) -> int:
    return int(prod(shape)) if shape else 1


def _loc(comp: HloComputation, instr: HloInstruction) -> str:
    loc = f"{comp.name}/%{instr.name}"
    if instr.source:
        loc += f" ({instr.source})"
    return loc


def _combiner_opcodes(module: HloModule, instr: HloInstruction) -> set[str]:
    """Opcodes of the instruction's ``to_apply`` region (reduce /
    all-reduce combiner) minus parameters — {"add"} for a sum."""
    ops: set[str] = set()
    for role, callee in instr.called:
        if role != "to_apply":
            continue
        comp = module.computations.get(callee)
        if comp is not None:
            ops |= {i.opcode for i in comp.instructions
                    if i.opcode != "parameter"}
    return ops


def _reduction_sites(module: HloModule) -> list[dict[str, Any]]:
    """Every fp accumulation in the module — dot contractions and
    add-combiner reduces, fusion bodies included — with the reduction
    length and both analytic error bounds."""
    sites: list[dict[str, Any]] = []
    for comp, instr in module.all_instructions():
        n = 0
        kind = None
        if instr.opcode == "dot" and _is_fp(instr.dtype):
            kind = "dot"
            if instr.operand_arrays:
                lhs_shape = instr.operand_arrays[0][1]
                n = int(prod(
                    lhs_shape[d] for d in instr.lhs_contracting_dims
                    if d < len(lhs_shape)
                )) if instr.lhs_contracting_dims else 1
        elif (instr.opcode == "reduce" and _is_fp(instr.dtype)
                and "add" in _combiner_opcodes(module, instr)):
            kind = "reduce"
            if instr.operand_arrays:
                n = _elems(instr.operand_arrays[0][1]) \
                    // max(_elems(instr.shape), 1)
        if kind is None or n <= 1:
            continue
        bound_seq, bound_tree = accumulation_error_bounds(n, instr.dtype)
        sites.append({
            "kind": kind,
            "dtype": instr.dtype,
            "elements": n,
            "bound_sequential": bound_seq,
            "bound_tree": bound_tree,
            "location": _loc(comp, instr),
            "op_name": instr.op_name,
            "execution_count": comp.execution_count,
        })
    return sites


def _data_operands(instr: HloInstruction) -> Optional[list[str]]:
    """The operand names a roundtrip trace may follow through ``instr``,
    or None when the op does real work (accumulation, contraction,
    communication) and the trace must abort."""
    op = instr.opcode
    if op in _PASS_UNARY:
        return list(instr.operands[:1])
    if op == "clamp":
        return [instr.operands[1]] if len(instr.operands) >= 2 else None
    # select is handled in _find_dequant (needs producer context to tell
    # a masking fill from a merge of two live data streams)
    if op in _BIN_SCALE:
        if len(instr.operand_arrays) >= 2:
            e0 = _elems(instr.operand_arrays[0][1])
            e1 = _elems(instr.operand_arrays[1][1])
            if e0 > e1:
                return [instr.operands[0]]
            if e1 > e0:
                return [instr.operands[1]]
            if op in _BIN_PASS_EQUAL:
                # elementwise scale: either side may carry the payload
                # (the scale path dead-ends at a constant/iota)
                return list(instr.operands[:2])
            return None  # equal-size combine: genuine accumulation
        return list(instr.operands[:1])
    return None


def _is_masking_fill(
    module: HloModule,
    comp: HloComputation,
    operand_name: str,
    sites: dict,
    max_steps: int = 16,
) -> bool:
    """True when ``%operand_name`` is a constant-like fill — a
    constant/iota, or a broadcast/layout chain over one.  A select with
    a fill on one side is a masking/padding op (layout-only); a select
    whose both sides carry computed data MERGES two live streams and is
    real arithmetic work.  Unresolvable producers count as live data
    (conservative: the merge aborts the roundtrip trace, and a masking
    fill is always resolvable — constants don't hide behind loop
    parameters)."""
    work = list(resolve_producers(module, comp, operand_name, sites))
    if not work:
        return False
    steps = 0
    while work and steps < max_steps:
        c, instr = work.pop()
        steps += 1
        if instr.opcode in ("constant", "iota"):
            continue
        if instr.opcode in _PASS_UNARY and instr.operands:
            nxt = resolve_producers(module, c, instr.operands[0], sites)
            if not nxt:
                return False
            work.extend(nxt)
            continue
        return False
    return not work  # ran out of steps with work left -> not provably a fill


def _find_dequant(
    module: HloModule,
    comp: HloComputation,
    quantise: HloInstruction,
    sites: dict,
    max_steps: int = 64,
) -> Optional[tuple[HloComputation, HloInstruction]]:
    """Walk backwards from a quantise convert through pass-through ops
    (crossing fusion boundaries); return the dequantise convert that
    feeds it with no arithmetic work in between, or None."""
    work: list[tuple[HloComputation, HloInstruction]] = []
    seen: set[tuple[str, str]] = set()

    def push(c: HloComputation, names: list[str]) -> None:
        for name in names:
            for c2, producer in resolve_producers(module, c, name, sites):
                work.append((c2, producer))

    push(comp, list(quantise.operands[:1]))
    steps = 0
    while work and steps < max_steps:
        c, instr = work.pop()
        steps += 1
        if (c.name, instr.name) in seen:
            continue
        seen.add((c.name, instr.name))
        if instr.opcode == "convert":
            src = instr.operand_arrays[0][0] if instr.operand_arrays else ""
            if src in QUANT_DTYPES and _is_fp(instr.dtype):
                return c, instr
            continue  # any other convert changes meaning: abort this path
        if instr.opcode == "select" and len(instr.operands) >= 3:
            # masking select (other side a constant fill): layout-only,
            # keep tracing through the data side.  Both sides live:
            # the select merges two data streams (e.g. the int8 decode
            # append writing the fresh token over the dequantised
            # window) — real work, abort this path.
            a, b = instr.operands[1], instr.operands[2]
            follow = [o for o, sib in ((a, b), (b, a))
                      if _is_masking_fill(module, c, sib, sites)]
            push(c, follow)
            continue
        follow = _data_operands(instr)
        if follow is None:
            continue
        push(c, follow)
    return None


def _consumer_counts(module: HloModule) -> dict[str, dict[str, int]]:
    """Per computation: instruction name -> number of operand references
    (how many times the value is consumed within its computation)."""
    counts: dict[str, dict[str, int]] = {}
    for comp in module.computations.values():
        c = counts.setdefault(comp.name, {})
        for instr in comp.instructions:
            for name in instr.operands:
                c[name] = c.get(name, 0) + 1
    return counts


def analyze_numerics(
    hlo: Union[str, HloModule],
    expectation: TargetExpectation,
    target: str,
    num_devices: int = 1,
    peak_live_bytes: Optional[int] = None,
    top_n: int = 8,
) -> tuple[list[Finding], dict[str, Any]]:
    """Audit one lowered module's dtype flow against its declared
    precision policy.  Returns (findings, meta); meta carries the
    baseline-gate keys (``numerics_*``) and the top-N reduction-site
    table the shadow cross-check replays."""
    module = parse_module(hlo) if isinstance(hlo, str) else hlo
    findings: list[Finding] = []
    sites_map = call_sites(module)
    policy = expectation.policy_dtype
    policy_prec = _precision(policy) if policy else 0

    fp_dtypes: set[str] = set()
    for _comp, instr in module.all_instructions():
        for d, _s in instr.arrays:
            if _is_fp(d):
                fp_dtypes.add(d)

    # -- accumulation sites: low-precision-accumulation + the error-bound
    #    meta the baseline gate and the fp64 shadow cross-check consume
    sites = _reduction_sites(module)
    low_precision_sites = 0
    max_bound_tree = 0.0
    max_elems = 0
    for site in sites:
        max_elems = max(max_elems, site["elements"])
        max_bound_tree = max(max_bound_tree, site["bound_tree"])
        if (site["dtype"] in LOW_PRECISION_DTYPES
                and site["elements"] >= LOW_PRECISION_ACCUM_FLOOR):
            low_precision_sites += 1
            n, dt = site["elements"], site["dtype"]
            findings.append(Finding(
                pass_name="numerics", rule="low-precision-accumulation",
                severity=SEVERITY_ERROR, target=target,
                message=(
                    f"{dt} accumulator on a {site['kind']} over {n} "
                    f"elements: worst-case relative error "
                    f"{site['bound_sequential']:.3g} sequential / "
                    f"{site['bound_tree']:.3g} tree "
                    f"(vs {accumulation_error_bounds(n, 'f32')[0]:.3g} "
                    "in f32) — accumulate in f32 "
                    "(preferred_element_type / an explicit upcast) and "
                    "round the result"
                ),
                location=site["location"],
                details=dict(site),
            ))

    # -- silent-upcast: f32/f64 where a bf16/f16 policy never priced it
    if policy in LOW_PRECISION_DTYPES:
        policy_bytes = _DTYPE_BYTES[policy]
        for comp, instr in module.all_instructions():
            if instr.kind and not instr.is_done:
                payload, dtype, shape = instr.collective_payload()
                if dtype in ("f32", "f64") and payload >= UPCAST_BYTES_FLOOR:
                    extra = payload - payload * policy_bytes \
                        // _DTYPE_BYTES[dtype]
                    findings.append(Finding(
                        pass_name="numerics", rule="silent-upcast",
                        severity=SEVERITY_ERROR, target=target,
                        message=(
                            f"{dtype} payload ({payload} B) crosses a "
                            f"{instr.kind} under a declared {policy} "
                            f"policy — {extra} B/device of wire per "
                            "execution the plan never priced; cast to "
                            f"{policy} before the collective or declare "
                            "the upcast in the expectation"
                        ),
                        location=_loc(comp, instr),
                        details={
                            "kind": instr.kind, "dtype": dtype,
                            "payload_bytes": payload,
                            "extra_bytes": extra,
                            "execution_count": comp.execution_count,
                        },
                    ))
            if instr.opcode == "while":
                for d, s in instr.arrays:
                    b = _array_bytes(d, s)
                    if d in ("f32", "f64") and b >= UPCAST_BYTES_FLOOR:
                        extra = b - b * policy_bytes // _DTYPE_BYTES[d]
                        details: dict[str, Any] = {
                            "dtype": d, "carry_bytes": b,
                            "extra_bytes": extra,
                        }
                        pct = ""
                        if peak_live_bytes:
                            details["peak_live_bytes"] = peak_live_bytes
                            pct = (f" ({extra / peak_live_bytes:.1%} of "
                                   "the audited peak_live_bytes)")
                        findings.append(Finding(
                            pass_name="numerics", rule="silent-upcast",
                            severity=SEVERITY_ERROR, target=target,
                            message=(
                                f"{d} while-carry element ({b} B) is "
                                "HBM-resident across every trip under a "
                                f"declared {policy} policy — {extra} B "
                                f"of unpriced state{pct}; carry the "
                                f"{policy} representation and upcast "
                                "inside the body"
                            ),
                            location=_loc(comp, instr),
                            details=details,
                        ))

    # -- quantise-roundtrip: dequantise feeding straight back into
    #    quantise with no arithmetic in between
    for comp, instr in module.all_instructions():
        if instr.opcode != "convert" or instr.dtype not in QUANT_DTYPES:
            continue
        src = instr.operand_arrays[0][0] if instr.operand_arrays else ""
        if not _is_fp(src):
            continue
        hit = _find_dequant(module, comp, instr, sites_map)
        if hit is not None:
            dq_comp, dq = hit
            findings.append(Finding(
                pass_name="numerics", rule="quantise-roundtrip",
                severity=SEVERITY_ERROR, target=target,
                message=(
                    f"dequantise ({dq.operand_arrays[0][0]} -> "
                    f"{dq.dtype} at {_loc(dq_comp, dq)}) feeds straight "
                    f"back into quantise ({src} -> {instr.dtype}) "
                    "through scaling/layout ops only — the roundtrip "
                    "does no arithmetic work and adds a rounding; keep "
                    "the wire representation across the hop"
                ),
                location=_loc(comp, instr),
                details={
                    "quantise": _loc(comp, instr),
                    "dequantise": _loc(dq_comp, dq),
                    "wire_dtype": instr.dtype,
                },
            ))

    # -- nondeterministic-reduction: fp reduction order on the wire
    nondet = 0
    for comp, instr in module.all_instructions():
        if instr.kind not in ("all-reduce", "reduce-scatter") \
                or instr.is_done:
            continue
        _payload, dtype, _shape = instr.collective_payload()
        combiner = _combiner_opcodes(module, instr)
        if _is_fp(dtype) and (instr.group_size or 0) > 1 \
                and ("add" in combiner or not combiner):
            nondet += 1
            if expectation.expect_bitwise_reproducible:
                findings.append(Finding(
                    pass_name="numerics",
                    rule="nondeterministic-reduction",
                    severity=SEVERITY_ERROR, target=target,
                    message=(
                        f"fp {dtype} {instr.kind} over "
                        f"{instr.group_size} replicas: the reduction "
                        "order is backend-scheduled, so results are not "
                        "bitwise reproducible across runs/topologies — "
                        "the target claims bitwise reproducibility "
                        "(expect_bitwise_reproducible); drop the claim "
                        "or reduce in integer/fixed-point"
                    ),
                    location=_loc(comp, instr),
                    details={
                        "kind": instr.kind, "dtype": dtype,
                        "group_size": instr.group_size,
                    },
                ))

    # -- policy-conformance: params / small accumulators / any f64
    if policy:
        f64_locs = [
            _loc(comp, instr)
            for comp, instr in module.all_instructions()
            if any(d == "f64" for d, _s in instr.arrays)
        ]
        if f64_locs:
            findings.append(Finding(
                pass_name="numerics", rule="policy-conformance",
                severity=SEVERITY_ERROR, target=target,
                message=(
                    f"{len(f64_locs)} f64 instruction(s) in a module "
                    f"whose declared policy is {policy} — a host-side "
                    "float64 literal / astype leaked into the jitted "
                    "program (see the float64-literal-in-jit lint); "
                    f"first: {f64_locs[0]}"
                ),
                location=f64_locs[0],
                details={"count": len(f64_locs),
                         "locations": f64_locs[:top_n]},
            ))
        entry = module.entry_computation()
        for instr in (entry.instructions if entry is not None else []):
            if instr.opcode != "parameter":
                continue
            for d, s in instr.arrays:
                b = _array_bytes(d, s)
                if (_is_fp(d) and _precision(d) < policy_prec
                        and b >= POLICY_BYTES_FLOOR):
                    findings.append(Finding(
                        pass_name="numerics", rule="policy-conformance",
                        severity=SEVERITY_ERROR, target=target,
                        message=(
                            f"parameter %{instr.name} stores {b} B as "
                            f"{d}, below the declared {policy} policy — "
                            "params/activations must carry at least "
                            "policy precision (f32 master copies above "
                            "a low policy are fine; storage below it "
                            "is silent quantisation)"
                        ),
                        location=_loc(entry, instr),
                        details={"dtype": d, "bytes": b,
                                 "policy": policy},
                    ))
        for site in sites:
            if (_precision(site["dtype"]) < policy_prec
                    and site["elements"] < LOW_PRECISION_ACCUM_FLOOR):
                findings.append(Finding(
                    pass_name="numerics", rule="policy-conformance",
                    severity=SEVERITY_ERROR, target=target,
                    message=(
                        f"{site['dtype']} accumulator on a "
                        f"{site['kind']} under a declared {policy} "
                        "policy (short reduction, "
                        f"n={site['elements']}) — accumulators must "
                        "carry at least policy precision"
                    ),
                    location=site["location"],
                    details=dict(site, policy=policy),
                ))

    # -- convert-churn: identity converts and widening roundtrips
    consumers = _consumer_counts(module)
    convert_count = 0
    for comp, instr in module.all_instructions():
        if instr.opcode != "convert":
            continue
        convert_count += max(comp.execution_count, 1)
        src = instr.operand_arrays[0][0] if instr.operand_arrays else None
        if src is None:
            continue
        if src == instr.dtype:
            findings.append(Finding(
                pass_name="numerics", rule="convert-churn",
                severity=SEVERITY_ERROR, target=target,
                message=(
                    f"identity convert {src} -> {instr.dtype}: a "
                    "dead cast XLA failed to fold"
                ),
                location=_loc(comp, instr),
                details={"chain": [src, instr.dtype]},
            ))
            continue
        for c2, inner in resolve_producers(
                module, comp, instr.operands[0], sites_map):
            if inner.opcode != "convert" or not inner.operand_arrays:
                continue
            gsrc = inner.operand_arrays[0][0]
            mid = inner.dtype
            if not (gsrc == instr.dtype and _is_fp(gsrc) and _is_fp(mid)
                    and _precision(mid) >= _precision(gsrc)):
                continue
            # a narrowing middle is a deliberate precision clamp; a
            # widening middle consumed elsewhere is a shared upcast —
            # only a single-use widening roundtrip is pure churn
            uses = consumers.get(c2.name, {}).get(inner.name, 0)
            if inner.is_root and not c2.is_entry:
                for caller, site in sites_map.get(c2.name, []):
                    uses += consumers.get(caller.name, {}) \
                        .get(site.name, 0)
            if uses > 1:
                continue
            findings.append(Finding(
                pass_name="numerics", rule="convert-churn",
                severity=SEVERITY_ERROR, target=target,
                message=(
                    f"redundant convert chain {gsrc} -> {mid} -> "
                    f"{instr.dtype}: the widening intermediate has no "
                    "other consumer, so the roundtrip is a no-op pair "
                    "of casts XLA failed to fold"
                ),
                location=_loc(comp, instr),
                details={"chain": [gsrc, mid, instr.dtype],
                         "intermediate": _loc(c2, inner)},
            ))

    sites_sorted = sorted(
        sites, key=lambda s: (s["bound_tree"], s["elements"]),
        reverse=True,
    )
    meta: dict[str, Any] = {
        "numerics_schema": NUMERICS_REPORT_SCHEMA,
        "policy_dtype": policy,
        "fp_dtypes": sorted(fp_dtypes),
        "reduction_sites": len(sites),
        "max_reduction_elems": max_elems,
        "nondeterministic_reductions": nondet,
        "numerics_low_precision_sites": low_precision_sites,
        "numerics_convert_count": convert_count,
        "numerics_max_rel_error_bound": max_bound_tree,
        "sites": sites_sorted[:top_n],
    }
    return findings, meta


# ---------------------------------------------------------------------------
# manifest / Prometheus surface (`analyze numerics --output DIR`)
# ---------------------------------------------------------------------------


def numerics_metrics(numerics: dict[str, dict], registry=None):
    """The numerics audit as Prometheus gauges — per-target worst error
    bound, low-precision site count and convert count, next to the
    memory/calibration gauges on the same scrape dashboard."""
    from dlbb_tpu.obs.export import MetricsRegistry

    registry = registry or MetricsRegistry()
    for target in sorted(numerics):
        meta = numerics[target]
        registry.set_gauge(
            "analysis_numerics_max_rel_error_bound",
            meta.get("numerics_max_rel_error_bound", 0.0),
            help="worst analytic tree-order accumulation error bound "
                 "(relative, vs sum|x_i|) over the target's fp "
                 "reduction sites",
            target=target,
        )
        registry.set_gauge(
            "analysis_numerics_low_precision_sites",
            meta.get("numerics_low_precision_sites", 0),
            help="bf16/f16 accumulation sites at or above the "
                 "LOW_PRECISION_ACCUM_FLOOR",
            target=target,
        )
        registry.set_gauge(
            "analysis_numerics_convert_count",
            meta.get("numerics_convert_count", 0),
            help="execution-weighted convert instructions in the "
                 "lowered module",
            target=target,
        )
    registry.set_gauge("analysis_numerics_targets", len(numerics),
                       help="targets the numerics audit covered")
    return registry


def write_numerics_artifacts(numerics: dict[str, dict],
                             out_dir: "str | Path") -> Path:
    """Write the per-target numerics report under ``out_dir`` and merge
    the aggregate into ``sweep_manifest.json`` + ``metrics.prom``
    without clobbering co-located exports (the memory auditor's
    convention)."""
    from dlbb_tpu.obs.calibration import METRICS_NAME, _fold_metrics
    from dlbb_tpu.utils.config import atomic_write_text, save_json

    out_dir = Path(out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    report = {
        "schema": NUMERICS_REPORT_SCHEMA,
        "targets": numerics,
        "timestamp": time.time(),
    }
    path = atomic_write_text(
        json.dumps(report, indent=2, sort_keys=True),
        out_dir / NUMERICS_REPORT_NAME,
    )

    from dlbb_tpu.bench.schedule import MANIFEST_NAME, MANIFEST_SCHEMA

    manifest_path = out_dir / MANIFEST_NAME
    manifest: dict[str, Any] = {"schema": MANIFEST_SCHEMA,
                                "kind": "numerics-audit"}
    if manifest_path.exists():
        try:
            manifest = json.loads(manifest_path.read_text())
        except (OSError, json.JSONDecodeError):
            pass  # torn/legacy manifest: rewrite with the audit only
    manifest["numerics_audit"] = {
        "targets_audited": len(numerics),
        "max_rel_error_bound": {
            t: numerics[t].get("numerics_max_rel_error_bound")
            for t in sorted(numerics)
        },
        "low_precision_sites": {
            t: numerics[t].get("numerics_low_precision_sites")
            for t in sorted(numerics)
        },
    }
    manifest.setdefault("timestamp", time.time())
    save_json(manifest, manifest_path)
    _fold_metrics(numerics_metrics(numerics), out_dir / METRICS_NAME)
    return path
