"""Parser for collective instructions in compiled (post-SPMD) HLO text.

The auditor reads ``jit(fn).lower(args).compile().as_text()`` — the
optimized HLO module *after* GSPMD partitioning — because that is where
XLA-inserted collectives live; the pre-partitioning StableHLO only shows
sharding annotations, not the all-gathers a sharding mismatch smuggles in.

Instruction grammar handled (CPU and TPU backends emit the same shapes):

    %all-reduce.1 = f32[1,256]{1,0} all-reduce(f32[1,256]{1,0} %p), \
        channel_id=1, replica_groups={{0,1,2,3,4,5,6,7}}, ..., \
        metadata={... source_file="..." source_line=96}
    ROOT %all-gather = f32[64,32]{1,0} all-gather(f32[8,32]{1,0} %dot), \
        channel_id=1, replica_groups=[1,8]<=[8], dimensions={0}, ...
    %collective-permute = ... , source_target_pairs={{0,1},{1,2}}

Both replica-group syntaxes are parsed: the explicit nested-brace list and
the iota form ``[groups,size]<=[n]``.  Async pairs count once: the
``-start`` op is parsed, the ``-done`` op is ignored.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from math import prod
from typing import Optional

COLLECTIVE_KINDS = (
    "all-reduce",
    "all-gather",
    "reduce-scatter",
    "collective-permute",
    "all-to-all",
)

# HLO primitive-type byte widths
_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "f8e4m3fn": 1, "f8e5m2": 1, "f8e4m3": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}

# the result type may be a variadic tuple with /*index=N*/ comments, so
# the type group matches lazily up to the first collective keyword that is
# directly followed by its operand paren
_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%[\w.\-]+\s*=\s*(?P<type>\(?[a-z0-9]+\[.+?)\s"
    r"(?P<kind>" + "|".join(COLLECTIVE_KINDS) + r")(?P<start>-start)?\("
)
_ARRAY_TYPE_RE = re.compile(r"([a-z0-9]+)\[([0-9,]*)\]")
_GROUPS_BRACE_RE = re.compile(r"replica_groups=\{(\{[^=]*?\})\}(?=[,\s)]|$)")
_GROUPS_IOTA_RE = re.compile(r"replica_groups=\[(\d+),(\d+)\]<=\[")
_PAIRS_RE = re.compile(r"source_target_pairs=\{(\{[^=]*?\})\}(?=[,\s)]|$)")
_META_RE = re.compile(r'source_file="([^"]+)"\s+source_line=(\d+)')


@dataclass
class CollectiveInstr:
    """One collective instruction in compiled HLO."""

    kind: str                       # one of COLLECTIVE_KINDS
    dtype: str                      # result element type (first array)
    shape: tuple[int, ...]          # result shape (first array)
    result_bytes: int               # summed over all result arrays
    replica_groups: str             # raw groups / pairs text
    group_count: Optional[int]
    group_size: Optional[int]
    source: Optional[str]           # "file:line" from HLO metadata
    raw: str = field(repr=False, default="")

    def to_dict(self) -> dict:
        return {
            "kind": self.kind,
            "dtype": self.dtype,
            "shape": list(self.shape),
            "result_bytes": self.result_bytes,
            "replica_groups": self.replica_groups,
            "group_count": self.group_count,
            "group_size": self.group_size,
            "source": self.source,
        }


def _parse_arrays(type_text: str) -> list[tuple[str, tuple[int, ...]]]:
    out = []
    for dtype, dims in _ARRAY_TYPE_RE.findall(type_text):
        shape = tuple(int(d) for d in dims.split(",")) if dims else ()
        out.append((dtype, shape))
    return out


def _array_bytes(dtype: str, shape: tuple[int, ...]) -> int:
    return _DTYPE_BYTES.get(dtype, 4) * int(prod(shape)) if shape else \
        _DTYPE_BYTES.get(dtype, 4)


def _parse_groups(line: str) -> tuple[str, Optional[int], Optional[int]]:
    m = _GROUPS_BRACE_RE.search(line)
    if m:
        groups = [g for g in m.group(1).split("},{")]
        sizes = {len([x for x in g.strip("{}").split(",") if x])
                 for g in groups}
        size = sizes.pop() if len(sizes) == 1 else None
        return "{" + m.group(1) + "}", len(groups), size
    m = _GROUPS_IOTA_RE.search(line)
    if m:
        count, size = int(m.group(1)), int(m.group(2))
        return line[m.start(): line.find("]", m.end()) + 1], count, size
    m = _PAIRS_RE.search(line)
    if m:
        pairs = m.group(1).count("},{") + 1
        return "{" + m.group(1) + "}", pairs, 2
    return "", None, None


def parse_collectives(hlo_text: str) -> list[CollectiveInstr]:
    """All collective instructions in an optimized-HLO module dump."""
    out = []
    for line in hlo_text.splitlines():
        m = _INSTR_RE.match(line)
        if m is None:
            continue
        arrays = _parse_arrays(m.group("type"))
        kind = m.group("kind")
        if m.group("start") and arrays:
            # async start ops return (operand, result, ...) scratch tuples;
            # the payload is the result array, whose size relative to the
            # operand depends on the kind: reduce-scatter shrinks by the
            # group size (result is the smallest element), all-gather grows
            # (largest), the rest are size-preserving (either extreme works)
            sizes = [_array_bytes(d, s) for d, s in arrays]
            pick = min if kind == "reduce-scatter" else max
            idx = sizes.index(pick(sizes))
            payload = sizes[idx]
            dtype, shape = arrays[idx]
        else:
            payload = sum(_array_bytes(d, s) for d, s in arrays)
            dtype, shape = arrays[0] if arrays else ("", ())
        groups, count, size = _parse_groups(line)
        meta = _META_RE.search(line)
        source = f"{meta.group(1)}:{meta.group(2)}" if meta else None
        out.append(CollectiveInstr(
            kind=m.group("kind"), dtype=dtype, shape=shape,
            result_bytes=payload, replica_groups=groups,
            group_count=count, group_size=size, source=source, raw=line,
        ))
    return out


def has_donation(lowered_text: str, compiled_text: str) -> bool:
    """True when the computation donates at least one input buffer:
    ``tf.aliasing_output``/``jax.buffer_donor`` arg attributes in the
    lowered StableHLO, or an ``input_output_alias`` table in the compiled
    module header."""
    return ("tf.aliasing_output" in lowered_text
            or "jax.buffer_donor" in lowered_text
            or "input_output_alias={ {" in compiled_text
            or "input_output_alias={{" in compiled_text)
