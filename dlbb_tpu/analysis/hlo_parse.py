"""Parser for compiled (post-SPMD) HLO text — instruction dependency graphs.

The auditors read ``jit(fn).lower(args).compile().as_text()`` — the
optimized HLO module *after* GSPMD partitioning — because that is where
XLA-inserted collectives live; the pre-partitioning StableHLO only shows
sharding annotations, not the all-gathers a sharding mismatch smuggles in.

Two layers:

- ``parse_module`` — the full instruction-dependency-graph parser: every
  computation (entry, while bodies/conditions, conditional branches, fused
  computations), every instruction with its operands, control
  predecessors, called computations, async ``-start``/``-done`` pairing,
  and the per-computation **execution count** (the product of enclosing
  ``while`` known trip counts — a collective inside a scanned layer body
  runs ``num_layers`` times, not once).  This is the substrate of the
  schedule auditor (``schedule_audit.py``).
- ``parse_collectives`` — the flat collective inventory the byte auditor
  consumes, now built on ``parse_module`` so collectives in while-loop
  bodies and nested computations carry their true ``execution_count``
  (the bug the old line-oriented parser had: scanned-ring bodies were
  charged one iteration of wire volume regardless of trip count).

Instruction grammar handled (CPU and TPU backends emit the same shapes):

    %all-reduce.1 = f32[1,256]{1,0} all-reduce(f32[1,256]{1,0} %p), \
        channel_id=1, replica_groups={{0,1,2,3,4,5,6,7}}, ..., \
        metadata={... source_file="..." source_line=96}
    ROOT %all-gather = f32[64,32]{1,0} all-gather(f32[8,32]{1,0} %dot), \
        channel_id=1, replica_groups=[1,8]<=[8], dimensions={0}, ...
    %collective-permute = ... , source_target_pairs={{0,1},{1,2}}
    %while.3 = (...) while((...) %tuple), condition=%cond, body=%body, \
        backend_config={"known_trip_count":{"n":"2"}}

Both replica-group syntaxes are parsed: the explicit nested-brace list and
the iota form ``[groups,size]<=[n]``.  Async pairs count once: the
``-start`` op carries the payload, the ``-done`` op is ignored by the
inventory (the graph keeps both, linked, for the overlap-window analysis).

This module must stay importable WITHOUT jax — the source lint and the
schedule auditor's unit tests run backend-free.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from math import prod
from typing import Iterator, Optional, Union

COLLECTIVE_KINDS = (
    "all-reduce",
    "all-gather",
    "reduce-scatter",
    "collective-permute",
    "all-to-all",
)

# HLO primitive-type byte widths
_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "f8e4m3fn": 1, "f8e5m2": 1, "f8e4m3": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}

_ARRAY_TYPE_RE = re.compile(r"([a-z0-9]+)\[([0-9,]*)\]")
_GROUPS_BRACE_RE = re.compile(r"replica_groups=\{(\{[^=]*?\})\}(?=[,\s)]|$)")
_GROUPS_IOTA_RE = re.compile(r"replica_groups=\[(\d+),(\d+)\]<=\[")
_PAIRS_RE = re.compile(r"source_target_pairs=\{(\{[^=]*?\})\}(?=[,\s)]|$)")
_META_RE = re.compile(r'source_file="([^"]+)"\s+source_line=(\d+)')
_OP_NAME_RE = re.compile(r'op_name="([^"]+)"')
_TRIP_COUNT_RE = re.compile(r'known_trip_count[^0-9]*"?n"?[^0-9]*(\d+)')
_CONTROL_RE = re.compile(r"control-predecessors=\{([^}]*)\}")
_OPERAND_NAME_RE = re.compile(r"%([\w.\-]+)")
_CONTRACT_RE = re.compile(r"lhs_contracting_dims=\{([0-9,]*)\}")

# computation header: ``%name (params) -> type {`` / ``ENTRY %main ... {``
_COMP_HEADER_RE = re.compile(
    r"^(?P<entry>ENTRY\s+)?%?(?P<name>[\w.\-]+)\s*(?:\(.*)?\{\s*$"
)
# the module header's donation table: ``input_output_alias={ {0}: (0, {},
# may-alias), {1}: (2, {}, must-alias) }`` — output tuple index path ->
# (parameter number, parameter index path, kind); the table span is cut
# with _balanced_span (its entries nest braces)
_ALIAS_ENTRY_RE = re.compile(
    r"\{(?P<out>[0-9,\s]*)\}:\s*\((?P<param>\d+),\s*\{(?P<pidx>[0-9,\s]*)\}"
)
_INSTR_START_RE = re.compile(
    r"^(?P<root>ROOT\s+)?%(?P<name>[\w.\-]+)\s*=\s*(?P<rest>.+)$"
)
# called-computation attributes and the role they play for scheduling
_CALL_ATTR_RE = re.compile(
    r"(?P<role>condition|body|calls|to_apply|true_computation|"
    r"false_computation|branch_computations)="
    r"(?:\{(?P<many>[^}]*)\}|%(?P<one>[\w.\-]+))"
)


# ---------------------------------------------------------------------------
# graph model
# ---------------------------------------------------------------------------


@dataclass
class HloInstruction:
    """One instruction in a parsed HLO computation."""

    name: str
    opcode: str                       # e.g. "dot", "all-gather-start"
    dtype: str                        # result element type (first array)
    shape: tuple[int, ...]            # result shape (first array)
    arrays: list[tuple[str, tuple[int, ...]]]  # all result arrays
    operands: tuple[str, ...]         # %names consumed (same computation)
    operand_arrays: list[tuple[str, tuple[int, ...]]]  # operand types
    control_deps: tuple[str, ...]     # control-predecessors
    called: tuple[tuple[str, str], ...]  # (role, computation name)
    is_root: bool = False
    raw: str = field(repr=False, default="")
    # collective decoration (kind is None for non-collectives)
    kind: Optional[str] = None        # base collective kind
    is_start: bool = False
    is_done: bool = False
    replica_groups: str = ""
    group_count: Optional[int] = None
    group_size: Optional[int] = None
    # metadata
    source: Optional[str] = None      # "file:line"
    op_name: Optional[str] = None     # jax name-stack, incl. named_scope
    trip_count: Optional[int] = None  # while only: known_trip_count
    lhs_contracting_dims: tuple[int, ...] = ()
    # "parameter" instructions only: the entry/computation parameter
    # number (``%p = f32[...] parameter(2)`` -> 2) — what the module's
    # input_output_alias table keys donated buffers by
    parameter_number: Optional[int] = None

    @property
    def result_bytes(self) -> int:
        return sum(_array_bytes(d, s) for d, s in self.arrays)

    def collective_payload(self) -> tuple[int, str, tuple[int, ...]]:
        """(payload bytes, dtype, shape) of a collective instruction.

        Async ``-start`` ops return (operand, result, ...) scratch tuples;
        the payload is the result array, whose size relative to the
        operand depends on the kind: reduce-scatter shrinks by the group
        size (result is the smallest element), all-gather grows (largest),
        the rest are size-preserving (either extreme works).
        """
        if self.is_start and self.arrays:
            sizes = [_array_bytes(d, s) for d, s in self.arrays]
            pick = min if self.kind == "reduce-scatter" else max
            idx = sizes.index(pick(sizes))
            dtype, shape = self.arrays[idx]
            return sizes[idx], dtype, shape
        payload = sum(_array_bytes(d, s) for d, s in self.arrays)
        dtype, shape = self.arrays[0] if self.arrays else ("", ())
        return payload, dtype, shape


@dataclass
class HloComputation:
    """One computation (entry, loop body/condition, branch, fusion)."""

    name: str
    is_entry: bool = False
    instructions: list[HloInstruction] = field(default_factory=list)
    # how many times this computation executes per module invocation:
    # product of enclosing while trip counts along the call chain (1 when
    # a trip count is unknown — the conservative floor)
    execution_count: int = 1

    def by_name(self) -> dict[str, HloInstruction]:
        return {i.name: i for i in self.instructions}

    @property
    def root(self) -> Optional[HloInstruction]:
        for i in self.instructions:
            if i.is_root:
                return i
        return self.instructions[-1] if self.instructions else None


@dataclass(frozen=True)
class BufferAlias:
    """One entry of the module's ``input_output_alias`` donation table:
    output tuple element ``output_index`` reuses the buffer of parameter
    ``parameter_number`` (element ``parameter_index`` when the parameter
    is itself a tuple)."""

    output_index: tuple[int, ...]
    parameter_number: int
    parameter_index: tuple[int, ...] = ()


@dataclass
class HloModule:
    """A parsed HLO module: the computation graph of one compiled program."""

    computations: dict[str, HloComputation] = field(default_factory=dict)
    entry: Optional[str] = None
    # the compiled module's donation table (empty when nothing aliases)
    input_output_alias: list[BufferAlias] = field(default_factory=list)

    def entry_computation(self) -> Optional[HloComputation]:
        if self.entry is not None and self.entry in self.computations:
            return self.computations[self.entry]
        return next(iter(self.computations.values()), None)

    def all_instructions(self) -> Iterator[tuple[HloComputation,
                                                 HloInstruction]]:
        for comp in self.computations.values():
            for instr in comp.instructions:
                yield comp, instr


def call_sites(
    module: HloModule,
) -> dict[str, list[tuple[HloComputation, HloInstruction]]]:
    """Reverse call map: callee computation name -> every (caller
    computation, calling instruction) pair that references it.

    The parser links calls downward only (``HloInstruction.called``); any
    walk that needs to step *out* of a fusion body / loop region — e.g.
    resolving a fusion parameter to the tensor the caller actually passed
    — needs this back-edge table.  Fusion computations normally have
    exactly one caller; while bodies/conditions share one ``while``."""
    sites: dict[str, list[tuple[HloComputation, HloInstruction]]] = {}
    for comp, instr in module.all_instructions():
        for _role, callee in instr.called:
            sites.setdefault(callee, []).append((comp, instr))
    return sites


def resolve_producers(
    module: HloModule,
    comp: HloComputation,
    operand_name: str,
    sites: Optional[dict[str, list[tuple[HloComputation,
                                         HloInstruction]]]] = None,
    max_hops: int = 8,
) -> list[tuple[HloComputation, HloInstruction]]:
    """The instruction(s) that actually produce ``%operand_name`` as seen
    from ``comp``, looking THROUGH fusion boundaries in both directions:

    - a ``fusion``/``call`` instruction resolves to its body's root;
    - a fusion-body ``parameter`` resolves to the matching positional
      call-site operand in every caller.

    A same-computation ``by_name`` lookup stops dead at either boundary —
    which is exactly where the interesting dtype transitions live (XLA
    fuses convert chains and bf16 accumulator arithmetic into fusion
    bodies).  Ascent is positional, so it is only taken for real call-like
    sites (``fusion``/``call``); loop-region parameters (while body /
    condition, branch computations) are NOT crossed — stepping out of a
    while body conflates loop iterations.  Returns de-duplicated
    (computation, instruction) pairs; empty when the name cannot be
    resolved inside ``max_hops`` boundary crossings."""
    if sites is None:
        sites = call_sites(module)
    out: list[tuple[HloComputation, HloInstruction]] = []
    emitted: set[tuple[str, str]] = set()
    seen: set[tuple[str, str]] = set()
    work: list[tuple[HloComputation, str, int]] = [(comp, operand_name, 0)]
    while work:
        c, name, hops = work.pop()
        if (c.name, name) in seen:
            continue
        seen.add((c.name, name))
        instr = c.by_name().get(name)
        if instr is None:
            continue
        if instr.opcode in ("fusion", "call") and hops < max_hops:
            for role, callee in instr.called:
                body = module.computations.get(callee) \
                    if role == "calls" else None
                if body is not None and body.root is not None:
                    work.append((body, body.root.name, hops + 1))
            continue
        if (instr.opcode == "parameter" and not c.is_entry
                and instr.parameter_number is not None and hops < max_hops):
            ascended = False
            for caller, site in sites.get(c.name, []):
                if site.opcode not in ("fusion", "call"):
                    continue
                idx = instr.parameter_number
                if idx < len(site.operands):
                    work.append((caller, site.operands[idx], hops + 1))
                    ascended = True
            if ascended:
                continue
        if (c.name, instr.name) not in emitted:
            emitted.add((c.name, instr.name))
            out.append((c, instr))
    return out


# ---------------------------------------------------------------------------
# low-level text helpers
# ---------------------------------------------------------------------------


def _parse_arrays(type_text: str) -> list[tuple[str, tuple[int, ...]]]:
    out = []
    for dtype, dims in _ARRAY_TYPE_RE.findall(type_text):
        shape = tuple(int(d) for d in dims.split(",")) if dims else ()
        out.append((dtype, shape))
    return out


def _array_bytes(dtype: str, shape: tuple[int, ...]) -> int:
    return _DTYPE_BYTES.get(dtype, 4) * int(prod(shape)) if shape else \
        _DTYPE_BYTES.get(dtype, 4)


def _parse_groups(line: str) -> tuple[str, Optional[int], Optional[int]]:
    m = _GROUPS_BRACE_RE.search(line)
    if m:
        groups = [g for g in m.group(1).split("},{")]
        sizes = {len([x for x in g.strip("{}").split(",") if x])
                 for g in groups}
        size = sizes.pop() if len(sizes) == 1 else None
        return "{" + m.group(1) + "}", len(groups), size
    m = _GROUPS_IOTA_RE.search(line)
    if m:
        count, size = int(m.group(1)), int(m.group(2))
        return line[m.start(): line.find("]", m.end()) + 1], count, size
    m = _PAIRS_RE.search(line)
    if m:
        pairs = m.group(1).count("},{") + 1
        return "{" + m.group(1) + "}", pairs, 2
    return "", None, None


def _balanced_span(text: str, start: int) -> int:
    """Index one past the bracket that closes ``text[start]`` (one of
    ``([{``), honouring nesting of all three bracket kinds."""
    depth = 0
    opens, closes = "([{", ")]}"
    for i in range(start, len(text)):
        c = text[i]
        if c in opens:
            depth += 1
        elif c in closes:
            depth -= 1
            if depth == 0:
                return i + 1
    return len(text)


def _split_type(rest: str) -> tuple[str, str]:
    """Split ``rest`` into (result-type text, remainder): the type is the
    leading token — a possibly-tuple shape with layout braces — ending at
    the first top-level whitespace."""
    i = 0
    while i < len(rest):
        c = rest[i]
        if c in "([{":
            i = _balanced_span(rest, i)
        elif c.isspace():
            return rest[:i], rest[i:].lstrip()
        else:
            i += 1
    return rest, ""


def _collective_of(opcode: str) -> tuple[Optional[str], bool, bool]:
    """(base kind, is_start, is_done) for an opcode."""
    for kind in COLLECTIVE_KINDS:
        if opcode == kind:
            return kind, False, False
        if opcode == kind + "-start":
            return kind, True, False
        if opcode == kind + "-done":
            return kind, False, True
    return None, False, False


def _parse_instruction(line: str) -> Optional[HloInstruction]:
    s = line.strip()
    m = _INSTR_START_RE.match(s)
    if m is None:
        return None
    type_text, rest = _split_type(m.group("rest"))
    om = re.match(r"[\w\-]+", rest)
    if om is None:
        return None
    opcode = om.group(0)
    after = rest[om.end():]
    operands_text, attrs_text = "", after
    if after.startswith("("):
        end = _balanced_span(after, 0)
        operands_text = after[1: end - 1]
        attrs_text = after[end:]

    arrays = _parse_arrays(type_text)
    operand_arrays = _parse_arrays(operands_text)
    operands = tuple(_OPERAND_NAME_RE.findall(operands_text))
    ctrl = _CONTROL_RE.search(attrs_text)
    control_deps = tuple(
        _OPERAND_NAME_RE.findall(ctrl.group(1))) if ctrl else ()
    called = []
    for cm in _CALL_ATTR_RE.finditer(attrs_text):
        role = cm.group("role")
        if cm.group("one"):
            called.append((role, cm.group("one")))
        else:
            for name in _OPERAND_NAME_RE.findall(cm.group("many") or ""):
                called.append((role, name))
    kind, is_start, is_done = _collective_of(opcode)
    groups, count, size = _parse_groups(s) if kind else ("", None, None)
    meta = _META_RE.search(s)
    opn = _OP_NAME_RE.search(s)
    trip = None
    if opcode == "while":
        tm = _TRIP_COUNT_RE.search(s)
        trip = int(tm.group(1)) if tm else None
    contract = _CONTRACT_RE.search(attrs_text)
    lhs_dims = tuple(
        int(d) for d in contract.group(1).split(",") if d
    ) if contract else ()
    param_no = None
    if opcode == "parameter":
        try:
            param_no = int(operands_text.strip())
        except ValueError:
            param_no = None
    return HloInstruction(
        name=m.group("name"), opcode=opcode,
        dtype=arrays[0][0] if arrays else "",
        shape=arrays[0][1] if arrays else (),
        arrays=arrays, operands=operands, operand_arrays=operand_arrays,
        control_deps=control_deps, called=tuple(called),
        is_root=bool(m.group("root")), raw=line,
        kind=kind, is_start=is_start, is_done=is_done,
        replica_groups=groups, group_count=count, group_size=size,
        source=f"{meta.group(1)}:{meta.group(2)}" if meta else None,
        op_name=opn.group(1) if opn else None,
        trip_count=trip, lhs_contracting_dims=lhs_dims,
        parameter_number=param_no,
    )


def parse_alias_table(header_line: str) -> list[BufferAlias]:
    """The ``input_output_alias`` donation table of an ``HloModule``
    header line (empty when the module aliases nothing)."""
    key = "input_output_alias="
    start = header_line.find(key)
    if start < 0:
        return []
    start += len(key)
    span = header_line[start:_balanced_span(header_line, start)]
    out = []
    for m in _ALIAS_ENTRY_RE.finditer(span):
        out.append(BufferAlias(
            output_index=tuple(
                int(d) for d in m.group("out").split(",") if d.strip()),
            parameter_number=int(m.group("param")),
            parameter_index=tuple(
                int(d) for d in m.group("pidx").split(",") if d.strip()),
        ))
    return out


# ---------------------------------------------------------------------------
# module parsing
# ---------------------------------------------------------------------------


_BRANCH_ROLES = ("branch_computations", "true_computation",
                 "false_computation")


def _propagate_execution_counts(module: HloModule) -> None:
    """Fill ``HloComputation.execution_count``: the entry runs once; a
    while body runs ``known_trip_count`` times per call site (1 when
    unknown — the conservative floor); plain calls/fusions run once per
    caller execution.  Of a ``conditional``'s branches exactly ONE
    executes per invocation — the first branch carries the call site's
    count and the rest get 0, so inventories never charge both sides of
    a conditional (the divergence check separately enforces that the
    branches post identical collective sequences, which is what makes
    counting one of them honest).  ``to_apply`` reducers are applied
    elementwise and carry no schedulable work of their own, so they are
    not walked (they contain no collectives)."""
    # call edges caller -> [(callee, factor)]
    edges: dict[str, list[tuple[str, int]]] = {}
    indeg: dict[str, int] = {name: 0 for name in module.computations}
    for comp in module.computations.values():
        out = edges.setdefault(comp.name, [])
        for instr in comp.instructions:
            first_branch = True
            for role, callee in instr.called:
                if callee not in module.computations or role == "to_apply":
                    continue
                if role == "body":
                    factor = instr.trip_count or 1
                elif role in _BRANCH_ROLES:
                    factor = 1 if first_branch else 0
                    first_branch = False
                else:
                    factor = 1
                out.append((callee, factor))
                indeg[callee] += 1
    counts = {name: 0 for name in module.computations}
    referenced = {name: d > 0 for name, d in indeg.items()}
    entry = module.entry_computation()
    if entry is None:
        return
    counts[entry.name] = 1
    # Kahn over the computation DAG, accumulating multipliers
    queue = [n for n, d in indeg.items() if d == 0]
    while queue:
        name = queue.pop()
        for callee, factor in edges.get(name, ()):
            counts[callee] += counts[name] * factor
            indeg[callee] -= 1
            if indeg[callee] == 0:
                queue.append(callee)
    for name, comp in module.computations.items():
        if referenced[name]:
            # may legitimately be 0: a non-first conditional branch
            comp.execution_count = counts[name]
        else:
            # unreferenced roots (the entry, standalone fixture
            # fragments) run once
            comp.execution_count = max(1, counts[name])


def parse_module(hlo_text: str) -> HloModule:
    """Parse an optimized-HLO module dump into its computation graph.

    Tolerant of fragments: bare instruction lines outside any computation
    header (the unit-test fixtures) land in an implicit entry computation
    named ``<fragment>``."""
    module = HloModule()
    cur: Optional[HloComputation] = None
    for line in hlo_text.splitlines():
        s = line.strip()
        if not s or s.startswith("//"):
            continue
        if s.startswith("HloModule"):
            module.input_output_alias = parse_alias_table(s)
            continue
        if s.endswith("{") and _INSTR_START_RE.match(s) is None:
            m = _COMP_HEADER_RE.match(s)
            if m is not None:
                cur = HloComputation(
                    name=m.group("name"), is_entry=bool(m.group("entry")),
                )
                module.computations[cur.name] = cur
                if cur.is_entry:
                    module.entry = cur.name
                continue
        if s.startswith("}"):
            cur = None
            continue
        instr = _parse_instruction(line)
        if instr is None:
            continue
        if cur is None:
            cur = module.computations.get("<fragment>")
            if cur is None:
                cur = HloComputation(name="<fragment>", is_entry=True)
                module.computations["<fragment>"] = cur
                if module.entry is None:
                    module.entry = "<fragment>"
        cur.instructions.append(instr)
    _propagate_execution_counts(module)
    return module


# ---------------------------------------------------------------------------
# flat collective inventory (byte-auditor surface)
# ---------------------------------------------------------------------------


@dataclass
class CollectiveInstr:
    """One collective instruction in compiled HLO."""

    kind: str                       # one of COLLECTIVE_KINDS
    dtype: str                      # result element type (first array)
    shape: tuple[int, ...]          # result shape (first array)
    result_bytes: int               # summed over all result arrays
    replica_groups: str             # raw groups / pairs text
    group_count: Optional[int]
    group_size: Optional[int]
    source: Optional[str]           # "file:line" from HLO metadata
    raw: str = field(repr=False, default="")
    # graph decoration (new in the dependency-graph parser): how many
    # times the instruction executes per module invocation (product of
    # enclosing while trip counts), which computation holds it, and the
    # jax name-stack (carries the ring_hop naming hooks)
    execution_count: int = 1
    computation: str = ""
    name: str = ""
    op_name: Optional[str] = None

    def to_dict(self) -> dict:
        return {
            "kind": self.kind,
            "dtype": self.dtype,
            "shape": list(self.shape),
            "result_bytes": self.result_bytes,
            "replica_groups": self.replica_groups,
            "group_count": self.group_count,
            "group_size": self.group_size,
            "source": self.source,
            "execution_count": self.execution_count,
            "computation": self.computation,
        }


def parse_collectives(
    hlo: Union[str, HloModule],
) -> list[CollectiveInstr]:
    """All collective instructions in an optimized-HLO module dump, across
    EVERY computation — entry, while bodies, conditional branches — each
    carrying its ``execution_count`` (enclosing while trip counts
    multiplied in).  ``-done`` halves of async pairs are skipped; the
    ``-start`` op carries the payload."""
    module = hlo if isinstance(hlo, HloModule) else parse_module(hlo)
    out = []
    for comp, instr in module.all_instructions():
        if instr.kind is None or instr.is_done:
            continue
        payload, dtype, shape = instr.collective_payload()
        out.append(CollectiveInstr(
            kind=instr.kind, dtype=dtype, shape=shape,
            result_bytes=payload, replica_groups=instr.replica_groups,
            group_count=instr.group_count, group_size=instr.group_size,
            source=instr.source, raw=instr.raw,
            execution_count=comp.execution_count,
            computation=comp.name, name=instr.name, op_name=instr.op_name,
        ))
    return out


def has_donation(lowered_text: str, compiled_text: str) -> bool:
    """True when the computation donates at least one input buffer:
    ``tf.aliasing_output``/``jax.buffer_donor`` arg attributes in the
    lowered StableHLO, or an ``input_output_alias`` table in the compiled
    module header."""
    return ("tf.aliasing_output" in lowered_text
            or "jax.buffer_donor" in lowered_text
            or "input_output_alias={ {" in compiled_text
            or "input_output_alias={{" in compiled_text)
