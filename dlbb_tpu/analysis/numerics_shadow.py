"""fp64 shadow cross-check for the static numerics auditor.

The ``low-precision-accumulation`` rule (``numerics_audit.py``) prices a
flagged reduction with two analytic worst-case relative-error bounds —
sequential ``(n-1)·u`` and balanced-tree ``ceil(log2 n)·u``, both
relative to ``sum(|x_i|)`` (Higham, *Accuracy and Stability of Numerical
Algorithms*, §4.2, where ``u`` is the accumulator dtype's unit
roundoff).  A static bound nobody has ever measured against is a claim,
not a gate — so this module closes the loop empirically:

1. **Static side** — for each shadow case, a seeded HLO module with a
   genuinely low-precision accumulator (hand-written text: XLA's CPU
   pipeline auto-upcasts bf16 reduce combiners to f32, so a lowered
   fixture could not carry the violation) is run through
   ``analyze_numerics``; the case must be FLAGGED and carry the analytic
   bounds.
2. **Empirical side** — the same reduction shape is executed for real at
   the case's dtype on backend-agnostic jax (a ``lax.scan`` carry for
   sequential order, a pairwise halving ladder for tree order — carries
   and explicit adds cannot be silently upcast), against an fp64 shadow
   reference computed with numpy.  The measured relative error
   ``|sum_lp - sum_f64| / sum(|x|)`` must land within the analytic bound
   for the case's summation order.
3. A case is **confirmed** when static flagging and the measured bound
   agree (flagged and within bound), **refuted** otherwise.  An f32
   control case (static: clean; empirical: error orders of magnitude
   under the bf16 bound) guards against the instrument itself saturating.

The committed report lives at ``stats/analysis/numerics/shadow_report.json``
and CI re-runs the check via ``scripts/run_static_analysis.sh`` (grep:
zero refuted, >=1 confirmed).

CLI::

    python -m dlbb_tpu.analysis.numerics_shadow --output stats/analysis/numerics
"""

from __future__ import annotations

import json
import math
from dataclasses import dataclass
from pathlib import Path
from typing import Optional

SHADOW_REPORT_SCHEMA = "dlbb_numerics_shadow_v1"
SHADOW_REPORT_NAME = "shadow_report.json"
DEFAULT_SHADOW_DIR = Path("stats/analysis/numerics")

# jax dtype name per HLO dtype used by the shadow cases
_JAX_DTYPES = {"bf16": "bfloat16", "f16": "float16", "f32": "float32"}


# ---------------------------------------------------------------------------
# seeded HLO fixtures (shared with tests/test_numerics_audit.py)
# ---------------------------------------------------------------------------


def seeded_reduction_hlo(n: int, dtype: str = "bf16") -> str:
    """Minimal post-SPMD-shaped HLO text: a length-``n`` add reduction
    whose combiner accumulates at ``dtype``.  Hand-written because the
    CPU XLA pipeline rewrites low-precision reduce combiners to f32 +
    convert (exactly the upcast the rule exists to verify is absent)."""
    return f"""\
HloModule seeded_reduction_{dtype}_{n}, entry_computation_layout={{({dtype}[{n}]{{0}})->{dtype}[]}}

%add_{dtype} (a: {dtype}[], b: {dtype}[]) -> {dtype}[] {{
  %a = {dtype}[] parameter(0)
  %b = {dtype}[] parameter(1)
  ROOT %add = {dtype}[] add({dtype}[] %a, {dtype}[] %b)
}}

ENTRY %main (x: {dtype}[{n}]) -> {dtype}[] {{
  %x = {dtype}[{n}]{{0}} parameter(0)
  %zero = {dtype}[] constant(0)
  ROOT %reduce = {dtype}[] reduce({dtype}[{n}]{{0}} %x, {dtype}[] %zero), dimensions={{0}}, to_apply=%add_{dtype}
}}
"""


def _static_audit(n: int, dtype: str) -> tuple[bool, dict]:
    """Run the seeded module through the real analyzer; returns
    (flagged, finding details or bound meta)."""
    from dlbb_tpu.analysis.expectations import TargetExpectation
    from dlbb_tpu.analysis.hlo_parse import parse_module
    from dlbb_tpu.analysis.numerics_audit import analyze_numerics

    module = parse_module(seeded_reduction_hlo(n, dtype))
    findings, meta = analyze_numerics(
        module, TargetExpectation(), f"shadow::reduce[{dtype},{n}]"
    )
    flagged = [f for f in findings
               if f.rule == "low-precision-accumulation"]
    details = flagged[0].details if flagged else {
        "reduction_sites": meta.get("reduction_sites", 0)}
    return bool(flagged), details


# ---------------------------------------------------------------------------
# empirical low-precision reductions
# ---------------------------------------------------------------------------


def _measured_rel_error(data, dtype: str, order: str) -> float:
    """Execute the reduction at ``dtype`` in the given summation
    ``order`` and return ``|sum - shadow_f64_sum| / sum(|x|)``.

    The accumulator genuinely runs at ``dtype``: a ``lax.scan`` carry
    (sequential) or explicit pairwise adds (tree) — dtype-pinned program
    points XLA must honour, unlike a ``reduce`` combiner it may upcast."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    jdt = jnp.dtype(_JAX_DTYPES[dtype])
    x = jnp.asarray(data).astype(jdt)

    if order == "sequential":
        def _sum(v):
            def body(carry, xi):
                return carry + xi, None
            acc, _ = jax.lax.scan(body, jnp.zeros((), jdt), v)
            return acc
    elif order == "tree":
        def _sum(v):
            while v.shape[0] > 1:
                v = v[0::2] + v[1::2]
            return v[0]
    else:  # pragma: no cover - case-table integrity
        raise ValueError(f"unknown summation order {order!r}")

    measured = float(np.asarray(jax.jit(_sum)(x), dtype=np.float64))
    shadow = data.astype(np.float64)
    ref = float(shadow.sum())
    denom = float(np.abs(shadow).sum()) or 1.0
    return abs(measured - ref) / denom


# ---------------------------------------------------------------------------
# the case table
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class ShadowCase:
    """One static-flag + empirical-replay pair."""

    name: str
    dtype: str      # HLO dtype of the accumulator
    n: int          # reduction length (power of two: the tree ladder halves)
    order: str      # "sequential" | "tree"
    expect_flagged: bool = True  # False for the f32 control


DEFAULT_CASES: tuple[ShadowCase, ...] = (
    ShadowCase("bf16-sequential-4096", "bf16", 4096, "sequential"),
    ShadowCase("bf16-tree-4096", "bf16", 4096, "tree"),
    ShadowCase("f16-sequential-4096", "f16", 4096, "sequential"),
    # control: statically clean, and its measured error must sit far
    # below the bf16 bound or the instrument is saturated
    ShadowCase("f32-control-4096", "f32", 4096, "sequential",
               expect_flagged=False),
)


def run_shadow(cases: tuple[ShadowCase, ...] = DEFAULT_CASES,
               seed: int = 0) -> dict:
    """Run every case; returns the report dict (see module docstring)."""
    import numpy as np

    from dlbb_tpu.analysis.numerics_audit import (
        accumulation_error_bounds,
        unit_roundoff,
    )

    rng = np.random.default_rng(seed)
    rows = []
    for case in cases:
        # positive, O(1)-magnitude data: the running partial sums grow to
        # ~n so low-precision roundoff must actually accrue (a zero-mean
        # stream would hide sequential error behind cancellation)
        data = rng.uniform(0.5, 1.5, size=case.n)
        bound_seq, bound_tree = accumulation_error_bounds(case.n, case.dtype)
        bound = bound_seq if case.order == "sequential" else bound_tree
        flagged, details = _static_audit(case.n, case.dtype)
        measured = _measured_rel_error(data, case.dtype, case.order)
        if case.expect_flagged:
            confirmed = flagged and measured <= bound
        else:
            # the control must be clean AND resolve errors well under the
            # low-precision bounds it is controlling for
            confirmed = (not flagged
                         and measured <= 8 * case.n * unit_roundoff("f32"))
        rows.append({
            "case": case.name,
            "dtype": case.dtype,
            "n": case.n,
            "order": case.order,
            "static_flagged": flagged,
            "static_details": details,
            "predicted_bound_seq": bound_seq,
            "predicted_bound_tree": bound_tree,
            "gating_bound": bound,
            "measured_rel_error": measured,
            "measured_over_bound": measured / bound if bound else None,
            "confirmed": confirmed,
        })
    confirmed = sum(r["confirmed"] for r in rows)
    return {
        "schema": SHADOW_REPORT_SCHEMA,
        "seed": seed,
        "unit_roundoff": {d: unit_roundoff(d)
                          for d in ("f64", "f32", "f16", "bf16")},
        "cases": rows,
        "confirmed": confirmed,
        "refuted": len(rows) - confirmed,
    }


def write_shadow_report(report: dict, out_dir) -> Path:
    from dlbb_tpu.utils.config import atomic_write_text

    out_dir = Path(out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    path = out_dir / SHADOW_REPORT_NAME
    atomic_write_text(json.dumps(report, indent=2, sort_keys=True) + "\n",
                      path)
    return path


def main(argv: Optional[list[str]] = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.split("\n", 1)[0])
    ap.add_argument("--output", default=str(DEFAULT_SHADOW_DIR),
                    metavar="DIR",
                    help="directory for the shadow report "
                         f"(default: {DEFAULT_SHADOW_DIR})")
    ap.add_argument("--seed", type=int, default=0,
                    help="rng seed for the shadow payloads")
    args = ap.parse_args(argv)

    report = run_shadow(seed=args.seed)
    path = write_shadow_report(report, args.output)
    for row in report["cases"]:
        status = "confirmed" if row["confirmed"] else "REFUTED"
        print(f"[shadow] {row['case']}: {status} — measured rel err "
              f"{row['measured_rel_error']:.3g} vs bound "
              f"{row['gating_bound']:.3g} "
              f"({row['order']}, static_flagged={row['static_flagged']})")
    print(f"[shadow] {report['confirmed']} confirmed, "
          f"{report['refuted']} refuted; report at {path}")
    return 0 if report["refuted"] == 0 and report["confirmed"] else 1


if __name__ == "__main__":  # pragma: no cover - exercised via scripts/
    raise SystemExit(main())
