"""Structured findings shared by both comm-lint passes.

A finding is one violation (or notable observation) from either the HLO
collective auditor (``hlo_audit``) or the AST source lint (``source_lint``),
carrying enough structure for machines (JSON report consumed by CI) and
humans (one-line rendering in the CLI summary).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Optional

SEVERITY_ERROR = "error"
SEVERITY_WARNING = "warning"

# the `analyze` CLI exit-code contract (docs/schedule_audit.md; pinned by
# tests/test_schedule_audit.py so the CI diff gate can compose with the
# chaos and compression smoke stages): 0 = clean, 1 = findings (errors,
# or warnings under --strict-warnings), 2 = the analyzer itself crashed
# (or unusable arguments).  Anything mapping findings to a different
# code is a bug.
EXIT_CLEAN = 0
EXIT_FINDINGS = 1
EXIT_CRASH = 2


@dataclass
class Finding:
    """One comm-lint violation.

    pass_name: "hlo", "lint", "schedule", "memory" or "numerics".
    rule:      stable rule identifier (see docs/analysis.md catalogue).
    severity:  "error" findings fail the run; "warning" findings do not.
    target:    audit-target name (hlo) or repo-relative file path (lint).
    message:   human-readable one-liner.
    location:  "file:line" when known (lint always; hlo when the compiled
               instruction carries source metadata).
    details:   rule-specific structure — for HLO findings this includes the
               op kind, shape, dtype, per-device byte volume, replica
               groups, and the plan-derived expected volume.
    """

    pass_name: str
    rule: str
    severity: str
    target: str
    message: str
    location: Optional[str] = None
    details: dict[str, Any] = field(default_factory=dict)

    def to_dict(self) -> dict[str, Any]:
        return {
            "pass": self.pass_name,
            "rule": self.rule,
            "severity": self.severity,
            "target": self.target,
            "message": self.message,
            "location": self.location,
            "details": self.details,
        }

    def render(self) -> str:
        loc = f" ({self.location})" if self.location else ""
        return (f"[{self.pass_name}/{self.severity}] {self.rule} "
                f"@ {self.target}{loc}: {self.message}")


@dataclass
class AnalysisReport:
    """Aggregate result of one ``analyze`` run."""

    findings: list[Finding] = field(default_factory=list)
    suppressed: int = 0
    targets_audited: list[str] = field(default_factory=list)
    files_linted: int = 0
    skipped_targets: list[dict[str, str]] = field(default_factory=list)
    # target name -> schedule meta (critical_path_us / overlap_efficiency
    # / inventory; schedule_audit.analyze_schedule) — the baseline payload
    schedule: dict[str, dict] = field(default_factory=dict)
    # target name -> memory meta (peak_live_bytes / live set at peak /
    # transients; memory_audit.analyze_memory) — feeds the same baseline
    # snapshots as the schedule pass
    memory: dict[str, dict] = field(default_factory=dict)
    # target name -> numerics meta (reduction-site table / error bounds /
    # convert counts; numerics_audit.analyze_numerics) — its numerics_*
    # gate keys fold into the same baseline snapshots
    numerics: dict[str, dict] = field(default_factory=dict)

    def extend(self, other: "AnalysisReport") -> None:
        self.findings.extend(other.findings)
        self.suppressed += other.suppressed
        self.targets_audited.extend(other.targets_audited)
        self.files_linted += other.files_linted
        self.skipped_targets.extend(other.skipped_targets)
        self.schedule.update(other.schedule)
        self.memory.update(other.memory)
        self.numerics.update(other.numerics)

    @property
    def errors(self) -> list[Finding]:
        return [f for f in self.findings if f.severity == SEVERITY_ERROR]

    @property
    def warnings(self) -> list[Finding]:
        return [f for f in self.findings if f.severity == SEVERITY_WARNING]

    def exit_code(self, strict_warnings: bool = False) -> int:
        if self.errors:
            return EXIT_FINDINGS
        if strict_warnings and self.warnings:
            return EXIT_FINDINGS
        return EXIT_CLEAN

    def to_dict(self) -> dict[str, Any]:
        return {
            "findings": [f.to_dict() for f in self.findings],
            "schedule": self.schedule,
            "memory": self.memory,
            "numerics": self.numerics,
            "summary": {
                "errors": len(self.errors),
                "warnings": len(self.warnings),
                "suppressed": self.suppressed,
                "targets_audited": self.targets_audited,
                "files_linted": self.files_linted,
                "skipped_targets": self.skipped_targets,
            },
        }

    def write_json(self, path) -> None:
        from dlbb_tpu.utils.config import atomic_write_text

        atomic_write_text(
            json.dumps(self.to_dict(), indent=2, sort_keys=True), Path(path)
        )

    def render_summary(self) -> str:
        lines = []
        for f in self.findings:
            lines.append(f.render())
        lines.append(
            f"comm-lint: {len(self.errors)} error(s), "
            f"{len(self.warnings)} warning(s), {self.suppressed} suppressed; "
            f"{len(self.targets_audited)} HLO target(s) audited, "
            f"{self.files_linted} file(s) linted"
            + (f", {len(self.schedule)} schedule report(s)"
               if self.schedule else "")
            + (f", {len(self.memory)} memory report(s)"
               if self.memory else "")
            + (f", {len(self.numerics)} numerics report(s)"
               if self.numerics else "")
            + (f", {len(self.skipped_targets)} target(s) skipped"
               if self.skipped_targets else "")
        )
        return "\n".join(lines)
