"""comm-lint: static verification that benchmarks match their parallelism
plan.

Five passes (see docs/analysis.md + docs/schedule_audit.md +
docs/memory_audit.md + docs/numerics.md for the rule catalogues):

- ``hlo``      — lower + compile every registered benchmark computation on
  the current (usually ``--simulate N`` CPU) mesh and audit the post-SPMD
  HLO for unexpected / missing / oversized collectives and missing buffer
  donation (``hlo_audit``).
- ``schedule`` — the α–β schedule auditor over the same lowered modules:
  overlap verification (every ring hop must have a straddling matmul),
  critical-path estimate, divergent-branch deadlock check
  (``schedule_audit``).
- ``memory``   — the buffer-liveness memory auditor over the same
  modules: per-target ``peak_live_bytes`` (donation/aliasing-aware,
  while/conditional/fusion composed), analytic peak ceilings, the
  transient-replicated-buffer spike gate, the serving cache
  cross-check, and ``hbm_headroom`` feasibility per cost tier
  (``memory_audit``).
- ``numerics`` — the dtype-flow numerics auditor over the same modules:
  low-precision accumulation with analytic error bounds, silent f32
  upcasts under a bf16 policy, quantise roundtrips, nondeterministic fp
  wire reductions, precision-policy conformance, and convert churn —
  fusion bodies included (``numerics_audit``; the fp64 shadow
  cross-check lives in ``numerics_shadow``).
- ``lint``     — AST rules over ``dlbb_tpu/`` and ``scripts/`` for host
  syncs and wall-clock reads in timed regions, undonated train-step jits,
  jit-in-loop recompile hazards, per-iteration host transfers in loops,
  unsorted set iteration, and non-atomic artifact writes
  (``source_lint``).

Plus the regression-baseline gate over the schedule + memory + numerics
passes:

- ``snapshot`` — write per-target baselines to ``stats/analysis/baselines``
  (refuses while the audit itself has error findings).
- ``diff``     — compare a fresh audit against the committed baselines and
  fail on unexplained growth (>10 % critical path / wire / peak memory /
  largest transient, new collective kind, new low-precision accumulation
  site / numerics error-bound drift).

CLI: ``python -m dlbb_tpu.cli analyze
[hlo|lint|schedule|memory|numerics|all|snapshot|diff] --simulate 8``.  Exit codes
are a pinned contract (``findings.EXIT_*``): 0 = clean, 1 = findings,
2 = the analyzer crashed.
"""

from __future__ import annotations

from pathlib import Path
from typing import Optional

from dlbb_tpu.analysis.findings import (  # noqa: F401
    EXIT_CLEAN,
    EXIT_CRASH,
    EXIT_FINDINGS,
    SEVERITY_ERROR,
    AnalysisReport,
    Finding,
)
from dlbb_tpu.analysis.source_lint import run_source_lint  # noqa: F401

_HLO_PASSES = {
    "hlo": ("hlo",),
    "schedule": ("schedule",),
    "memory": ("memory",),
    "numerics": ("numerics",),
    "all": ("hlo", "schedule", "memory", "numerics"),
    "snapshot": ("hlo", "schedule", "memory", "numerics"),
    "diff": ("hlo", "schedule", "memory", "numerics"),
}

# memory-meta keys folded into the per-target baseline snapshots next to
# the schedule keys (the one committed gate file per target)
_MEMORY_BASELINE_KEYS = ("peak_live_bytes", "max_transient_bytes")
# numerics-meta keys folded the same way (already numerics_-prefixed in
# the meta, so they cannot collide with schedule keys)
_NUMERICS_BASELINE_KEYS = (
    "numerics_low_precision_sites",
    "numerics_convert_count",
    "numerics_max_rel_error_bound",
)


def run_analysis(
    which: str = "all",
    root: Optional[str] = None,
    json_path: Optional[str] = None,
    verbose: bool = True,
    strict_warnings: bool = False,
    baselines: Optional[str] = None,
    tier: Optional[str] = None,
    model: str = "cm1",
    output: Optional[str] = None,
) -> int:
    """Run the requested passes; print the human summary; optionally write
    the JSON report.  Returns the pinned exit code: 0 clean / 1 findings /
    2 crash (an exception anywhere in the analyzer must surface as 2, not
    as a stack trace with an arbitrary code — the CI gates compose on
    this)."""
    try:
        return _run_analysis(
            which=which, root=root, json_path=json_path, verbose=verbose,
            strict_warnings=strict_warnings, baselines=baselines, tier=tier,
            model=model, output=output,
        )
    except Exception:  # noqa: BLE001 — the exit-code contract
        import traceback

        traceback.print_exc()
        return EXIT_CRASH


def _run_analysis(
    which: str,
    root: Optional[str],
    json_path: Optional[str],
    verbose: bool,
    strict_warnings: bool,
    baselines: Optional[str],
    tier: Optional[str],
    model: str = "cm1",
    output: Optional[str] = None,
) -> int:
    from dlbb_tpu.analysis.schedule_audit import DEFAULT_BASELINE_DIR

    report = AnalysisReport()
    if which in ("lint", "all"):
        report.extend(run_source_lint(root=root, verbose=False))
    hlo_passes = _HLO_PASSES.get(which)
    if hlo_passes:
        # imported lazily: the lint pass must work without touching jax
        from dlbb_tpu.analysis.hlo_audit import run_hlo_audit

        hlo = run_hlo_audit(verbose=verbose, passes=hlo_passes, tier=tier,
                            model=model)
        if not hlo.targets_audited:
            # every target skipped for lack of devices — a CI gate wired to
            # our exit code must not read that as a clean audit
            hlo.findings.append(Finding(
                pass_name="hlo", rule="no-targets-audited",
                severity=SEVERITY_ERROR, target="<backend>",
                message=(
                    f"0 HLO targets audited ({len(hlo.skipped_targets)} "
                    "skipped for lack of devices); pass --simulate N "
                    "(e.g. 8) to stand up a large-enough CPU mesh"
                ),
            ))
        report.extend(hlo)

    # the memory pass rides the same per-target baseline snapshots as the
    # schedule pass: fold its gate keys into the schedule meta so
    # `analyze snapshot`/`diff` carry (and regression-gate) the memory
    # axis alongside critical path and wire volume
    if which in ("all", "snapshot", "diff"):
        for target, mem in report.memory.items():
            dest = report.schedule.setdefault(target, {})
            for key in _MEMORY_BASELINE_KEYS:
                if key in mem:
                    dest[key] = mem[key]
        for target, num in report.numerics.items():
            dest = report.schedule.setdefault(target, {})
            for key in _NUMERICS_BASELINE_KEYS:
                if key in num:
                    dest[key] = num[key]

    if output and report.memory:
        # the observability surface (`analyze memory --output DIR`,
        # docs/memory_audit.md): peak bytes + the audit tier land in the
        # directory's sweep_manifest.json, and an
        # analysis_peak_live_bytes{target} gauge per target folds into
        # metrics.prom next to the calibration-health gauges
        from dlbb_tpu.analysis.costmodel import resolve_tier
        from dlbb_tpu.analysis.hlo_audit import default_tier
        from dlbb_tpu.analysis.memory_audit import write_memory_artifacts

        cost_tier = resolve_tier(tier or default_tier(), model=model,
                                 warn=False)
        path = write_memory_artifacts(report.memory, output, cost_tier)
        if verbose:
            print(f"[analyze] memory report written to {path} "
                  "(manifest + metrics.prom updated)")

    if output and report.numerics:
        from dlbb_tpu.analysis.numerics_audit import write_numerics_artifacts

        path = write_numerics_artifacts(report.numerics, output)
        if verbose:
            print(f"[analyze] numerics report written to {path} "
                  "(manifest + metrics.prom updated)")

    if output:
        # per-pass finding counts as gauges (obs/export.analysis_metrics):
        # suppression/violation drift stays observable across PRs even
        # when the run is clean — all five passes always report a sample
        from dlbb_tpu.obs.calibration import METRICS_NAME, _fold_metrics
        from dlbb_tpu.obs.export import analysis_metrics

        _fold_metrics(analysis_metrics(report),
                      Path(output) / METRICS_NAME)

    base_dir = Path(baselines) if baselines else DEFAULT_BASELINE_DIR
    if which == "snapshot":
        from dlbb_tpu.analysis.schedule_audit import snapshot_baselines

        if report.errors:
            # refuse to freeze a dirty tree: a snapshot of a failing audit
            # would launder the failure into the committed gate
            print("[analyze] snapshot refused: the audit has error "
                  "findings — fix them first")
        else:
            written = snapshot_baselines(
                report.schedule, base_dir,
                skipped_targets=tuple(
                    s["target"] for s in report.skipped_targets
                ),
            )
            if verbose:
                print(f"[analyze] {len(written)} baseline snapshot(s) "
                      f"written to {base_dir}")
    elif which == "diff":
        from dlbb_tpu.analysis.schedule_audit import diff_baselines

        report.findings.extend(diff_baselines(
            report.schedule, base_dir,
            skipped_targets=tuple(
                s["target"] for s in report.skipped_targets
            ),
        ))
    if verbose:
        print(report.render_summary())
    if json_path:
        report.write_json(json_path)
        if verbose:
            print(f"[analyze] JSON report written to {json_path}")
    return report.exit_code(strict_warnings=strict_warnings)
