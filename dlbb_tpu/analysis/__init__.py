"""comm-lint: static verification that benchmarks match their parallelism
plan.

Two passes (see docs/analysis.md for the rule catalogue):

- ``hlo``  — lower + compile every registered benchmark computation on the
  current (usually ``--simulate N`` CPU) mesh and audit the post-SPMD HLO
  for unexpected / missing / oversized collectives and missing buffer
  donation (``hlo_audit``).
- ``lint`` — AST rules over ``dlbb_tpu/`` and ``scripts/`` for host syncs
  in timed regions, undonated train-step jits, jit-in-loop recompile
  hazards, and unsorted set iteration (``source_lint``).

CLI: ``python -m dlbb_tpu.cli analyze [hlo|lint|all] --simulate 8``.
"""

from __future__ import annotations

from typing import Optional

from dlbb_tpu.analysis.findings import (  # noqa: F401
    SEVERITY_ERROR,
    AnalysisReport,
    Finding,
)
from dlbb_tpu.analysis.source_lint import run_source_lint  # noqa: F401


def run_analysis(
    which: str = "all",
    root: Optional[str] = None,
    json_path: Optional[str] = None,
    verbose: bool = True,
    strict_warnings: bool = False,
) -> int:
    """Run the requested passes; print the human summary; optionally write
    the JSON report.  Returns the process exit code (0 = clean)."""
    report = AnalysisReport()
    if which in ("lint", "all"):
        report.extend(run_source_lint(root=root, verbose=False))
    if which in ("hlo", "all"):
        # imported lazily: the lint pass must work without touching jax
        from dlbb_tpu.analysis.hlo_audit import run_hlo_audit

        hlo = run_hlo_audit(verbose=verbose)
        if not hlo.targets_audited:
            # every target skipped for lack of devices — a CI gate wired to
            # our exit code must not read that as a clean audit
            hlo.findings.append(Finding(
                pass_name="hlo", rule="no-targets-audited",
                severity=SEVERITY_ERROR, target="<backend>",
                message=(
                    f"0 HLO targets audited ({len(hlo.skipped_targets)} "
                    "skipped for lack of devices); pass --simulate N "
                    "(e.g. 8) to stand up a large-enough CPU mesh"
                ),
            ))
        report.extend(hlo)
    if verbose:
        print(report.render_summary())
    if json_path:
        report.write_json(json_path)
        if verbose:
            print(f"[analyze] JSON report written to {json_path}")
    return report.exit_code(strict_warnings=strict_warnings)
