"""Pass 4 — static memory auditor (buffer liveness / peak HBM).

The byte auditor proves *what* a lowered program sends over the wire,
the schedule auditor *when* it runs — this pass proves *how much memory*
it needs.  Over the instruction dependency graph
(``hlo_parse.parse_module`` of the post-SPMD module, whose shapes are
already per-device) it computes a classic buffer-liveness analysis:

- every non-aliasing instruction allocates its result buffer
  (shape x dtype summed over tuple elements); ``bitcast`` /
  ``get-tuple-element`` / ``tuple`` are zero-cost views of their
  operands, and a ``while`` / ``conditional`` result reuses its carry
  / branch-root buffers (XLA's in-place loop convention), so consumers
  of the loop keep the *carry* alive rather than a phantom copy;
- a buffer is live from its defining instruction to its last consumer
  (operand + control edges; scheduled HLO text order is the schedule);
  entry parameters are live for the whole program (the caller owns
  them), outputs from their definition to program end;
- nested computations charge their internal peak (parameters excluded —
  they alias the caller's operands, which are live at the call instant
  anyway) at the call site: a while body's peak — including its root,
  the new carry that double-buffers against the old one — is resident
  across every trip, a conditional charges its worst branch, a fusion
  charges only its root (fused intermediates never materialise);
- donation is tracked through the compiled module's
  ``input_output_alias`` table: a donated parameter stays resident to
  program end (its buffer holds the aliased output at return) and the
  output element it aliases is charged zero, so donated state is never
  double-counted.

Per target the pass reports ``peak_live_bytes``, the live set at the
peak instant, and a top-N transient-buffer table, plus the
``hbm_headroom_bytes`` / ``feasible`` term against the cost tier's
capacity (``costmodel.hbm_headroom_bytes`` — the static OOM-pruning
input of the future ``cli plan --auto`` search).

Rules (docs/memory_audit.md):

- ``peak-memory-ceiling``   — ``TargetExpectation.max_peak_bytes``
  exceeded (the whole-program twin of the per-instruction byte gate).
- ``unaliased-donation``    — the lowered module marks donor buffers
  (``jax.buffer_donor`` / ``tf.aliasing_output``) but the compiled
  module aliases fewer of them: XLA silently dropped a donation and
  input + output state are simultaneously resident.
- ``transient-replicated-buffer`` — on a >1-device mesh, a transient
  intermediate at least ``num_devices`` x larger than everything that
  feeds it AND everything that consumes it: a full-size replicated
  buffer between sharded producer and sharded consumer (the PR-6
  EF-residual spike, now a lint).  Collectives are exempt (a gather's
  P x growth is its job and the wire auditor prices it); buffers under
  ``REPLICATED_FLOOR_BYTES`` are ignored.
- ``serving-cache-drift``   — the donated-buffer bytes disagree with
  ``TargetExpectation.donated_bytes_expected`` beyond the tolerance:
  the serving decode step's cache carry drifted from the analytic
  ``kv_cache_bytes_per_device`` the build-time HBM budget gate prices.
- ``hbm-infeasible``        — warning: the audited peak exceeds the
  cost tier's recorded per-device capacity.

Pure text/graph analysis — importable WITHOUT jax (the unit tests run
backend-free; only the lowering in ``hlo_audit`` needs a backend).
"""

from __future__ import annotations

import json
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Optional

from dlbb_tpu.analysis.costmodel import (
    CostTier,
    hbm_headroom_bytes,
    memory_feasible,
)
from dlbb_tpu.analysis.expectations import TargetExpectation
from dlbb_tpu.analysis.findings import (
    SEVERITY_ERROR,
    SEVERITY_WARNING,
    Finding,
)
from dlbb_tpu.analysis.hlo_parse import (
    HloComputation,
    HloModule,
    _array_bytes,
    parse_module,
)

# zero-cost views: the instruction's "result" is its operand's memory
ALIAS_OPCODES = ("bitcast", "get-tuple-element", "tuple")
# results that reuse their carry / branch-root buffers (charged at the
# operand / in the callee's internal peak, never twice)
CARRY_OPCODES = ("while", "conditional")

# transient-replicated-buffer floor: intermediates below this are noise
# (every default audit target's buffers are KB-scale; the rule exists
# for the [dp, total_params]-class spikes that matter at model scale)
REPLICATED_FLOOR_BYTES = 1 << 20

# donation-marker attributes a lowered (StableHLO) module stamps on
# donor arguments — counted against the compiled alias table
DONOR_MARKERS = ("jax.buffer_donor", "tf.aliasing_output")

# the baseline-gate slack for the memory axes lives with the diff gate:
# schedule_audit.PEAK_MEMORY_SLACK (one contract, one constant)

MEMORY_REPORT_SCHEMA = "dlbb_memory_audit_v1"
MEMORY_REPORT_NAME = "memory_audit.json"


# ---------------------------------------------------------------------------
# per-computation liveness
# ---------------------------------------------------------------------------


@dataclass
class _Buffer:
    """One allocation root: a charged buffer with a live range."""

    index: int                 # defining instruction index (-1 = param)
    name: str
    opcode: str
    bytes: int                 # charged bytes (0 for aliased-away)
    last_use: int
    is_param: bool = False
    parameter_number: Optional[int] = None
    donated: bool = False      # param aliased by an output
    aliased_output: bool = False  # output element reusing a donated param
    source: Optional[str] = None


@dataclass
class _CompMem:
    """Liveness analysis of one computation (single execution)."""

    peak_bytes: int = 0            # parameters included
    peak_extra_bytes: int = 0      # parameters excluded (call-site charge)
    peak_index: int = 0
    buffers: list[_Buffer] = field(default_factory=list)
    extra_at: dict[int, int] = field(default_factory=dict)


class _ModuleMemory:
    """Buffer-liveness analysis over a parsed module."""

    def __init__(self, module: HloModule):
        self.module = module
        self._memo: dict[str, _CompMem] = {}
        # computations whose buffers never materialise on their own:
        # fused computations (the fusion charges its root) and to_apply
        # reducers (applied elementwise)
        self.skipped: set[str] = set()
        for _, instr in module.all_instructions():
            for role, callee in instr.called:
                if role == "to_apply" or instr.opcode == "fusion":
                    self.skipped.add(callee)

    # -- nested charge ------------------------------------------------------

    def _call_extra(self, instr) -> int:
        """Bytes a call-site instruction keeps resident beyond its own
        result: the callee's internal peak (parameters excluded).  A
        while alternates body and condition (max), a conditional runs
        one branch (max — the divergence check separately pins that
        branches agree on collectives, and memory takes the worst)."""
        if instr.opcode == "fusion":
            return 0
        extra = 0
        for role, callee in instr.called:
            if role == "to_apply" or callee not in self.module.computations:
                continue
            callee_mem = self.analyze(self.module.computations[callee])
            extra = max(extra, callee_mem.peak_extra_bytes) \
                if instr.opcode in CARRY_OPCODES \
                else extra + callee_mem.peak_extra_bytes
        return extra

    # -- one computation ----------------------------------------------------

    def analyze(self, comp: HloComputation) -> _CompMem:
        cached = self._memo.get(comp.name)
        if cached is not None:
            return cached
        # cycle guard (invalid HLO / truncated dumps must not hang)
        self._memo[comp.name] = _CompMem()

        instrs = comp.instructions
        n = len(instrs)
        idx = {i.name: k for k, i in enumerate(instrs)}

        # allocation roots: alias-like results point at the buffers they
        # view (a tuple keeps ALL its elements alive through consumers)
        roots: list[frozenset[int]] = []
        for k, instr in enumerate(instrs):
            aliasing = (instr.opcode in ALIAS_OPCODES
                        or instr.opcode in CARRY_OPCODES
                        or instr.is_done)
            if aliasing and instr.operands:
                s: set[int] = set()
                for o in instr.operands:
                    j = idx.get(o)
                    if j is not None and j < k:
                        s |= roots[j]
                roots.append(frozenset(s) if s else frozenset({k}))
            else:
                roots.append(frozenset({k}))

        def charged(k: int) -> int:
            instr = instrs[k]
            if (instr.opcode in ALIAS_OPCODES
                    or instr.opcode in CARRY_OPCODES or instr.is_done):
                return 0
            return instr.result_bytes

        buffers: dict[int, _Buffer] = {}
        for k, instr in enumerate(instrs):
            if k not in roots[k]:
                continue  # pure alias, never an allocation root
            buffers[k] = _Buffer(
                index=-1 if instr.opcode == "parameter" else k,
                name=instr.name,
                opcode=instr.opcode,
                bytes=charged(k),
                last_use=k,
                is_param=instr.opcode == "parameter",
                parameter_number=instr.parameter_number,
                source=instr.source,
            )

        # live ranges: last consumer over operand + control edges
        for k, instr in enumerate(instrs):
            for o in (*instr.operands, *instr.control_deps):
                j = idx.get(o)
                if j is None:
                    continue
                for r in roots[j]:
                    if r in buffers:
                        buffers[r].last_use = max(buffers[r].last_use, k)
        root_instr = comp.root
        if root_instr is not None:
            for r in roots[idx[root_instr.name]]:
                if r in buffers:
                    buffers[r].last_use = n  # output: live through end
        for b in buffers.values():
            if b.is_param:
                b.last_use = n  # caller-owned: resident the whole run

        mem = _CompMem(buffers=sorted(buffers.values(),
                                      key=lambda b: max(b.index, 0)))
        mem.extra_at = {
            k: self._call_extra(instr)
            for k, instr in enumerate(instrs) if instr.called
        }
        self._memo[comp.name] = mem
        self._sweep(mem, n)
        return mem

    @staticmethod
    def _sweep(mem: _CompMem, n: int) -> None:
        """Peak over the schedule: at each instruction instant, the sum
        of live charged buffers plus the instant's nested extra."""
        if n == 0:
            return
        delta = [0] * (n + 1)
        base = 0
        delta_np = [0] * (n + 1)   # parameters excluded
        base_np = 0
        for b in mem.buffers:
            lo = b.index
            hi = min(b.last_use, n - 1)
            if lo < 0:
                base += b.bytes
                if not b.is_param:
                    base_np += b.bytes
                lo = 0
            else:
                delta[lo] += b.bytes
                if not b.is_param:
                    delta_np[lo] += b.bytes
            if hi + 1 <= n:
                delta[hi + 1] -= b.bytes
                if not b.is_param:
                    delta_np[hi + 1] -= b.bytes
        live, live_np = base, base_np
        for k in range(n):
            live += delta[k]
            live_np += delta_np[k]
            extra = mem.extra_at.get(k, 0)
            if live + extra > mem.peak_bytes:
                mem.peak_bytes = live + extra
                mem.peak_index = k
            mem.peak_extra_bytes = max(mem.peak_extra_bytes,
                                       live_np + extra)


# ---------------------------------------------------------------------------
# the memory pass (per audit target)
# ---------------------------------------------------------------------------


def _count_donor_markers(lowered_text: str) -> int:
    return sum(lowered_text.count(marker) for marker in DONOR_MARKERS)


def _apply_donation(module: HloModule, entry: HloComputation,
                    mem: _CompMem) -> list[dict]:
    """Mark donated parameters and zero-charge the output elements that
    reuse their buffers (the donated region is occupied once, for the
    whole program).  Returns the donated-parameter records."""
    donated_numbers = {a.parameter_number
                       for a in module.input_output_alias}
    by_param = {b.parameter_number: b for b in mem.buffers if b.is_param}
    by_name = {b.name: b for b in mem.buffers}
    root = entry.root
    idx = {i.name: k for k, i in enumerate(entry.instructions)}
    for alias in module.input_output_alias:
        p = by_param.get(alias.parameter_number)
        if p is not None:
            p.donated = True
        # the output element reusing the donated region: charged zero
        target = root
        if (root is not None and root.opcode == "tuple"
                and alias.output_index
                and alias.output_index[0] < len(root.operands)):
            j = idx.get(root.operands[alias.output_index[0]])
            target = entry.instructions[j] if j is not None else None
        if target is None:
            continue
        # follow alias chains to the allocation root(s); zero the first
        # non-parameter one (a param pass-through keeps its param charge)
        stack = [target.name]
        seen: set[str] = set()
        while stack:
            name = stack.pop()
            if name in seen:
                continue
            seen.add(name)
            b = by_name.get(name)
            if b is not None and not b.is_param and not b.aliased_output:
                b.aliased_output = True
                b.bytes = 0
            elif b is None and name in idx:
                for o in entry.instructions[idx[name]].operands:
                    stack.append(o)
    return [
        {
            "name": b.name,
            "parameter_number": b.parameter_number,
            "bytes": b.bytes,
            "aliased": b.donated,
        }
        for b in mem.buffers if b.is_param
        and (b.donated or donated_numbers)
    ]


def _transients(analysis: _ModuleMemory,
                top_n: int) -> tuple[list[dict], int]:
    """Charged, non-parameter buffers that die before their computation
    ends — the intermediates XLA's temp allocation must hold — across
    every materialising computation, largest first."""
    rows: list[dict] = []
    for name, comp in analysis.module.computations.items():
        if name in analysis.skipped:
            continue
        mem = analysis.analyze(comp)
        end = len(comp.instructions)
        for b in mem.buffers:
            if b.is_param or b.bytes <= 0 or b.last_use >= end:
                continue
            rows.append({
                "name": b.name,
                "opcode": b.opcode,
                "bytes": b.bytes,
                "computation": name,
                "execution_count": comp.execution_count,
                "source": b.source,
            })
    rows.sort(key=lambda r: (-r["bytes"], r["name"]))
    max_bytes = rows[0]["bytes"] if rows else 0
    return rows[:top_n], max_bytes


def _check_replicated(analysis: _ModuleMemory, num_devices: int,
                      target: str, findings: list[Finding],
                      floor: int = REPLICATED_FLOOR_BYTES) -> None:
    if num_devices <= 1:
        return
    for cname, comp in analysis.module.computations.items():
        if cname in analysis.skipped:
            continue
        instrs = comp.instructions
        idx = {i.name: k for k, i in enumerate(instrs)}
        consumers: dict[int, list[int]] = {}
        for k, instr in enumerate(instrs):
            for o in instr.operands:
                j = idx.get(o)
                if j is not None:
                    consumers.setdefault(j, []).append(k)
        end = len(instrs)
        mem = analysis.analyze(comp)
        by_index = {b.index: b for b in mem.buffers}
        for k, instr in enumerate(instrs):
            b = by_index.get(k)
            if (b is None or b.is_param or b.bytes < floor
                    or b.last_use >= end or instr.kind is not None):
                continue
            if not instr.operand_arrays:
                # constants/iota materialise from nothing — "P x larger
                # than every operand" is vacuous there, and a baked
                # weight table must never trip an error finding
                continue
            max_operand = max(
                _array_bytes(d, s) for d, s in instr.operand_arrays
            )
            if max_operand * num_devices > b.bytes:
                continue  # producer not sharded relative to this buffer
            shrunk = [
                instrs[c] for c in consumers.get(k, ())
                if instrs[c].result_bytes * num_devices <= b.bytes
            ]
            if not shrunk:
                continue
            findings.append(Finding(
                pass_name="memory",
                rule="transient-replicated-buffer",
                severity=SEVERITY_ERROR,
                target=target,
                message=(
                    f"{instr.opcode} {instr.name} materialises "
                    f"{b.bytes} B/device — at least {num_devices}x "
                    f"every operand that feeds it and consumer "
                    f"{shrunk[0].name} shrinks it back by the same "
                    "factor: a full-size replicated intermediate "
                    "between sharded producer and consumer (the "
                    "transient HBM spike class); create the value "
                    "directly under its target sharding (jit "
                    "out-shardings / sharding constraint) instead of "
                    "materialising the replicated copy"
                ),
                location=instr.source,
                details={
                    "name": instr.name,
                    "opcode": instr.opcode,
                    "bytes": b.bytes,
                    "max_operand_bytes": max_operand,
                    "num_devices": num_devices,
                    "computation": cname,
                    "shrinking_consumers": [i.name for i in shrunk],
                },
            ))


def analyze_memory(
    hlo: "str | HloModule",
    expectation: TargetExpectation,
    target: str,
    lowered_text: str = "",
    num_devices: int = 1,
    tier: Optional[CostTier] = None,
    top_n: int = 8,
) -> tuple[list[Finding], dict]:
    """Run the buffer-liveness memory audit over one compiled module.
    Returns the findings plus the per-target memory meta (the JSON-report
    / baseline payload)."""
    module = hlo if isinstance(hlo, HloModule) else parse_module(hlo)
    findings: list[Finding] = []
    analysis = _ModuleMemory(module)
    entry = module.entry_computation()
    if entry is None:
        return findings, {"peak_live_bytes": 0}

    mem = analysis.analyze(entry)
    donated_params = _apply_donation(module, entry, mem)
    # donation rewrites buffer charges: re-sweep the entry
    mem.peak_bytes = mem.peak_extra_bytes = 0
    analysis._sweep(mem, len(entry.instructions))

    end = len(entry.instructions)
    param_bytes = sum(b.bytes for b in mem.buffers if b.is_param)
    donated_bytes = sum(b.bytes for b in mem.buffers
                        if b.is_param and b.donated)
    # output buffers: the only non-parameter allocations living through
    # program end (donated-aliased elements were zero-charged above)
    output_bytes = sum(
        b.bytes for b in mem.buffers
        if not b.is_param and b.last_use >= end and b.bytes > 0
    )

    # live set at the peak instant
    peak_k = mem.peak_index
    live_at_peak = sorted(
        (
            {"name": b.name, "opcode": b.opcode, "bytes": b.bytes}
            for b in mem.buffers
            if b.bytes > 0 and b.index <= peak_k <= b.last_use
        ),
        key=lambda r: (-r["bytes"], r["name"]),
    )
    top_transients, max_transient = _transients(analysis, top_n)

    meta: dict[str, Any] = {
        "peak_live_bytes": int(mem.peak_bytes),
        "peak_instruction": (
            entry.instructions[peak_k].name
            if 0 <= peak_k < end else None
        ),
        "parameter_bytes": int(param_bytes),
        "donated_param_bytes": int(donated_bytes),
        "output_bytes": int(output_bytes),
        "num_buffers": sum(
            1 for b in mem.buffers if b.bytes > 0 or b.is_param
        ),
        "donated_params": donated_params,
        "live_at_peak": live_at_peak[:top_n],
        "top_transients": top_transients,
        "max_transient_bytes": int(max_transient),
    }
    if tier is not None:
        headroom = hbm_headroom_bytes(mem.peak_bytes, tier)
        meta["hbm_bytes"] = int(tier.hbm_bytes) or None
        meta["hbm_headroom_bytes"] = headroom
        meta["feasible"] = memory_feasible(mem.peak_bytes, tier)
        if meta["feasible"] is False:
            findings.append(Finding(
                pass_name="memory", rule="hbm-infeasible",
                severity=SEVERITY_WARNING, target=target,
                message=(
                    f"audited peak {mem.peak_bytes} B/device exceeds the "
                    f"{tier.name} tier's recorded capacity of "
                    f"{int(tier.hbm_bytes)} B — this program OOMs on "
                    "that hardware; a plan search must prune it"
                ),
                details={"peak_live_bytes": mem.peak_bytes,
                         "hbm_bytes": int(tier.hbm_bytes)},
            ))

    # -- rules --------------------------------------------------------------

    if (expectation.max_peak_bytes is not None
            and mem.peak_bytes > expectation.max_peak_bytes):
        findings.append(Finding(
            pass_name="memory", rule="peak-memory-ceiling",
            severity=SEVERITY_ERROR, target=target,
            message=(
                f"peak live bytes {mem.peak_bytes} B/device exceed the "
                f"plan ceiling of {expectation.max_peak_bytes} B — the "
                "lowered program keeps more resident than the analytic "
                "model (params + state + activations + cache) accounts "
                "for; inspect live_at_peak/top_transients for the "
                "buffer the plan does not know about"
            ),
            details={
                "peak_live_bytes": int(mem.peak_bytes),
                "max_peak_bytes": expectation.max_peak_bytes,
                "live_at_peak": live_at_peak[:top_n],
            },
        ))

    donors = _count_donor_markers(lowered_text)
    aliased = sum(1 for p in donated_params if p["aliased"])
    # the contract can demand donation even when the lowered text is
    # unavailable (or the donor marker never made it in): at least one
    # aliased buffer must exist on an expect_donation target
    expected_donors = donors or (1 if expectation.expect_donation else 0)
    if expected_donors and aliased < expected_donors:
        findings.append(Finding(
            pass_name="memory", rule="unaliased-donation",
            severity=SEVERITY_ERROR, target=target,
            message=(
                f"{donors} donor marker(s) in the lowered module "
                f"(expectation demands >= {expected_donors}) but the "
                f"compiled module aliases only {aliased} — the donation "
                "was dropped (layout/sharding mismatch between the "
                "donated input and its output, or a missing "
                "donate_argnums), so input AND output state stay "
                "simultaneously resident; the donated buffer's live "
                "range runs to program end without an aliased output "
                "reusing it"
            ),
            details={
                "donor_markers": donors,
                "aliased_parameters": aliased,
                "donated_params": donated_params,
            },
        ))

    _check_replicated(analysis, num_devices, target, findings)

    if expectation.donated_bytes_expected is not None:
        expected = expectation.donated_bytes_expected
        tol = expectation.donated_bytes_tolerance
        if abs(donated_bytes - expected) > tol * expected:
            findings.append(Finding(
                pass_name="memory", rule="serving-cache-drift",
                severity=SEVERITY_ERROR, target=target,
                message=(
                    f"donated input buffers sum to {donated_bytes} "
                    f"B/device but the analytic model (validate_serving's "
                    f"kv_cache_bytes_per_device) prices {expected} B "
                    f"(tolerance {tol:.0%}) — the build-time HBM budget "
                    "gate and the compiled program disagree about the "
                    "cache footprint; fix whichever drifted and re-pin"
                ),
                details={
                    "donated_param_bytes": int(donated_bytes),
                    "expected_bytes": expected,
                    "tolerance": tol,
                    "donated_params": donated_params,
                },
            ))
        meta["analytic_donated_bytes"] = expected
    return findings, meta


# ---------------------------------------------------------------------------
# manifest / Prometheus surface (`analyze memory --output DIR`)
# ---------------------------------------------------------------------------


def memory_metrics(memory: dict[str, dict], tier: Optional[CostTier] = None,
                   registry=None):
    """The memory audit as Prometheus gauges — one
    ``analysis_peak_live_bytes{target=...}`` sample per audited target
    (plus headroom where the tier records capacity), folded into the
    same ``metrics.prom`` the calibration gauges land in so memory
    regressions show up next to cost-model health on a scrape
    dashboard."""
    from dlbb_tpu.obs.export import MetricsRegistry

    registry = registry or MetricsRegistry()
    tier_label = tier.name if tier is not None else "unknown"
    for target in sorted(memory):
        meta = memory[target]
        registry.set_gauge(
            "analysis_peak_live_bytes", meta.get("peak_live_bytes", 0),
            help="statically audited per-device peak live bytes "
                 "(buffer-liveness pass)",
            target=target, tier=tier_label,
        )
        headroom = meta.get("hbm_headroom_bytes")
        if headroom is not None:
            registry.set_gauge(
                "analysis_hbm_headroom_bytes", headroom,
                help="tier capacity minus audited peak",
                target=target, tier=tier_label,
            )
    registry.set_gauge("analysis_memory_targets", len(memory),
                       help="targets the memory audit covered",
                       tier=tier_label)
    return registry


def write_memory_artifacts(memory: dict[str, dict], out_dir: "str | Path",
                           tier: Optional[CostTier] = None) -> Path:
    """Write the per-target memory report under ``out_dir`` and surface
    it where runtime health already lives: the audit aggregate (peak
    per target + the pricing tier) merges into the directory's
    ``sweep_manifest.json`` and the gauges fold into ``metrics.prom``
    without clobbering a co-located sweep/serving export."""
    from dlbb_tpu.obs.calibration import METRICS_NAME, _fold_metrics
    from dlbb_tpu.utils.config import atomic_write_text, save_json

    out_dir = Path(out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    report = {
        "schema": MEMORY_REPORT_SCHEMA,
        "tier": tier.name if tier is not None else None,
        "cost_model_version": tier.version if tier is not None else None,
        "targets": memory,
        "timestamp": time.time(),
    }
    path = atomic_write_text(
        json.dumps(report, indent=2, sort_keys=True),
        out_dir / MEMORY_REPORT_NAME,
    )

    from dlbb_tpu.bench.schedule import MANIFEST_NAME, MANIFEST_SCHEMA

    manifest_path = out_dir / MANIFEST_NAME
    manifest: dict[str, Any] = {"schema": MANIFEST_SCHEMA,
                                "kind": "memory-audit"}
    if manifest_path.exists():
        try:
            manifest = json.loads(manifest_path.read_text())
        except (OSError, json.JSONDecodeError):
            pass  # torn/legacy manifest: rewrite with the audit only
    manifest["memory_audit"] = {
        "tier": tier.name if tier is not None else None,
        "cost_model_version": tier.version if tier is not None else None,
        "targets_audited": len(memory),
        "peak_live_bytes": {
            t: memory[t].get("peak_live_bytes") for t in sorted(memory)
        },
    }
    manifest.setdefault("timestamp", time.time())
    save_json(manifest, manifest_path)
    _fold_metrics(memory_metrics(memory, tier), out_dir / METRICS_NAME)
    return path
