"""Pass 3 — HLO schedule auditor (α–β critical path + overlap proof).

``hlo_audit`` proves *which* collectives a lowered program contains and
*how many bytes* they move; this pass proves *when* they run.  On the
instruction dependency graph (``hlo_parse.parse_module``) it computes,
per audit target:

- **Overlap verification** — for every collective (sync, or an async
  ``-start``/``-done`` pair), the dense-compute instructions that can
  execute concurrently with the transfer: instructions that are neither
  ancestors nor descendants of the collective in the dependency order
  (restricted, for async pairs, to the scheduled window strictly between
  start and done).  A ring hop with **zero** straddling matmul FLOPs is a
  ``serialized-collective`` finding on targets whose expectation claims
  overlap (``TargetExpectation.expect_overlap`` — the PR-4 ring/bidir
  collective-matmul schedules): it turns the overlap contract from
  "≥ 4(tp−1) permutes exist" into "each hop is hidden".
- **α–β critical path** — every instruction priced by the versioned
  cost-model table (``costmodel.py``): collectives at
  ``α(tier) + wire_bytes/β(tier)`` (analytic ring wire volume,
  ``expectations.wire_bytes``), dense compute at ``FLOPs/peak``, nested
  computations recursively (a ``while`` multiplies its body's critical
  path by the known trip count).  Reported per target as
  ``critical_path_us``, ``comm_on_critical_path_us`` and
  ``overlap_efficiency`` (the fraction of total comm time that can hide
  behind independent compute — an ASAP infinite-resource bound, so it is
  an *upper* bound on achievable overlap and a hard zero for a
  serialized schedule).
- **Divergent-branch check** — the collective sequences reachable from
  each branch of every ``conditional`` must be identical in kind +
  replica groups: on a pod, ranks taking different branches would post
  mismatched collectives and deadlock the slice.
- **Regression baselines** — per-target snapshots of the inventory and
  the critical-path numbers under ``stats/analysis/baselines/``;
  ``analyze diff`` fails on unexplained growth (>10 % critical path or
  wire volume, any new collective kind) and ``analyze snapshot``
  regenerates them.  Baselines record the cost-model version + tier and
  refuse to compare across either.

Everything here is pure text/graph analysis — importable WITHOUT jax
(only the lowering in ``hlo_audit`` needs a backend).
"""

from __future__ import annotations

import json
import re
from dataclasses import dataclass, field
from math import prod
from pathlib import Path
from typing import Optional

from dlbb_tpu.analysis.costmodel import (
    COST_MODEL_VERSION,
    CostTier,
    collective_cost_us,
    compute_cost_us,
    dispatch_cost_us,
    resolve_tier,
)
from dlbb_tpu.analysis.expectations import TargetExpectation, wire_bytes
from dlbb_tpu.analysis.findings import (
    SEVERITY_ERROR,
    SEVERITY_WARNING,
    Finding,
)
from dlbb_tpu.analysis.hlo_parse import (
    HloComputation,
    HloInstruction,
    HloModule,
    parse_module,
)

# the naming hooks parallel/collective_matmul.py (ring_hop) and
# comm/compression.py (qring_hop) put into the jax name stack: ring hops
# are the instructions the overlap gate pins; qring hops are the
# deliberately sequential quantised-ring hops (dequant-accumulate-requant
# chains) and are exempt from it
RING_HOP_MARK = "ring_hop"
QRING_HOP_MARK = "qring_hop"

# baseline-gate thresholds: growth beyond these fails `analyze diff`
CRITICAL_PATH_SLACK = 1.10
WIRE_SLACK = 1.10
# memory axis (the buffer-liveness pass, memory_audit.py): same 10 %
# contract on peak live bytes and on the largest transient buffer
PEAK_MEMORY_SLACK = 1.10
# numerics axis (the dtype-flow pass, numerics_audit.py): the worst-case
# relative error bound moves only with reduction SHAPE (log2 of the tree
# fan-in) or accumulator DTYPE (>= 2^13x when f32 drops to bf16), so 2x
# absorbs shape jitter while any precision downgrade still trips; convert
# churn gets 25 % headroom on the execution-weighted convert count
NUMERICS_ERROR_SLACK = 2.0
NUMERICS_CONVERT_SLACK = 1.25


# ---------------------------------------------------------------------------
# per-computation dependency-graph analysis
# ---------------------------------------------------------------------------


def _dot_flops(instr: HloInstruction) -> int:
    """2 * prod(result) * prod(contracted lhs dims) for a ``dot``; 0 for
    everything that is not dense compute."""
    if instr.opcode != "dot" or not instr.operand_arrays:
        return 0
    lhs_shape = instr.operand_arrays[0][1]
    contracted = prod(
        lhs_shape[d] for d in instr.lhs_contracting_dims
        if d < len(lhs_shape)
    ) if instr.lhs_contracting_dims else 1
    out = prod(instr.shape) if instr.shape else 1
    return 2 * int(out) * int(contracted)


def _fusion_flops(instr: HloInstruction, module: HloModule,
                  memo: dict[str, int]) -> int:
    """Dense FLOPs inside a fused computation (dots can be fused on TPU;
    elementwise work is priced at zero — it is never what hides comm)."""
    total = 0
    for role, callee in instr.called:
        if role != "calls" or callee not in module.computations:
            continue
        if callee not in memo:
            memo[callee] = 0  # cycle guard (impossible in valid HLO)
            memo[callee] = sum(
                _instr_flops(i, module, memo)
                for i in module.computations[callee].instructions
            )
        total += memo[callee]
    return total


def _instr_flops(instr: HloInstruction, module: HloModule,
                 memo: dict[str, int]) -> int:
    if instr.opcode == "dot":
        return _dot_flops(instr)
    if instr.opcode == "fusion":
        return _fusion_flops(instr, module, memo)
    return 0


def _collective_wire(instr: HloInstruction) -> int:
    payload, _, _ = instr.collective_payload()
    return wire_bytes(instr.kind, payload, instr.group_size)


@dataclass
class _CompStats:
    """Cached schedule analysis of one computation (single execution)."""

    critical_path_us: float = 0.0
    comm_on_cp_us: float = 0.0
    comm_total_us: float = 0.0
    compute_total_us: float = 0.0
    hidden_comm_us: float = 0.0
    collectives: list[dict] = field(default_factory=list)


class _ModuleAnalysis:
    """Schedule analysis over a parsed module with one cost tier."""

    def __init__(self, module: HloModule, tier: CostTier):
        self.module = module
        self.tier = tier
        self._flops_memo: dict[str, int] = {}
        self._comp_memo: dict[str, _CompStats] = {}

    # -- instruction pricing ------------------------------------------------

    def _instr_cost(self, instr: HloInstruction) -> tuple[float, float]:
        """(total cost, comm component) of one instruction, nested
        computations included.  Async ``-done`` ops cost nothing (the
        transfer is charged to the ``-start``, which is what makes the
        start→…→done path carry the wire time)."""
        if instr.kind is not None:
            if instr.is_done:
                return 0.0, 0.0
            c = collective_cost_us(_collective_wire(instr), self.tier)
            return c, c
        if instr.opcode == "while":
            body = cond = None
            for role, callee in instr.called:
                if role == "body":
                    body = callee
                elif role == "condition":
                    cond = callee
            trip = instr.trip_count or 1
            cost = comm = 0.0
            if body in self.module.computations:
                s = self._analyze_comp(self.module.computations[body])
                cost += trip * s.critical_path_us
                comm += trip * s.comm_on_cp_us
            if cond in self.module.computations:
                s = self._analyze_comp(self.module.computations[cond])
                cost += trip * s.critical_path_us
                comm += trip * s.comm_on_cp_us
            return cost, comm
        if instr.opcode == "conditional":
            best = (0.0, 0.0)
            for role, callee in instr.called:
                if callee in self.module.computations and role in (
                        "branch_computations", "true_computation",
                        "false_computation"):
                    s = self._analyze_comp(self.module.computations[callee])
                    if s.critical_path_us > best[0]:
                        best = (s.critical_path_us, s.comm_on_cp_us)
            return best
        if instr.opcode in ("call", "async-start"):
            cost = comm = 0.0
            for role, callee in instr.called:
                if role == "calls" and callee in self.module.computations:
                    s = self._analyze_comp(self.module.computations[callee])
                    cost += s.critical_path_us
                    comm += s.comm_on_cp_us
            return cost, comm
        flops = _instr_flops(instr, self.module, self._flops_memo)
        if flops:
            return compute_cost_us(flops, self.tier), 0.0
        return 0.0, 0.0

    # -- per-computation DAG analysis ---------------------------------------

    def _analyze_comp(self, comp: HloComputation) -> _CompStats:
        cached = self._comp_memo.get(comp.name)
        if cached is not None:
            return cached
        # cycle guard: self-referential HLO is invalid, but a truncated
        # dump must not hang the auditor
        self._comp_memo[comp.name] = _CompStats()

        instrs = comp.instructions
        idx = {i.name: n for n, i in enumerate(instrs)}
        deps: list[list[int]] = [
            sorted({idx[o] for o in (*i.operands, *i.control_deps)
                    if o in idx and idx[o] != n})
            for n, i in enumerate(instrs)
        ]
        order = _topo_order(len(instrs), deps)

        costs = [self._instr_cost(i) for i in instrs]
        flops = [
            _instr_flops(i, self.module, self._flops_memo) for i in instrs
        ]

        # ancestor bitsets in topo order (operand + control edges)
        anc = [0] * len(instrs)
        for n in order:
            a = 0
            for d in deps[n]:
                a |= anc[d] | (1 << d)
            anc[n] = a

        # ASAP longest-path arrival times + comm time along the argmax path
        finish = [0.0] * len(instrs)
        comm_on_path = [0.0] * len(instrs)
        for n in order:
            start, comm = 0.0, 0.0
            for d in deps[n]:
                if finish[d] > start:
                    start, comm = finish[d], comm_on_path[d]
            finish[n] = start + costs[n][0]
            comm_on_path[n] = comm + costs[n][1]
        stats = _CompStats()
        if instrs:
            end = max(range(len(instrs)), key=lambda n: finish[n])
            stats.critical_path_us = finish[end]
            stats.comm_on_cp_us = comm_on_path[end]
        stats.compute_total_us = sum(
            compute_cost_us(f, self.tier) for f in flops if f
        )

        # async pairing: done instruction consuming a start's value
        done_pos: dict[int, int] = {}
        for n, i in enumerate(instrs):
            if i.kind is not None and i.is_done:
                for o in i.operands:
                    s = idx.get(o)
                    if s is not None and instrs[s].is_start:
                        done_pos[s] = n

        # per-collective overlap: compute independent of the collective
        # (neither ancestor nor descendant), window-restricted for async
        # pairs to the instructions scheduled strictly between start/done
        for n, i in enumerate(instrs):
            if i.kind is None or i.is_done:
                continue
            cost = costs[n][0]
            lo, hi = 0, len(instrs)
            if n in done_pos:
                lo, hi = n + 1, done_pos[n]
            indep_us, indep_flops = 0.0, 0
            for m in range(lo, hi):
                if not flops[m] or m == n:
                    continue
                if (anc[n] >> m) & 1 or (anc[m] >> n) & 1:
                    continue
                indep_us += compute_cost_us(flops[m], self.tier)
                indep_flops += flops[m]
            op_name = i.op_name or ""
            stats.collectives.append({
                "name": i.name,
                "kind": i.kind,
                "cost_us": cost,
                "wire_bytes": _collective_wire(i),
                "straddling_flops": indep_flops,
                "straddling_compute_us": indep_us,
                "hidden_us": min(cost, indep_us),
                "async": n in done_pos,
                "is_ring_hop": (RING_HOP_MARK in op_name
                                and QRING_HOP_MARK not in op_name),
                "op_name": i.op_name,
                "source": i.source,
                "computation": comp.name,
            })
        stats.comm_total_us = sum(c["cost_us"] for c in stats.collectives)
        stats.hidden_comm_us = sum(c["hidden_us"] for c in stats.collectives)
        self._comp_memo[comp.name] = stats
        return stats

    # -- module-level aggregation -------------------------------------------

    def analyze(self) -> dict:
        entry = self.module.entry_computation()
        if entry is None:
            return {
                "cost_model_version": self.tier.version,
                "tier": self.tier.name,
                "critical_path_us": 0.0,
                "dispatch_count": 1,
                "dispatch_overhead_us": round(
                    dispatch_cost_us(1, self.tier), 6),
                "predicted_wall_us": round(
                    dispatch_cost_us(1, self.tier), 6),
                "comm_on_critical_path_us": 0.0,
                "comm_total_us": 0.0,
                "compute_total_us": 0.0,
                "overlap_efficiency": None,
                "total_wire_bytes": 0,
                "num_collectives": 0,
                "collective_kinds": {},
                "collectives": [],
            }
        entry_stats = self._analyze_comp(entry)
        dispatch_overhead = dispatch_cost_us(1, self.tier)
        # fused computations are priced at their fusion call site
        # (_fusion_flops feeds the caller's flops[] and compute_total);
        # walking them again here would double-count their dots.  They
        # can never hold collectives, so skipping them drops nothing.
        fused = {
            callee
            for _, instr in self.module.all_instructions()
            if instr.opcode == "fusion"
            for role, callee in instr.called if role == "calls"
        }
        comm_total = hidden = compute_total = 0.0
        total_wire = 0
        kinds: dict[str, int] = {}
        collectives: list[dict] = []
        for comp in self.module.computations.values():
            if comp.name in fused:
                continue
            s = self._analyze_comp(comp)
            mult = comp.execution_count
            comm_total += mult * s.comm_total_us
            hidden += mult * s.hidden_comm_us
            compute_total += mult * s.compute_total_us
            for c in s.collectives:
                total_wire += mult * c["wire_bytes"]
                if mult:
                    # mult 0 = a non-first conditional branch: keep the
                    # instruction in the inventory (it is still schedule-
                    # checked) but charge it nothing
                    kinds[c["kind"]] = kinds.get(c["kind"], 0) + mult
                collectives.append({**c, "execution_count": mult})
        return {
            "cost_model_version": self.tier.version,
            "tier": self.tier.name,
            "critical_path_us": round(entry_stats.critical_path_us, 6),
            # the wall prediction for ONE execution of this program:
            # critical path + γ x 1 host dispatch (γ = 0 under cm1 — the
            # un-modelled term the cm2 fit supplies)
            "dispatch_count": 1,
            "dispatch_overhead_us": round(dispatch_overhead, 6),
            "predicted_wall_us": round(
                entry_stats.critical_path_us + dispatch_overhead, 6
            ),
            "comm_on_critical_path_us": round(entry_stats.comm_on_cp_us, 6),
            "comm_total_us": round(comm_total, 6),
            "compute_total_us": round(compute_total, 6),
            "overlap_efficiency": (
                round(hidden / comm_total, 6) if comm_total > 0 else None
            ),
            "total_wire_bytes": total_wire,
            "num_collectives": sum(kinds.values()),
            "collective_kinds": dict(sorted(kinds.items())),
            "collectives": collectives,
        }


def _topo_order(n: int, deps: list[list[int]]) -> list[int]:
    """Kahn topological order (text order is already topological in
    scheduled HLO, but a defensive sort keeps synthetic fixtures honest).
    Nodes in dependency cycles (invalid HLO) are appended in text order so
    the analysis degrades instead of dropping instructions."""
    indeg = [0] * n
    out: list[list[int]] = [[] for _ in range(n)]
    for i, ds in enumerate(deps):
        for d in ds:
            out[d].append(i)
            indeg[i] += 1
    queue = [i for i in range(n) if indeg[i] == 0]
    order: list[int] = []
    while queue:
        i = queue.pop()
        order.append(i)
        for j in out[i]:
            indeg[j] -= 1
            if indeg[j] == 0:
                queue.append(j)
    if len(order) < n:
        seen = set(order)
        order.extend(i for i in range(n) if i not in seen)
    return order


# ---------------------------------------------------------------------------
# divergent-branch (cross-shard deadlock) check
# ---------------------------------------------------------------------------


def _collective_signature(module: HloModule, comp_name: str,
                          _seen: Optional[set] = None) -> list[tuple]:
    """Ordered (kind, replica_groups) sequence posted by one computation,
    recursing through calls / loop bodies (trip-count-expanded) — the
    thing that must match across conditional branches for all shards to
    agree on the collective schedule."""
    if _seen is None:
        _seen = set()
    if comp_name in _seen or comp_name not in module.computations:
        return []
    _seen = _seen | {comp_name}
    sig: list[tuple] = []
    for instr in module.computations[comp_name].instructions:
        if instr.kind is not None and not instr.is_done:
            sig.append((instr.kind, instr.replica_groups))
        for role, callee in instr.called:
            if role == "to_apply":
                continue
            reps = (instr.trip_count or 1) if role == "body" else 1
            child = _collective_signature(module, callee, _seen)
            sig.extend(child * reps)
    return sig


def _check_divergent_branches(module: HloModule, target: str,
                              findings: list[Finding]) -> None:
    for comp, instr in module.all_instructions():
        if instr.opcode != "conditional":
            continue
        branches = [
            (callee, _collective_signature(module, callee))
            for role, callee in instr.called
            if role in ("branch_computations", "true_computation",
                        "false_computation")
        ]
        if len(branches) < 2:
            continue
        base_name, base_sig = branches[0]
        for name, sig in branches[1:]:
            if sig != base_sig:
                findings.append(Finding(
                    pass_name="schedule",
                    rule="divergent-branch-collectives",
                    severity=SEVERITY_ERROR,
                    target=target,
                    message=(
                        f"conditional {instr.name} posts different "
                        f"collective sequences per branch ({base_name}: "
                        f"{len(base_sig)} vs {name}: {len(sig)}) — on a "
                        "pod, shards taking different branches would "
                        "post mismatched collectives and deadlock the "
                        "slice; hoist the collectives out of the branch "
                        "or make the sequences identical in kind + "
                        "replica groups"
                    ),
                    location=instr.source,
                    details={
                        "conditional": instr.name,
                        "computation": comp.name,
                        "branches": {
                            base_name: [list(t) for t in base_sig],
                            name: [list(t) for t in sig],
                        },
                    },
                ))
                break


# ---------------------------------------------------------------------------
# the schedule pass (per audit target)
# ---------------------------------------------------------------------------


def analyze_schedule(
    hlo: "str | HloModule",
    expectation: TargetExpectation,
    target: str,
    tier: "Optional[str | CostTier]" = None,
    model: str = COST_MODEL_VERSION,
) -> tuple[list[Finding], dict]:
    """Run the schedule audit over one compiled module.  Returns the
    findings plus the per-target schedule meta (the JSON-report /
    baseline payload).  ``model`` selects cm1 (analytic constants) or
    cm2 (the fitted DB, falling back loudly when absent); a pre-resolved
    :class:`CostTier` may be passed directly as ``tier``."""
    module = hlo if isinstance(hlo, HloModule) else parse_module(hlo)
    cost_tier = (tier if isinstance(tier, CostTier)
                 else resolve_tier(tier, model=model))
    findings: list[Finding] = []

    meta = _ModuleAnalysis(module, cost_tier).analyze()
    _check_divergent_branches(module, target, findings)

    if expectation.expect_overlap:
        hops = [c for c in meta["collectives"] if c["is_ring_hop"]]
        if not hops:
            # naming hooks absent (e.g. a hand-built fixture): fall back
            # to every permute — the overlap contract is about the ring
            hops = [c for c in meta["collectives"]
                    if c["kind"] == "collective-permute"]
        serialized = [c for c in hops if c["straddling_flops"] == 0]
        meta["ring_hops"] = {
            "total": len(hops),
            "straddled": len(hops) - len(serialized),
        }
        for c in serialized:
            findings.append(Finding(
                pass_name="schedule",
                rule="serialized-collective",
                severity=SEVERITY_ERROR,
                target=target,
                message=(
                    f"ring hop {c['name']} ({c['kind']}, "
                    f"{c['wire_bytes']} wire B) has no straddling "
                    "matmul — no dense compute is independent of the "
                    "transfer, so the hop serialises into the critical "
                    "path and the overlap claim is void for this "
                    "schedule"
                ),
                location=c["source"],
                details={k: c[k] for k in (
                    "name", "kind", "cost_us", "wire_bytes",
                    "straddling_flops", "computation", "op_name",
                )},
            ))
    return findings, meta


# ---------------------------------------------------------------------------
# regression baselines (snapshot / diff gate)
# ---------------------------------------------------------------------------

DEFAULT_BASELINE_DIR = Path("stats/analysis/baselines")

# keys of the schedule meta that are snapshotted and diffed (the
# peak_live_bytes / max_transient_bytes pair is folded in from the
# memory pass by analysis.run_analysis — one gate file per target)
_BASELINE_KEYS = (
    "cost_model_version", "tier", "critical_path_us",
    "comm_on_critical_path_us", "comm_total_us", "compute_total_us",
    "overlap_efficiency", "total_wire_bytes", "num_collectives",
    "collective_kinds", "peak_live_bytes", "max_transient_bytes",
    "numerics_low_precision_sites", "numerics_convert_count",
    "numerics_max_rel_error_bound",
)


def baseline_path(directory: Path, target: str) -> Path:
    """File for one target's snapshot: the target name slugified (exact
    name kept inside the JSON)."""
    slug = re.sub(r"[^\w.]+", "_", target).strip("_")
    return Path(directory) / f"{slug}.json"


def snapshot_baselines(schedule_meta: dict[str, dict],
                       directory: Path,
                       skipped_targets: tuple[str, ...] = ()) -> list[Path]:
    """Write one baseline JSON per audited target; returns the paths.
    Stale snapshots for targets that no longer exist are removed so the
    committed directory always mirrors the audit surface — but a target
    merely SKIPPED this run (insufficient devices, e.g. a snapshot taken
    on a small host) keeps its committed baseline: pruning it would make
    the next full-mesh ``analyze diff`` fail missing-baseline on every
    target the small host could not audit."""
    from dlbb_tpu.utils.config import atomic_write_text

    directory = Path(directory)
    directory.mkdir(parents=True, exist_ok=True)
    written: list[Path] = []
    keep = {
        baseline_path(directory, t).name for t in skipped_targets
    }
    for target in sorted(schedule_meta):
        meta = schedule_meta[target]
        payload = {"target": target}
        payload.update({k: meta.get(k) for k in _BASELINE_KEYS})
        path = baseline_path(directory, target)
        keep.add(path.name)
        atomic_write_text(
            json.dumps(payload, indent=2, sort_keys=True) + "\n", path
        )
        written.append(path)
    for stale in sorted(directory.glob("*.json")):
        if stale.name not in keep:
            stale.unlink()
    return written


def load_baselines(directory: Path) -> dict[str, dict]:
    out: dict[str, dict] = {}
    for path in sorted(Path(directory).glob("*.json")):
        data = json.loads(path.read_text())
        out[data["target"]] = data
    return out


def diff_baselines(
    schedule_meta: dict[str, dict],
    directory: Path,
    skipped_targets: tuple[str, ...] = (),
) -> list[Finding]:
    """Compare one audit run against the committed snapshots.  Errors on
    unexplained growth (> 10 % critical path or wire volume, any new
    collective kind), on a target with no snapshot, and on cost-model
    version/tier skew; warns (never fails CI) when the numbers *improved*
    enough that a re-snapshot would tighten the gate."""
    findings: list[Finding] = []
    directory = Path(directory)
    baselines = load_baselines(directory) if directory.is_dir() else {}
    if not baselines:
        findings.append(Finding(
            pass_name="schedule", rule="missing-baseline",
            severity=SEVERITY_ERROR, target=str(directory),
            message=(
                f"no committed schedule baselines under {directory} — "
                "run `python -m dlbb_tpu.cli analyze snapshot "
                "--simulate 8` and commit the result"
            ),
        ))
        return findings

    for target in sorted(schedule_meta):
        cur = schedule_meta[target]
        base = baselines.get(target)
        if base is None:
            findings.append(Finding(
                pass_name="schedule", rule="missing-baseline",
                severity=SEVERITY_ERROR, target=target,
                message=(
                    "audited target has no committed baseline snapshot — "
                    "a new target must land with its expectation: run "
                    "`analyze snapshot` and commit "
                    f"{baseline_path(directory, target)}"
                ),
            ))
            continue
        if (base.get("cost_model_version") != cur.get("cost_model_version")
                or base.get("tier") != cur.get("tier")):
            findings.append(Finding(
                pass_name="schedule", rule="cost-model-mismatch",
                severity=SEVERITY_ERROR, target=target,
                message=(
                    f"baseline priced with cost model "
                    f"{base.get('cost_model_version')}/{base.get('tier')} "
                    f"but this run uses {cur.get('cost_model_version')}/"
                    f"{cur.get('tier')} — numbers are not comparable; "
                    "re-snapshot after a cost-model change"
                ),
            ))
            continue
        new_kinds = sorted(
            set(cur.get("collective_kinds", {}))
            - set(base.get("collective_kinds", {}))
        )
        if new_kinds:
            findings.append(Finding(
                pass_name="schedule", rule="new-collective-kind",
                severity=SEVERITY_ERROR, target=target,
                message=(
                    f"collective kind(s) {new_kinds} appear that the "
                    "baseline does not contain — a sharding change "
                    "introduced a new communication pattern; explain it "
                    "and re-snapshot, or fix the sharding"
                ),
                details={
                    "new_kinds": new_kinds,
                    "baseline_kinds": base.get("collective_kinds", {}),
                    "current_kinds": cur.get("collective_kinds", {}),
                },
            ))
        for key, slack, rule in (
            ("critical_path_us", CRITICAL_PATH_SLACK,
             "critical-path-regression"),
            ("total_wire_bytes", WIRE_SLACK, "wire-volume-regression"),
            ("peak_live_bytes", PEAK_MEMORY_SLACK,
             "peak-memory-regression"),
            ("max_transient_bytes", PEAK_MEMORY_SLACK,
             "transient-buffer-regression"),
            ("numerics_max_rel_error_bound", NUMERICS_ERROR_SLACK,
             "numerics-error-regression"),
            ("numerics_convert_count", NUMERICS_CONVERT_SLACK,
             "convert-churn-regression"),
        ):
            b, c = base.get(key), cur.get(key)
            if not b or c is None:
                continue
            if c > b * slack:
                findings.append(Finding(
                    pass_name="schedule", rule=rule,
                    severity=SEVERITY_ERROR, target=target,
                    message=(
                        f"{key} grew {c / b:.2f}x over the committed "
                        f"baseline ({b} -> {c}, gate at {slack:.2f}x) — "
                        "unexplained "
                        + ("memory" if "bytes" in key
                           and "wire" not in key else "schedule")
                        + " regression; investigate, then re-snapshot "
                        "if the growth is intended"
                    ),
                    details={"key": key, "baseline": b, "current": c,
                             "ratio": round(c / b, 4)},
                ))
            elif c < b / slack and key in ("critical_path_us",
                                           "peak_live_bytes"):
                findings.append(Finding(
                    pass_name="schedule", rule="baseline-improved",
                    severity=SEVERITY_WARNING, target=target,
                    message=(
                        f"{key} improved {b / max(c, 1e-9):.2f}x under "
                        "the committed baseline — re-snapshot to tighten "
                        "the regression gate"
                    ),
                    details={"key": key, "baseline": b, "current": c},
                ))
        # low-precision accumulation sites gate at exactly zero growth
        # (the committed fleet is all-f32 today, so the ratio gate above
        # skips its falsy baseline): any NEW bf16/f16 accumulator is a
        # deliberate precision decision, like a new collective kind
        b_sites = base.get("numerics_low_precision_sites")
        c_sites = cur.get("numerics_low_precision_sites")
        if (b_sites is not None and c_sites is not None
                and c_sites > b_sites):
            findings.append(Finding(
                pass_name="schedule", rule="new-low-precision-accumulation",
                severity=SEVERITY_ERROR, target=target,
                message=(
                    f"low-precision accumulation sites grew {b_sites} -> "
                    f"{c_sites} over the committed baseline — a reduction "
                    "or dot accumulator dropped below f32; confirm the "
                    "error bound (analyze numerics) and re-snapshot if "
                    "the downgrade is intended"
                ),
                details={"baseline": b_sites, "current": c_sites},
            ))
    audited = set(schedule_meta) | set(skipped_targets)
    for target in sorted(set(baselines) - audited):
        findings.append(Finding(
            pass_name="schedule", rule="stale-baseline",
            severity=SEVERITY_WARNING, target=target,
            message=(
                "committed baseline has no matching audit target — the "
                "target was removed or renamed; run `analyze snapshot` "
                "to prune"
            ),
        ))
    return findings
