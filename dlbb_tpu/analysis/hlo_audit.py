"""Pass 1 — HLO collective auditor.

Lowers every registered benchmark computation on a (usually CPU-simulated)
mesh, compiles it, and audits the post-SPMD HLO against the analytic
expectation model (``expectations.py``): every collective instruction must
be of an allowed kind and within its byte envelope, the op's defining
primitive must actually appear, and train-step computations must donate
their state buffers.  This catches the GSPMD failure mode the framework is
most exposed to — a sharding mismatch silently inserting an all-gather (or
replicating a computation) *before* any device time is spent measuring it.

Audit targets are plain builders ``mesh_free_callable() -> (fn, args,
expectation)`` so the default registry below can be extended by tests (the
seeded-violation fixtures) and future benchmarks.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Optional, Sequence

from dlbb_tpu.analysis.expectations import (
    TargetExpectation,
    compressed_op_expectation,
    op_expectation,
    overlap_op_expectation,
    plan_expected_kinds,
    wire_bytes,
)
from dlbb_tpu.analysis.findings import (
    SEVERITY_ERROR,
    AnalysisReport,
    Finding,
)
from dlbb_tpu.analysis.hlo_parse import (
    CollectiveInstr,
    has_donation,
    parse_collectives,
)


@dataclass
class AuditTarget:
    """One computation to lower + audit.

    ``build()`` returns ``(fn, args)`` where ``fn`` is jittable (or already
    a ``jax.jit`` object) and ``args`` the example arguments to lower with.
    ``min_devices`` lets the driver skip targets the current platform
    cannot host instead of crashing mid-audit.
    """

    name: str
    build: Callable[[], tuple[Any, tuple]]
    expectation: TargetExpectation
    min_devices: int = 1


def audit_target(
    target: AuditTarget,
    passes: Sequence[str] = ("hlo",),
    tier: Optional[object] = None,
    model: str = "cm1",
) -> tuple[list[Finding], dict]:
    """Lower, compile, parse, and check one target.  Returns the findings
    plus a meta dict (instruction inventory, and — when the ``schedule``
    pass is requested — the α–β schedule report) for the JSON report.
    One lowering serves both passes: ``analyze all`` does not compile the
    30-target surface twice."""
    import jax

    from dlbb_tpu.analysis.hlo_parse import parse_module

    fn, args = target.build()
    jitted = fn if hasattr(fn, "lower") else jax.jit(fn)
    lowered = jitted.lower(*args)
    compiled = lowered.compile()
    compiled_text = compiled.as_text()
    module = parse_module(compiled_text)
    exp = target.expectation

    findings: list[Finding] = []
    meta: dict = {}
    if "schedule" in passes:
        from dlbb_tpu.analysis.schedule_audit import analyze_schedule

        sched_findings, sched_meta = analyze_schedule(
            module, exp, target.name, tier=tier, model=model,
        )
        findings.extend(sched_findings)
        meta["schedule"] = sched_meta
    if "memory" in passes:
        from dlbb_tpu.analysis.costmodel import CostTier
        from dlbb_tpu.analysis.memory_audit import analyze_memory

        mem_findings, mem_meta = analyze_memory(
            module, exp, target.name,
            lowered_text=lowered.as_text(),
            # the TARGET's mesh size, not the host's device count: every
            # builder stands up exactly min_devices devices (a dp1 x tp4
            # compaction target on an 8-device host still runs a 4-way
            # mesh, and the replicated-spike P-factor must match it)
            num_devices=max(1, target.min_devices),
            tier=tier if isinstance(tier, CostTier) else None,
        )
        findings.extend(mem_findings)
        meta["memory"] = mem_meta
    if "numerics" in passes:
        from dlbb_tpu.analysis.numerics_audit import analyze_numerics

        num_findings, num_meta = analyze_numerics(
            module, exp, target.name,
            num_devices=max(1, target.min_devices),
            # price silent-upcast carries against the memory pass's peak
            # when both passes ride the same lowering (`analyze all`)
            peak_live_bytes=meta.get("memory", {}).get("peak_live_bytes"),
        )
        findings.extend(num_findings)
        meta["numerics"] = num_meta
    if "hlo" not in passes:
        return findings, meta

    instrs = parse_collectives(module)
    for instr in instrs:
        base = _instr_details(instr, exp)
        if instr.kind not in exp.allowed:
            findings.append(Finding(
                pass_name="hlo",
                rule="unexpected-collective",
                severity=SEVERITY_ERROR,
                target=target.name,
                message=(
                    f"{instr.kind} of {instr.dtype}{list(instr.shape)} "
                    f"({instr.result_bytes} B/device) is not in the "
                    f"plan's allowed set {sorted(exp.allowed)} — likely a "
                    "sharding mismatch (GSPMD inserted a collective the "
                    "parallelism plan does not account for)"
                ),
                location=instr.source,
                details=base,
            ))
        elif (exp.max_bytes_per_instr is not None
                and instr.result_bytes > exp.max_bytes_per_instr):
            findings.append(Finding(
                pass_name="hlo",
                rule="oversized-collective",
                severity=SEVERITY_ERROR,
                target=target.name,
                message=(
                    f"{instr.kind} carries {instr.result_bytes} B/device, "
                    f"over the plan ceiling of {exp.max_bytes_per_instr} B "
                    "— a larger buffer than the benchmark claims to move"
                ),
                location=instr.source,
                details=base,
            ))
    if exp.required_any:
        # execution-weighted: a collective inside a scanned layer body
        # counts once per trip, not once per static instruction (the
        # while-body undercount fix, pinned by test_schedule_audit)
        hits = sum(
            i.execution_count for i in instrs if i.kind in exp.required_any
        )
        if hits < exp.min_required:
            findings.append(Finding(
                pass_name="hlo",
                rule="missing-collective",
                severity=SEVERITY_ERROR,
                target=target.name,
                message=(
                    f"expected >= {exp.min_required} execution(s) of "
                    f"{sorted(exp.required_any)}, found {hits} — the "
                    "benchmark does not perform the collective it claims "
                    "(XLA may have elided or replaced it)"
                ),
                details={
                    "expected_kinds": sorted(exp.required_any),
                    "expected_min_count": exp.min_required,
                    "found_count": hits,
                    "present": [i.to_dict() for i in instrs],
                },
            ))
    total_wire = sum(
        wire_bytes(i.kind, i.result_bytes, i.group_size)
        * i.execution_count
        for i in instrs
    )
    if (exp.max_total_wire_bytes is not None
            and total_wire > exp.max_total_wire_bytes):
        findings.append(Finding(
            pass_name="hlo",
            rule="wire-volume-ceiling",
            severity=SEVERITY_ERROR,
            target=target.name,
            message=(
                f"total analytic wire volume {total_wire} B/device exceeds "
                f"the ceiling of {exp.max_total_wire_bytes} B — for a "
                "compressed collective this means the quantisation did "
                "not reach the wire (XLA dequantised before the "
                "collective, or an uncompressed reduction survived)"
            ),
            details={
                "total_wire_bytes": total_wire,
                "max_total_wire_bytes": exp.max_total_wire_bytes,
                "per_instr_wire_bytes": [
                    {"kind": i.kind,
                     "execution_count": i.execution_count,
                     "wire_bytes": wire_bytes(
                         i.kind, i.result_bytes, i.group_size)}
                    for i in instrs
                ],
            },
        ))
    if exp.expect_donation and not has_donation(lowered.as_text(),
                                                compiled_text):
        findings.append(Finding(
            pass_name="hlo",
            rule="missing-donation",
            severity=SEVERITY_ERROR,
            target=target.name,
            message=(
                "no input buffer is donated (no aliasing/buffer-donor "
                "marker in the lowered module and no input_output_alias "
                "in the compiled one) — the step keeps input AND output "
                "state resident, doubling state HBM"
            ),
            details={"expected": "donate_argnums on the step jit"},
        ))
    meta.update({
        "collectives": [i.to_dict() for i in instrs],
        "num_collectives": sum(i.execution_count for i in instrs),
        "total_wire_bytes": total_wire,
    })
    return findings, meta


def _instr_details(instr: CollectiveInstr, exp: TargetExpectation) -> dict:
    d = instr.to_dict()
    d["expected_allowed_kinds"] = sorted(exp.allowed)
    d["expected_max_bytes_per_instr"] = exp.max_bytes_per_instr
    d["analytic_wire_bytes"] = wire_bytes(
        instr.kind, instr.result_bytes, instr.group_size
    )
    return d


# ---------------------------------------------------------------------------
# default target registry
# ---------------------------------------------------------------------------

_TINY_MODEL = dict(hidden_size=64, num_layers=2, num_heads=4,
                   ffn_intermediate=128, dtype="float32",
                   attention="full")


# (B, S, H) audit payload for the collective-matmul targets: S and H
# divisible by the 8-rank ring, small enough to lower in milliseconds
_MATMUL_SHAPE = (2, 16, 64)


def _tiny_params_bytes() -> int:
    """f32 parameter bytes of the shared tiny audit model — the unit every
    model/train/serve peak-memory ceiling is priced in (the analytic
    "model size" the memory audit's ceilings are seeded from)."""
    from dlbb_tpu.models.configs import ModelConfig
    from dlbb_tpu.models.transformer import num_parameters

    return num_parameters(ModelConfig(**_TINY_MODEL)) * 4


def _collective_matmul_target(op_name: str, schedule: str,
                              num_ranks: int = 8) -> AuditTarget:
    """One audit target per (micro-op, schedule).  The fused schedule must
    show its defining gather/scatter; the decomposed schedules must show
    the pure collective-permute chain (``overlap_op_expectation``) —
    comm-lint is the correctness gate for the overlap claim."""
    import numpy as np

    def build():
        import jax.numpy as jnp

        from dlbb_tpu.comm.mesh import MeshSpec, build_mesh
        from dlbb_tpu.comm.ops import (
            build_ag_matmul,
            build_matmul_rs,
            get_op,
            make_payload,
        )

        mesh = build_mesh(MeshSpec.ring(num_ranks))
        builder = (build_ag_matmul if op_name == "ag_matmul"
                   else build_matmul_rs)
        fn = builder(mesh, ("ranks",), schedule=schedule)
        x = make_payload(
            get_op(op_name), mesh, ("ranks",),
            int(np.prod(_MATMUL_SHAPE)), dtype=jnp.float32,
            shape=_MATMUL_SHAPE,
        )
        return fn, (x,)

    per_rank = int(np.prod(_MATMUL_SHAPE)) * 4  # float32
    if schedule == "fused":
        # the gather/scatter result may span the whole gathered payload
        exp = op_expectation(op_name, per_rank * num_ranks)
        # resident: gathered activations (P x per-rank) + input + weight
        # + partials — a fused schedule's peak is gather-dominated
        exp.max_peak_bytes = int(2.5 * per_rank * num_ranks)
    else:
        # each hop carries at most one travelling per-rank chunk
        exp = overlap_op_expectation(num_ranks, per_rank)
        # the whole point of the ring: never materialise the P x gather
        # — input + weight + accumulator + in-flight chunks stay within
        # a few per-rank payloads, far under the fused ceiling (XLA
        # undoing the decomposition blows this before the kind gate)
        exp.max_peak_bytes = 8 * per_rank
    return AuditTarget(
        name=f"comm/ops.py::{op_name}[{schedule}]",
        build=build,
        expectation=exp,
        min_devices=num_ranks,
    )


def _compressed_op_target(op_name: str, compression: str,
                          num_ranks: int = 8,
                          num_elements: int = 4096) -> AuditTarget:
    """One audit target per (compressed micro-op, wire dtype).  The
    expectation is the compression proof: a pure quantised ring (plus the
    wire-dtype gather phase for allreduce_q) whose TOTAL analytic wire —
    scale side channel included — stays under 0.55x the uncompressed
    bf16 wire (``expectations.compressed_op_expectation``,
    docs/compression.md)."""
    import jax.numpy as jnp

    def build():
        from dlbb_tpu.comm.mesh import MeshSpec, build_mesh
        from dlbb_tpu.comm.ops import get_op, make_payload

        op = get_op(op_name)
        mesh = build_mesh(MeshSpec.ring(num_ranks))
        fn = op.build(mesh, ("ranks",), compression=compression)
        # bf16 payload: the baseline the 0.55x ceiling is priced against
        x = make_payload(op, mesh, ("ranks",), num_elements,
                         dtype=jnp.bfloat16)
        return fn, (x,)

    exp = compressed_op_expectation(
        op_name, num_ranks, num_elements, compression=compression)
    # bf16 payload + quantised wire buffers + scales; the per-peer
    # reducescatter_q input is a [P, n] slab per rank
    exp.max_peak_bytes = (
        2 * num_ranks * num_elements * 2 if op_name == "reducescatter_q"
        else 4 * num_elements * 2 + 8192
    )
    return AuditTarget(
        name=f"comm/ops.py::{op_name}[{compression}]",
        build=build,
        expectation=exp,
        min_devices=num_ranks,
    )


def _registry_op_target(op_name: str, num_ranks: int = 8,
                        num_elements: int = 256) -> AuditTarget:
    import jax.numpy as jnp

    def build():
        from dlbb_tpu.comm.mesh import MeshSpec, build_mesh
        from dlbb_tpu.comm.ops import get_op, make_payload

        op = get_op(op_name)
        if op_name == "allreduce_hierarchical":
            mesh = build_mesh(MeshSpec.grid(
                (2, num_ranks // 2), ("outer", "inner")))
            axes = ("outer", "inner")
        else:
            mesh = build_mesh(MeshSpec.ring(num_ranks))
            axes = ("ranks",)
        fn = op.build(mesh, axes)
        x = make_payload(op, mesh, axes, num_elements, dtype=jnp.float32)
        return fn, (x,)

    per_rank = num_elements * 4  # float32 payloads
    # gather-family results hold every rank's buffer on each device; the
    # per-peer input kinds already carry a [P, n] slab per rank
    if op_name in ("allgather", "gather", "scatter", "alltoall",
                   "reducescatter"):
        ceiling = per_rank * num_ranks
    else:
        ceiling = per_rank
    exp = op_expectation(op_name, ceiling)
    # resident: input (+ the [P, n] slab for per-peer kinds), result, and
    # a couple of masked-contribution temps — all payload-scale
    exp.max_peak_bytes = 4 * ceiling + 8192
    return AuditTarget(
        name=f"comm/ops.py::{op_name}",
        build=build,
        expectation=exp,
        min_devices=num_ranks,
    )


def _barrier_target(num_ranks: int = 8) -> AuditTarget:
    """``build_barrier`` is the timing synchronisation point, not a
    registry op, so it gets its own target — the barrier must stay a
    scalar-sized all-reduce, never anything that moves real data."""
    import jax.numpy as jnp

    def build():
        from dlbb_tpu.comm.mesh import MeshSpec, build_mesh
        from dlbb_tpu.comm.ops import build_barrier

        mesh = build_mesh(MeshSpec.ring(num_ranks))
        fn = build_barrier(mesh, ("ranks",))
        x = jnp.ones((num_ranks, 1), jnp.float32)
        return fn, (x,)

    exp = op_expectation("barrier", 4)  # one f32 scalar/device
    exp.max_peak_bytes = 8192  # scalars only — anything more is data
    return AuditTarget(
        name="comm/ops.py::barrier",
        build=build,
        expectation=exp,
        min_devices=num_ranks,
    )


def _tp_forward_target(dp: int = 2, tp: int = 4) -> AuditTarget:
    def build():
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding

        from dlbb_tpu.comm.mesh import build_parallelism_mesh
        from dlbb_tpu.models.configs import ModelConfig
        from dlbb_tpu.models.sharding import batch_spec
        from dlbb_tpu.models.transformer import (
            forward,
            init_params_sharded,
        )

        cfg = ModelConfig(**_TINY_MODEL)
        mesh = build_parallelism_mesh(data_parallel=dp, tensor_parallel=tp)
        params = init_params_sharded(cfg, jax.random.key(0), mesh)
        x = jax.device_put(
            jnp.ones((2 * dp, 8, cfg.hidden_size), jnp.float32),
            NamedSharding(mesh, batch_spec(mesh)),
        )
        fn = jax.jit(
            lambda p, a: forward(p, a, cfg, mesh=mesh),
            out_shardings=NamedSharding(mesh, batch_spec(mesh)),
        )
        return fn, (params, x)

    # per-device activation shard: [B/dp, S, H] f32
    act_bytes = (2 * dp // dp) * 8 * _TINY_MODEL["hidden_size"] * 4
    return AuditTarget(
        name="models/transformer.py::forward[dp,tp]",
        build=build,
        expectation=TargetExpectation(
            allowed=plan_expected_kinds(dp=dp, tp=tp),
            required_any={"all-reduce"},
            min_required=1,  # Megatron row-parallel psum (XLA may combine)
            max_bytes_per_instr=int(act_bytes * 1.25),
            # tp-sharded weights (~n4/tp) + activations/temps; a Megatron
            # layout collapsing to replication puts the FULL n4 resident
            # and blows this before the all-gather even fires
            max_peak_bytes=int(0.7 * _tiny_params_bytes()),
        ),
        min_devices=dp * tp,
    )


def _cp_forward_target(attention: str, dp: int = 2, sp: int = 4) -> AuditTarget:
    def build():
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding

        from dlbb_tpu.comm.mesh import build_parallelism_mesh
        from dlbb_tpu.models.configs import ModelConfig
        from dlbb_tpu.models.sharding import batch_spec
        from dlbb_tpu.models.transformer import forward, init_params_sharded

        cfg = ModelConfig(**{**_TINY_MODEL, "attention": attention})
        mesh = build_parallelism_mesh(data_parallel=dp, sequence_parallel=sp)
        params = init_params_sharded(cfg, jax.random.key(0), mesh)
        x = jax.device_put(
            jnp.ones((dp, 16, cfg.hidden_size), jnp.float32),
            NamedSharding(mesh, batch_spec(mesh)),
        )
        fn = jax.jit(
            lambda p, a: forward(p, a, cfg, mesh=mesh),
            out_shardings=NamedSharding(mesh, batch_spec(mesh)),
        )
        return fn, (params, x)

    required = ("collective-permute" if attention == "ring"
                else "all-to-all")
    return AuditTarget(
        name=f"models/transformer.py::forward[sp,{attention}]",
        build=build,
        expectation=TargetExpectation(
            allowed=plan_expected_kinds(dp=dp, sp=sp, attention=attention),
            required_any={required},
            min_required=1,
            # sp shards the sequence, NOT the weights: the full f32
            # parameter set is resident per device, plus sp-sharded
            # activations/ring buffers
            max_peak_bytes=int(1.3 * _tiny_params_bytes()) + 65536,
        ),
        min_devices=dp * sp,
    )


def _tp_overlap_forward_target(schedule: str, dp: int = 2,
                               tp: int = 4) -> AuditTarget:
    """The overlapped TP forward (model.tp_overlap = ring|bidir).  The
    audit is the correctness gate for the decomposition: every projection
    collective must be a ppermute chain (>= 4 ring matmuls x (tp-1) hops
    in the scanned layer body), NO all-reduce may survive, and the only
    all-gather allowed is the single activation-sized reshard back to the
    caller's batch layout — anything bigger means the Megatron layout
    collapsed or the decomposition was undone."""
    def build():
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding

        from dlbb_tpu.comm.mesh import build_parallelism_mesh
        from dlbb_tpu.models.configs import ModelConfig
        from dlbb_tpu.models.sharding import batch_spec
        from dlbb_tpu.models.transformer import (
            forward,
            init_params_sharded,
        )

        cfg = ModelConfig(**_TINY_MODEL, tp_overlap=schedule)
        mesh = build_parallelism_mesh(data_parallel=dp, tensor_parallel=tp)
        params = init_params_sharded(cfg, jax.random.key(0), mesh)
        x = jax.device_put(
            jnp.ones((2 * dp, 8, cfg.hidden_size), jnp.float32),
            NamedSharding(mesh, batch_spec(mesh)),
        )
        fn = jax.jit(
            lambda p, a: forward(p, a, cfg, mesh=mesh),
            out_shardings=NamedSharding(mesh, batch_spec(mesh)),
        )
        return fn, (params, x)

    # per-device activation shard: [B/dp, S, H] f32 — the ceiling for the
    # final reshard gather AND every travelling ring chunk (chunks are
    # 1/tp of it)
    act_bytes = (2 * dp // dp) * 8 * _TINY_MODEL["hidden_size"] * 4
    return AuditTarget(
        name=f"models/transformer.py::forward[dp,tp,overlap={schedule}]",
        build=build,
        expectation=TargetExpectation(
            allowed=plan_expected_kinds(tp=tp, tp_overlap=schedule),
            required_any={"collective-permute"},
            # 4 ring matmuls per scanned layer body, (tp-1) hops each
            min_required=4 * (tp - 1),
            max_bytes_per_instr=int(act_bytes * 1.25),
            # every ring hop must be hidden behind a partial matmul —
            # the schedule auditor's serialized-collective gate
            expect_overlap=True,
            # same resident set as the GSPMD forward: tp-sharded weights
            # + sequence-sharded activations + ring chunks
            max_peak_bytes=int(0.7 * _tiny_params_bytes()),
        ),
        min_devices=dp * tp,
    )


def _tp_overlap_train_target(schedule: str, dp: int = 2,
                             tp: int = 4) -> AuditTarget:
    """The overlapped train step: the custom VJP must keep the backward
    on ppermute chains too (forward + dx + dw rings), with the only
    all-reduces the dp gradient reductions (weight-shard sized, inserted
    by the psum over batch axes inside the weight-grad rings) — and the
    state donation of the train-step convention intact."""
    def build():
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding

        import optax

        from dlbb_tpu.comm.mesh import build_parallelism_mesh
        from dlbb_tpu.models.configs import ModelConfig
        from dlbb_tpu.models.sharding import batch_spec
        from dlbb_tpu.models.transformer import init_params_sharded
        from dlbb_tpu.train.loop import make_train_step

        cfg = ModelConfig(**_TINY_MODEL, tp_overlap=schedule)
        mesh = build_parallelism_mesh(data_parallel=dp, tensor_parallel=tp)
        params = init_params_sharded(cfg, jax.random.key(0), mesh)
        jit_step, state = make_train_step(
            cfg, mesh, optax.adam(1e-3), params, zero_stage=0,
        )
        sharding = NamedSharding(mesh, batch_spec(mesh))
        batch = jax.device_put(
            jnp.ones((2 * dp, 8, cfg.hidden_size), jnp.float32), sharding)
        tgt = jax.device_put(
            jnp.ones((2 * dp, 8, cfg.hidden_size), jnp.float32), sharding)
        return jit_step, (state, batch, tgt)

    # combined dp weight-grad all-reduces are bounded by the full f32
    # parameter pytree; every ring chunk and the final activation reshard
    # are far below it
    params_bytes = _tiny_params_bytes()
    return AuditTarget(
        name=f"train/loop.py::train_step[dp,tp,overlap={schedule}]",
        build=build,
        expectation=TargetExpectation(
            # all-to-all: GSPMD reshards the scanned backward's
            # broadcast-zero cotangent init with a (tiny, constant-operand)
            # all-to-all on this jaxlib — covered by the byte ceiling, and
            # absent from the forward target where the strict set holds
            allowed=plan_expected_kinds(dp=dp, tp=tp, tp_overlap=schedule)
            | {"all-to-all"},
            required_any={"collective-permute"},
            # forward chain alone is 4 rings x (tp-1); the backward adds
            # its own dx/dw rings on top
            min_required=4 * (tp - 1),
            max_bytes_per_instr=int(params_bytes * 1.25),
            expect_donation=True,
            expect_overlap=True,
            # tp-sharded Adam state (3 x n4/tp, donated) + grads + ring
            # transients; a dropped donation re-adds the whole state
            # shard and blows this first
            max_peak_bytes=int(2.0 * params_bytes),
        ),
        min_devices=dp * tp,
    )


def _compressed_train_target(compression: str = "int8",
                             dp: int = 8) -> AuditTarget:
    """The compressed DDP train step (training.grad_compression): the dp
    gradient reduction must be the quantised ring — collective-permutes
    plus the wire-dtype all-gather — with the only all-reduce the scalar
    loss mean, the error-feedback residual donated with the rest of the
    state, and the TOTAL analytic wire (scales included) under 0.55x the
    bf16 baseline's ``2(P-1)/P x 2 bytes x n_params``.  This is the
    acceptance gate proving XLA did not dequantise before the wire."""
    def build():
        import jax
        import jax.numpy as jnp
        import optax
        from jax.sharding import NamedSharding

        from dlbb_tpu.comm.mesh import build_parallelism_mesh
        from dlbb_tpu.models.configs import ModelConfig
        from dlbb_tpu.models.sharding import batch_spec
        from dlbb_tpu.models.transformer import init_params_sharded
        from dlbb_tpu.train.loop import make_train_step

        cfg = ModelConfig(**_TINY_MODEL)
        mesh = build_parallelism_mesh(data_parallel=dp)
        params = init_params_sharded(cfg, jax.random.key(0), mesh)
        jit_step, state = make_train_step(
            cfg, mesh, optax.adam(1e-3), params, zero_stage=0,
            grad_compression=compression,
        )
        sharding = NamedSharding(mesh, batch_spec(mesh))
        batch = jax.device_put(
            jnp.ones((dp, 8, cfg.hidden_size), jnp.float32), sharding)
        tgt = jax.device_put(
            jnp.ones((dp, 8, cfg.hidden_size), jnp.float32), sharding)
        return jit_step, (state, batch, tgt)

    from dlbb_tpu.analysis.expectations import (
        compression_wire_ceiling,
        op_wire_bytes,
        scale_bytes,
    )

    n_params = _tiny_params_bytes() // 4
    baseline = wire_bytes("all-reduce", n_params * 2, dp)  # bf16 ring AR
    # the grads ride as one flat allreduce_q-shaped reduction; the
    # ceiling is the shared contract of compression_wire_ceiling
    analytic = op_wire_bytes("allreduce_q", n_params, dp, 2,
                             compression=compression)
    return AuditTarget(
        name=f"train/loop.py::train_step[ddp,compressed={compression}]",
        build=build,
        expectation=TargetExpectation(
            allowed=plan_expected_kinds(dp=dp, compression=compression),
            required_any={"collective-permute"},
            min_required=dp - 1,
            # largest legitimate instruction: the quantised flat-grad
            # all-gather (~n_params wire bytes, chunk-padded)
            max_bytes_per_instr=int(
                n_params * 1.25 + scale_bytes(n_params) * dp),
            max_total_wire_bytes=compression_wire_ceiling(
                baseline, analytic),
            expect_donation=True,
            # DDP Adam state (3 x n4) + the P("dp")-sharded EF residual
            # (~n4/device) + grads + quantise/dequantise ring buffers
            max_peak_bytes=int(7.5 * n_params * 4),
        ),
        min_devices=dp,
    )


# Serving audit geometry (dlbb_tpu/serve/): the tiny model on a dp2 x
# tp4 mesh, 4 decode slots of 4 x 8-token cache blocks, one 16-token
# prefill bucket.  Shared by the decode and prefill targets so their
# byte ceilings price the same cache.
_SERVE_SHAPE = dict(max_batch=4, num_blocks=4, block_size=8, bucket=16)


def _serve_cache_bytes_per_device(dp: int, tp: int,
                                  num_layers: Optional[int] = None,
                                  kv_quantization: str = "none") -> int:
    """Analytic per-device KV-cache footprint of the serving audit
    geometry — the SAME ``models.configs.kv_cache_bytes_per_device``
    the build-time HBM budget gate prices, wired into the decode/prefill
    expectations as ``donated_bytes_expected`` so the memory audit's
    ``serving-cache-drift`` rule pins formula and compiled program to
    each other.  ``num_layers`` overrides the tiny model's depth — the
    speculative draft plane (1 layer) prices through the same formula;
    ``kv_quantization="int8"`` prices the quantized layout (int8 data
    planes + the per-(block, kv-head) fp32 scale side-channel)."""
    from dlbb_tpu.models.configs import (
        ModelConfig,
        kv_cache_bytes_per_device,
    )

    model = dict(_TINY_MODEL)
    if num_layers is not None:
        model["num_layers"] = num_layers
    return kv_cache_bytes_per_device(
        ModelConfig(**model),
        _SERVE_SHAPE["max_batch"],
        _SERVE_SHAPE["num_blocks"] * _SERVE_SHAPE["block_size"],
        dp=dp, tp=tp,
        kv_quantization=kv_quantization,
        block_size=_SERVE_SHAPE["block_size"],
    )


def _serve_build(dp: int, tp: int, what: str, k: int = 4):
    """Common builder for the serving targets: engine jits + example
    args on a (dp, tp) mesh — the exact programs ``serve/engine.py``
    runs, so the audit gates the real decode/prefill/fast-path
    lowerings.  ``what`` selects decode / decode_fused / prefill /
    prefill_chunk / compact_gather / compact_scatter — plus the
    speculative-decoding programs decode_fused_token / verify /
    draft_scan; ``k`` is the fused-scan trip count (and doubles as γ
    for the speculative targets)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dlbb_tpu.comm.mesh import build_parallelism_mesh
    from dlbb_tpu.data.synthetic import token_embedding_table
    from dlbb_tpu.models.configs import ModelConfig
    from dlbb_tpu.models.transformer import init_params_sharded
    from dlbb_tpu.serve.engine import (
        build_compact_gather,
        build_compact_scatter,
        build_decode_fused,
        build_decode_fused_token,
        build_decode_step,
        build_draft_scan,
        build_prefill,
        build_prefill_chunk,
        build_verify_step,
        decode_batch_spec,
    )
    from dlbb_tpu.serve.kvcache import create_kv_cache

    cfg = ModelConfig(**_TINY_MODEL)
    mesh = build_parallelism_mesh(data_parallel=dp, tensor_parallel=tp)
    params = init_params_sharded(cfg, jax.random.key(0), mesh)
    cache = create_kv_cache(
        cfg, _SERVE_SHAPE["max_batch"], _SERVE_SHAPE["num_blocks"],
        _SERVE_SHAPE["block_size"], mesh=mesh,
    )
    x = jax.device_put(
        jnp.zeros((_SERVE_SHAPE["max_batch"], 1, cfg.hidden_size),
                  jnp.float32),
        NamedSharding(mesh, decode_batch_spec(mesh)),
    )
    active = jax.device_put(
        jnp.ones((_SERVE_SHAPE["max_batch"],), bool),
        NamedSharding(mesh, P()),
    )
    if what == "decode":
        fn = build_decode_step(cfg, mesh)
        return fn, ((cache, x), params, active)
    if what == "decode_fused":
        fn = build_decode_fused(cfg, mesh, k)
        remaining = jax.device_put(
            jnp.full((_SERVE_SHAPE["max_batch"],), k, jnp.int32),
            NamedSharding(mesh, P()),
        )
        return fn, ((cache, x), params, active, remaining)
    if what in ("decode_fused_token", "verify", "draft_scan"):
        table = token_embedding_table(cfg.hidden_size, dtype=jnp.float32)
        remaining = jax.device_put(
            jnp.full((_SERVE_SHAPE["max_batch"],), k, jnp.int32),
            NamedSharding(mesh, P()),
        )
        if what == "decode_fused_token":
            fn = build_decode_fused_token(cfg, mesh, k)
            return fn, ((cache, x), params, table, active, remaining)
        if what == "verify":
            fn = build_verify_step(cfg, mesh, k)
            ids = jax.device_put(
                jnp.zeros((_SERVE_SHAPE["max_batch"], k), jnp.int32),
                NamedSharding(mesh, P(decode_batch_spec(mesh)[0], None)),
            )
            return fn, ((cache, x), params, table, ids, active, remaining)
        # draft_scan: the SHALLOW draft model (1 layer, everything else
        # identical) over its OWN cache plane, host-committed lengths
        # passed explicitly — the exact program the draft-model drafter
        # dispatches
        draft_cfg = ModelConfig(**{**_TINY_MODEL, "num_layers": 1})
        draft_params = init_params_sharded(draft_cfg, jax.random.key(1),
                                           mesh)
        draft_cache = create_kv_cache(
            draft_cfg, _SERVE_SHAPE["max_batch"], _SERVE_SHAPE["num_blocks"],
            _SERVE_SHAPE["block_size"], mesh=mesh,
        )
        lengths = jax.device_put(
            jnp.zeros((_SERVE_SHAPE["max_batch"],), jnp.int32),
            NamedSharding(mesh, P()),
        )
        fn = build_draft_scan(draft_cfg, mesh, k)
        return fn, (draft_cache, draft_params, table, x, lengths, active)
    if what == "prefill_chunk":
        # second chunk (nonzero static offset): nonempty prefix carry +
        # offset block write — the interesting lowering
        from dlbb_tpu.serve.engine import prefix_spec

        chunk = _SERVE_SHAPE["block_size"]
        fn = build_prefill_chunk(cfg, mesh, chunk, chunk)
        pre_sh = NamedSharding(mesh, prefix_spec(mesh))
        pk = jax.device_put(
            jnp.zeros((cfg.num_layers, chunk, cfg.kv_heads,
                       cfg.head_dim), jnp.float32), pre_sh)
        xc = jnp.zeros((1, chunk, cfg.hidden_size), jnp.float32)
        return fn, (cache, (pk, pk), params, xc, np.int32(0),
                    np.int32(2 * chunk))
    if what == "prefix_attach":
        # one matched block copied donor -> destination slot plus the
        # dequantised fp prefix carry — the shared-prefix admission's
        # entire device program (dp=1 by contract, like compaction)
        from dlbb_tpu.serve.engine import build_prefix_attach

        fn = build_prefix_attach(cfg, mesh, _SERVE_SHAPE["block_size"],
                                 _SERVE_SHAPE["block_size"])
        return fn, (cache, np.int32(0), np.int32(1))
    if what == "decode_quant":
        from dlbb_tpu.serve.kvcache import create_quant_kv_cache

        qcache = create_quant_kv_cache(
            cfg, _SERVE_SHAPE["max_batch"], _SERVE_SHAPE["num_blocks"],
            _SERVE_SHAPE["block_size"], mesh=mesh,
        )
        fn = build_decode_step(cfg, mesh, quantized=True)
        return fn, ((qcache, x), params, active)
    if what in ("compact_gather", "compact_scatter"):
        bucket = _SERVE_SHAPE["max_batch"] // 2
        idx = jnp.arange(bucket, dtype=jnp.int32)
        if what == "compact_gather":
            return build_compact_gather(mesh), ((cache, x), idx)
        from dlbb_tpu.serve.kvcache import gather_cache_slots

        small_cache = jax.jit(gather_cache_slots)(cache, idx)
        small_x = x[:bucket]
        return (build_compact_scatter(mesh),
                ((cache, x), (small_cache, small_x), idx))
    fn = build_prefill(cfg, mesh)
    xp = jnp.zeros((1, _SERVE_SHAPE["bucket"], cfg.hidden_size),
                   jnp.float32)
    return fn, (cache, params, xp, np.int32(0),
                np.int32(_SERVE_SHAPE["bucket"]))


def _decode_step_target(dp: int = 2, tp: int = 4) -> AuditTarget:
    """The serving decode step (``serve/engine.py::decode_step``).  The
    contract is the serving-path comm story: ONLY tiny per-token tp
    collectives (row-parallel psums of [max_batch, 1, H] + QKV realign
    permutes) may exist — dp contributes nothing (no gradients) — and
    the activation-sized byte ceiling is the proof that no step
    re-gathers the KV-cache: even one slot's single-layer cache shard is
    several times the ceiling, so a cache regather fails on both the
    kind axis and the byte axis.  The cache carry must stay donated
    (an undonated decode doubles cache HBM — fatal at real sizes)."""
    def build():
        return _serve_build(dp, tp, "decode")

    cfg_dict = _TINY_MODEL
    # largest legitimate instruction: an all-reduce (or realign permute)
    # of one decode step's activations — [max_batch, 1, qkv_width] f32
    # bounds every projection collective.  One layer's k (or v) cache
    # plane [max_batch, num_blocks, block_size, kvh, d] is ~8.5x this
    # ceiling (a single slot's plane alone is ~2x), so any cache-sized
    # transfer trips.
    qkv_width = 3 * cfg_dict["hidden_size"]
    act_bytes = _SERVE_SHAPE["max_batch"] * qkv_width * 4
    cache_dev = _serve_cache_bytes_per_device(dp, tp)
    return AuditTarget(
        name="serve/engine.py::decode_step[dp,tp]",
        build=build,
        expectation=TargetExpectation(
            allowed=plan_expected_kinds(dp=dp, tp=tp, decode=True),
            required_any={"all-reduce"},
            min_required=1,  # row-parallel psum per scanned layer
            max_bytes_per_instr=int(act_bytes * 1.25),
            expect_donation=True,
            # resident: tp-sharded weights + the donated cache shard +
            # per-token activations — a cache REGATHER (the full
            # unsharded cache materialising) adds (dp*tp - 1) x
            # cache_dev and blows this before the byte/kind axes even
            # report
            max_peak_bytes=int(
                1.3 * (_tiny_params_bytes() // tp + cache_dev)
            ) + 16 * act_bytes,
            # the validate_serving cross-check: the donated decode
            # carry IS the cache (plus the [max_batch, 1, H] hidden
            # state and the lengths vector, together <5% here) — the
            # analytic kv_cache_bytes_per_device must match it
            donated_bytes_expected=cache_dev,
        ),
        min_devices=dp * tp,
    )


def _prefill_target(dp: int = 2, tp: int = 4) -> AuditTarget:
    """The serving prefill (cache-append) step: full causal attention
    over one request's bucketed prompt, K/V written into the request's
    slot by masked select.  Same kind set as decode; the ceiling is one
    bucket of activations — the cache write itself must lower to zero
    collectives (a write that round-trips the wire would trip it)."""
    def build():
        return _serve_build(dp, tp, "prefill")

    act_bytes = _SERVE_SHAPE["bucket"] * 3 * _TINY_MODEL["hidden_size"] * 4
    cache_dev = _serve_cache_bytes_per_device(dp, tp)
    return AuditTarget(
        name="serve/engine.py::prefill[dp,tp]",
        build=build,
        expectation=TargetExpectation(
            allowed=plan_expected_kinds(dp=dp, tp=tp, decode=True),
            required_any={"all-reduce"},
            min_required=1,
            max_bytes_per_instr=int(act_bytes * 1.25),
            expect_donation=True,
            # weights + donated cache + one bucket of activations/scores
            max_peak_bytes=int(
                1.3 * (_tiny_params_bytes() // tp + cache_dev)
            ) + 8 * act_bytes,
            donated_bytes_expected=cache_dev,
        ),
        min_devices=dp * tp,
    )


def _decode_fused_target(dp: int = 2, tp: int = 4,
                         k: int = 4) -> AuditTarget:
    """The fused multi-step decode scan (``serve/engine.py::
    build_decode_fused``): the scan body may contain only the tiny
    per-token tp collectives, execution-weighted through the scan's
    ``known_trip_count`` — the body's row-parallel psum must fire >= k
    times (the while-body pricing from the schedule auditor), each
    within ONE step's activation byte ceiling.  A cache regather inside
    the body is k-times amplified on the wire axis, so the committed
    schedule baseline turns it into an ``analyze diff`` failure as well
    as an audit error."""
    from dlbb_tpu.analysis.expectations import decode_scan_expectation

    def build():
        return _serve_build(dp, tp, "decode_fused", k=k)

    qkv_width = 3 * _TINY_MODEL["hidden_size"]
    act_bytes = _SERVE_SHAPE["max_batch"] * qkv_width * 4
    cache_dev = _serve_cache_bytes_per_device(dp, tp)
    exp = decode_scan_expectation(dp, tp, k, act_bytes)
    # the fused scan carries the same donated (cache, x) as the per-step
    # engine — K trips reuse the carry in place, so the peak must NOT
    # scale with k
    exp.max_peak_bytes = int(
        1.3 * (_tiny_params_bytes() // tp + cache_dev)) + 16 * act_bytes
    exp.donated_bytes_expected = cache_dev
    return AuditTarget(
        name=f"serve/engine.py::decode_fused[k{k},dp,tp]",
        build=build,
        expectation=exp,
        min_devices=dp * tp,
    )


def _decode_fused_token_target(dp: int = 2, tp: int = 4,
                               k: int = 4) -> AuditTarget:
    """The token-feedback fused scan (``serve/engine.py::
    build_decode_fused_token``) — the n-gram-drafted engine's
    between-verify workhorse and the speculative modes' plain-decode
    fallback.  Identical contract to the float fused scan: the greedy
    quantisation (argmax + a replicated [H, H] table take) adds ZERO
    collectives, so the same ``decode_scan_expectation`` applies
    unchanged — any new wire from the token feedback is a regression."""
    from dlbb_tpu.analysis.expectations import decode_scan_expectation

    def build():
        return _serve_build(dp, tp, "decode_fused_token", k=k)

    qkv_width = 3 * _TINY_MODEL["hidden_size"]
    act_bytes = _SERVE_SHAPE["max_batch"] * qkv_width * 4
    cache_dev = _serve_cache_bytes_per_device(dp, tp)
    exp = decode_scan_expectation(dp, tp, k, act_bytes)
    exp.max_peak_bytes = int(
        1.3 * (_tiny_params_bytes() // tp + cache_dev)) + 16 * act_bytes
    exp.donated_bytes_expected = cache_dev
    return AuditTarget(
        name=f"serve/engine.py::decode_fused_token[k{k},dp,tp]",
        build=build,
        expectation=exp,
        min_devices=dp * tp,
    )


def _verify_step_target(dp: int = 2, tp: int = 4,
                        gamma: int = 4) -> AuditTarget:
    """The speculative verify step (``serve/engine.py::
    build_verify_step``): γ drafted tokens + the carry token through ONE
    batched [max_batch, γ+1, H] target forward.  The expectation
    (``verify_step_expectation``) pins the "one fused forward, zero
    per-draft-token collectives" contract: per-token decode kinds only,
    one psum per scanned layer, every instruction within (γ+1) x one
    step's activation bytes — the γ+1 one-hot cache appends must lower
    to collective-free selects exactly like the decode step's single
    append, and the acceptance math (argmax + cumprod + gather) is
    elementwise/local."""
    from dlbb_tpu.analysis.expectations import verify_step_expectation

    def build():
        return _serve_build(dp, tp, "verify", k=gamma)

    qkv_width = 3 * _TINY_MODEL["hidden_size"]
    act_bytes = _SERVE_SHAPE["max_batch"] * qkv_width * 4
    cache_dev = _serve_cache_bytes_per_device(dp, tp)
    exp = verify_step_expectation(dp, tp, gamma, act_bytes)
    # weights + donated cache + (γ+1)-wide activations/scores (the
    # verify's [B, γ+1, S] mask and [B, n, γ+1, S] score planes are a
    # few KB at the audit geometry)
    exp.max_peak_bytes = int(
        1.3 * (_tiny_params_bytes() // tp + cache_dev)
    ) + 16 * (gamma + 1) * act_bytes
    exp.donated_bytes_expected = cache_dev
    return AuditTarget(
        name=f"serve/engine.py::verify_step[gamma{gamma},dp,tp]",
        build=build,
        expectation=exp,
        min_devices=dp * tp,
    )


def _draft_scan_target(dp: int = 2, tp: int = 4,
                       gamma: int = 4) -> AuditTarget:
    """The draft-model proposal scan (``serve/engine.py::
    build_draft_scan``): γ greedy steps of the 1-layer draft transformer
    over its OWN donated cache plane, sharded by the SAME plan as the
    target (``draft_model_config``).  The fused-scan expectation applies
    at trip count γ; the donated-bytes cross-check prices the SECOND
    cache plane — the same ``kv_cache_bytes_per_device`` formula
    ``validate_serving``'s draft-aware HBM gate prices at admission, so
    the build-time rejection can never drift from the draft plane XLA
    actually allocates."""
    from dlbb_tpu.analysis.expectations import decode_scan_expectation

    def build():
        return _serve_build(dp, tp, "draft_scan", k=gamma)

    qkv_width = 3 * _TINY_MODEL["hidden_size"]
    act_bytes = _SERVE_SHAPE["max_batch"] * qkv_width * 4
    draft_cache_dev = _serve_cache_bytes_per_device(dp, tp, num_layers=1)
    exp = decode_scan_expectation(dp, tp, gamma, act_bytes)
    # 1-layer draft weights are a fraction of the target's; pricing the
    # full tiny-model params keeps comfortable headroom while the
    # donated check stays exact on the draft plane
    exp.max_peak_bytes = int(
        1.3 * (_tiny_params_bytes() // tp + draft_cache_dev)
    ) + 16 * act_bytes
    exp.donated_bytes_expected = draft_cache_dev
    return AuditTarget(
        name=f"serve/engine.py::draft_scan[gamma{gamma},dp,tp]",
        build=build,
        expectation=exp,
        min_devices=dp * tp,
    )


def _prefill_chunk_target(dp: int = 2, tp: int = 4) -> AuditTarget:
    """One chunk of a chunked prefill at a nonzero static offset: the
    prefix K/V rides an explicit (slot-dim-free) carry, so the lowered
    program must look exactly like monolithic prefill — tp collectives
    only, one chunk of activations as the ceiling, zero collectives for
    the cache write, cache carry donated."""

    def build():
        return _serve_build(dp, tp, "prefill_chunk")

    chunk = _SERVE_SHAPE["block_size"]
    act_bytes = chunk * 3 * _TINY_MODEL["hidden_size"] * 4
    cache_dev = _serve_cache_bytes_per_device(dp, tp)
    return AuditTarget(
        name="serve/engine.py::prefill_chunk[dp,tp]",
        build=build,
        expectation=TargetExpectation(
            allowed=plan_expected_kinds(dp=dp, tp=tp, decode=True),
            required_any={"all-reduce"},
            min_required=1,
            max_bytes_per_instr=int(act_bytes * 1.25),
            expect_donation=True,
            # weights + donated cache + explicit prefix K/V carry + one
            # chunk of activations
            max_peak_bytes=int(
                1.3 * (_tiny_params_bytes() // tp + cache_dev)
            ) + 12 * act_bytes,
            donated_bytes_expected=cache_dev,
        ),
        min_devices=dp * tp,
    )


def _compact_target(what: str, tp: int = 4) -> AuditTarget:
    """Slot compaction (dp=1 by contract): the gather that repacks
    active slots into the half-size bucket, and the scatter that writes
    them back, must both lower to ZERO collectives — the slot dim is
    unsharded and the kv-head shard is untouched, so any collective
    here means the repack crossed the wire and the variant's pricing is
    void."""
    from dlbb_tpu.analysis.expectations import compact_expectation

    def build():
        return _serve_build(1, tp, what)

    exp = compact_expectation()
    cache_dev = _serve_cache_bytes_per_device(1, tp)
    # gather holds the full cache + the repacked half-size copy; scatter
    # additionally donates the full carry it writes back into
    exp.max_peak_bytes = int(
        (2.2 if what == "compact_gather" else 2.8) * cache_dev)
    if what == "compact_scatter":
        exp.donated_bytes_expected = cache_dev
    return AuditTarget(
        name=f"serve/engine.py::{what}[tp]",
        build=build,
        expectation=exp,
        min_devices=tp,
    )


def _prefix_attach_target(tp: int = 4) -> AuditTarget:
    """The shared-prefix attach jit (``serve/engine.py::prefix_attach``,
    dp=1 by contract): a masked-select copy of the donor slot's matched
    blocks into the destination slot plus the dequantised fp prefix
    carry.  Pure LOCAL data movement — the slot dim is unsharded and
    the kv-head shard is untouched, so the lowering must contain ZERO
    collectives: a shared-prefix prefill that costs even one extra
    collective has no TTFT story.  The donated carry is the cache (the
    serving-cache-drift pin extends to the attach program)."""
    from dlbb_tpu.analysis.expectations import compact_expectation

    def build():
        return _serve_build(1, tp, "prefix_attach")

    exp = compact_expectation()
    cache_dev = _serve_cache_bytes_per_device(1, tp)
    # the full donated cache + the one-block prefix carry + the masked
    # copy's transient
    exp.max_peak_bytes = int(2.2 * cache_dev)
    exp.donated_bytes_expected = cache_dev
    return AuditTarget(
        name="serve/engine.py::prefix_attach[tp]",
        build=build,
        expectation=exp,
        min_devices=tp,
    )


def _decode_quant_target(tp: int = 4) -> AuditTarget:
    """The int8-KV decode step (``serve/engine.py::decode_step`` with
    ``serving.kv_quantization=int8``, dp=1 — the prefix/quant serving
    envelope): same tiny-collectives contract as the fp decode target,
    but the donated carry and the peak ceiling are priced from the
    QUANTIZED layout — int8 data planes + fp32 per-(block, kv-head)
    scales, ~4x smaller than fp32 planes.  This is the static proof of
    the capacity claim: if the compiled carry were still fp-sized, the
    donation pin (serving-cache-drift) trips on the analytic int8
    number."""
    def build():
        return _serve_build(1, tp, "decode_quant")

    qkv_width = 3 * _TINY_MODEL["hidden_size"]
    act_bytes = _SERVE_SHAPE["max_batch"] * qkv_width * 4
    cache_q = _serve_cache_bytes_per_device(1, tp,
                                            kv_quantization="int8")
    # dequantise-to-fp32 transients: each scanned layer materialises one
    # layer's fp32 view of its k/v planes (cache_q * ~4 / num_layers per
    # plane pair) — bounded inside the peak term below
    fp_layer = 4 * cache_q // _TINY_MODEL["num_layers"]
    # the donated carry also holds the [B, 1, H] f32 hidden state and
    # the int32 lengths vector — negligible against fp planes but >10%
    # of the 4x-smaller int8 cache, so the pin must price them
    carry_extra = _SERVE_SHAPE["max_batch"] * (
        _TINY_MODEL["hidden_size"] * 4 + 4)
    return AuditTarget(
        name="serve/engine.py::decode_step[int8,tp]",
        build=build,
        expectation=TargetExpectation(
            allowed=plan_expected_kinds(dp=1, tp=tp, decode=True),
            required_any={"all-reduce"},
            min_required=1,
            max_bytes_per_instr=int(act_bytes * 1.25),
            expect_donation=True,
            max_peak_bytes=int(
                1.3 * (_tiny_params_bytes() // tp + cache_q + fp_layer)
            ) + 16 * act_bytes,
            donated_bytes_expected=cache_q + carry_extra,
        ),
        min_devices=tp,
    )


def _train_step_target(zero_stage: int, dp: int = 8) -> AuditTarget:
    def build():
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding

        from dlbb_tpu.comm.mesh import build_parallelism_mesh
        from dlbb_tpu.models.configs import ModelConfig
        from dlbb_tpu.models.sharding import batch_spec
        from dlbb_tpu.models.transformer import init_params_sharded
        from dlbb_tpu.train.loop import make_train_step

        import optax

        cfg = ModelConfig(**_TINY_MODEL)
        mesh = build_parallelism_mesh(data_parallel=dp)
        params = init_params_sharded(cfg, jax.random.key(0), mesh)
        jit_step, state = make_train_step(
            cfg, mesh, optax.adam(1e-3), params, zero_stage=zero_stage,
        )
        sharding = NamedSharding(mesh, batch_spec(mesh))
        batch = jax.device_put(
            jnp.ones((dp, 8, cfg.hidden_size), jnp.float32), sharding)
        tgt = jax.device_put(
            jnp.ones((dp, 8, cfg.hidden_size), jnp.float32), sharding)
        return jit_step, (state, batch, tgt)

    # resident train state: full f32 params everywhere; Adam moments
    # replicated at ZeRO-0, dp-sharded at ZeRO-1 — plus gradients and
    # backward transients.  A dropped donation re-adds the whole state.
    n4 = _tiny_params_bytes()
    peak_ceiling = int(6.5 * n4) if zero_stage == 0 else int(2.85 * n4)
    return AuditTarget(
        name=f"train/loop.py::train_step[zero{zero_stage},dp]",
        build=build,
        expectation=TargetExpectation(
            allowed=plan_expected_kinds(dp=8, zero_stage=zero_stage),
            required_any={"all-reduce", "reduce-scatter"},
            min_required=1,  # the gradient reduction must exist
            expect_donation=True,
            max_peak_bytes=peak_ceiling,
        ),
        min_devices=dp,
    )


def registry_op_targets() -> list[AuditTarget]:
    """One audit target per ``comm/ops.py`` registry collective — the
    collective-matmul micro-ops need LLM-shaped payloads and get one
    dedicated target per schedule (fused vs the decomposed rings); the
    compressed micro-ops get one per wire dtype, audited against the
    compression byte ceiling instead of the plain kind table."""
    from dlbb_tpu.comm.ops import COMPRESSED_OPS, MATMUL_OPS, OPERATIONS

    targets = [
        _registry_op_target(name)
        for name in sorted(OPERATIONS)
        if name not in MATMUL_OPS and name not in COMPRESSED_OPS
    ]
    targets += [
        _collective_matmul_target(name, schedule)
        for name in MATMUL_OPS
        for schedule in ("fused", "ring", "bidir")
    ]
    targets += [
        _compressed_op_target(name, compression)
        for name in COMPRESSED_OPS
        for compression in ("int8", "fp8")
    ]
    return targets


def default_targets() -> list[AuditTarget]:
    """The repo's standing audit surface: every registry collective, the
    TP/sequence-parallel model forwards (the e2e benchmark's jit) with
    and without the overlapped collective-matmul schedule, the
    DDP + ZeRO-1 + overlapped-TP train steps, and the serving programs
    — per-step decode + monolithic prefill plus the decode fast path
    (fused K-step scan, chunked prefill, compaction gather/scatter), the
    speculative-decoding programs (token-feedback fused scan, γ-token
    verify step, draft-model proposal scan), and the prefix/quant cache
    programs (zero-collective shared-prefix attach, int8-KV decode with
    the quantized-layout donation pin) — all tiny-collectives-only with
    the cache-regather byte gate."""
    targets = registry_op_targets()
    targets.append(_barrier_target())
    targets.append(_tp_forward_target())
    targets.append(_tp_overlap_forward_target("ring"))
    targets.append(_tp_overlap_forward_target("bidir"))
    targets.append(_cp_forward_target("ring"))
    targets.append(_cp_forward_target("ulysses"))
    targets.append(_train_step_target(zero_stage=0))
    targets.append(_train_step_target(zero_stage=1))
    targets.append(_tp_overlap_train_target("ring"))
    targets.append(_compressed_train_target("int8"))
    targets.append(_decode_step_target())
    targets.append(_prefill_target())
    targets.append(_decode_fused_target())
    targets.append(_decode_fused_token_target())
    targets.append(_verify_step_target())
    targets.append(_draft_scan_target())
    targets.append(_prefill_chunk_target())
    targets.append(_compact_target("compact_gather"))
    targets.append(_compact_target("compact_scatter"))
    targets.append(_prefix_attach_target())
    targets.append(_decode_quant_target())
    return targets


def default_tier() -> str:
    """The cost-model tier matching the current backend: the CPU-simulated
    mesh prices at ``cpu-sim`` (the committed-baseline tier); a real TPU
    at ``tpu-v5lite``."""
    import jax

    return "cpu-sim" if jax.default_backend() == "cpu" else "tpu-v5lite"


def run_hlo_audit(
    targets: Optional[Sequence[AuditTarget]] = None,
    verbose: bool = False,
    passes: Sequence[str] = ("hlo",),
    tier: Optional[str] = None,
    model: str = "cm1",
) -> AnalysisReport:
    """Audit ``targets`` (default: the standing registry) on the current
    backend.  ``passes`` selects the byte auditor (``"hlo"``), the α–β
    schedule auditor (``"schedule"``), or both — one lowering per target
    either way.  ``model`` selects the cost model the schedule pass
    prices with (cm1 analytic / cm2 fitted).  Targets needing more
    devices than available are recorded as skipped, not failed — the
    CLI's ``--simulate N`` controls the mesh."""
    import jax

    if "schedule" in passes or "memory" in passes:
        if tier is None:
            tier = default_tier()
        # resolve once, before any lowering: a mistyped --tier/--model
        # must be EXIT_CRASH (unusable arguments), not 30 repeated
        # audit-crash findings after minutes of wasted compiles — and a
        # cm2 fit-missing fallback must warn ONCE, not per target
        from dlbb_tpu.analysis.costmodel import resolve_tier

        tier = resolve_tier(tier, model=model)
    report = AnalysisReport()
    n_devices = len(jax.devices())
    for target in targets if targets is not None else default_targets():
        if target.min_devices > n_devices:
            report.skipped_targets.append({
                "target": target.name,
                "reason": (f"needs {target.min_devices} devices, "
                           f"{n_devices} available"),
            })
            continue
        try:
            findings, _meta = audit_target(target, passes=passes, tier=tier)
        except Exception as e:  # noqa: BLE001 — one target's lowering
            # failure must not abort the audit of the rest (same per-config
            # containment convention as bench/runner.run_sweep); it is still
            # an error finding, not a silent skip
            report.findings.append(Finding(
                pass_name="hlo", rule="audit-crash",
                severity=SEVERITY_ERROR, target=target.name,
                message=f"audit raised {type(e).__name__}: {e}",
            ))
            if verbose:
                print(f"[hlo] {target.name}: CRASH ({type(e).__name__})")
            continue
        report.findings.extend(findings)
        report.targets_audited.append(target.name)
        if "schedule" in _meta:
            report.schedule[target.name] = _meta["schedule"]
        if "memory" in _meta:
            report.memory[target.name] = _meta["memory"]
        if "numerics" in _meta:
            report.numerics[target.name] = _meta["numerics"]
        if verbose:
            status = "FAIL" if findings else "ok"
            sched = _meta.get("schedule")
            n_coll = _meta.get(
                "num_collectives",
                sched["num_collectives"] if sched else 0,
            )
            extra = ""
            if sched is not None:
                eff = sched["overlap_efficiency"]
                extra = (
                    f", cp {sched['critical_path_us']:.1f}us"
                    + (f", overlap {eff:.2f}" if eff is not None else "")
                )
            mem = _meta.get("memory")
            if mem is not None:
                extra += (f", peak "
                          f"{mem['peak_live_bytes'] / 1024:.1f}KiB")
            num = _meta.get("numerics")
            if num is not None:
                extra += (f", err<="
                          f"{num['numerics_max_rel_error_bound']:.2g}")
            print(f"[hlo] {target.name}: {status} "
                  f"({n_coll} collective(s){extra})")
    return report
