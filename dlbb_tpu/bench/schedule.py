"""Pipelined sweep execution engine (compile-ahead scheduler).

The sweep driver (``dlbb_tpu.bench.runner``) is the hot path of the whole
framework — every published curve in ``results/`` flows through it — and
before this module it was strictly serial: each config traced and compiled
its jitted shard_map program while the device sat idle, and every re-run
paid full recompilation again.  XLA compilation releases the GIL and JAX
ships a persistent compilation cache, so compile time can be overlapped
with measurement and amortised across runs without touching timing
semantics.  Three mechanisms, all orthogonal to *how* a config is timed:

- **Work units** — the sweep grid is walked once up front and deduplicated
  by :func:`work_unit_key` ``(op, variant, mesh, payload aval,
  compiler_options, timing fingerprint)``.  Configs that share a key share
  one traced/compiled program; configs that differ in ANY key component
  (same shape under a different variant, say) never do.
- **Compile-ahead** — :class:`CompileAheadScheduler` AOT-lowers and
  compiles work unit N+1..N+k on a background thread while unit N's
  configs are being measured on the main thread.  Lowering uses abstract
  payloads (:func:`dlbb_tpu.comm.ops.payload_aval`), so the background
  thread never materialises a (possibly GiB-scale) payload.  ``k`` is the
  sweep's ``prefetch``; ``pipeline=False`` degrades to inline
  compile-on-demand through the *same* code path (the ``--no-pipeline``
  debug mode).
- **Persistent compilation cache** — :func:`configure_compilation_cache`
  wires ``jax_compilation_cache_dir`` (default ``results/.xla_cache``,
  ``DLBB_XLA_CACHE`` env / ``--compile-cache`` CLI override, ``off`` to
  disable), so publisher re-runs and ``resume`` sweeps deserialise
  executables instead of recompiling.  Hits/misses are observed through
  ``jax.monitoring`` events and recorded per work unit — each result
  artifact carries honest ``compile_seconds`` / ``compile_cache_hit``
  fields, and each sweep a ``sweep_manifest.json`` with the totals.

Payloads are cached too (:class:`PayloadCache`): ops that share
``(input_kind, shape, dtype, sharding, seed)`` at the same rank count reuse
one device array instead of regenerating it per config — except in chained
timing, which DONATES its carry (``utils/timing.py``); donated entries are
invalidated so a deleted array can never be handed to the next config.

Measurement semantics are bit-for-bit those of the serial driver: per_iter
vs chained selection, donation, and the plausibility probe all live in
``utils/timing.py`` and receive the pre-compiled executable through
explicit parameters (``executable`` / ``chained_loop``) rather than a
changed code path.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Callable, Iterable, Optional, Sequence

import jax

from dlbb_tpu.comm.ops import CollectiveOp, payload_aval
from dlbb_tpu.obs import spans
from dlbb_tpu.resilience import inject
from dlbb_tpu.resilience.errors import DeadlineExceeded, InjectedFault
from dlbb_tpu.utils.timing import build_chained_loop, chained_chunk_size

# ---------------------------------------------------------------------------
# persistent compilation cache
# ---------------------------------------------------------------------------

# Default under results/: the cache is a results-adjacent artifact of the
# publisher corpus (gitignored), salted by jaxlib version inside JAX's own
# cache key, so upgrading jaxlib invalidates it automatically.
DEFAULT_CACHE_DIR = os.path.join("results", ".xla_cache")

_CACHE_OFF_VALUES = {"", "off", "none", "0", "disabled"}

# last directory this process configured (sentinel: never configured).
# jax 0.4.x latches cache-enablement state at the FIRST compile of the
# process (compilation_cache._cache_checked): a compile that ran before
# any cache dir was set pins the cache "unused" forever unless the state
# is reset — so every directory CHANGE resets it.
_configured_dir: Any = object()

# the caller's jax cache config (dir, min-compile-time, min-entry-size)
# captured before the first mutation, so deactivation RESTORES a
# pre-existing user configuration (e.g. JAX_COMPILATION_CACHE_DIR set in
# an embedding process) instead of clobbering it to disabled
_saved_cache_state: Optional[tuple] = None


def _snapshot_cache_state() -> None:
    global _saved_cache_state
    if _saved_cache_state is None:
        _saved_cache_state = (
            jax.config.jax_compilation_cache_dir,
            jax.config.jax_persistent_cache_min_compile_time_secs,
            jax.config.jax_persistent_cache_min_entry_size_bytes,
        )


def _reset_jax_cache_state() -> None:
    from jax.experimental.compilation_cache import compilation_cache as cc

    cc.reset_cache()


def configure_compilation_cache(
    setting: Optional[str] = "auto",
) -> Optional[str]:
    """Point JAX's persistent compilation cache at a directory (or disable).

    ``setting``: ``"auto"`` → :data:`DEFAULT_CACHE_DIR`; an explicit path →
    that path; ``None``/``"off"``/``"0"`` → disabled.  The ``DLBB_XLA_CACHE``
    environment variable overrides whatever the caller passes (the launcher
    analogue of the CLI flag).  Returns the configured directory, or None
    when disabled.

    The min-compile-time/min-entry-size thresholds are zeroed: the
    simulated-mesh micro-programs compile in milliseconds and would
    otherwise never be cached, which is exactly the regime where re-run
    compile time dominates sweep wall time.
    """
    global _configured_dir
    env = os.environ.get("DLBB_XLA_CACHE")
    if env is not None:
        setting = env
    if setting is None or str(setting).lower() in _CACHE_OFF_VALUES:
        _snapshot_cache_state()
        jax.config.update("jax_compilation_cache_dir", None)
        if _configured_dir is not None:
            _reset_jax_cache_state()
            _configured_dir = None
        return None
    _snapshot_cache_state()
    cache_dir = DEFAULT_CACHE_DIR if setting == "auto" else str(setting)
    Path(cache_dir).mkdir(parents=True, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    if _configured_dir != cache_dir:
        # also clears the "cache unused" latch a pre-configuration compile
        # may have pinned (see _configured_dir comment)
        _reset_jax_cache_state()
        _configured_dir = cache_dir
    return cache_dir


def deactivate_compilation_cache() -> None:
    """Disable the persistent cache and clear JAX's latched cache state.

    The cache is SCOPED TO SWEEPS: ``run_sweep`` activates it for its own
    compiles and calls this on exit, so no other compile in the process
    ever goes through executable (de)serialization.  That scoping is a
    correctness requirement on this jaxlib, not hygiene: with the cache
    left enabled process-wide, XLA:CPU hard-aborts (fatal ``Aborted``, not
    an exception) serialising some non-sweep programs — observed
    deterministically on the checkpoint-restore train step
    (``tests/test_checkpoint.py::test_resume_continues_trajectory``) the
    moment a prior sweep left the cache on.  Sweep programs (shard_map
    collectives and the chained timing loop) round-trip fine.

    A configuration the CALLER had in place before the sweep (e.g.
    ``JAX_COMPILATION_CACHE_DIR`` in an embedding process) is restored,
    thresholds included, not clobbered to disabled — the sweep scope
    must be invisible to the surrounding process.  Unlike
    :func:`configure_compilation_cache` this ignores ``DLBB_XLA_CACHE``
    — the env var picks the cache *location*, it must not be able to
    veto the restore."""
    global _configured_dir, _saved_cache_state
    if _saved_cache_state is not None:
        prev_dir, prev_mct, prev_mes = _saved_cache_state
        jax.config.update("jax_compilation_cache_dir", prev_dir)
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", prev_mct)
        jax.config.update(
            "jax_persistent_cache_min_entry_size_bytes", prev_mes)
        _saved_cache_state = None
    else:
        jax.config.update("jax_compilation_cache_dir", None)
    if _configured_dir is not None:
        _reset_jax_cache_state()
        _configured_dir = None


def default_pipeline() -> bool:
    """Whether the compile-ahead thread should run on this host.

    The measurement gate means a background compile can only overlap the
    sweep's un-timed work, and that overlap needs spare host cores to be
    a win: on the 2-core simulated-mesh box the thread is a measured net
    tax (BENCH_sweep.json: pipelined cold ~0.6x serial on compile-heavy
    grids — pure contention + scheduling overhead), while on multi-core
    TPU hosts the compile runs on otherwise-idle cores.  Auto therefore
    enables the thread only with >= 4 cores; ``DLBB_SWEEP_PIPELINE=1/0``
    forces either way, and lifting the gate (``DLBB_COMPILE_OVERLAP=1``)
    implies the host has cores to burn.  Serial mode keeps every other
    engine win (work-unit dedup, payload/mesh reuse, the persistent
    cache, compile accounting).
    """
    env = os.environ.get("DLBB_SWEEP_PIPELINE")
    if env is not None:
        return env.lower() not in ("0", "off", "false", "no")
    if os.environ.get("DLBB_COMPILE_OVERLAP") == "1":
        return True
    return (os.cpu_count() or 1) >= 4


class _CacheEventCounter:
    """Counts JAX persistent-compilation-cache hit/miss monitoring events.

    ``jax.monitoring`` listeners are global and cannot be unregistered, so
    one process-wide counter is registered lazily and compile sites sample
    it before/after each compile (under :data:`_COMPILE_LOCK`, which
    serialises compiles so the delta attributes to exactly one of them).
    """

    HIT = "/jax/compilation_cache/cache_hits"
    MISS = "/jax/compilation_cache/cache_misses"

    def __init__(self) -> None:
        self.hits = 0
        self.misses = 0
        self._registered = False
        self._lock = threading.Lock()

    def ensure_registered(self) -> None:
        with self._lock:
            if self._registered:
                return
            from jax import monitoring

            def _listener(event: str, **kwargs: Any) -> None:
                if event == self.HIT:
                    self.hits += 1
                elif event == self.MISS:
                    self.misses += 1

            monitoring.register_event_listener(_listener)
            self._registered = True

    def snapshot(self) -> tuple[int, int]:
        return self.hits, self.misses


CACHE_EVENTS = _CacheEventCounter()

# Serialises trace+lower+compile so persistent-cache hit events attribute
# to the unit being compiled.  XLA compilation would release the GIL, but
# correct per-unit cache accounting beats compile/compile parallelism —
# the pipeline's win is compile/*measure* overlap, which the lock never
# blocks (the measuring thread does not compile).
_COMPILE_LOCK = threading.Lock()


# ---------------------------------------------------------------------------
# work units
# ---------------------------------------------------------------------------


def work_unit_key(
    op: CollectiveOp,
    variant_name: str,
    mesh,
    axes: Sequence[str],
    root: int,
    aval: jax.ShapeDtypeStruct,
    mode: str,
    iterations: int,
    compiler_options: Optional[dict[str, str]],
) -> tuple:
    """Dedup identity of one compiled program.

    Everything that changes the traced/compiled artifact is in the key:
    the op, the variant *name* (two variants can share a mesh shape yet
    build different programs — hierarchical vs joint reduction — so the
    name itself is a component, never just its mesh spec), the mesh
    topology and device identity, the payload aval, per-computation
    compiler options, and the timing fingerprint (chained mode bakes the
    chunk size into the compiled loop).
    """
    timing_fp = (
        ("chained", chained_chunk_size(iterations))
        if mode == "chained" else ("per_iter",)
    )
    return (
        op.name,
        variant_name,
        tuple(mesh.devices.shape),
        tuple(mesh.axis_names),
        tuple(id(d) for d in mesh.devices.flat),
        tuple(axes),
        root,
        tuple(aval.shape),
        str(aval.dtype),
        tuple(sorted(compiler_options.items())) if compiler_options else (),
        timing_fp,
    )


@dataclass
class WorkUnit:
    """One deduplicated (trace, lower, compile) job and its products."""

    key: tuple
    build: Callable[[], tuple[Callable, Callable]]  # -> (traceable, compiled)
    label: str = ""
    chained: bool = False
    fn: Optional[Callable] = None          # traceable jitted program
    executable: Optional[Callable] = None  # compiled program / chained loop
    compile_seconds: float = 0.0
    persistent_cache_hit: bool = False
    error: Optional[Exception] = None
    consumers: int = 0  # configs measured against this unit (main thread)
    # set once a consumer has RECORDED the compile cost in an artifact —
    # attribution must go to the first config that actually writes one,
    # not the first that merely starts (its measurement may fail before
    # saving, which would make the compile cost vanish and later sharers
    # claim a cache hit for a program compiled fresh this process)
    compile_reported: bool = False
    ready: threading.Event = field(default_factory=threading.Event)


def _compile_unit(unit: WorkUnit, locked: bool = True) -> None:
    """Trace + lower + compile one unit; idempotent; never raises (build
    failures are contained in ``unit.error`` so one poisoned unit skips its
    configs while the pipeline drains).

    ``locked=False`` skips :data:`_COMPILE_LOCK` — only for the
    wedged-worker fallback (:meth:`CompileAheadScheduler.get`), where the
    zombie worker holds the lock inside a hung compile forever; the cost
    is per-unit persistent-cache-hit attribution for that compile, never
    correctness."""
    if unit.ready.is_set():
        return
    try:
        CACHE_EVENTS.ensure_registered()
        if inject.fire("compile-fail"):
            raise InjectedFault(f"injected compile failure for {unit.label}")
        if inject.fire("compile-hang"):
            # models a wedged XLA compile: the watchdog (deadline-aware
            # get()) must abandon + quarantine without blocking the drain
            time.sleep(inject.param("hang_seconds"))
        # the span wraps lock wait + compile (docs/observability.md) —
        # its clock reads sit OUTSIDE the compile_seconds bracket, so
        # tracing never inflates the compile accounting
        with spans.span("compile", cat="compile", label=unit.label,
                        chained=unit.chained), \
                (_COMPILE_LOCK if locked else contextlib.nullcontext()):
            hits0, misses0 = CACHE_EVENTS.snapshot()
            t0 = time.perf_counter()
            unit.fn, unit.executable = unit.build()
            unit.compile_seconds = time.perf_counter() - t0
            hits1, misses1 = CACHE_EVENTS.snapshot()
        # a hit claim requires BOTH a hit event and no miss in the window:
        # under DLBB_COMPILE_OVERLAP=1 a main-thread compile (the per-iter
        # fallback's loop jit, a first forced-completion reduction) can
        # fire events concurrently, and a fresh compile always fires its
        # own miss — requiring miss-free windows turns any such collision
        # into an under-reported hit, never a fabricated one
        unit.persistent_cache_hit = hits1 > hits0 and misses1 == misses0
    except Exception as e:  # noqa: BLE001 — containment is the contract
        unit.error = e
    finally:
        unit.ready.set()


def plan_collective_unit(
    units: "OrderedDict[tuple, WorkUnit]",
    op: CollectiveOp,
    build_fn: Callable[[], Callable],
    variant_name: str,
    mesh,
    axes: Sequence[str],
    root: int,
    num_ranks: int,
    num_elements: int,
    dtype,
    payload_shape: Optional[tuple[int, ...]],
    mode: str,
    iterations: int,
    compiler_options: Optional[dict[str, str]],
) -> WorkUnit:
    """Intern the work unit for one sweep config into ``units``.

    ``build_fn`` constructs the traceable jitted program (the runner's op
    builder); the returned unit's ``build`` wraps it with AOT lowering
    against the abstract payload and — in chained mode — the jitted timing
    loop with the chunk size :func:`chained_chunk_size` will pick for
    ``iterations``, so the compiled artifact is exactly what the
    measurement executes.
    """
    aval = payload_aval(op, mesh, axes, num_elements, dtype=dtype,
                        shape=payload_shape)
    key = work_unit_key(op, variant_name, mesh, axes, root, aval, mode,
                        iterations, compiler_options)
    unit = units.get(key)
    if unit is not None:
        return unit
    chained = mode == "chained"
    options = dict(compiler_options) if compiler_options else None

    def build() -> tuple[Callable, Callable]:
        fn = build_fn()
        if chained:
            chain = (op.make_chain(num_ranks)
                     if op.make_chain is not None else None)
            looped = build_chained_loop(
                fn, chain, chained_chunk_size(iterations)
            )
            lowered = looped.lower((), aval)
        else:
            lowered = fn.lower(aval)
        compiled = (lowered.compile(compiler_options=options)
                    if options else lowered.compile())
        return fn, compiled

    unit = WorkUnit(
        key=key,
        build=build,
        label=f"{op.name}/{variant_name}/r{num_ranks}/"
              f"{'x'.join(map(str, aval.shape))}/{aval.dtype}",
        chained=chained,
    )
    units[key] = unit
    return unit


# ---------------------------------------------------------------------------
# measurement gate
# ---------------------------------------------------------------------------


class MeasureGate:
    """The measurement-honesty mutex between timed regions and background
    compiles — a ``threading.Lock`` with two resilience affordances:

    - **timeout acquisition** (:meth:`acquire`): the compile worker polls
      instead of blocking forever, so a measurement thread abandoned by
      the watchdog while holding the gate can never wedge the pipeline
      drain;
    - **degraded mode** (:meth:`degrade`): once the watchdog has
      abandoned a hung unit, the gate may be held by a zombie thread for
      an unbounded time.  Rather than stalling every remaining config
      behind it, acquisition falls through ungated after a bounded wait.
      Degradation is one-way and recorded in the sweep manifest
      (``watchdog.gate_degraded``) — the measurement-honesty claim of
      post-hang configs is weakened (a zombie may still be doing device
      work) and the artifact trail says so.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.degraded = False
        self._held_here = threading.local()

    def degrade(self) -> None:
        self.degraded = True

    def acquire(self, timeout: float = 0.25) -> bool:
        return self._lock.acquire(timeout=timeout)

    def release(self) -> None:
        self._lock.release()

    def __enter__(self) -> "MeasureGate":
        # bounded wait once degraded; patient (but interruptible-by-
        # degradation) wait otherwise
        while True:
            if self._lock.acquire(timeout=0.25):
                self._held_here.held = True
                return self
            if self.degraded:
                self._held_here.held = False
                return self

    def __exit__(self, *exc) -> None:
        if getattr(self._held_here, "held", False):
            self._held_here.held = False
            self._lock.release()


# ---------------------------------------------------------------------------
# compile-ahead scheduler
# ---------------------------------------------------------------------------


class CompileAheadScheduler:
    """Bounded producer/consumer compiler.

    The worker thread compiles units in first-use order, at most
    ``prefetch`` ahead of consumption; :meth:`get` blocks until the
    requested unit is ready and frees a prefetch slot the first time each
    unit is consumed.  With ``pipeline=False`` no thread is started and
    :meth:`get` compiles inline — same code path, same metadata, zero
    overlap (the ``--no-pipeline`` debugging mode).
    """

    def __init__(
        self,
        units: Iterable[WorkUnit],
        prefetch: int = 2,
        pipeline: bool = True,
        measure_gate: "Optional[MeasureGate | threading.Lock]" = None,
    ) -> None:
        self._units = list(units)
        self._pipeline = bool(pipeline) and bool(self._units)
        # prefetch slots: the unit being measured + k compiled ahead
        self._slots = threading.Semaphore(max(1, int(prefetch)) + 1)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # Measurement-honesty invariant: the worker never compiles while
        # the consumer holds this lock (i.e. while a config is being
        # TIMED).  A background compile contends for host cores with the
        # measured program — on the 2-core simulated-mesh host it was
        # measured to double tiny-op medians — so compiles overlap the
        # sweep's un-timed work instead: payload generation (seconds at
        # the GiB labels), result IO, resume allgathers, planning.
        # ``DLBB_COMPILE_OVERLAP=1`` disables the gate for hosts with
        # cores to spare.
        self._measure_gate = measure_gate
        # watchdog state: a deadline overrun abandoned a compile — the
        # worker thread may be permanently stuck inside it
        self.wedged = False
        self.abandoned = 0
        # unit keys whose compile already blew a deadline: NEVER re-run
        # those builds inline (a deterministically hanging build would
        # hang the consumer thread, where no watchdog applies)
        self._abandoned_keys: set[tuple] = set()

    @property
    def pipelined(self) -> bool:
        return self._pipeline

    def start(self) -> None:
        if not self._pipeline or self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._worker, name="dlbb-compile-ahead", daemon=True
        )
        self._thread.start()

    def _acquire_gate(self) -> bool:
        """Poll the gate with stop/degradation checks — an abandoned
        measurement thread holding the gate must never wedge the drain.
        Returns whether the gate is actually held (False = proceed
        ungated: stopping, or gate degraded by the watchdog)."""
        gate = self._measure_gate
        if gate is None:
            return False
        while not self._stop.is_set():
            if gate.acquire(timeout=0.25):
                return True
            if getattr(gate, "degraded", False):
                return False
        return False

    def _worker(self) -> None:
        try:
            for unit in self._units:
                if self._stop.is_set():
                    break
                self._slots.acquire()
                if self._stop.is_set():
                    break
                held = self._acquire_gate()
                try:
                    if not self._stop.is_set():
                        _compile_unit(unit)
                finally:
                    if held:
                        self._measure_gate.release()
        finally:
            # a unit left un-ready would hang get() forever — fail closed
            for unit in self._units:
                if not unit.ready.is_set():
                    unit.error = RuntimeError(
                        "compile-ahead worker exited before compiling "
                        f"unit {unit.label or unit.key}"
                    )
                    unit.ready.set()

    def get(self, unit: WorkUnit,
            deadline: Optional[float] = None) -> WorkUnit:
        """Block until ``unit`` is compiled (or failed); inline-compile in
        serial mode.  Call once per consuming config.

        ``deadline`` (pipelined mode only) is the watchdog: a compile
        still not ready after that many seconds raises
        :class:`~dlbb_tpu.resilience.errors.DeadlineExceeded`, marks the
        scheduler wedged, and degrades the measurement gate — the hung
        compile is abandoned on its daemon thread, never joined.  After a
        wedge, later units compile inline on the consumer thread (the
        zombie worker still holds :data:`_COMPILE_LOCK`, so the inline
        path skips it and forfeits cache-hit attribution, not
        correctness).  A serial (``pipeline=False``) scheduler compiles
        on the calling thread, where a hung compile cannot be abandoned —
        the deadline only covers what runs on the worker."""
        if not self._pipeline:
            _compile_unit(unit)
        elif self.wedged and not unit.ready.is_set():
            if unit.key in self._abandoned_keys:
                # this exact build already blew the deadline once —
                # re-running it inline would hang the consumer thread
                # (every config sharing the unit quarantines instead)
                raise DeadlineExceeded(
                    unit.label or str(unit.key), float(deadline or 0.0),
                    phase="compile (unit previously abandoned)",
                )
            clone = WorkUnit(
                key=unit.key, build=unit.build,
                label=f"{unit.label}/inline-after-wedge",
                chained=unit.chained,
            )
            _compile_unit(clone, locked=False)
            clone.consumers += 1
            return clone
        else:
            if not unit.ready.wait(deadline):
                self.wedged = True
                self.abandoned += 1
                self._abandoned_keys.add(unit.key)
                gate = self._measure_gate
                if gate is not None and hasattr(gate, "degrade"):
                    gate.degrade()
                raise DeadlineExceeded(
                    unit.label or str(unit.key), float(deadline or 0.0),
                    phase="compile",
                )
            if unit.consumers == 0:
                self._slots.release()
        unit.consumers += 1
        return unit

    def close(self) -> None:
        self._stop.set()
        self._slots.release()  # unblock a worker waiting for a slot
        if self._thread is not None:
            if self.wedged:
                # the worker may be stuck inside an abandoned compile
                # forever; bounded join, then leave the daemon thread
                # behind (recorded in the manifest via `wedged`).  The
                # cache-config reset that follows in run_sweep's finally
                # can race the zombie's eventual cache write — accepted:
                # the alternative is a sweep that never returns.
                self._thread.join(timeout=5.0)
            else:
                # join WITHOUT timeout: run_sweep's finally resets the
                # process-wide persistent-cache config right after
                # close(), and doing that while a compile is still in
                # flight races its cache write (serial mode would be
                # equally stuck inside the same wedged compile, so no
                # liveness is lost by waiting)
                self._thread.join()
            self._thread = None


# ---------------------------------------------------------------------------
# payload cache
# ---------------------------------------------------------------------------

_PAYLOAD_CACHE_BYTES_ENV = "DLBB_PAYLOAD_CACHE_BYTES"
DEFAULT_PAYLOAD_CACHE_BYTES = 1 << 30  # 1 GiB of device payloads


class PayloadCache:
    """Byte-budgeted LRU of device payloads keyed by
    :func:`dlbb_tpu.comm.ops.payload_cache_key`.

    Ops that share (shape, dtype, sharding, seed) reuse one array instead
    of re-running the rank-seeded host RNG + device_put per config.
    Entries a measurement DONATED (chained timing, or the per-iter
    plausibility fallback) must be :meth:`invalidate`-d — the array is
    deleted and unusable.  Oversized payloads (> budget) are passed
    through uncached so the 1 GB-label sweeps keep their
    build-measure-free memory profile.
    """

    def __init__(self, max_bytes: Optional[int] = None) -> None:
        if max_bytes is None:
            max_bytes = int(os.environ.get(
                _PAYLOAD_CACHE_BYTES_ENV, DEFAULT_PAYLOAD_CACHE_BYTES
            ))
        self.max_bytes = max_bytes
        self._entries: OrderedDict[tuple, Any] = OrderedDict()
        self._nbytes = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def get(self, key: tuple, build: Callable[[], Any]) -> Any:
        arr = self._entries.get(key)
        if arr is not None:
            self.hits += 1
            self._entries.move_to_end(key)
            return arr
        self.misses += 1
        arr = build()
        nbytes = int(getattr(arr, "nbytes", 0))
        if nbytes > self.max_bytes:
            return arr  # uncached pass-through
        self._entries[key] = arr
        self._nbytes += nbytes
        while self._nbytes > self.max_bytes and len(self._entries) > 1:
            _, old = self._entries.popitem(last=False)
            self._nbytes -= int(getattr(old, "nbytes", 0))
            self.evictions += 1
        return arr

    def invalidate(self, key: tuple) -> None:
        arr = self._entries.pop(key, None)
        if arr is not None:
            self._nbytes -= int(getattr(arr, "nbytes", 0))

    def stats(self) -> dict[str, int]:
        return {
            "hits": self.hits,
            "misses": self.misses,
            "evictions": self.evictions,
            "resident_bytes": self._nbytes,
            "budget_bytes": self.max_bytes,
        }


# ---------------------------------------------------------------------------
# sweep manifest
# ---------------------------------------------------------------------------

MANIFEST_NAME = "sweep_manifest.json"
MANIFEST_SCHEMA = "dlbb_sweep_manifest_v1"


def write_sweep_manifest(out_dir, payload: dict[str, Any]):
    """Write the per-sweep engine manifest (wall/compile totals, cache and
    dedup accounting) next to the result artifacts.  Overwrites the
    previous sweep's manifest in the same directory — it documents the
    most recent run; the per-config compile fields in each result JSON are
    the durable record."""
    from dlbb_tpu.utils.config import save_json

    payload = {"schema": MANIFEST_SCHEMA, **payload}
    return save_json(payload, Path(out_dir) / MANIFEST_NAME)
