"""End-to-end tensor-parallel forward-pass benchmark.

Replacement for the reference's E2E harness (``run_mpi.py``): YAML config in,
TP transformer + fixed synthetic batch, warmup + timed forward passes,
metrics JSON out.  Differences by design:

- ``mpirun``-spawned ranks → a ``(dp, tp)`` device mesh; the reference's
  ``world_size`` is the TP degree (its only model parallelism — SURVEY §2.2);
- per-iteration ``comm.Barrier()`` pairs (``run_mpi.py:177,183``) →
  ``block_until_ready`` on the jitted step;
- the warmup loop (``run_mpi.py:154-166``) absorbs XLA compilation, which is
  timed separately (first-call cost is compile, not page-faulting —
  SURVEY §7);
- cross-rank variance/CV of forward means (``run_mpi.py:199-212``) becomes
  cross-*host* variance; on a single process it is zero and recorded as such.
"""

from __future__ import annotations

import time
from pathlib import Path
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from dlbb_tpu.data.synthetic import create_dataset_from_config
from dlbb_tpu.models.configs import ModelConfig
from dlbb_tpu.parallel.plan import ParallelismPlan
from dlbb_tpu.models.sharding import batch_spec
from dlbb_tpu.models.transformer import (
    forward,
    forward_flops,
    init_params_sharded,
    num_parameters,
)
from dlbb_tpu.utils.config import load_config, save_json
from dlbb_tpu.utils.metrics import Timer, summarize
from dlbb_tpu.utils.profiling import annotate
from dlbb_tpu.utils.sysinfo import collect_system_info
from dlbb_tpu.utils.timing import (
    force_completion,
    resolve_timing_mode,
    time_fn_chained,
    time_fn_per_iter,
)


def run_e2e(
    config: dict[str, Any],
    devices: Optional[Sequence] = None,
    output_dir: Optional[str] = None,
    verbose: bool = True,
) -> dict[str, Any]:
    """Run the benchmark described by ``config`` (schema:
    ``configs/baseline_config.yaml``; parity with ``run_mpi.py:main``)."""
    with Timer() as t_init:
        model_cfg = ModelConfig.from_dict(config["model"])
        plan = ParallelismPlan.from_config(config, model_cfg, devices)
        mesh, num_microbatches = plan.mesh, plan.num_microbatches
        dtype = jnp.bfloat16 if model_cfg.dtype == "bfloat16" else jnp.float32

        params = init_params_sharded(
            model_cfg, jax.random.key(config["input"].get("seed", 42)), mesh
        )
        # hidden size comes from the resolved ModelConfig, not the raw YAML —
        # a `size: "7B"` config need not spell out hidden_size
        dataset = create_dataset_from_config(
            config, mesh=mesh, spec=batch_spec(mesh), dtype=dtype,
            hidden_size=model_cfg.hidden_size,
        )
        batch = dataset.get_batch()
    init_time = t_init.elapsed

    out_sharding = NamedSharding(mesh, batch_spec(mesh))
    step = jax.jit(
        lambda p, x: forward(p, x, model_cfg, mesh=mesh,
                             num_microbatches=num_microbatches),
        out_shardings=out_sharding,
    )

    execution = config.get("execution", {})
    warmup = execution.get("warmup_iterations", 5)
    iters = execution.get("benchmark_iterations", 10)
    # variant-tuned XLA compilation, same contract as run_train
    comp_opts = {
        str(k): str(v)
        for k, v in (execution.get("compiler_options") or {}).items()
    }

    # The model maps [B,S,H] -> [B,S,H], so chained timing on remote-async
    # backends feeds the output straight back as the next input.
    mode = resolve_timing_mode("auto")

    with annotate("compile+warmup"):
        with Timer() as t_compile:
            if comp_opts and mode == "per_iter":
                step = step.lower(params, batch).compile(
                    compiler_options=comp_opts
                )
            force_completion(step(params, batch))
        compile_time = t_compile.elapsed

    with annotate("measure"):
        if mode == "per_iter":
            forward_times, _, _ = time_fn_per_iter(
                step, params, batch, warmup=max(0, warmup - 1),
                iterations=iters
            )
            timing_meta = {
                "timing_mode": "per_iter",
                "timing_method": "time.perf_counter() + jax.block_until_ready()",
            }
        else:
            # batch is donated to the timing loop; it is not used again
            forward_times, timing_meta, _ = time_fn_chained(
                step, batch, warmup=1, iterations=iters,
                chunk_size=min(5, iters), op_args=(params,),
                compiler_options=comp_opts or None,
            )

    # cross-host spread of mean forward time (run_mpi.py:199-212 analogue)
    local_mean = float(np.mean(forward_times))
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        host_means = np.asarray(
            multihost_utils.process_allgather(np.float64(local_mean))
        ).ravel()
    else:
        host_means = np.asarray([local_mean])
    variance = float(host_means.var())
    cv = float(host_means.std() / host_means.mean()) if host_means.mean() > 0 else 0.0

    tokens = (config["input"]["batch_size"] * config["input"]["sequence_length"])
    flops = forward_flops(
        model_cfg, config["input"]["batch_size"],
        config["input"]["sequence_length"],
    )
    result = {
        "experiment": config.get("experiment", {}),
        "backend": "xla_tpu",
        "config": config,
        "model": {
            "num_parameters": num_parameters(model_cfg),
            "attention": model_cfg.attention,
            "dtype": model_cfg.dtype,
            # TP collective-matmul schedule (off = GSPMD fused; ring/bidir
            # = overlapped decomposition, docs/overlap.md)
            "tp_overlap": model_cfg.tp_overlap,
        },
        "mesh": plan.mesh_dict(),
        "init_time_s": init_time,
        "compiler_options": comp_opts or None,
        "compile_time_s": compile_time,
        "forward_time": summarize(forward_times),
        **timing_meta,
        "per_host_means_s": host_means.tolist(),
        "cross_host_variance": variance,
        "cross_host_cv": cv,
        "tokens_per_second": tokens / local_mean,
        "model_flops_per_forward": flops,
        "achieved_tflops_per_second": flops / local_mean / 1e12,
        "timings": [forward_times],
        "system_info": collect_system_info(),
        "timestamp": time.time(),
    }

    if verbose:
        ft = result["forward_time"]
        print(
            f"[e2e] {config.get('experiment', {}).get('name', 'experiment')}: "
            f"forward mean {ft['mean'] * 1e3:.2f} ms "
            f"(p95 {ft['p95'] * 1e3:.2f} ms), compile {compile_time:.1f} s, "
            f"{result['tokens_per_second']:.0f} tok/s"
        )

    if output_dir is not None:
        name = config.get("experiment", {}).get("name", "experiment")
        save_json(result, Path(output_dir) / f"xla_tpu_{name}.json")
    return result


def run_e2e_from_config(
    config_path: str,
    output_dir: Optional[str] = None,
    devices: Optional[Sequence] = None,
    tp_overlap: Optional[str] = None,
) -> dict[str, Any]:
    """``tp_overlap`` overrides the config's ``model.tp_overlap`` (the
    ``--tp-overlap`` CLI flag): one YAML can be swept fused-vs-ring-vs-
    bidir without editing it."""
    config = load_config(config_path)
    if tp_overlap is not None:
        config.setdefault("model", {})["tp_overlap"] = tp_overlap
    out = output_dir or config.get("experiment", {}).get("output_dir")
    return run_e2e(config, devices=devices, output_dir=out)
