"""Unified collective-benchmark driver.

Replaces the duplicated skeleton of the reference's benchmark scripts
(constants → init → per-(op,size) loop of {warmup, timed measurement, gather,
JSON dump}; e.g. ``collectives/1d/openmpi.py:204-300``,
``collectives/3d/dsccl.py:120-241``) with one driver over declarative sweep
configs.  "Which backend executes the collective" — the reference's
MPI/Gloo/oneCCL axis — becomes a named :class:`~dlbb_tpu.comm.variants.Variant`
(mesh topology / reduction strategy / combiner flags), recorded in the result
JSON's implementation field so stats curves stay comparable.

Timing semantics (SURVEY §7 "hard parts"): each op is a jitted shard_map
micro-program; warmup absorbs XLA compilation; each timed iteration is
``perf_counter``-bracketed ``fn(x).block_until_ready()`` — the async-dispatch
analogue of ``comm.Barrier(); MPI.Wtime(); op; Wtime()``
(``collectives/1d/openmpi.py:60-66``).

Result JSON schema is reference-compatible: the 1D stats reader accepts
``implementation`` (``collectives/1d/stats.py:167``), and field names /
filenames match ``collectives/1d/openmpi.py:273-295`` and
``collectives/3d/openmpi.py:205-233``.
"""

from __future__ import annotations

import contextlib
import os
import signal
import threading
import time
import traceback
from dataclasses import dataclass
from pathlib import Path
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from dlbb_tpu.analysis.costmodel import COST_MODEL_VERSION
from dlbb_tpu.bench import schedule
from dlbb_tpu.comm.mesh import get_mesh
from dlbb_tpu.comm.ops import (
    COMPRESSED_OPS,
    MATMUL_OPS,
    build_allreduce_hierarchical,
    get_op,
    make_payload,
    payload_cache_key,
)
from dlbb_tpu.comm.variants import Variant, get_variant
from dlbb_tpu.obs import capture as obs_capture
from dlbb_tpu.obs import spans
from dlbb_tpu.obs.export import MetricsRegistry, sweep_metrics
from dlbb_tpu.resilience import inject
from dlbb_tpu.resilience.errors import (
    CorruptStats,
    DeadlineExceeded,
    exception_chain,
    is_transient,
)
from dlbb_tpu.resilience.journal import SweepJournal
from dlbb_tpu.resilience.preempt import PreemptionGuard
from dlbb_tpu.resilience.validate import (
    validate_result_json,
    validate_timings,
)
from dlbb_tpu.utils.config import save_json
from dlbb_tpu.utils.sysinfo import collect_system_info
from dlbb_tpu.utils.timing import resolve_timing_mode, time_collective

# Reference 1D sweep constants (``collectives/1d/openmpi.py:14-49``).
# NOTE the reference's size labels are 2x the actual fp16 payload
# ("16MB" = 4,194,304 elements x 2 B = 8 MiB — BASELINE.md); labels are kept
# verbatim for curve comparability, with honest byte counts in the JSON.
DATA_SIZES_1D: dict[str, int] = {
    "1KB": 256,
    "64KB": 16384,
    "1MB": 262144,
    "16MB": 4194304,
}

# Extension to the north-star 1 KB–1 GB curve (BASELINE.json metric).
EXTENDED_DATA_SIZES_1D: dict[str, int] = {
    **DATA_SIZES_1D,
    "64MB": 16777216,
    "256MB": 67108864,
    "1GB": 268435456,
}

OPERATIONS_1D: tuple[str, ...] = (
    "allreduce",
    "allgather",
    "broadcast",
    "gather",
    "scatter",
    "reduce",
    "alltoall",
    "sendrecv",
)

# Reference 3D sweep grid (``collectives/3d/openmpi.py:19-31``).
OPERATIONS_3D: tuple[str, ...] = (
    "allreduce",
    "allgather",
    "broadcast",
    "gather",
    "reduce",
)
GRID_3D: dict[str, Sequence[int]] = {
    "batch_sizes": (1, 8, 16, 32),
    "seq_lengths": (1, 2048, 4096, 8192),
    "hidden_dims": (2048, 4096),
}


@dataclass(frozen=True)
class Sweep1D:
    """1D collective microbenchmark sweep (flat element-count payloads)."""

    implementation: str = "xla_tpu"
    variant: str = "default"
    operations: tuple[str, ...] = OPERATIONS_1D
    data_sizes: tuple[tuple[str, int], ...] = tuple(DATA_SIZES_1D.items())
    rank_counts: tuple[int, ...] = (2, 4, 8)
    dtype: str = "bfloat16"
    warmup_iterations: int = 10
    measurement_iterations: int = 100
    output_dir: str = "results/1d"
    root: int = 0
    # "auto" | "per_iter" | "chained" — see dlbb_tpu.utils.timing
    timing_mode: str = "auto"
    # wall-time cap per config; iteration counts scale down to fit (actual
    # counts recorded in the result JSON) — for slow hosts / huge payloads
    max_config_seconds: Optional[float] = None
    # skip configs whose estimated global input+output footprint exceeds
    # this (host-simulated meshes hold every shard in one RAM pool)
    max_global_bytes: Optional[int] = None
    # skip configs whose result JSON already exists AND validates (parse +
    # finite stats, dlbb_tpu.resilience.validate) in output_dir — lets an
    # interrupted sweep (time-budgeted publisher runs, preemptions) pick up
    # where it left off instead of re-measuring the whole grid; an invalid
    # existing artifact (torn write) is re-measured with a warning
    resume: bool = False
    # pipelined execution engine (dlbb_tpu.bench.schedule): compile config
    # N+1..N+prefetch on a background thread between measurements.
    # None = auto (schedule.default_pipeline: only on hosts with spare
    # cores); False = serial debug mode (--no-pipeline), identical
    # schema/semantics; True forces the thread on
    pipeline: Optional[bool] = None
    prefetch: int = 2
    # persistent XLA compilation cache: "auto" -> results/.xla_cache, an
    # explicit directory, or None/"off" to disable (DLBB_XLA_CACHE env
    # overrides either way)
    compile_cache: Optional[str] = "auto"
    # --- resilience knobs (docs/resilience.md) ---------------------------
    # fault-injection plan spec (dlbb_tpu.resilience.inject grammar);
    # None = DLBB_FAULT_PLAN env (itself usually unset -> no injection)
    fault_plan: Optional[str] = None
    # wall-clock watchdog per work unit, covering both the background
    # compile and the measurement: an overrun is abandoned + quarantined,
    # never blocks the pipeline drain (DLBB_UNIT_DEADLINE env default)
    unit_deadline_seconds: Optional[float] = None
    # bounded retry with exponential backoff for transient failures;
    # retried configs recompute from scratch and carry `retries: N`
    max_retries: int = 2
    retry_backoff_seconds: float = 0.05
    # append-only crash-safe sweep_journal.jsonl next to the artifacts
    journal: bool = True
    # --- observability knobs (docs/observability.md) ---------------------
    # host-side span trace (Chrome trace-event JSON, Perfetto-loadable):
    # a file path, or None = DLBB_SPANS env (usually unset -> disabled)
    span_trace: Optional[str] = None
    # per-config jax.profiler device captures on DEDICATED profile reps
    # excluded from the stats series and run outside the measurement
    # gate; a directory, or None = DLBB_DEVICE_TRACE env
    device_trace_dir: Optional[str] = None

    kind: str = "1d"


@dataclass(frozen=True)
class Sweep3D:
    """3D LLM-shaped tensor collective sweep over (batch, seq, hidden)."""

    implementation: str = "xla_tpu"
    variant: str = "default"
    operations: tuple[str, ...] = OPERATIONS_3D
    batch_sizes: tuple[int, ...] = tuple(GRID_3D["batch_sizes"])
    seq_lengths: tuple[int, ...] = tuple(GRID_3D["seq_lengths"])
    hidden_dims: tuple[int, ...] = tuple(GRID_3D["hidden_dims"])
    rank_counts: tuple[int, ...] = (4, 8)
    dtype: str = "bfloat16"
    warmup_iterations: int = 10
    measurement_iterations: int = 100
    output_dir: str = "results/3d"
    root: int = 0
    timing_mode: str = "auto"
    max_config_seconds: Optional[float] = None
    max_global_bytes: Optional[int] = None
    resume: bool = False
    # pipelined execution engine — see Sweep1D (None = host-auto)
    pipeline: Optional[bool] = None
    prefetch: int = 2
    compile_cache: Optional[str] = "auto"
    # resilience knobs — see Sweep1D / docs/resilience.md
    fault_plan: Optional[str] = None
    unit_deadline_seconds: Optional[float] = None
    max_retries: int = 2
    retry_backoff_seconds: float = 0.05
    journal: bool = True
    # observability knobs — see Sweep1D / docs/observability.md
    span_trace: Optional[str] = None
    device_trace_dir: Optional[str] = None

    kind: str = "3d"


def _dtype_of(name: str):
    return {
        "bfloat16": jnp.bfloat16,
        "float16": jnp.float16,
        "float32": jnp.float32,
    }[name]


def _impl_name(sweep) -> str:
    if sweep.variant and sweep.variant != "default":
        return f"{sweep.implementation}_{sweep.variant}"
    return sweep.implementation


def _gather_timings(local: list[float]) -> list[list[float]]:
    """Per-host × per-iteration timings, shaped like the reference's
    ``[rank][iteration]`` gather (``collectives/1d/openmpi.py:270``).

    Single-process (incl. the CPU-simulated mesh): one timing stream for the
    whole SPMD program — the schema keeps the 2D shape with one row.
    Multi-host: each host contributes its own dispatch timings via a host-side
    allgather, so load-imbalance across hosts is still computable.
    """
    if jax.process_count() == 1:
        return [local]
    from jax.experimental import multihost_utils

    arr = multihost_utils.process_allgather(np.asarray(local, dtype=np.float64))
    return np.asarray(arr).reshape(jax.process_count(), -1).tolist()


def _check_variant_flags(variant: Variant) -> None:
    """XLA flags (combiner thresholds etc.) are process-start options: they
    must already be in ``XLA_FLAGS`` before backend init.  Refuse to run —
    rather than silently mislabel results — if a flag variant was requested
    without its flags set (they are the launcher's job, see
    ``launch/launch_tpu_pod.sh``)."""
    import os

    missing = [f for f in variant.xla_flags if f not in os.environ.get("XLA_FLAGS", "")]
    if missing:
        raise RuntimeError(
            f"variant {variant.name!r} requires XLA_FLAGS to contain "
            f"{missing}; relaunch the process with them set (process-start "
            "option; cannot be applied after backend init)"
        )
    from dlbb_tpu.compat import supports_compiler_option

    unsupported = [
        k for k, v in variant.compiler_options
        if not supports_compiler_option(k, v)
    ]
    if unsupported:
        raise RuntimeError(
            f"variant {variant.name!r} needs per-computation compiler "
            f"option(s) {unsupported}, which this jaxlib's compile path "
            "rejects (protobuf reflection cannot set repeated DebugOptions "
            "fields); the variant cannot run — and cannot be labeled "
            "honestly — on this jaxlib; upgrade jaxlib to one whose PJRT "
            "compile path accepts these options"
        )


_NULL_GATE = contextlib.nullcontext()


def _build_fn(op_name: str, variant: Variant, mesh, axes, root: int):
    if op_name == "allreduce" and variant.hierarchical:
        return build_allreduce_hierarchical(mesh, axes, root)
    if op_name in MATMUL_OPS and variant.overlap_schedule is not None:
        # decomposed collective-matmul schedule (docs/overlap.md) — same
        # dispatch convention as `hierarchical` above
        return get_op(op_name).build(
            mesh, axes, root, schedule=variant.overlap_schedule
        )
    if op_name in COMPRESSED_OPS and (
            variant.compression is not None
            or variant.accum_dtype is not None):
        # quantised-wire knobs (docs/compression.md) — dispatch like the
        # overlap schedule above; unset fields keep the op defaults
        kwargs: dict[str, Any] = {}
        if variant.compression is not None:
            kwargs["compression"] = variant.compression
        if variant.accum_dtype is not None:
            kwargs["accum_dtype"] = _dtype_of(variant.accum_dtype)
        return get_op(op_name).build(mesh, axes, root, **kwargs)
    return get_op(op_name).build(mesh, axes, root)


@dataclass
class _Planned:
    """One measurable sweep config, resolved at plan time."""

    num_ranks: int
    mesh: Any
    axes: tuple[str, ...]
    config: dict[str, Any]
    unit: schedule.WorkUnit
    payload_key: tuple
    # derived once here; _run_one must build the payload the unit's
    # executable was AOT-compiled against, never re-derive it
    num_elements: int
    payload_shape: Optional[tuple[int, ...]]


def _payload_geometry(
    sweep, config,
) -> tuple[int, Optional[tuple[int, ...]]]:
    """(num_elements, per-rank payload shape) of one config."""
    if sweep.kind == "1d":
        return config["num_elements"], None
    shape = (config["batch"], config["seq_len"], config["hidden_dim"])
    return int(np.prod(shape)), shape


def _plan_config(
    sweep, variant, mesh, axes, num_ranks, config,
    units, mode,
) -> _Planned:
    """Resolve one config's payload identity and compile work unit."""
    op = get_op(config["operation"])
    dtype = _dtype_of(sweep.dtype)
    num_elements, payload_shape = _payload_geometry(sweep, config)
    unit = schedule.plan_collective_unit(
        units,
        op=op,
        build_fn=lambda: _build_fn(
            config["operation"], variant, mesh, axes, sweep.root
        ),
        variant_name=variant.name,
        mesh=mesh,
        axes=axes,
        root=sweep.root,
        num_ranks=num_ranks,
        num_elements=num_elements,
        dtype=dtype,
        payload_shape=payload_shape,
        mode=mode,
        iterations=sweep.measurement_iterations,
        compiler_options=(
            dict(variant.compiler_options) if variant.compiler_options
            else None
        ),
    )
    pkey = payload_cache_key(
        op, mesh, axes, num_elements, dtype=dtype, shape=payload_shape
    )
    return _Planned(num_ranks, mesh, axes, config, unit, pkey,
                    num_elements, payload_shape)


def run_sweep(
    sweep: Sweep1D | Sweep3D,
    devices: Optional[Sequence] = None,
    verbose: bool = True,
) -> list[Path]:
    """Run a full sweep, writing one reference-schema JSON per config.

    The grid is walked twice: a *planning* pass resolves skips
    (rank gates, memory caps, ``resume``) and interns each measurable
    config's compile work unit — deduplicated by
    :func:`dlbb_tpu.bench.schedule.work_unit_key` — then the *measurement*
    pass consumes configs in plan order while a background thread compiles
    up to ``sweep.prefetch`` units ahead (``sweep.pipeline=False`` compiles
    inline through the same path).  Payloads and meshes are reused across
    configs that share them; a ``sweep_manifest.json`` with wall/compile
    totals lands next to the artifacts.

    Per-config failures — compile failures included — are contained:
    transient ones retry with exponential backoff (recomputing from
    scratch; the artifact records ``retries``), permanent ones are
    QUARANTINED — journaled ``failed`` with the exception chain in
    ``sweep_manifest.json`` — never silently skipped (hardened version of
    reference ``collectives/1d/openmpi.py:253-267``).  A per-unit
    wall-clock deadline (``unit_deadline_seconds``) watchdogs both the
    background compile and the measurement; SIGTERM lands as a graceful
    journaled stop a ``--resume`` run completes exactly
    (docs/resilience.md).
    """
    variant = get_variant(sweep.variant)
    _check_variant_flags(variant)
    impl = _impl_name(sweep)
    out_dir = Path(sweep.output_dir)
    written: list[Path] = []
    sysinfo = collect_system_info()
    n_avail = len(devices) if devices is not None else len(jax.devices())
    t_sweep0 = time.perf_counter()
    mode = resolve_timing_mode(sweep.timing_mode)

    # chaos-harness activation: an explicit sweep.fault_plan wins; else an
    # already-active plan (embedding harness) is left alone; else the env
    fault_spec = sweep.fault_plan
    if fault_spec is None and inject.active() is None:
        fault_spec = os.environ.get(inject.ENV_VAR, "").strip() or None

    # span tracing (docs/observability.md): scoped to the sweep when a
    # path is configured; a tracer an embedding harness (the CLI
    # --span-trace wrapper, a test) already opened WINS and collects this
    # sweep's spans — the tracing() scope is then a pure pass-through
    span_path = sweep.span_trace or spans.default_span_path()
    # everything from here — planning included — runs with the persistent
    # compilation cache scoped to this sweep; the finally guarantees no
    # later non-sweep compile ever sees it (see
    # schedule.deactivate_compilation_cache)
    cache_dir = schedule.configure_compilation_cache(sweep.compile_cache)
    try:
        with spans.tracing(span_path,
                           meta={"kind": sweep.kind,
                                 "implementation": impl,
                                 "variant": variant.name}), \
                inject.plan_scope(fault_spec), PreemptionGuard() as guard:
            return _run_sweep_configured(
                sweep, variant, impl, out_dir, written, sysinfo, n_avail,
                devices, mode, cache_dir, t_sweep0, verbose, guard,
            )
    finally:
        schedule.deactivate_compilation_cache()


def _collective_stop(requested: bool) -> bool:
    """Pod-uniform preemption decision: ANY host's SIGTERM stops every
    host at the same config boundary.  Called by every process for every
    config in the same order (like ``_resume_ok``), so the allgather
    schedule stays uniform — a per-host stop would send the surviving
    hosts into the next config's SPMD collective alone and hang the pod."""
    if jax.process_count() == 1:
        return requested
    from jax.experimental import multihost_utils

    bits = multihost_utils.process_allgather(
        np.asarray([requested], dtype=np.int32)
    )
    return bool(np.asarray(bits).any())


def _resolve_deadline(sweep) -> Optional[float]:
    """Per-work-unit wall-clock deadline: sweep field, else
    ``DLBB_UNIT_DEADLINE`` env, else off."""
    if sweep.unit_deadline_seconds is not None:
        return float(sweep.unit_deadline_seconds)
    env = os.environ.get("DLBB_UNIT_DEADLINE", "").strip()
    return float(env) if env else None


def _call_with_deadline(fn, deadline: Optional[float], label: str,
                        gate) -> Any:
    """Run ``fn`` under the measurement watchdog.

    With no deadline this is a direct call (zero threads, zero overhead).
    With one, ``fn(cancel)`` runs on a daemon thread joined for
    ``deadline`` seconds; an overrun ABANDONS the thread (it cannot be
    killed — it may be wedged inside a C extension), sets the ``cancel``
    event so the zombie — if it ever wakes — suppresses its artifact
    write (``_run_one`` checks it immediately before ``save_json``: a
    quarantined config must never be resurrected on disk by a thread the
    manifest says failed), degrades the measurement gate so the zombie
    can never block later configs or the compile worker, and raises
    :class:`DeadlineExceeded` for the quarantine path."""
    if deadline is None:
        return fn(None)
    box: dict[str, Any] = {}
    cancel = threading.Event()

    def target() -> None:
        try:
            box["value"] = fn(cancel)
        except BaseException as e:  # noqa: BLE001 — marshalled to caller
            box["error"] = e

    t = threading.Thread(target=target, daemon=True,
                         name=f"dlbb-measure-{label}")
    t.start()
    t.join(deadline)
    if t.is_alive():
        cancel.set()
        if gate is not None and hasattr(gate, "degrade"):
            gate.degrade()
        raise DeadlineExceeded(label, deadline, phase="measure")
    if "error" in box:
        raise box["error"]
    return box["value"]


def _run_sweep_configured(
    sweep, variant, impl, out_dir, written, sysinfo, n_avail, devices,
    mode, cache_dir, t_sweep0, verbose, guard: Optional[PreemptionGuard],
) -> list[Path]:
    journal = SweepJournal(
        out_dir,
        meta={"kind": sweep.kind, "implementation": impl,
              "variant": variant.name, "resume": sweep.resume,
              "fault_plan": getattr(inject.active(), "spec", None)},
        # multi-host: every process walks the same grid in the same order
        # (collective resume decisions), so one journal — the
        # coordinator's — records the run; per-host journals on a shared
        # filesystem would interleave duplicate lines
        enabled=sweep.journal and jax.process_index() == 0,
        # every journal event doubles as a span-trace instant (no-op
        # with no tracer active), so the trace and the fsync'd journal
        # tell the same story — docs/observability.md
        sink=spans.journal_sink,
    )
    # topology fingerprint (ROADMAP item 5 standing chore): which fabric
    # this sweep actually measured, journaled + manifested — a degraded
    # CPU fallback is a durable record, never just a log line
    from dlbb_tpu.utils.simulate import topology_record

    topology = topology_record()
    journal.event("topology", **topology)
    if topology["degraded"] and verbose:
        print(f"[topology] DEGRADED backend: {topology.get('degraded_reason')}")
    # ---- planning pass -------------------------------------------------
    plan: list[_Planned] = []
    units: "dict[tuple, schedule.WorkUnit]" = {}
    # per-sweep metrics registry (dlbb_tpu.obs.export): the config-outcome
    # counters below are registry-backed, so the manifest's `configs`
    # section and the metrics.prom textfile export come from one source
    metrics = MetricsRegistry()
    # a degraded-probe fallback is a FIRST-CLASS event (ROADMAP standing
    # chore): its own journal record + Prometheus counter, so `obs
    # trace` timelines and scrapes both see it — not just a field
    # buried in the topology record
    metrics.inc("sweep_degraded", 1 if topology["degraded"] else 0,
                help="sweeps measured on a degraded (fallback) backend")
    if topology["degraded"]:
        journal.event("degraded",
                      reason=topology.get("degraded_reason"))
    # every counter counts CONFIGS (a skipped rank count skips one whole
    # grid of them), so planned+skipped+resumed+failed adds up
    # (resume_invalid configs re-run, so they also land in
    # measured/failed — the counter is informational)
    grid_size = sum(1 for _ in _iter_configs(sweep))
    counts = metrics.labeled_counter(
        "sweep_configs", "outcome",
        initial=("resumed", "resume_invalid", "skipped_mem",
                 "skipped_ranks", "measured", "failed"),
        help="sweep configs by lifecycle outcome",
    )
    quarantined: list[dict[str, Any]] = []
    retries_total = 0
    abandoned_measurements = 0
    preempted = False
    with spans.span("plan", cat="sweep", grid_configs=grid_size,
                    rank_counts=str(tuple(sweep.rank_counts))):
        for num_ranks in sweep.rank_counts:
            if num_ranks > n_avail:
                counts["skipped_ranks"] += grid_size
                journal.event("rank-skip", num_ranks=num_ranks,
                              reason=f"{num_ranks} ranks > {n_avail} devices")
                if verbose:
                    print(
                        f"[skip] {num_ranks} ranks > {n_avail} devices "
                        "available"
                    )
                continue
            try:
                spec = variant.mesh_spec(num_ranks)
                mesh = get_mesh(spec, devices=devices)
            except ValueError as e:
                # e.g. fixed-shape variant (2x2x2) asked for an incompatible
                # rank count — skip this rank count, keep sweeping (parity
                # with the reference's per-config error-skip,
                # collectives/1d/openmpi.py:253)
                counts["skipped_ranks"] += grid_size
                journal.event("rank-skip", num_ranks=num_ranks,
                              reason=str(e))
                if verbose:
                    print(f"[skip] ranks={num_ranks}: {e}")
                continue
            axes = spec.axis_names
            for config in _iter_configs(sweep):
                fname = _result_filename(sweep, impl, num_ranks, config)
                # per-config containment covers the WHOLE planning of a
                # config (mem estimate included — it resolves the op name
                # too): e.g. an unknown op skips that config and keeps
                # sweeping, exactly like a measurement-time failure
                try:
                    if sweep.max_global_bytes is not None:
                        est = _estimate_global_bytes(sweep, config,
                                                     num_ranks)
                        if est > sweep.max_global_bytes:
                            counts["skipped_mem"] += 1
                            journal.event("skipped", config=fname,
                                          reason="memory-cap",
                                          estimated_bytes=est)
                            if verbose:
                                print(
                                    f"[skip-mem] {config['operation']} "
                                    f"ranks={num_ranks} {config}: "
                                    f"~{est / 2**30:.1f} GiB > cap "
                                    f"{sweep.max_global_bytes / 2**30:.1f}"
                                    " GiB"
                                )
                            continue
                    if sweep.resume:
                        existing = out_dir / fname
                        ok, why = _resume_ok(existing)
                        if ok:
                            counts["resumed"] += 1
                            journal.event("resume-valid", config=fname)
                            if verbose:
                                print(f"  [resume-skip] {existing.name}")
                            written.append(existing)
                            continue
                        if why != "missing":
                            # died-mid-write / corrupt artifact: NEVER
                            # trust it — re-measure (atomic overwrite)
                            # with a durable record of why
                            counts["resume_invalid"] += 1
                            journal.event("resume-invalid", config=fname,
                                          reason=why)
                            if verbose:
                                print(f"  [resume-INVALID] "
                                      f"{existing.name}: {why} — "
                                      "re-measuring")
                    plan.append(_plan_config(
                        sweep, variant, mesh, axes, num_ranks, config,
                        units, mode,
                    ))
                    journal.event("planned", config=fname)
                except Exception as e:  # noqa: BLE001 — containment
                    counts["failed"] += 1
                    quarantined.append({"config": fname,
                                        "phase": "planning",
                                        "retries": 0,
                                        **exception_chain(e)})
                    journal.event("failed", config=fname, phase="planning",
                                  error=str(e))
                    if verbose:
                        print(f"[error] {impl} {config}: planning "
                              f"failed: {e}")
                    continue

    # ---- measurement pass, compile-ahead overlapped --------------------
    # the gate keeps background compiles out of timed regions (see
    # CompileAheadScheduler); DLBB_COMPILE_OVERLAP=1 lifts it on hosts
    # with cores to spare
    measure_gate = (
        None if os.environ.get("DLBB_COMPILE_OVERLAP") == "1"
        else schedule.MeasureGate()
    )
    pipeline = (sweep.pipeline if sweep.pipeline is not None
                else schedule.default_pipeline())
    scheduler = schedule.CompileAheadScheduler(
        units.values(), prefetch=sweep.prefetch, pipeline=pipeline,
        measure_gate=measure_gate,
    )
    payloads = schedule.PayloadCache()
    # gated device-trace capture (docs/observability.md): when a capture
    # directory is configured, every measured config runs ONE dedicated
    # profile rep after its timed region, outside the measurement gate —
    # the rep never joins the stats series
    capture_dir = (sweep.device_trace_dir
                   or obs_capture.default_capture_dir())
    deadline = _resolve_deadline(sweep)
    if deadline is not None and jax.process_count() > 1:
        # a per-host abandon cannot be coordinated through a hung SPMD
        # collective (the other hosts are stuck inside it), and letting
        # one host quarantine + move on desynchronizes the pod's
        # collective schedule — the exact hang _resume_ok's allgather
        # exists to prevent.  The watchdog is single-process semantics;
        # disable it loudly on pods.
        journal.event("watchdog-disabled",
                      reason="multi-host run: per-host abandonment would "
                             "desynchronize the SPMD schedule")
        if verbose:
            print("[watchdog] unit deadline disabled: multi-host run "
                  "(per-host abandonment would desynchronize the pod)")
        deadline = None
    attempts = max(0, int(sweep.max_retries)) + 1
    scheduler.start()
    try:
        for entry in plan:
            fname = _result_filename(sweep, impl, entry.num_ranks,
                                     entry.config)
            if inject.fire("preempt"):
                # chaos harness: deliver a real SIGTERM to ourselves —
                # the PreemptionGuard turns it into the flag below
                os.kill(os.getpid(), signal.SIGTERM)
            if _collective_stop(guard is not None and guard.requested):
                preempted = True
                journal.event("preempted", config=fname,
                              signal=guard.signal_received)
                if verbose:
                    print(f"[preempt] SIGTERM received — stopping before "
                          f"{fname}; journal flushed, resume completes "
                          "the grid")
                break
            try:
                with spans.span("compile-wait", cat="sweep", config=fname):
                    unit = scheduler.get(entry.unit, deadline=deadline)
            except DeadlineExceeded as e:
                counts["failed"] += 1
                quarantined.append({
                    "config": fname, "label": entry.unit.label,
                    "phase": "compile", "retries": 0,
                    **exception_chain(e),
                })
                journal.event("failed", config=fname, phase="compile",
                              error=str(e))
                if verbose:
                    print(f"[watchdog] {impl} {fname}: {e}")
                continue
            if unit.error is not None:
                counts["failed"] += 1
                quarantined.append({
                    "config": fname, "label": unit.label,
                    "phase": "compile", "retries": 0,
                    **exception_chain(unit.error),
                })
                journal.event("failed", config=fname, phase="compile",
                              error=str(unit.error))
                if verbose:
                    print(f"[error] {impl} {entry.config}: compile failed "
                          f"for {unit.label}: {unit.error}")
                continue
            journal.event("started", config=fname)
            last_exc: Optional[BaseException] = None
            attempt = 0
            for attempt in range(attempts):
                try:
                    with spans.span(fname, cat="config",
                                    unit=unit.label, attempt=attempt):
                        path = _call_with_deadline(
                            lambda cancel: _run_one(
                                sweep, variant, impl, entry, out_dir,
                                sysinfo, verbose, mode=mode,
                                payloads=payloads,
                                measure_gate=measure_gate, retries=attempt,
                                unit=unit, cancel=cancel,
                                capture_dir=capture_dir, metrics=metrics,
                            ),
                            deadline, unit.label, measure_gate,
                        )
                    written.append(path)
                    counts["measured"] += 1
                    retries_total += attempt
                    journal.event("completed", config=fname,
                                  retries=attempt)
                    last_exc = None
                    break
                except DeadlineExceeded as e:
                    # a hang is not transient: the zombie thread still
                    # owns the payload cache (and possibly the gate) —
                    # hand later configs a fresh cache and quarantine
                    abandoned_measurements += 1
                    payloads = schedule.PayloadCache()
                    last_exc = e
                    break
                except Exception as e:  # noqa: BLE001 — sweep resilience
                    payloads.invalidate(entry.payload_key)
                    last_exc = e
                    if is_transient(e) and attempt < attempts - 1:
                        delay = (sweep.retry_backoff_seconds
                                 * (2 ** attempt))
                        journal.event("retry", config=fname,
                                      attempt=attempt + 1, error=str(e),
                                      backoff_seconds=delay)
                        if verbose:
                            print(f"[retry] {impl} {fname}: transient "
                                  f"{type(e).__name__}: {e} — backing off "
                                  f"{delay:.3f}s (attempt "
                                  f"{attempt + 1}/{attempts - 1})")
                        time.sleep(delay)
                        continue
                    break
            if last_exc is not None:
                counts["failed"] += 1
                quarantined.append({
                    "config": fname, "label": unit.label,
                    "phase": "measure", "retries": attempt,
                    **exception_chain(last_exc),
                })
                journal.event("failed", config=fname, phase="measure",
                              retries=attempt, error=str(last_exc))
                if verbose:
                    print(f"[error] {impl} {entry.config}: {last_exc}")
                    traceback.print_exception(
                        type(last_exc), last_exc, last_exc.__traceback__
                    )
                continue
    finally:
        scheduler.close()

    if plan or counts["resumed"]:
        unit_list = list(units.values())
        compiled = [u for u in unit_list if u.ready.is_set() and not u.error]
        tracer = spans.active()
        manifest_payload = {
            "kind": sweep.kind,
            "implementation": impl,
            "variant": variant.name,
            "topology": topology,
            # the α–β table version (analysis/costmodel.py) current when
            # this sweep ran: artifacts feed the fitted cost model
            # (ROADMAP item 2), and a fit must know which analytic seed
            # its residuals are priced against
            "cost_model_version": COST_MODEL_VERSION,
            "timing_mode": mode,
            "pipeline": scheduler.pipelined,
            "prefetch": sweep.prefetch,
            "wall_seconds": time.perf_counter() - t_sweep0,
            "compile_seconds_total": sum(
                u.compile_seconds for u in unit_list
            ),
            "compile_cache": {
                "dir": cache_dir,
                "enabled": cache_dir is not None,
                "persistent_hits": sum(
                    1 for u in compiled if u.persistent_cache_hit
                ),
                "persistent_misses": sum(
                    1 for u in compiled if not u.persistent_cache_hit
                ),
            },
            "work_units": {
                "planned_configs": len(plan),
                "unique": len(unit_list),
                "compile_failed": sum(
                    1 for u in unit_list if u.error is not None
                ),
            },
            "configs": dict(counts),
            "payload_cache": payloads.stats(),
            # where this sweep's wall clock went (docs/observability.md):
            # the span-trace path when tracing was on, and how many
            # dedicated profile reps were captured (all outside the
            # stats series by construction)
            "observability": {
                "span_trace": str(tracer.path) if tracer else None,
                "device_trace_dir": capture_dir,
                "device_captures": int(metrics.get("sweep_device_captures")),
            },
            "resilience": {
                "fault_plan": getattr(inject.active(), "spec", None),
                "unit_deadline_seconds": deadline,
                "max_retries": sweep.max_retries,
                "retries_total": retries_total,
                "quarantined": quarantined,
                "preempted": preempted,
                "watchdog": {
                    "abandoned_measurements": abandoned_measurements,
                    "abandoned_compiles": scheduler.abandoned,
                    "scheduler_wedged": scheduler.wedged,
                    "gate_degraded": bool(
                        getattr(measure_gate, "degraded", False)
                    ),
                },
            },
            "timestamp": time.time(),
        }
        schedule.write_sweep_manifest(out_dir, manifest_payload)
        # the Prometheus textfile export next to the manifest: the same
        # registry that backed the config counters, plus the manifest's
        # aggregate gauges (obs/export.sweep_metrics)
        sweep_metrics(manifest_payload, metrics).write_textfile(
            out_dir / "metrics.prom"
        )
        if tracer is not None:
            # checkpoint the trace now (stop() rewrites it at scope exit):
            # a crash after this point still leaves a loadable timeline
            tracer.finish()
    journal.event("sweep-end", preempted=preempted,
                  measured=counts["measured"], failed=counts["failed"])
    journal.close()
    return written


def _estimate_global_bytes(sweep, config, num_ranks: int) -> int:
    """Rough global input+output footprint of one config.

    Both multipliers come from the op registry's declared buffer kinds
    (``per_peer`` scales with P^2 x payload, ``per_rank`` with P) — not
    from a hard-coded op-name list, so a newly registered collective is
    estimated by its declaration instead of silently defaulting to the
    per-rank multiplier.  ``tests/test_bench.py`` pins every registry op's
    estimate."""
    op = get_op(config["operation"])
    n = _payload_geometry(sweep, config)[0]
    itemsize = jnp.dtype(_dtype_of(sweep.dtype)).itemsize
    p = num_ranks

    def mult(kind):
        return p * p if kind == "per_peer" else p

    transient = mult(op.transient_kind) if op.transient_kind else 0
    if (transient and op.name in MATMUL_OPS
            and get_variant(sweep.variant).overlap_schedule is not None):
        # the declared transient models the FUSED schedule (the gathered
        # activation / full partial product); the decomposed ring never
        # materialises it — one travelling chunk rides inside the in+out
        # estimate, so charging the fused footprint would skip exactly
        # the configs whose memory behavior the overlap variant exists
        # to demonstrate
        transient = 0
    return (mult(op.input_kind) + mult(op.output_kind) + transient) \
        * n * itemsize


def _iter_configs(sweep):
    if sweep.kind == "1d":
        for op in sweep.operations:
            for label, n in sweep.data_sizes:
                yield {"operation": op, "size_label": label, "num_elements": n}
    else:
        for op in sweep.operations:
            for b in sweep.batch_sizes:
                for s in sweep.seq_lengths:
                    for h in sweep.hidden_dims:
                        yield {
                            "operation": op,
                            "batch": b,
                            "seq_len": s,
                            "hidden_dim": h,
                        }


def _resume_ok(path: Path) -> tuple[bool, str]:
    """Whether a resume-mode sweep may skip this config, and why not.

    Existence is NOT enough: a process killed mid-write (or a torn legacy
    artifact) must be re-measured, so the existing JSON is validated —
    parses, carries the result schema, all timings finite
    (``dlbb_tpu.resilience.validate``) — before resume trusts it.

    Multi-host runs decide collectively: hosts have non-shared disks, and a
    run killed between one host's ``save_json`` and another's would leave
    them disagreeing — a per-host decision would send some hosts into the
    config's SPMD collective while others skip it, hanging the pod.  Every
    process calls this for every candidate config in the same order, so the
    allgather schedule stays uniform; the config re-runs everywhere unless
    ALL hosts already hold a VALID artifact (re-measuring on the hosts that
    had it just atomically overwrites)."""
    ok, why = validate_result_json(path)
    if jax.process_count() == 1:
        return ok, why
    from jax.experimental import multihost_utils

    bits = multihost_utils.process_allgather(
        np.asarray([ok], dtype=np.int32)
    )
    all_ok = bool(np.asarray(bits).all())
    if ok and not all_ok:
        why = "valid here but invalid/missing on another host"
    return all_ok, why


# filename tags for non-default dtypes: the bf16 corpus keeps the original
# (un-suffixed) names so the committed corpus stays stable; other dtypes of
# the same config coexist in the same directory (north-star curve is
# "fp32+bf16", BASELINE.json configs[1])
_DTYPE_FILE_TAG = {"float32": "fp32", "float16": "fp16"}


def _result_filename(sweep, impl: str, num_ranks: int, config) -> str:
    op_name = config["operation"]
    tag = _DTYPE_FILE_TAG.get(sweep.dtype)
    suffix = f"_{tag}" if tag else ""
    if sweep.kind == "1d":
        return (f"{impl}_{op_name}_ranks{num_ranks}_"
                f"{config['size_label']}{suffix}.json")
    b, s, h = config["batch"], config["seq_len"], config["hidden_dim"]
    return f"{impl}_{op_name}_ranks{num_ranks}_b{b}_s{s}_h{h}{suffix}.json"


def _run_one(
    sweep, variant, impl, planned: _Planned, out_dir, sysinfo, verbose,
    *, mode: str, payloads: schedule.PayloadCache,
    measure_gate=None, retries: int = 0,
    unit: Optional[schedule.WorkUnit] = None,
    cancel: Optional[threading.Event] = None,
    capture_dir: Optional[str] = None,
    metrics: Optional[MetricsRegistry] = None,
) -> Path:
    mesh, axes = planned.mesh, planned.axes
    num_ranks, config = planned.num_ranks, planned.config
    # the unit the SCHEDULER resolved: normally planned.unit itself, but
    # after a wedged compile worker it is a fresh inline-compiled clone
    # (schedule.CompileAheadScheduler.get) — never read planned.unit here
    if unit is None:
        unit = planned.unit
    op_name = config["operation"]
    op = get_op(op_name)
    dtype = _dtype_of(sweep.dtype)
    elem_bytes = jnp.dtype(dtype).itemsize
    # the plan-time geometry: what the unit's executable was compiled for
    num_elements = planned.num_elements
    payload_shape = planned.payload_shape

    def build_payload():
        return make_payload(
            op, mesh, axes, num_elements, dtype=dtype, shape=payload_shape
        )

    # chained timing DONATES its carry, so a cached payload would come back
    # deleted — only per-iter configs share payloads
    with spans.span("payload", cat="payload", label=unit.label):
        x = (build_payload() if mode == "chained"
             else payloads.get(planned.payload_key, build_payload))
    fn = unit.fn
    chain = op.make_chain(num_ranks) if op.make_chain is not None else None

    # chaos-harness sites, strictly BEFORE the timed region (zero
    # instructions inside it; see dlbb_tpu/resilience/inject.py)
    if inject.fire("exec-transient"):
        payloads.invalidate(planned.payload_key)
        raise inject.TransientFault(
            f"injected transient runtime failure for {unit.label}"
        )
    if inject.fire("exec-hang"):
        time.sleep(inject.param("hang_seconds"))

    # holding the gate keeps the compile-ahead worker out of the timed
    # region — background compilation contends for the host cores the
    # measured program runs on (measurement-honesty invariant; see
    # schedule.CompileAheadScheduler).  The span brackets the region from
    # the OUTSIDE (its clock reads happen before the gate is taken and
    # after it is released).
    try:
        with spans.span("measure", cat="measure", label=unit.label,
                        mode=mode), \
                (measure_gate if measure_gate is not None else _NULL_GATE):
            local, timing_meta = time_collective(
                fn, x,
                chain=chain,
                warmup=sweep.warmup_iterations,
                iterations=sweep.measurement_iterations,
                mode=mode,
                max_seconds=sweep.max_config_seconds,
                compiler_options=(
                    dict(variant.compiler_options)
                    if variant.compiler_options else None
                ),
                executable=None if unit.chained else unit.executable,
                chained_loop=unit.executable if unit.chained else None,
            )
    except BaseException:
        # a failure mid-measurement may have already donated the cached
        # payload (the per-iter plausibility fallback) — drop the entry
        # so no later config is handed a deleted array
        payloads.invalidate(planned.payload_key)
        raise
    if timing_meta.get("timing_mode") == "chained" and mode != "chained":
        # the per-iter plausibility fallback donated the (cached) payload
        payloads.invalidate(planned.payload_key)
    if inject.fire("stats-nan"):
        # chaos harness: poison the timing vector AFTER the timed region —
        # the pre-write validation below must refuse to publish it
        local = list(local)
        local[0] = float("nan")
        if len(local) > 1:
            local[-1] = float("inf")
    timings = _gather_timings(local)
    ok, why = validate_timings(timings)
    if not ok:
        # NaN/Inf must never reach an artifact; CorruptStats is transient
        # so the retry loop re-measures from scratch
        payloads.invalidate(planned.payload_key)
        raise CorruptStats(
            f"{unit.label}: {why} — refusing to write the artifact"
        )

    # gated device-trace capture (docs/observability.md): one DEDICATED
    # profile rep on a FRESH payload, after the timed region and outside
    # the measurement gate — its timing never joins `timings`, and a
    # capture failure never fails the config (error lands in the
    # metadata instead)
    capture_meta = None
    if capture_dir:
        fname_cap = _result_filename(sweep, impl, num_ranks, config)
        with spans.span("device-capture", cat="capture", label=unit.label):
            capture_meta = obs_capture.capture_device_trace(
                fn, build_payload, capture_dir,
                label=fname_cap.rsplit(".", 1)[0],
            )
        # only SUCCESSFUL captures count — a contained failure (profiler
        # held elsewhere) left no trace on disk and must not inflate the
        # manifest's device_captures
        if metrics is not None and "error" not in capture_meta:
            metrics.inc("sweep_device_captures",
                        help="dedicated profile reps captured "
                             "(excluded from stats)")
        elif metrics is not None:
            # a contained failure is invisible in the stats series by
            # design — the labelled counter (folded into metrics.prom)
            # is where a fleet notices its captures silently dying
            metrics.inc("obs_device_capture_failures",
                        reason=capture_meta.get("error_kind", "unknown"),
                        help="contained device-capture failures "
                             "(error recorded in the result JSON)")

    # the first config that WRITES an artifact reports the compile its
    # work unit paid for (see WorkUnit.compile_reported); later sharers
    # paid nothing (in-process dedup) and report a cache hit
    first_consumer = not unit.compile_reported
    compile_seconds = unit.compile_seconds if first_consumer else 0.0
    compile_cache_hit = (unit.persistent_cache_hit if first_consumer
                         else True)

    result: dict[str, Any] = {
        "implementation": impl,
        "mpi_implementation": impl,  # legacy key the 1D stats reader prefers
        "operation": op_name,
        "num_ranks": num_ranks,
        "num_elements": num_elements,
        "dtype": sweep.dtype,
        "warmup_iterations": sweep.warmup_iterations,
        "measurement_iterations": sweep.measurement_iterations,
        # compile accounting (dlbb_tpu.bench.schedule): what THIS config
        # paid — 0.0 with a hit when its program was already compiled
        # (in-process work-unit dedup or the persistent XLA cache)
        "compile_seconds": compile_seconds,
        "compile_cache_hit": compile_cache_hit,
        # transient-failure retries this config burned before succeeding
        # (0 = first attempt measured clean); retried attempts recompute
        # from scratch, so nothing of a failed attempt is in `timings`
        "retries": retries,
        **timing_meta,
        "timings": timings,
        "variant": variant.name,
        # wire compression of the quantised micro-ops (docs/compression.md)
        # — consumed by the stats pipeline's analytic bytes_on_wire column
        **({"compression": variant.compression or "int8"}
           if op_name in COMPRESSED_OPS else {}),
        **dict(variant.extra),
        "mesh_shape": list(mesh.devices.shape),
        "mesh_axis_names": list(mesh.axis_names),
        "payload_bytes_per_rank": num_elements * elem_bytes,
        "timestamp": time.time(),
        "system_info": sysinfo,
        # device-capture metadata (trace path + the excluded_from_stats
        # marker); absent on untraced runs — every stats field above is
        # identical either way (the obs_smoke equivalence gate)
        **({"device_trace": capture_meta} if capture_meta else {}),
    }

    if sweep.kind == "1d":
        result["data_size_name"] = config["size_label"]
    else:
        b, s, h = config["batch"], config["seq_len"], config["hidden_dim"]
        tensor_size_bytes = num_elements * 2  # reported as-bf16, like the
        # reference (``collectives/3d/openmpi.py:167-168``)
        result["tensor_shape"] = {"batch": b, "seq_len": s, "hidden_dim": h}
        result["tensor_size_bytes"] = tensor_size_bytes
        result["tensor_size_mb"] = tensor_size_bytes / 2**20

    if cancel is not None and cancel.is_set():
        # the watchdog abandoned this thread and QUARANTINED the config —
        # a late-waking zombie must not resurrect it on disk (resume and
        # the stats pipeline would trust an artifact measured concurrently
        # with later configs, contradicting the manifest's failed record)
        raise DeadlineExceeded(unit.label, 0.0, phase="measure (zombie "
                               "write suppressed after abandonment)")
    fname = _result_filename(sweep, impl, num_ranks, config)
    with spans.span("write", cat="io", file=fname):
        path = save_json(result, out_dir / fname)
    unit.compile_reported = True
    if verbose:
        # the same median the stats pipeline publishes
        # (stats1d.calculate_statistics: np.median over the flattened
        # per-host matrix), labeled with the mode actually used — a mean
        # over chained chunk means is not comparable to a per-iter mean
        median_ms = float(np.median(np.asarray(timings))) * 1e3
        print(f"  [{impl}] {fname}: median {median_ms:.3f} ms "
              f"({timing_meta.get('timing_mode', mode)})")
    return path
