"""Unified collective-benchmark driver.

Replaces the duplicated skeleton of the reference's benchmark scripts
(constants → init → per-(op,size) loop of {warmup, timed measurement, gather,
JSON dump}; e.g. ``collectives/1d/openmpi.py:204-300``,
``collectives/3d/dsccl.py:120-241``) with one driver over declarative sweep
configs.  "Which backend executes the collective" — the reference's
MPI/Gloo/oneCCL axis — becomes a named :class:`~dlbb_tpu.comm.variants.Variant`
(mesh topology / reduction strategy / combiner flags), recorded in the result
JSON's implementation field so stats curves stay comparable.

Timing semantics (SURVEY §7 "hard parts"): each op is a jitted shard_map
micro-program; warmup absorbs XLA compilation; each timed iteration is
``perf_counter``-bracketed ``fn(x).block_until_ready()`` — the async-dispatch
analogue of ``comm.Barrier(); MPI.Wtime(); op; Wtime()``
(``collectives/1d/openmpi.py:60-66``).

Result JSON schema is reference-compatible: the 1D stats reader accepts
``implementation`` (``collectives/1d/stats.py:167``), and field names /
filenames match ``collectives/1d/openmpi.py:273-295`` and
``collectives/3d/openmpi.py:205-233``.
"""

from __future__ import annotations

import time
import traceback
from dataclasses import dataclass
from pathlib import Path
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from dlbb_tpu.comm.mesh import build_mesh
from dlbb_tpu.comm.ops import (
    build_allreduce_hierarchical,
    get_op,
    make_payload,
)
from dlbb_tpu.comm.variants import Variant, get_variant
from dlbb_tpu.utils.config import save_json
from dlbb_tpu.utils.sysinfo import collect_system_info
from dlbb_tpu.utils.timing import time_collective

# Reference 1D sweep constants (``collectives/1d/openmpi.py:14-49``).
# NOTE the reference's size labels are 2x the actual fp16 payload
# ("16MB" = 4,194,304 elements x 2 B = 8 MiB — BASELINE.md); labels are kept
# verbatim for curve comparability, with honest byte counts in the JSON.
DATA_SIZES_1D: dict[str, int] = {
    "1KB": 256,
    "64KB": 16384,
    "1MB": 262144,
    "16MB": 4194304,
}

# Extension to the north-star 1 KB–1 GB curve (BASELINE.json metric).
EXTENDED_DATA_SIZES_1D: dict[str, int] = {
    **DATA_SIZES_1D,
    "64MB": 16777216,
    "256MB": 67108864,
    "1GB": 268435456,
}

OPERATIONS_1D: tuple[str, ...] = (
    "allreduce",
    "allgather",
    "broadcast",
    "gather",
    "scatter",
    "reduce",
    "alltoall",
    "sendrecv",
)

# Reference 3D sweep grid (``collectives/3d/openmpi.py:19-31``).
OPERATIONS_3D: tuple[str, ...] = (
    "allreduce",
    "allgather",
    "broadcast",
    "gather",
    "reduce",
)
GRID_3D: dict[str, Sequence[int]] = {
    "batch_sizes": (1, 8, 16, 32),
    "seq_lengths": (1, 2048, 4096, 8192),
    "hidden_dims": (2048, 4096),
}


@dataclass(frozen=True)
class Sweep1D:
    """1D collective microbenchmark sweep (flat element-count payloads)."""

    implementation: str = "xla_tpu"
    variant: str = "default"
    operations: tuple[str, ...] = OPERATIONS_1D
    data_sizes: tuple[tuple[str, int], ...] = tuple(DATA_SIZES_1D.items())
    rank_counts: tuple[int, ...] = (2, 4, 8)
    dtype: str = "bfloat16"
    warmup_iterations: int = 10
    measurement_iterations: int = 100
    output_dir: str = "results/1d"
    root: int = 0
    # "auto" | "per_iter" | "chained" — see dlbb_tpu.utils.timing
    timing_mode: str = "auto"
    # wall-time cap per config; iteration counts scale down to fit (actual
    # counts recorded in the result JSON) — for slow hosts / huge payloads
    max_config_seconds: Optional[float] = None
    # skip configs whose estimated global input+output footprint exceeds
    # this (host-simulated meshes hold every shard in one RAM pool)
    max_global_bytes: Optional[int] = None
    # skip configs whose result JSON already exists in output_dir — lets an
    # interrupted sweep (time-budgeted publisher runs) pick up where it left
    # off instead of re-measuring the whole grid
    resume: bool = False

    kind: str = "1d"


@dataclass(frozen=True)
class Sweep3D:
    """3D LLM-shaped tensor collective sweep over (batch, seq, hidden)."""

    implementation: str = "xla_tpu"
    variant: str = "default"
    operations: tuple[str, ...] = OPERATIONS_3D
    batch_sizes: tuple[int, ...] = tuple(GRID_3D["batch_sizes"])
    seq_lengths: tuple[int, ...] = tuple(GRID_3D["seq_lengths"])
    hidden_dims: tuple[int, ...] = tuple(GRID_3D["hidden_dims"])
    rank_counts: tuple[int, ...] = (4, 8)
    dtype: str = "bfloat16"
    warmup_iterations: int = 10
    measurement_iterations: int = 100
    output_dir: str = "results/3d"
    root: int = 0
    timing_mode: str = "auto"
    max_config_seconds: Optional[float] = None
    max_global_bytes: Optional[int] = None
    resume: bool = False

    kind: str = "3d"


def _dtype_of(name: str):
    return {
        "bfloat16": jnp.bfloat16,
        "float16": jnp.float16,
        "float32": jnp.float32,
    }[name]


def _impl_name(sweep) -> str:
    if sweep.variant and sweep.variant != "default":
        return f"{sweep.implementation}_{sweep.variant}"
    return sweep.implementation


def _gather_timings(local: list[float]) -> list[list[float]]:
    """Per-host × per-iteration timings, shaped like the reference's
    ``[rank][iteration]`` gather (``collectives/1d/openmpi.py:270``).

    Single-process (incl. the CPU-simulated mesh): one timing stream for the
    whole SPMD program — the schema keeps the 2D shape with one row.
    Multi-host: each host contributes its own dispatch timings via a host-side
    allgather, so load-imbalance across hosts is still computable.
    """
    if jax.process_count() == 1:
        return [local]
    from jax.experimental import multihost_utils

    arr = multihost_utils.process_allgather(np.asarray(local, dtype=np.float64))
    return np.asarray(arr).reshape(jax.process_count(), -1).tolist()


def _check_variant_flags(variant: Variant) -> None:
    """XLA flags (combiner thresholds etc.) are process-start options: they
    must already be in ``XLA_FLAGS`` before backend init.  Refuse to run —
    rather than silently mislabel results — if a flag variant was requested
    without its flags set (they are the launcher's job, see
    ``launch/launch_tpu_pod.sh``)."""
    import os

    missing = [f for f in variant.xla_flags if f not in os.environ.get("XLA_FLAGS", "")]
    if missing:
        raise RuntimeError(
            f"variant {variant.name!r} requires XLA_FLAGS to contain "
            f"{missing}; relaunch the process with them set (process-start "
            "option; cannot be applied after backend init)"
        )
    from dlbb_tpu.compat import supports_compiler_option

    unsupported = [
        k for k, v in variant.compiler_options
        if not supports_compiler_option(k, v)
    ]
    if unsupported:
        raise RuntimeError(
            f"variant {variant.name!r} needs per-computation compiler "
            f"option(s) {unsupported}, which this jaxlib's compile path "
            "rejects (protobuf reflection cannot set repeated DebugOptions "
            "fields); the variant cannot run — and cannot be labeled "
            "honestly — on this jaxlib; upgrade jaxlib to one whose PJRT "
            "compile path accepts these options"
        )


def _build_fn(op_name: str, variant: Variant, mesh, axes, root: int):
    if op_name == "allreduce" and variant.hierarchical:
        return build_allreduce_hierarchical(mesh, axes, root)
    return get_op(op_name).build(mesh, axes, root)


def run_sweep(
    sweep: Sweep1D | Sweep3D,
    devices: Optional[Sequence] = None,
    verbose: bool = True,
) -> list[Path]:
    """Run a full sweep, writing one reference-schema JSON per config.

    Per-config failures are caught, reported, and skipped so one failing
    combination doesn't kill the sweep (reference
    ``collectives/1d/openmpi.py:253-267``).
    """
    variant = get_variant(sweep.variant)
    _check_variant_flags(variant)
    impl = _impl_name(sweep)
    out_dir = Path(sweep.output_dir)
    written: list[Path] = []
    sysinfo = collect_system_info()
    n_avail = len(devices) if devices is not None else len(jax.devices())

    for num_ranks in sweep.rank_counts:
        if num_ranks > n_avail:
            if verbose:
                print(
                    f"[skip] {num_ranks} ranks > {n_avail} devices available"
                )
            continue
        try:
            spec = variant.mesh_spec(num_ranks)
            mesh = build_mesh(spec, devices=devices)
        except ValueError as e:
            # e.g. fixed-shape variant (2x2x2) asked for an incompatible rank
            # count — skip this rank count, keep sweeping (parity with the
            # reference's per-config error-skip, collectives/1d/openmpi.py:253)
            if verbose:
                print(f"[skip] ranks={num_ranks}: {e}")
            continue
        axes = spec.axis_names
        for config in _iter_configs(sweep):
            if sweep.max_global_bytes is not None:
                est = _estimate_global_bytes(sweep, config, num_ranks)
                if est > sweep.max_global_bytes:
                    if verbose:
                        print(
                            f"[skip-mem] {config['operation']} ranks="
                            f"{num_ranks} {config}: ~{est / 2**30:.1f} GiB "
                            f"> cap {sweep.max_global_bytes / 2**30:.1f} GiB"
                        )
                    continue
            if sweep.resume:
                existing = out_dir / _result_filename(
                    sweep, impl, num_ranks, config
                )
                if _resume_exists(existing):
                    if verbose:
                        print(f"  [resume-skip] {existing.name}")
                    written.append(existing)
                    continue
            try:
                path = _run_one(
                    sweep, variant, impl, mesh, axes, num_ranks, config,
                    out_dir, sysinfo, verbose,
                )
                written.append(path)
            except Exception as e:  # noqa: BLE001 — sweep resilience
                if verbose:
                    print(f"[error] {impl} {config}: {e}")
                    traceback.print_exc()
                continue
    return written


def _estimate_global_bytes(sweep, config, num_ranks: int) -> int:
    """Rough global input+output footprint of one config: per_peer inputs
    and (all)gather/alltoall outputs scale with P^2 x payload."""
    op = get_op(config["operation"])
    n = (config["num_elements"] if sweep.kind == "1d"
         else config["batch"] * config["seq_len"] * config["hidden_dim"])
    itemsize = jnp.dtype(_dtype_of(sweep.dtype)).itemsize
    p = num_ranks
    in_mult = p * p if op.input_kind == "per_peer" else p
    out_mult = p * p if op.name in ("allgather", "gather", "alltoall") else p
    return (in_mult + out_mult) * n * itemsize


def _iter_configs(sweep):
    if sweep.kind == "1d":
        for op in sweep.operations:
            for label, n in sweep.data_sizes:
                yield {"operation": op, "size_label": label, "num_elements": n}
    else:
        for op in sweep.operations:
            for b in sweep.batch_sizes:
                for s in sweep.seq_lengths:
                    for h in sweep.hidden_dims:
                        yield {
                            "operation": op,
                            "batch": b,
                            "seq_len": s,
                            "hidden_dim": h,
                        }


def _resume_exists(path: Path) -> bool:
    """Whether a resume-mode sweep may skip this config.

    Multi-host runs decide collectively: hosts have non-shared disks, and a
    run killed between one host's ``save_json`` and another's would leave
    them disagreeing — a per-host decision would send some hosts into the
    config's SPMD collective while others skip it, hanging the pod.  Every
    process calls this for every candidate config in the same order, so the
    allgather schedule stays uniform; the config re-runs everywhere unless
    ALL hosts already hold the artifact (re-measuring on the hosts that had
    it just atomically overwrites)."""
    exists = path.exists()
    if jax.process_count() == 1:
        return exists
    from jax.experimental import multihost_utils

    bits = multihost_utils.process_allgather(
        np.asarray([exists], dtype=np.int32)
    )
    return bool(np.asarray(bits).all())


# filename tags for non-default dtypes: the bf16 corpus keeps the original
# (un-suffixed) names so the committed corpus stays stable; other dtypes of
# the same config coexist in the same directory (north-star curve is
# "fp32+bf16", BASELINE.json configs[1])
_DTYPE_FILE_TAG = {"float32": "fp32", "float16": "fp16"}


def _result_filename(sweep, impl: str, num_ranks: int, config) -> str:
    op_name = config["operation"]
    tag = _DTYPE_FILE_TAG.get(sweep.dtype)
    suffix = f"_{tag}" if tag else ""
    if sweep.kind == "1d":
        return (f"{impl}_{op_name}_ranks{num_ranks}_"
                f"{config['size_label']}{suffix}.json")
    b, s, h = config["batch"], config["seq_len"], config["hidden_dim"]
    return f"{impl}_{op_name}_ranks{num_ranks}_b{b}_s{s}_h{h}{suffix}.json"


def _run_one(
    sweep, variant, impl, mesh, axes, num_ranks, config, out_dir, sysinfo,
    verbose,
) -> Path:
    op_name = config["operation"]
    op = get_op(op_name)
    dtype = _dtype_of(sweep.dtype)
    elem_bytes = jnp.dtype(dtype).itemsize

    if sweep.kind == "1d":
        num_elements = config["num_elements"]
        payload_shape = None
    else:
        payload_shape = (config["batch"], config["seq_len"], config["hidden_dim"])
        num_elements = int(np.prod(payload_shape))

    x = make_payload(
        op, mesh, axes, num_elements, dtype=dtype, shape=payload_shape
    )
    fn = _build_fn(op_name, variant, mesh, axes, sweep.root)
    chain = op.make_chain(num_ranks) if op.make_chain is not None else None

    local, timing_meta = time_collective(
        fn, x,
        chain=chain,
        warmup=sweep.warmup_iterations,
        iterations=sweep.measurement_iterations,
        mode=sweep.timing_mode,
        max_seconds=sweep.max_config_seconds,
        compiler_options=(
            dict(variant.compiler_options) if variant.compiler_options
            else None
        ),
    )
    timings = _gather_timings(local)

    result: dict[str, Any] = {
        "implementation": impl,
        "mpi_implementation": impl,  # legacy key the 1D stats reader prefers
        "operation": op_name,
        "num_ranks": num_ranks,
        "num_elements": num_elements,
        "dtype": sweep.dtype,
        "warmup_iterations": sweep.warmup_iterations,
        "measurement_iterations": sweep.measurement_iterations,
        **timing_meta,
        "timings": timings,
        "variant": variant.name,
        **dict(variant.extra),
        "mesh_shape": list(mesh.devices.shape),
        "mesh_axis_names": list(mesh.axis_names),
        "payload_bytes_per_rank": num_elements * elem_bytes,
        "timestamp": time.time(),
        "system_info": sysinfo,
    }

    if sweep.kind == "1d":
        result["data_size_name"] = config["size_label"]
    else:
        b, s, h = config["batch"], config["seq_len"], config["hidden_dim"]
        tensor_size_bytes = num_elements * 2  # reported as-bf16, like the
        # reference (``collectives/3d/openmpi.py:167-168``)
        result["tensor_shape"] = {"batch": b, "seq_len": s, "hidden_dim": h}
        result["tensor_size_bytes"] = tensor_size_bytes
        result["tensor_size_mb"] = tensor_size_bytes / 2**20

    fname = _result_filename(sweep, impl, num_ranks, config)
    path = save_json(result, out_dir / fname)
    if verbose:
        mean_ms = float(np.mean(timings)) * 1e3
        print(f"  [{impl}] {fname}: mean {mean_ms:.3f} ms")
    return path
