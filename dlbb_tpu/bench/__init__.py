"""Benchmark harness (L4/L5 replacement).

One declarative driver replaces the reference's eight near-identical
per-backend scripts (``collectives/{1d,3d}/{openmpi,intelmpi,dsgloo,dsccl}.py``
— SURVEY §1: "factor this duplicated skeleton into one harness with pluggable
collectives").
"""

from dlbb_tpu.bench.runner import (
    DATA_SIZES_1D,
    EXTENDED_DATA_SIZES_1D,
    GRID_3D,
    OPERATIONS_1D,
    OPERATIONS_3D,
    Sweep1D,
    Sweep3D,
    run_sweep,
)

__all__ = [
    "Sweep1D",
    "Sweep3D",
    "run_sweep",
    "DATA_SIZES_1D",
    "EXTENDED_DATA_SIZES_1D",
    "GRID_3D",
    "OPERATIONS_1D",
    "OPERATIONS_3D",
]
