"""Reference-stack CPU baseline for the E2E forward benchmark.

The reference framework is torch-on-CPU (bf16 Megatron-style decoder,
``models.py``) and publishes **no** E2E result JSON (BASELINE.md: "the E2E
baseline must be (re)established").  This module re-establishes it on the
current host: a torch implementation with the reference's exact forward
semantics (LN → QKV → query-third "attention" → out-proj → residual;
LN → FFN up → gelu → down → residual; final LN), world_size=1 so the
hand-written Allreduce disappears, measured single-process.

Written from scratch against the documented semantics — no reference code is
imported or copied.
"""

from __future__ import annotations

import time
from typing import Any


def measure_torch_cpu_forward(
    hidden_size: int,
    num_layers: int,
    ffn_intermediate: int,
    batch_size: int,
    seq_length: int,
    warmup: int = 2,
    iterations: int = 10,
    threads: int | None = None,
) -> dict[str, Any]:
    import torch

    if threads:
        torch.set_num_threads(threads)

    dtype = torch.bfloat16
    h, f = hidden_size, ffn_intermediate
    torch.manual_seed(42)

    class Block(torch.nn.Module):
        def __init__(self):
            super().__init__()
            self.ln1 = torch.nn.LayerNorm(h, dtype=dtype)
            self.qkv = torch.nn.Linear(h, 3 * h, dtype=dtype)
            self.out = torch.nn.Linear(h, h, dtype=dtype)
            self.ln2 = torch.nn.LayerNorm(h, dtype=dtype)
            self.up = torch.nn.Linear(h, f, dtype=dtype)
            self.down = torch.nn.Linear(f, h, dtype=dtype)

        def forward(self, x):
            r = x
            y = self.ln1(x)
            qkv = self.qkv(y)
            attn = qkv[:, :, :h]  # reference's simplified attention
            x = self.out(attn) + r
            r = x
            y = self.ln2(x)
            x = self.down(torch.nn.functional.gelu(self.up(y))) + r
            return x

    class Model(torch.nn.Module):
        def __init__(self):
            super().__init__()
            self.blocks = torch.nn.ModuleList(Block() for _ in range(num_layers))
            self.ln_f = torch.nn.LayerNorm(h, dtype=dtype)

        def forward(self, x):
            for b in self.blocks:
                x = b(x)
            return self.ln_f(x)

    model = Model().eval()
    x = torch.randn(batch_size, seq_length, h, dtype=dtype)

    with torch.no_grad():
        for _ in range(warmup):
            model(x)
        times = []
        for _ in range(iterations):
            t0 = time.perf_counter()
            model(x)
            times.append(time.perf_counter() - t0)

    mean = sum(times) / len(times)
    return {
        "forward_mean_s": mean,
        "forward_min_s": min(times),
        "forward_max_s": max(times),
        "tokens_per_second": batch_size * seq_length / mean,
        "iterations": iterations,
        "warmup_iterations": warmup,
        "torch_version": torch.__version__,
        "threads": torch.get_num_threads(),
        "config": {
            "hidden_size": h,
            "num_layers": num_layers,
            "ffn_intermediate": f,
            "batch_size": batch_size,
            "seq_length": seq_length,
            "dtype": "bfloat16",
        },
    }
