"""Continuous-batching inference engine over the paged KV-cache.

Two jitted device programs, fixed shapes for the whole run:

- **prefill** (one compile per sequence-length *bucket*): runs the full
  transformer stack over one request's ``[1, bucket, H]`` prompt with
  ordinary causal attention, writes its K/V into the request's cache
  slot (block-aligned masked select — see ``serve/kvcache.py``), sets
  the slot length, and returns the last real token's output — the
  request's FIRST generated token (TTFT stops here).
- **decode_step** (one compile, ``[max_batch, 1, H]``): appends each
  active slot's pending token to the cache at its own length, attends
  over the slot's valid prefix (length-masked, GQA-grouped at
  ``kv_heads`` width), and produces every active slot's next token.
  The output hidden state IS the next step's input embedding (the model
  is its own next-token function — same convention as the chained
  timing loop), so the decode carry ``(cache, x)`` feeds back without
  any host round-trip, and both leaves are donated.

Around them, a host-side continuous-batching scheduler (Orca-style
iteration-level scheduling): arrivals from a ``TrafficTrace`` pass
admission control (bounded queue — overflow is a *rejected* request),
waiting requests are granted slots + worst-case block reservations at
step boundaries, completed requests free both immediately, and the next
decode step runs with whatever mix of old and new requests is resident.
Per-phase obs spans (``serve-admission`` / ``serve-prefill`` /
``serve-decode``), request-lifecycle events into the resilience journal,
and live MetricsRegistry counters/gauges come for free from the
machinery the sweep engine already has.

Communication contract (audited — ``analysis/hlo_audit.py`` decode and
prefill targets, ``plan_expected_kinds(decode=True)``): a decode step
may contain only the tiny per-token TP collectives (row-parallel psums
of ``[max_batch, 1, H]`` + QKV realignment permutes); the cache never
crosses the wire.  A byte ceiling of activation size proves no step
accidentally re-gathers the KV-cache.

Decode fast path (``docs/serving.md``, all off by default so the
engine's legacy per-step behaviour is bit-for-bit preserved):

- **fused multi-step decode** (``decode_horizon > 1``): when the ledger
  knows no scheduling event is imminent, the next K decode steps run as
  ONE jitted ``lax.scan`` over the donated ``(cache, x)`` carry — one
  host dispatch instead of K.  K is chosen per step as
  ``min(horizon_cap, steps_until_next_event)`` (next event = the
  earliest completion while anything is waiting for a slot, else the
  batch's full drain), rounded down to a power-of-two bucket so the
  scan retraces at most ``log2(horizon)`` times.  Slots that complete
  mid-scan are masked inactive INSIDE the scan by a per-slot
  ``remaining`` step budget, so logits stay equivalent to the per-step
  engine; their block frees happen at scan exit.
- **host-overlap dispatch** (``inflight_window > 1``): decode units are
  dispatched without ``block_until_ready`` into a bounded in-flight
  window (dispatch N+1 while N computes); syncs happen only at scan
  boundaries — window full, an admission about to prefill, idle, or
  run end.  TTFT stays honest: the first token is synced exactly as in
  the per-step engine (prefill blocks on ``y_last``).
- **chunked prefill** (``prefill_chunk``): long prompts split into
  fixed-size chunks (block-multiples, one jit per static chunk offset
  reusing ``_serve_block``) interleaved with decode steps, so a long
  admission no longer head-of-line-blocks the resident decode batch.
  Each chunk writes its K/V blocks exactly as monolithic prefill does
  and carries the running prefix K/V explicitly ([L, start, kvh, d],
  no slot dim) so the cache is never re-read across the slot shard.
- **slot compaction** (``compact_threshold``, dp=1 meshes only): when
  occupancy drops to or below the threshold, active slots are
  gather-repacked into a half-size decode batch bucket for the fused
  scan and scattered back at scan exit — priced as a measured variant
  (``scripts/bench_serving.py``), never assumed to win.

Resilience (``docs/resilience.md``, serving faults): every fault site
fires strictly on the HOST side of a dispatch boundary — the jitted
programs above are byte-identical with or without an active plan
(statically pinned).  A transiently-failed prefill/decode dispatch
rolls the host ledger/slot bookkeeping back to a pre-dispatch snapshot
and re-issues with exponential backoff; exhausted retries fail only
the affected requests, journaled ``request-failed`` with full
exception chains — never the run.  ``dispatch_deadline_factor`` arms
an EMA-scaled watchdog (the PR-5 daemon-thread pattern) that abandons
a hung dispatch or window sync and continues on a fresh carry.
Requests may carry per-arrival SLO deadlines (blown queue heads shed
as ``request-rejected[reason=deadline]``, late completions counted).
SIGTERM under the run's ``PreemptionGuard`` drains gracefully:
admission stops, the in-flight window settles, resident requests are
journaled ``request-preempted``, and the report carries the
remaining-rid cursor ``serve/bench.py`` checkpoints for
``cli serve --resume``.
"""

from __future__ import annotations

import math
import os
import signal
import threading
import time
from collections import deque
from dataclasses import dataclass, field, replace as dc_replace
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dlbb_tpu.data.synthetic import (
    prompt_token_ids,
    request_embeddings,
    token_embedding_table,
)
from dlbb_tpu.models.configs import ModelConfig, validate_serving
from dlbb_tpu.models.attention import dense_attention
from dlbb_tpu.models.transformer import (
    _dtype_of,
    _layernorm,
    init_params_sharded,
)
from dlbb_tpu.obs import spans
from dlbb_tpu.obs.export import MetricsRegistry
from dlbb_tpu.resilience import inject
from dlbb_tpu.resilience.errors import (
    CorruptStats,
    DeadlineExceeded,
    InjectedFault,
    TransientFault,
    exception_chain,
    is_transient,
)
from dlbb_tpu.resilience.preempt import PreemptionGuard
from dlbb_tpu.serve.kvcache import (
    BlockLedger,
    KVCache,
    QuantKVCache,
    cache_shardings,
    create_kv_cache,
    create_quant_kv_cache,
    dequantize_kv_blocks,
    quant_cache_shardings,
    quantize_kv_blocks,
)
from dlbb_tpu.serve.traffic import Request, TrafficTrace
from dlbb_tpu.utils.metrics import Timer, summarize

SERVING_REPORT_SCHEMA = "dlbb_serving_report_v1"

# decode feedback / drafting modes (ServingConfig.speculation):
# "off" = legacy continuous hidden-state feedback; "greedy" = token
# feedback without drafting (the speculative modes' pinned oracle);
# "ngram" / "draft-model" = draft-and-verify speculative decoding
SPECULATION_MODES = ("off", "greedy", "ngram", "draft-model")


# ---------------------------------------------------------------------------
# configuration
# ---------------------------------------------------------------------------


def _default_buckets(block_size: int, max_seq: int) -> tuple[int, ...]:
    """Doubling bucket ladder: block_size, 2x, 4x, ... up to max_seq."""
    buckets = []
    b = block_size
    while b < max_seq:
        buckets.append(b)
        b *= 2
    buckets.append(max_seq)
    return tuple(buckets)


@dataclass(frozen=True)
class ServingConfig:
    """The serving envelope (YAML ``serving:`` section).

    max_batch:       decode slots (the fixed decode batch dim).
    block_size:      tokens per cache block.
    max_seq:         per-slot capacity (prompt + output ceiling); must be
                     a block multiple — ``num_blocks = max_seq/block_size``.
    prefill_buckets: sequence-length buckets prefill compiles at
                     (block-multiples; default: doubling ladder up to
                     max_seq).  A prompt pads to the smallest bucket >= it.
    queue_capacity:  admission-control bound; an arrival finding the
                     queue full is REJECTED (counted, journaled).
    blocks_budget:   global cache-block budget the ledger enforces
                     (default: the physical pool, max_batch x num_blocks;
                     set lower to model cache pressure).
    hbm_budget_gb:   per-device HBM budget the build-time footprint gate
                     (``models.configs.validate_serving``) checks the
                     KV-cache against; None disables the gate.
    decode_horizon:  fused-scan horizon cap K (1 = the legacy per-step
                     engine; >1 fuses up to K decode steps into one
                     jitted lax.scan dispatch, bucketed by powers of 2).
    inflight_window: bounded in-flight decode dispatch window (1 = sync
                     every unit, the legacy behaviour; >1 dispatches the
                     next unit while the previous computes and syncs
                     only at scan boundaries).
    prefill_chunk:   tokens per prefill chunk (a block multiple; None =
                     monolithic bucketed prefill).  Long prompts are
                     processed chunk-by-chunk, interleaved with decode
                     steps for the resident batch.
    compact_threshold: occupancy fraction (0, 0.5] at or below which the
                     fused decode scan runs on a gather-compacted
                     half-size batch bucket (dp=1 meshes only; None
                     disables).  A measured variant, not a default win.
    reject_infeasible: reject-and-journal requests the envelope cannot
                     serve (reason="infeasible") instead of failing the
                     whole trace up front (the strict default).
    max_dispatch_retries: bounded retries (exponential backoff) for a
                     transiently-failed prefill/decode dispatch; each
                     retry rolls the host ledger/slot state back to the
                     pre-dispatch snapshot first.  Exhaustion fails only
                     the affected requests (journaled ``request-failed``
                     with the exception chain), never the run.
    retry_backoff_s: base backoff delay; attempt N sleeps
                     ``retry_backoff_s * 2**(N-1)``.
    dispatch_deadline_factor: arms the in-flight dispatch watchdog: a
                     decode unit (or its sync) exceeding
                     ``max(dispatch_deadline_min_s, factor * k *
                     per-step-EMA)`` wall seconds is abandoned on its
                     daemon thread (the PR-5 pattern), its slots'
                     requests journaled ``request-failed[reason=
                     hung-dispatch]`` and freed, and the engine
                     continues on a fresh carry.  None (default)
                     disables — zero threads, zero overhead.
    dispatch_deadline_min_s: watchdog floor while the per-step EMA is
                     still cold (and for tiny EMAs).
    speculation:     decode feedback / drafting mode ("off" = the legacy
                     continuous hidden-state feedback, bit-for-bit
                     preserved).  The token modes quantise decode
                     through the deterministic greedy token table
                     (``data.synthetic.token_embedding_table``):
                     "greedy" is token feedback WITHOUT drafting (the
                     pinned per-step/fused oracle the speculative modes
                     are token-identical to); "ngram" adds host-side
                     prompt-lookup self-speculation (zero extra model);
                     "draft-model" adds a shallow draft transformer on
                     the same ParallelismPlan with its own paged KV
                     plane (docs/serving.md, "Speculative decoding").
    spec_gamma:      draft tokens proposed per verify step (the γ of
                     draft-and-verify); requires a drafting mode.
    spec_adaptive:   per-request adaptive γ — back off to a smaller
                     verify ladder bucket on low acceptance EMA, climb
                     back on high (requires a drafting mode).
    spec_draft_layers: draft-model depth (layers of the shallow draft
                     transformer; every other dim matches the target).
    spec_draft_kv_heads: draft-model GQA kv_heads override (None =
                     the target's; must keep kv_heads % tp == 0).
    prefix_caching:  refcounted content-addressed shared-prefix KV
                     blocks (docs/serving.md, "Prefix cache & quantized
                     KV").  Full prompt blocks are indexed by their
                     token-block chain in a host-side radix trie inside
                     the ``BlockLedger``; an admitted request whose
                     prompt matches an existing chain attaches to the
                     matched blocks (one copy-on-attach jit replaces
                     the matched chunks' prefills — TTFT drops by the
                     matched fraction) and pays blocks only for its
                     unmatched suffix.  Requires ``prefill_chunk`` (the
                     suffix-only prefill IS the chunk machinery),
                     dp=1 (the donor->slot block copy must stay
                     shard-local, like compaction), and
                     speculation="off".
    kv_quantization: "none" (fp cache, bit-identical legacy layout) or
                     "int8": K/V planes stored as int8 blocks with a
                     per-(block, kv-head) fp32 scale side-channel
                     plane, dequantised inside the length-masked
                     attention — ~3.9x smaller cache, so
                     ``hbm_budget_gb`` admits proportionally more
                     resident requests (``kv_cache_bytes_per_device``
                     prices the quantized layout statically).
                     Requires speculation="off" and no
                     compact_threshold (fp-cache-only programs).
    temperature:     softmax temperature of the SAMPLED decode path
                     (0.0 = the greedy argmax law, bit-for-bit
                     untouched).  temperature > 0 routes every decode
                     unit through the residual-sampling verify
                     (``speculative_sample`` — Leviathan et al. 2023):
                     the target's verify logits come to host, each
                     drafted position is accepted with probability
                     ``p[draft]`` and rejected positions resample from
                     ``residual_distribution`` — the composite law is
                     exactly the temperature-``T`` softmax of the
                     target, so sampled speculative decode is
                     distribution-identical (not token-identical) to a
                     sequential sampler.  Requires a drafting
                     speculation mode, decode_horizon=1 and no
                     prefill_chunk (the fused/chunk-interleave token
                     programs are greedy-argmax only — running them
                     would silently emit greedy tokens mid-sampled-run).
    sample_seed:     host RNG seed of the sampled path (with the trace
                     seed this makes sampled runs replayable); only
                     meaningful with temperature > 0.
    hedge_factor:    fleet-level straggler hedging knob (``serve/
                     fleet.py``; ignored by a single-engine run): a
                     request still outstanding past ``hedge_factor`` x
                     the observed p99 end-to-end latency is duplicated
                     onto a second replica — first completion wins, the
                     loser is canceled and its blocks freed.  Greedy
                     token sequences depend only on (params, request
                     seed), so the committed tokens are identical
                     whichever copy wins.  None (default) disables
                     hedging; must be > 1.0 when set.
    """

    max_batch: int = 8
    block_size: int = 16
    max_seq: int = 256
    prefill_buckets: tuple[int, ...] = ()
    queue_capacity: int = 64
    blocks_budget: Optional[int] = None
    hbm_budget_gb: Optional[float] = 12.0
    decode_horizon: int = 1
    inflight_window: int = 1
    prefill_chunk: Optional[int] = None
    compact_threshold: Optional[float] = None
    reject_infeasible: bool = False
    max_dispatch_retries: int = 2
    retry_backoff_s: float = 0.05
    dispatch_deadline_factor: Optional[float] = None
    dispatch_deadline_min_s: float = 0.25
    speculation: str = "off"
    spec_gamma: int = 0
    spec_adaptive: bool = False
    spec_draft_layers: int = 1
    spec_draft_kv_heads: Optional[int] = None
    prefix_caching: bool = False
    kv_quantization: str = "none"
    temperature: float = 0.0
    sample_seed: int = 0
    hedge_factor: Optional[float] = None

    def __post_init__(self) -> None:
        if not self.prefill_buckets:
            object.__setattr__(
                self, "prefill_buckets",
                _default_buckets(self.block_size, self.max_seq),
            )
        else:
            # normalise: bucket_for's first-match walk and every
            # "buckets[-1] is the largest" consumer assume ascending
            # unique buckets
            object.__setattr__(
                self, "prefill_buckets",
                tuple(sorted(set(self.prefill_buckets))),
            )

    @property
    def num_blocks(self) -> int:
        return self.max_seq // self.block_size

    @property
    def total_blocks(self) -> int:
        return (self.blocks_budget if self.blocks_budget is not None
                else self.max_batch * self.num_blocks)

    def validate(self, config: ModelConfig, dp: int = 1,
                 tp: int = 1) -> None:
        budget = (None if self.hbm_budget_gb is None
                  else int(self.hbm_budget_gb * 2**30))
        if self.speculation not in SPECULATION_MODES:
            raise ValueError(
                f"serving.speculation={self.speculation!r} must be one "
                f"of {SPECULATION_MODES}"
            )
        # speculation with tp_overlap != off or non-dense attention is
        # rejected inside validate_serving (those envelopes cannot serve
        # at all); the draft plane re-runs the same gate on its own
        # config below, so a draft kv plane breaking kv_heads % tp
        # fails here at build time too
        draft = (self.draft_model_config(config)
                 if self.speculation == "draft-model" else None)
        validate_serving(config, self.max_batch, self.max_seq,
                         self.block_size, dp=dp, tp=tp,
                         hbm_budget_bytes=budget, draft_config=draft,
                         kv_quantization=self.kv_quantization)
        for b in self.prefill_buckets:
            if b % self.block_size != 0 or not 0 < b <= self.max_seq:
                raise ValueError(
                    f"prefill bucket {b} must be a block_size="
                    f"{self.block_size} multiple in (0, {self.max_seq}]"
                )
        if self.queue_capacity < 1:
            raise ValueError(
                f"serving.queue_capacity must be >= 1, got "
                f"{self.queue_capacity}"
            )
        if self.hedge_factor is not None and self.hedge_factor <= 1.0:
            raise ValueError(
                f"serving.hedge_factor must be > 1.0 (it scales the "
                f"observed p99 latency), got {self.hedge_factor}"
            )
        if self.total_blocks < 1:
            raise ValueError(
                f"serving.blocks_budget must be >= 1, got "
                f"{self.total_blocks}"
            )
        if self.decode_horizon < 1:
            raise ValueError(
                f"serving.decode_horizon must be >= 1, got "
                f"{self.decode_horizon}"
            )
        if self.inflight_window < 1:
            raise ValueError(
                f"serving.inflight_window must be >= 1, got "
                f"{self.inflight_window}"
            )
        if self.inflight_window > 1 and self.decode_horizon < 2:
            raise ValueError(
                "serving.inflight_window > 1 requires decode_horizon "
                ">= 2: per-step (k=1) units never stay in flight (their "
                "y may alias the donated carry), so the window would be "
                "a silent no-op on the per-step engine"
            )
        if self.prefill_chunk is not None:
            if (self.prefill_chunk % self.block_size != 0
                    or not 0 < self.prefill_chunk <= self.max_seq):
                raise ValueError(
                    f"serving.prefill_chunk={self.prefill_chunk} must be "
                    f"a block_size={self.block_size} multiple in "
                    f"(0, {self.max_seq}]"
                )
            if self.max_seq % self.prefill_chunk != 0:
                # a prompt near max_seq pads to ceil(prompt/chunk)*chunk;
                # unless the chunk divides max_seq that rounding can
                # overrun the slot's block ring for a perfectly feasible
                # request — reject the geometry up front
                raise ValueError(
                    f"serving.prefill_chunk={self.prefill_chunk} must "
                    f"divide serving.max_seq={self.max_seq} (chunk "
                    "rounding of a near-max_seq prompt would overrun "
                    "the slot's block ring)"
                )
        if self.compact_threshold is not None:
            if not 0.0 < self.compact_threshold <= 0.5:
                raise ValueError(
                    f"serving.compact_threshold must be in (0, 0.5] — "
                    f"compaction repacks into the half-size batch bucket "
                    f"(got {self.compact_threshold})"
                )
            if self.decode_horizon < 2:
                raise ValueError(
                    "serving.compact_threshold requires decode_horizon "
                    ">= 2: compaction only engages on fused scans, so "
                    "with the per-step engine it would be a silent no-op "
                    "that still pays the gather/scatter compiles"
                )
            if self.max_batch < 2:
                raise ValueError(
                    "serving.compact_threshold needs max_batch >= 2 "
                    "(nothing to compact into)"
                )
            if dp > 1:
                raise ValueError(
                    "serving.compact_threshold requires dp=1: the slot "
                    "gather/scatter must stay shard-local, and the slot "
                    f"dim is sharded over dp={dp}"
                )
        if self.max_dispatch_retries < 0:
            raise ValueError(
                f"serving.max_dispatch_retries must be >= 0, got "
                f"{self.max_dispatch_retries}"
            )
        if self.retry_backoff_s < 0:
            raise ValueError(
                f"serving.retry_backoff_s must be >= 0, got "
                f"{self.retry_backoff_s}"
            )
        if (self.dispatch_deadline_factor is not None
                and self.dispatch_deadline_factor <= 0):
            raise ValueError(
                f"serving.dispatch_deadline_factor must be > 0, got "
                f"{self.dispatch_deadline_factor}"
            )
        if self.dispatch_deadline_min_s <= 0:
            raise ValueError(
                f"serving.dispatch_deadline_min_s must be > 0 seconds, "
                f"got {self.dispatch_deadline_min_s}"
            )
        # -- speculation ladder (same no-op-trap contract as
        #    compact_threshold/inflight_window: a knob that would
        #    silently do nothing is a config error) --
        if self.spec_drafting:
            if self.spec_gamma < 1:
                raise ValueError(
                    f"serving.speculation={self.speculation!r} requires "
                    f"spec_gamma >= 1 (got {self.spec_gamma}): a drafter "
                    "with zero proposals per verify is a silent no-op "
                    "that still pays the verify compiles"
                )
            if self.spec_gamma + 1 > self.max_seq:
                raise ValueError(
                    f"serving.spec_gamma={self.spec_gamma} cannot exceed "
                    f"max_seq-1={self.max_seq - 1}: a verify step "
                    "appends gamma+1 positions to one slot"
                )
        else:
            if self.spec_gamma:
                raise ValueError(
                    f"serving.spec_gamma={self.spec_gamma} requires a "
                    "drafting speculation mode ('ngram' or "
                    "'draft-model'); with speculation="
                    f"{self.speculation!r} no verify step ever runs, so "
                    "the knob would be a silent no-op"
                )
            if self.spec_adaptive:
                raise ValueError(
                    "serving.spec_adaptive requires a drafting "
                    "speculation mode ('ngram' or 'draft-model'): "
                    "there is no acceptance EMA to adapt to with "
                    f"speculation={self.speculation!r}"
                )
        if self.speculation != "off" and self.compact_threshold is not None:
            raise ValueError(
                "serving.compact_threshold cannot combine with "
                f"speculation={self.speculation!r}: token-feedback and "
                "verify units run on the full decode batch (no "
                "compacted token/verify program exists), so compaction "
                "would be a silent no-op that still pays the gather/"
                "scatter compiles"
            )
        if self.speculation == "draft-model":
            if self.spec_draft_layers < 1:
                raise ValueError(
                    f"serving.spec_draft_layers must be >= 1, got "
                    f"{self.spec_draft_layers}"
                )
            if self.prefill_chunk is not None:
                raise ValueError(
                    "serving.prefill_chunk cannot combine with "
                    "speculation='draft-model': the draft KV plane is "
                    "prefilled monolithically at admission, and a "
                    "chunked target prefill would leave it silently "
                    "unfilled"
                )
        # -- shared-prefix cache + quantized KV planes (same no-op-trap
        #    contract: a knob that cannot engage is a config error) --
        if self.prefix_caching:
            if self.prefill_chunk is None:
                raise ValueError(
                    "serving.prefix_caching requires prefill_chunk: the "
                    "suffix-only prefill of a prefix hit IS the chunked-"
                    "prefill machinery (attach replaces the matched "
                    "chunks), so without it every admission would pay "
                    "the full prefill and the trie would be a silent "
                    "no-op"
                )
            if dp > 1:
                raise ValueError(
                    "serving.prefix_caching requires dp=1: the prefix "
                    "attach copies donor-slot blocks into the admitted "
                    "slot, and that copy must stay shard-local — the "
                    f"slot dim is sharded over dp={dp} (same constraint "
                    "as compact_threshold)"
                )
            if self.speculation != "off":
                raise ValueError(
                    "serving.prefix_caching cannot combine with "
                    f"speculation={self.speculation!r}: prefix attach "
                    "rides the chunked prefill, which the speculative "
                    "modes exclude (and generated tokens are never "
                    "indexed in the trie, so drafting gains nothing)"
                )
        if self.kv_quantization == "int8":
            if self.speculation != "off":
                raise ValueError(
                    "serving.kv_quantization='int8' cannot combine with "
                    f"speculation={self.speculation!r}: the token/"
                    "verify programs read and write the fp cache layout "
                    "only"
                )
            if self.compact_threshold is not None:
                raise ValueError(
                    "serving.kv_quantization='int8' cannot combine with "
                    "compact_threshold: the slot gather/scatter programs "
                    "repack the fp cache layout only, so compaction "
                    "would silently run on stale scale planes"
                )
        # -- sampled decode (same no-op-trap contract) --
        if self.temperature < 0:
            raise ValueError(
                f"serving.temperature must be >= 0, got "
                f"{self.temperature}"
            )
        if self.temperature > 0:
            if not self.spec_drafting:
                raise ValueError(
                    f"serving.temperature={self.temperature} requires a "
                    "drafting speculation mode ('ngram' or "
                    "'draft-model'): the sampled path runs inside the "
                    "verify unit (residual sampling over the verify "
                    "logits), and with speculation="
                    f"{self.speculation!r} every decode program is the "
                    "greedy argmax law — the knob would silently emit "
                    "greedy tokens"
                )
            if self.decode_horizon != 1:
                raise ValueError(
                    f"serving.temperature={self.temperature} requires "
                    f"decode_horizon=1 (got {self.decode_horizon}): the "
                    "fused token scans are greedy-argmax programs, so a "
                    "fused unit mid-sampled-run would silently emit "
                    "greedy tokens (the verify window is the sampled "
                    "path's multi-token mechanism)"
                )
            if self.prefill_chunk is not None:
                raise ValueError(
                    f"serving.temperature={self.temperature} cannot "
                    "combine with prefill_chunk: the chunk interleave's "
                    "per-step decode units are greedy token programs, "
                    "so a long admission would silently emit greedy "
                    "tokens mid-sampled-run"
                )
        elif self.sample_seed:
            raise ValueError(
                f"serving.sample_seed={self.sample_seed} requires "
                "temperature > 0: the greedy path never consumes the "
                "host RNG, so the knob would be a silent no-op"
            )

    @property
    def spec_drafting(self) -> bool:
        """True when a drafter runs (verify steps exist)."""
        return self.speculation in ("ngram", "draft-model")

    @property
    def spec_gammas(self) -> tuple[int, ...]:
        """The verify-step γ ladder: powers of two 1, 2, 4, ... below
        ``spec_gamma``, plus ``spec_gamma`` itself (adaptive γ backs
        off through these buckets; empty when not drafting)."""
        if not self.spec_drafting:
            return ()
        gs = []
        g = 1
        while g < self.spec_gamma:
            gs.append(g)
            g *= 2
        gs.append(self.spec_gamma)
        return tuple(sorted(set(gs)))

    def draft_model_config(self, config: ModelConfig) -> ModelConfig:
        """The draft transformer's config: the target at
        ``spec_draft_layers`` depth (and an optional kv_heads
        override), everything else — hidden size, heads, dtype,
        attention — identical, so the draft shares the target's
        ParallelismPlan and its outputs live in the same hidden/token
        space the verify step argmaxes over."""
        kwargs: dict[str, Any] = {"num_layers": self.spec_draft_layers}
        if self.spec_draft_kv_heads is not None:
            kwargs["num_kv_heads"] = self.spec_draft_kv_heads
        return dc_replace(config, **kwargs)

    def bucket_for(self, prompt_len: int) -> int:
        for b in self.prefill_buckets:
            if prompt_len <= b:
                return b
        raise ValueError(
            f"prompt_len={prompt_len} exceeds the largest prefill bucket "
            f"{self.prefill_buckets[-1]} (serving.max_seq={self.max_seq})"
        )

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "ServingConfig":
        fields = {}
        for k in ("max_batch", "block_size", "max_seq", "queue_capacity",
                  "blocks_budget", "hbm_budget_gb", "decode_horizon",
                  "inflight_window", "prefill_chunk", "compact_threshold",
                  "reject_infeasible", "max_dispatch_retries",
                  "retry_backoff_s", "dispatch_deadline_factor",
                  "dispatch_deadline_min_s", "speculation", "spec_gamma",
                  "spec_adaptive", "spec_draft_layers",
                  "spec_draft_kv_heads", "prefix_caching",
                  "kv_quantization", "temperature", "sample_seed",
                  "hedge_factor"):
            if k in d:
                fields[k] = d[k]
        if "prefill_buckets" in d:
            fields["prefill_buckets"] = tuple(d["prefill_buckets"])
        return cls(**fields)

    def to_dict(self) -> dict[str, Any]:
        return {
            "max_batch": self.max_batch,
            "block_size": self.block_size,
            "max_seq": self.max_seq,
            "num_blocks": self.num_blocks,
            "prefill_buckets": list(self.prefill_buckets),
            "queue_capacity": self.queue_capacity,
            "blocks_budget": self.total_blocks,
            "hbm_budget_gb": self.hbm_budget_gb,
            "decode_horizon": self.decode_horizon,
            "inflight_window": self.inflight_window,
            "prefill_chunk": self.prefill_chunk,
            "compact_threshold": self.compact_threshold,
            "reject_infeasible": self.reject_infeasible,
            "max_dispatch_retries": self.max_dispatch_retries,
            "retry_backoff_s": self.retry_backoff_s,
            "dispatch_deadline_factor": self.dispatch_deadline_factor,
            "dispatch_deadline_min_s": self.dispatch_deadline_min_s,
            "speculation": self.speculation,
            "spec_gamma": self.spec_gamma,
            "spec_adaptive": self.spec_adaptive,
            "spec_draft_layers": self.spec_draft_layers,
            "spec_draft_kv_heads": self.spec_draft_kv_heads,
            "prefix_caching": self.prefix_caching,
            "kv_quantization": self.kv_quantization,
            "temperature": self.temperature,
            "sample_seed": self.sample_seed,
            "hedge_factor": self.hedge_factor,
        }

    @property
    def fused_horizons(self) -> tuple[int, ...]:
        """The power-of-two fused-scan bucket ladder: 2, 4, ... up to
        ``decode_horizon`` (empty when the fast path is off)."""
        ks = []
        k = 2
        while k <= self.decode_horizon:
            ks.append(k)
            k *= 2
        return tuple(ks)


# ---------------------------------------------------------------------------
# device programs
# ---------------------------------------------------------------------------


def _split_qkv(qkv: jax.Array, config: ModelConfig):
    """[..., qkv_width] -> q [..., H], k/v [..., kv_heads * head_dim]."""
    h, kvd = config.hidden_size, config.kv_heads * config.head_dim
    return qkv[..., :h], qkv[..., h:h + kvd], qkv[..., h + kvd:]


def _serve_block(h, layer, config: ModelConfig, attention_step,
                 cache_state):
    """One transformer block with a pluggable attention step — the ONE
    copy of the ln1/qkv/out/ln2/ffn structure every serving program
    shares (the serving twin of ``transformer._block``, whose math the
    equivalence tests pin it against).  ``attention_step(q, k, v,
    cache_state) -> (attn [B, S, n*d], cache_state)`` owns everything
    that differs between prefill (dense causal + block write), decode
    (cached append + length-masked read), and chunked prefill (prefix
    carry + offset block write); ``cache_state`` is an opaque per-layer
    tuple (the scanned cache leaves, plus the prefix K/V for chunks)."""
    y = _layernorm(h, layer["ln1"]["scale"], layer["ln1"]["bias"])
    qkv = y @ layer["qkv"]["kernel"] + layer["qkv"]["bias"]
    q, k, v = _split_qkv(qkv, config)
    attn, cache_state = attention_step(q, k, v, cache_state)
    h = attn @ layer["out"]["kernel"] + layer["out"]["bias"] + h
    residual = h
    y2 = _layernorm(h, layer["ln2"]["scale"], layer["ln2"]["bias"])
    y2 = y2 @ layer["ffn_up"]["kernel"] + layer["ffn_up"]["bias"]
    y2 = jax.nn.gelu(y2)
    h = (y2 @ layer["ffn_down"]["kernel"]
         + layer["ffn_down"]["bias"] + residual)
    return h, cache_state


def _heads(t: jax.Array, nh: int, d: int) -> jax.Array:
    """[B, S, nh*d] -> [B, nh, S, d]."""
    b, s, _ = t.shape
    return t.reshape(b, s, nh, d).transpose(0, 2, 1, 3)


def _cached_attention(q: jax.Array, k_flat: jax.Array, v_flat: jax.Array,
                      valid: jax.Array) -> jax.Array:
    """Length-masked decode attention over the flattened cache.

    q: ``[B, n, 1, d]``; k_flat/v_flat: ``[B, S_max, kvh, d]``;
    valid: ``[B, S_max]`` bool.  Same math as
    ``models.attention.dense_attention`` (fp32 softmax, 1/sqrt(d),
    grouped-query einsum broadcasting) with the causal mask replaced by
    the per-slot validity mask — positions past a slot's length
    contribute exactly zero (softmax of -inf)."""
    b, n, _, d = q.shape
    kvh = k_flat.shape[2]
    q32 = q.astype(jnp.float32)
    k32 = k_flat.transpose(0, 2, 1, 3).astype(jnp.float32)  # [B, kvh, S, d]
    v32 = v_flat.transpose(0, 2, 1, 3).astype(jnp.float32)
    if kvh != n:
        q32 = q32.reshape(b, kvh, n // kvh, 1, d)
        logits = jnp.einsum("bhgqd,bhkd->bhgqk", q32, k32) / math.sqrt(d)
        logits = jnp.where(valid[:, None, None, None, :], logits, -jnp.inf)
        probs = jax.nn.softmax(logits, axis=-1)
        out = jnp.einsum("bhgqk,bhkd->bhgqd", probs, v32)
        out = out.reshape(b, n, 1, d)
    else:
        logits = jnp.einsum("bnqd,bnkd->bnqk", q32, k32) / math.sqrt(d)
        logits = jnp.where(valid[:, None, None, :], logits, -jnp.inf)
        probs = jax.nn.softmax(logits, axis=-1)
        out = jnp.einsum("bnqk,bnkd->bnqd", probs, v32)
    return out.astype(k_flat.dtype)


def _write_prompt_blocks(cache_layer: jax.Array, update: jax.Array,
                         slot: jax.Array, start_blk: int = 0) -> jax.Array:
    """Masked-select write of a prefill bucket (or chunk) into one slot's
    blocks, starting at static block offset ``start_blk``.

    cache_layer: ``[B, nb, bs, kvh, d]``; update: ``[wb, bs, kvh, d]``
    (``wb`` = bucket/block_size, static).  One-hot over the slot dim and
    a static block mask — pure elementwise, so GSPMD keeps the write
    local to the shard owning the slot (no collective, no regather)."""
    b_dim, nb = cache_layer.shape[:2]
    wb = update.shape[0]
    padded = jnp.pad(update, ((start_blk, nb - start_blk - wb),
                              (0, 0), (0, 0), (0, 0)))
    slot_mask = (jnp.arange(b_dim) == slot)[:, None, None, None, None]
    blk = jnp.arange(nb)
    blk_mask = ((blk >= start_blk)
                & (blk < start_blk + wb))[None, :, None, None, None]
    return jnp.where(slot_mask & blk_mask, padded[None], cache_layer)


def _write_scale_blocks(scale_layer: jax.Array, update: jax.Array,
                        slot: jax.Array, start_blk: int = 0) -> jax.Array:
    """``_write_prompt_blocks`` for the int8 side-channel scale plane:
    scale_layer ``[B, nb, kvh]``, update ``[wb, kvh]`` — same one-hot
    slot mask + static block mask, so the scale write is exactly as
    shard-local as the block write it accompanies."""
    b_dim, nb = scale_layer.shape[:2]
    wb = update.shape[0]
    padded = jnp.pad(update, ((start_blk, nb - start_blk - wb), (0, 0)))
    slot_mask = (jnp.arange(b_dim) == slot)[:, None, None]
    blk = jnp.arange(nb)
    blk_mask = ((blk >= start_blk)
                & (blk < start_blk + wb))[None, :, None]
    return jnp.where(slot_mask & blk_mask, padded[None], scale_layer)


def build_prefill(config: ModelConfig, mesh: Mesh,
                  quantized: bool = False):
    """Jitted ``prefill(cache, params, x, slot, length) -> (cache,
    y_last)`` — retraces once per prompt bucket (x's static shape).  The
    cache is donated (argnum 0), so the carried protocol matches the
    train-step convention the audit and calibration understand.

    ``quantized`` writes the int8 layout (``QuantKVCache``): each
    freshly-computed K/V block is quantised per (block, kv-head) and
    the fp32 scales land in the side-channel plane via
    ``_write_scale_blocks``.  Prefill attention runs over the chunk's
    own fp K/V (it never reads the cache), so quantisation touches
    only the write."""
    n, d, kvh = config.num_heads, config.head_dim, config.kv_heads

    def prefill(cache, params, x, slot, length):
        bs = cache.block_size
        s_bucket = x.shape[1]
        wb = s_bucket // bs

        def attention_step(q, k, v, cache_state):
            if quantized:
                k_l, v_l, ks_l, vs_l = cache_state
            else:
                k_l, v_l = cache_state
            qh, kh, vh = (_heads(q, n, d), _heads(k, kvh, d),
                          _heads(v, kvh, d))
            attn = dense_attention(qh, kh, vh, causal=config.causal)
            # write this layer's K/V blocks into the slot ([S, kvh, d]
            # token-major, re-tiled to whole blocks)
            k_blocks = kh.transpose(0, 2, 1, 3)[0].reshape(wb, bs, kvh, d)
            v_blocks = vh.transpose(0, 2, 1, 3)[0].reshape(wb, bs, kvh, d)
            if quantized:
                kq, ks = quantize_kv_blocks(k_blocks)
                vq, vs = quantize_kv_blocks(v_blocks)
                k_l = _write_prompt_blocks(k_l, kq, slot)
                v_l = _write_prompt_blocks(v_l, vq, slot)
                ks_l = _write_scale_blocks(ks_l, ks, slot)
                vs_l = _write_scale_blocks(vs_l, vs, slot)
                state = (k_l, v_l, ks_l, vs_l)
            else:
                k_l = _write_prompt_blocks(k_l, k_blocks, slot)
                v_l = _write_prompt_blocks(v_l, v_blocks, slot)
                state = (k_l, v_l)
            return (attn.transpose(0, 2, 1, 3).reshape(1, s_bucket, n * d),
                    state)

        def body(h, layer_and_cache):
            layer, *cache_state = layer_and_cache
            return _serve_block(h, layer, config, attention_step,
                                tuple(cache_state))

        planes = ((cache.k, cache.v, cache.k_scale, cache.v_scale)
                  if quantized else (cache.k, cache.v))
        h, new_planes = jax.lax.scan(
            body, x, (params["layers"], *planes)
        )
        y = _layernorm(h, params["ln_f"]["scale"], params["ln_f"]["bias"])
        y_last = jax.lax.dynamic_slice(
            y, (0, length - 1, 0), (1, 1, y.shape[-1])
        )[0, 0]
        lengths = jnp.where(jnp.arange(cache.max_batch) == slot,
                            length, cache.lengths).astype(jnp.int32)
        cache_cls = QuantKVCache if quantized else KVCache
        return cache_cls(*new_planes, lengths), y_last

    cache_sh = (quant_cache_shardings(mesh) if quantized
                else cache_shardings(mesh))
    return jax.jit(
        prefill,
        donate_argnums=(0,),
        out_shardings=(cache_sh, NamedSharding(mesh, P())),
    )


def prefix_spec(mesh: Mesh) -> P:
    """Chunked-prefill prefix K/V ``[L, start, kvh, d]``: kv-head dim
    over tp (the cache's own head split), no slot dim at all — the
    prefix never touches the dp shard."""
    axes = getattr(mesh, "axis_names", ())
    tp = "tp" if "tp" in axes and mesh.shape["tp"] > 1 else None
    return P(None, None, tp, None)


def create_prefix(config: ModelConfig, mesh: Mesh) -> tuple[jax.Array,
                                                            jax.Array]:
    """The empty (start=0) prefix carry for a chunked prefill."""
    from dlbb_tpu.models.transformer import _dtype_of as _dt

    shape = (config.num_layers, 0, config.kv_heads, config.head_dim)
    zeros = jnp.zeros(shape, _dt(config.dtype))
    sh = NamedSharding(mesh, prefix_spec(mesh))
    return (jax.device_put(zeros, sh), jax.device_put(zeros, sh))


def _chunk_attention(qh: jax.Array, k_all: jax.Array, v_all: jax.Array,
                     start: int) -> jax.Array:
    """Offset-causal fp32 attention for one prefill chunk.

    qh: ``[1, n, C, d]`` (the chunk's queries, global positions
    ``start..start+C``); k_all/v_all: ``[start+C, kvh, d]`` (prefix +
    chunk keys).  Same math as ``_cached_attention`` (fp32 softmax,
    1/sqrt(d), grouped-query broadcasting) with the per-slot validity
    mask replaced by the STATIC offset-causal mask ``j <= start + qi``
    — for real query positions this reaches only real keys, so pad
    positions in a final partial chunk never contaminate a real
    output (their own rows are discarded by the caller)."""
    b, n, c, d = qh.shape
    kvh = k_all.shape[1]
    s_tot = k_all.shape[0]
    q32 = qh.astype(jnp.float32)
    k32 = k_all.transpose(1, 0, 2).astype(jnp.float32)[None]  # [1,kvh,S,d]
    v32 = v_all.transpose(1, 0, 2).astype(jnp.float32)[None]
    mask = (jnp.arange(s_tot)[None, :]
            <= (start + jnp.arange(c))[:, None])            # [C, S]
    if kvh != n:
        q32 = q32.reshape(b, kvh, n // kvh, c, d)
        logits = jnp.einsum("bhgqd,bhkd->bhgqk", q32, k32) / math.sqrt(d)
        logits = jnp.where(mask[None, None, None], logits, -jnp.inf)
        probs = jax.nn.softmax(logits, axis=-1)
        out = jnp.einsum("bhgqk,bhkd->bhgqd", probs, v32)
        out = out.reshape(b, n, c, d)
    else:
        logits = jnp.einsum("bnqd,bnkd->bnqk", q32, k32) / math.sqrt(d)
        logits = jnp.where(mask[None, None], logits, -jnp.inf)
        probs = jax.nn.softmax(logits, axis=-1)
        out = jnp.einsum("bnqk,bnkd->bnqd", probs, v32)
    return out.astype(k_all.dtype)


def build_prefill_chunk(config: ModelConfig, mesh: Mesh, chunk_len: int,
                        start: int, quantized: bool = False):
    """Jitted ``prefill_chunk(cache, prefix, params, x, slot, length) ->
    (cache, prefix, y_last)`` — one chunk of a chunked prefill at STATIC
    global offset ``start`` (a block multiple; one retrace per chunk
    index, the "bucketed chunk jit").

    The chunk's K/V blocks are written into the slot exactly as
    monolithic prefill writes its bucket (``_write_prompt_blocks`` at
    block offset ``start/block_size`` — masked select, shard-local);
    attention runs over the explicitly-carried prefix K/V (``[L, start,
    kvh, d]``, no slot dim) concatenated with the chunk, so the
    dp-sharded cache is never re-read.  ``length`` is the TRUE prompt
    length; ``y_last`` is the output at the last real position when it
    falls inside this chunk (the engine uses only the final chunk's).
    Only the cache is donated (the returned prefix is larger than the
    input one, so its buffers can never alias).

    ``quantized`` writes the chunk's blocks in the int8 layout (scales
    into the side-channel plane); the carried prefix K/V stays fp —
    attention always runs over exact chunk values, so quantisation
    touches only the cache write, exactly as in monolithic prefill."""
    n, d, kvh = config.num_heads, config.head_dim, config.kv_heads

    def prefill_chunk(cache, prefix, params, x, slot, length):
        bs = cache.block_size
        wb = chunk_len // bs
        start_blk = start // bs

        def attention_step(q, k, v, cache_state):
            if quantized:
                k_l, v_l, ks_l, vs_l, pk_l, pv_l = cache_state
            else:
                k_l, v_l, pk_l, pv_l = cache_state
            qh = _heads(q, n, d)                        # [1, n, C, d]
            k_chunk = k[0].reshape(chunk_len, kvh, d)
            v_chunk = v[0].reshape(chunk_len, kvh, d)
            k_all = jnp.concatenate([pk_l, k_chunk], axis=0)
            v_all = jnp.concatenate([pv_l, v_chunk], axis=0)
            attn = _chunk_attention(qh, k_all, v_all, start)
            if quantized:
                kq, ks = quantize_kv_blocks(
                    k_chunk.reshape(wb, bs, kvh, d))
                vq, vs = quantize_kv_blocks(
                    v_chunk.reshape(wb, bs, kvh, d))
                k_l = _write_prompt_blocks(k_l, kq, slot, start_blk)
                v_l = _write_prompt_blocks(v_l, vq, slot, start_blk)
                ks_l = _write_scale_blocks(ks_l, ks, slot, start_blk)
                vs_l = _write_scale_blocks(vs_l, vs, slot, start_blk)
                state = (k_l, v_l, ks_l, vs_l, k_all, v_all)
            else:
                k_l = _write_prompt_blocks(
                    k_l, k_chunk.reshape(wb, bs, kvh, d), slot,
                    start_blk)
                v_l = _write_prompt_blocks(
                    v_l, v_chunk.reshape(wb, bs, kvh, d), slot,
                    start_blk)
                state = (k_l, v_l, k_all, v_all)
            return (attn.transpose(0, 2, 1, 3).reshape(1, chunk_len,
                                                       n * d),
                    state)

        def body(h, layer_and_cache):
            layer, *cache_state = layer_and_cache
            return _serve_block(h, layer, config, attention_step,
                                tuple(cache_state))

        pk, pv = prefix
        planes = ((cache.k, cache.v, cache.k_scale, cache.v_scale)
                  if quantized else (cache.k, cache.v))
        h, new_state = jax.lax.scan(
            body, x, (params["layers"], *planes, pk, pv)
        )
        new_planes, (pk_new, pv_new) = new_state[:-2], new_state[-2:]
        y = _layernorm(h, params["ln_f"]["scale"], params["ln_f"]["bias"])
        local = jnp.clip(length - 1 - start, 0, chunk_len - 1)
        y_last = jax.lax.dynamic_slice(
            y, (0, local, 0), (1, 1, y.shape[-1])
        )[0, 0]
        new_len = jnp.minimum(length, start + chunk_len)
        lengths = jnp.where(jnp.arange(cache.max_batch) == slot,
                            new_len, cache.lengths).astype(jnp.int32)
        cache_cls = QuantKVCache if quantized else KVCache
        return (cache_cls(*new_planes, lengths), (pk_new, pv_new), y_last)

    pre_sh = NamedSharding(mesh, prefix_spec(mesh))
    cache_sh = (quant_cache_shardings(mesh) if quantized
                else cache_shardings(mesh))
    # only the cache is donated: the returned prefix is LARGER than the
    # input one (start -> start + C), so its buffers can never alias
    return jax.jit(
        prefill_chunk,
        donate_argnums=(0,),
        out_shardings=(cache_sh, (pre_sh, pre_sh),
                       NamedSharding(mesh, P())),
    )


def build_prefix_attach(config: ModelConfig, mesh: Mesh,
                        matched_len: int, block_size: int,
                        quantized: bool = False):
    """Jitted ``attach(cache, src, dst) -> (cache, prefix)`` — the
    copy-on-attach step of the shared-prefix cache (one retrace per
    matched chunk count, like the bucketed chunk jits).

    Copies the donor slot ``src``'s first ``matched_len/block_size``
    blocks (every plane — K/V, and the scale side-channel in the int8
    layout) into the admitted slot ``dst`` via the same one-hot masked
    select as ``_write_prompt_blocks`` — pure elementwise on a dp=1
    slot dim (``ServingConfig.validate`` pins prefix_caching to dp=1),
    so the attach lowers to ZERO collectives (audited).  Also returns
    the matched prefix as the fp chunk-prefill carry ``[L, matched_len,
    kvh, d]``, exactly what the chunk jits would have produced for the
    same token blocks (bit-identical in the fp layout — the cache
    blocks ARE the chunk values; dequantised in the int8 layout), so
    the suffix chunks resume at static offset ``matched_len`` with no
    recompute.  The engine's scheduler replaces the matched chunks'
    prefill dispatches with this single copy — that is the TTFT win."""
    nb_m = matched_len // block_size
    kvh, d = config.kv_heads, config.head_dim
    dtype = _dtype_of(config.dtype)

    def copy(plane, src, dst):
        donor = jnp.take(plane, src, axis=1)     # slot dim dropped
        slot_mask = (jnp.arange(plane.shape[1]) == dst).reshape(
            (1, -1) + (1,) * (plane.ndim - 2))
        blk_mask = (jnp.arange(plane.shape[2]) < nb_m).reshape(
            (1, 1, -1) + (1,) * (plane.ndim - 3))
        return jnp.where(slot_mask & blk_mask, donor[:, None], plane)

    def attach(cache, src, dst):
        nl = cache.k.shape[0]
        k_q = jnp.take(cache.k, src, axis=1)[:, :nb_m]
        v_q = jnp.take(cache.v, src, axis=1)[:, :nb_m]
        if quantized:
            ks = jnp.take(cache.k_scale, src, axis=1)[:, :nb_m]
            vs = jnp.take(cache.v_scale, src, axis=1)[:, :nb_m]
            pk = dequantize_kv_blocks(k_q, ks, dtype)
            pv = dequantize_kv_blocks(v_q, vs, dtype)
            new_cache = QuantKVCache(
                copy(cache.k, src, dst), copy(cache.v, src, dst),
                copy(cache.k_scale, src, dst),
                copy(cache.v_scale, src, dst), cache.lengths)
        else:
            pk, pv = k_q, v_q
            new_cache = KVCache(copy(cache.k, src, dst),
                                copy(cache.v, src, dst), cache.lengths)
        prefix = (pk.reshape(nl, matched_len, kvh, d),
                  pv.reshape(nl, matched_len, kvh, d))
        return new_cache, prefix

    pre_sh = NamedSharding(mesh, prefix_spec(mesh))
    cache_sh = (quant_cache_shardings(mesh) if quantized
                else cache_shardings(mesh))
    return jax.jit(
        attach,
        donate_argnums=(0,),
        out_shardings=(cache_sh, (pre_sh, pre_sh)),
    )


def build_compact_gather(mesh: Mesh):
    """Jitted ``gather(carry, idx) -> small_carry``: repack the active
    slots named by ``idx`` into a smaller decode batch bucket (slot
    compaction, dp=1 only — the gather must stay shard-local).  The big
    carry is NOT donated: it survives on device and the compacted scan's
    results are scattered back into it at scan exit."""
    from dlbb_tpu.serve.kvcache import gather_cache_slots

    def gather(carry, idx):
        cache, x = carry
        return (gather_cache_slots(cache, idx), x[idx])

    x_sh = NamedSharding(mesh, decode_batch_spec(mesh))
    return jax.jit(
        gather, out_shardings=(cache_shardings(mesh), x_sh),
    )


def build_compact_scatter(mesh: Mesh):
    """Jitted ``scatter(carry, small_carry, idx) -> carry``: write the
    compacted rows back into their big-batch slots (only the big carry
    is donated — the small rows land inside larger output buffers;
    ``idx`` rows are distinct by construction — active slots padded
    with distinct free slots, so the scatter is unambiguous)."""
    from dlbb_tpu.serve.kvcache import scatter_cache_slots

    def scatter(carry, small_carry, idx):
        cache, x = carry
        s_cache, s_x = small_carry
        return (scatter_cache_slots(cache, s_cache, idx),
                x.at[idx].set(s_x))

    x_sh = NamedSharding(mesh, decode_batch_spec(mesh))
    # only the big carry is donated: the small rows land inside larger
    # output buffers, so their donation could never be honoured
    return jax.jit(
        scatter,
        donate_argnums=(0,),
        out_shardings=(cache_shardings(mesh), x_sh),
    )


def decode_batch_spec(mesh: Mesh) -> P:
    """Decode activations ``[max_batch, 1, H]``: slots over dp."""
    axes = getattr(mesh, "axis_names", ())
    dp = "dp" if "dp" in axes and mesh.shape["dp"] > 1 else None
    return P(dp, None, None)


def _decode_step_math(carry, params, active, config: ModelConfig,
                      quantized: bool = False):
    """The decode-step computation shared VERBATIM by the per-step jit
    and every trip of the fused scan (the equivalence contract between
    the two engines is that this is the one copy of the math).

    ``quantized`` reads/writes the int8 layout: each layer's blocks are
    dequantised to fp32 (exact — int8 times an fp32 scale), the token
    appended in fp, attention length-masked as ever, and the layer
    requantised with an active-slot select so an INACTIVE slot's int8/
    scale planes pass through verbatim.  An active slot's untouched
    blocks survive the dequant->requant round trip bit-stably: every
    stored value is ``q*s`` with ``|q| <= 127``, the recomputed scale
    differs from ``s`` only by fp32 rounding, so the re-rounded code is
    the same ``q`` (error ~2^-22 * 127, far below the 0.5 rounding
    threshold)."""
    n, d, kvh = config.num_heads, config.head_dim, config.kv_heads
    cache, x = carry
    b_dim, s_max = cache.max_batch, cache.max_seq
    nb, bs = cache.num_blocks, cache.block_size
    lengths = cache.lengths
    pos = jnp.arange(s_max)[None, :]
    write_mask = (pos == lengths[:, None]) & active[:, None]
    valid = pos <= lengths[:, None]
    sel5 = active[:, None, None, None, None]
    sel3 = active[:, None, None]

    def attention_step(q, k, v, cache_state):
        if quantized:
            k_l, v_l, ks_l, vs_l = cache_state
            k_fp = dequantize_kv_blocks(k_l, ks_l, jnp.float32)
            v_fp = dequantize_kv_blocks(v_l, vs_l, jnp.float32)
        else:
            k_l, v_l = cache_state
            k_fp, v_fp = k_l, v_l
        qh = _heads(q, n, d)                        # [B, n, 1, d]
        k_new = k[:, 0].reshape(b_dim, kvh, d).astype(k_fp.dtype)
        v_new = v[:, 0].reshape(b_dim, kvh, d).astype(v_fp.dtype)
        # append at each active slot's own length (masked select —
        # elementwise, shard-local; see serve/kvcache.py)
        k_flat = k_fp.reshape(b_dim, s_max, kvh, d)
        v_flat = v_fp.reshape(b_dim, s_max, kvh, d)
        k_flat = jnp.where(write_mask[..., None, None],
                           k_new[:, None], k_flat)
        v_flat = jnp.where(write_mask[..., None, None],
                           v_new[:, None], v_flat)
        attn = _cached_attention(qh, k_flat.astype(x.dtype),
                                 v_flat.astype(x.dtype), valid)
        if quantized:
            kq, ks = quantize_kv_blocks(
                k_flat.reshape(b_dim, nb, bs, kvh, d))
            vq, vs = quantize_kv_blocks(
                v_flat.reshape(b_dim, nb, bs, kvh, d))
            state = (jnp.where(sel5, kq, k_l),
                     jnp.where(sel5, vq, v_l),
                     jnp.where(sel3, ks, ks_l),
                     jnp.where(sel3, vs, vs_l))
        else:
            state = (k_flat.reshape(b_dim, nb, bs, kvh, d),
                     v_flat.reshape(b_dim, nb, bs, kvh, d))
        return (attn.transpose(0, 2, 1, 3).reshape(b_dim, 1, n * d),
                state)

    def body(h, layer_and_cache):
        layer, *cache_state = layer_and_cache
        return _serve_block(h, layer, config, attention_step,
                            tuple(cache_state))

    planes = ((cache.k, cache.v, cache.k_scale, cache.v_scale)
              if quantized else (cache.k, cache.v))
    h, new_planes = jax.lax.scan(
        body, x, (params["layers"], *planes)
    )
    y = _layernorm(h, params["ln_f"]["scale"], params["ln_f"]["bias"])
    lengths = lengths + active.astype(jnp.int32)
    cache_cls = QuantKVCache if quantized else KVCache
    new_cache = cache_cls(*new_planes, lengths)
    return (new_cache, y), y


def build_decode_step(config: ModelConfig, mesh: Mesh,
                      quantized: bool = False):
    """Jitted ``decode_step(carry, params, active) -> (carry, y)`` with
    ``carry = (cache, x)`` — ONE fixed-shape compile for the whole run.
    The carry is donated; its returned ``x`` is this step's output, so
    the engine (and the calibration harness's carry protocol) feeds
    ``out[0]`` straight back in."""

    def decode_step(carry, params, active):
        return _decode_step_math(carry, params, active, config,
                                 quantized=quantized)

    x_sh = NamedSharding(mesh, decode_batch_spec(mesh))
    cache_sh = (quant_cache_shardings(mesh) if quantized
                else cache_shardings(mesh))
    return jax.jit(
        decode_step,
        donate_argnums=(0,),
        out_shardings=((cache_sh, x_sh), x_sh),
    )


def build_decode_fused(config: ModelConfig, mesh: Mesh, k: int,
                       quantized: bool = False):
    """Jitted ``decode_fused(carry, params, active, remaining) ->
    (carry, ys)`` — ``k`` decode steps fused into ONE ``lax.scan``
    dispatch over the donated ``(cache, x)`` carry (static ``k``; the
    engine keeps a power-of-two ladder of these).

    ``remaining[b]`` is slot ``b``'s step budget within this scan
    (``min(k, tokens_left)``, 0 for inactive slots): step ``i`` runs
    with ``active & (i < remaining)``, so a slot that completes
    mid-scan is masked inactive for the rest of the trips — its cache
    stops advancing exactly as if the per-step engine had deactivated
    it, and the ledger frees its blocks at scan exit.  ``ys`` stacks
    every step's output ``[k, max_batch, 1, H]`` (step t's row is the
    token each then-active slot generated at trip t)."""
    cache_cls = QuantKVCache if quantized else KVCache

    def decode_fused(carry, params, active, remaining):
        # the slot-lengths vector deliberately stays OUT of the scan
        # carry: its trajectory is fully determined by the replicated
        # (lengths0, active, remaining) inputs — lengths at trip i are
        # ``lengths0 + active * min(i, remaining)`` — so recomputing it
        # per trip keeps it replicated everywhere.  Carried through the
        # loop instead, GSPMD propagates the cache's dp sharding onto
        # it and re-gathers at the loop boundary — a (tiny, but
        # contract-breaking) collective the decode kind-set forbids.
        # The trip index rides the carry as a scalar for the same
        # reason (an arange-xs array invites an iota reshard).  The
        # cache's data planes ride positionally (``cache[:-1]`` — K/V,
        # plus the int8 scale planes when quantized), lengths excluded.
        cache0, x0 = carry
        lengths0 = cache0.lengths
        act_i32 = active.astype(jnp.int32)

        def step(c, _):
            *planes, x, i = c
            step_active = active & (i < remaining)
            lengths_i = lengths0 + act_i32 * jnp.minimum(i, remaining)
            (cache, x2), y = _decode_step_math(
                (cache_cls(*planes, lengths_i), x), params, step_active,
                config, quantized=quantized)
            return (*cache[:-1], x2, i + 1), y

        final, ys = jax.lax.scan(
            step, (*cache0[:-1], x0, jnp.int32(0)), None, length=k)
        *planes, x, _i = final
        lengths_f = lengths0 + act_i32 * jnp.minimum(jnp.int32(k),
                                                     remaining)
        return (cache_cls(*planes, lengths_f), x), ys

    x_sh = NamedSharding(mesh, decode_batch_spec(mesh))
    ys_sh = NamedSharding(mesh, P(None, *decode_batch_spec(mesh)))
    cache_sh = (quant_cache_shardings(mesh) if quantized
                else cache_shardings(mesh))
    return jax.jit(
        decode_fused,
        donate_argnums=(0,),
        out_shardings=((cache_sh, x_sh), ys_sh),
    )


def _inject_token(carry, slot, vec):
    """Place a freshly-prefilled request's first token into the decode
    input buffer: ``x[slot, 0] = vec``."""
    cache, x = carry
    mask = (jnp.arange(x.shape[0]) == slot)[:, None, None]
    return cache, jnp.where(mask, vec[None, None, :].astype(x.dtype), x)


# ---------------------------------------------------------------------------
# speculative decoding (docs/serving.md, "Speculative decoding")
# ---------------------------------------------------------------------------


def _inject_token_greedy(carry, slot, vec, table):
    """Token-mode admission inject: quantise the prefill's last output
    through the greedy token table (``tok = argmax(vec)``, ``x[slot, 0]
    = table[tok]``) and return the token id — the 4-byte scalar is the
    only thing that ever comes to host (the n-gram drafter's history
    seed + the equivalence gate's capture)."""
    cache, x = carry
    tok = jnp.argmax(vec).astype(jnp.int32)
    emb = jnp.take(table, tok, axis=0)
    return ((cache,
             jnp.where((jnp.arange(x.shape[0]) == slot)[:, None, None],
                       emb[None, None, :].astype(x.dtype), x)),
            tok)


def _inject_token_sampled(carry, slot, tok, table):
    """Sampled-mode admission inject: the HOST already sampled the
    first token from the prefill's softmax (``temperature > 0``), so
    the device only embeds the committed id — ``x[slot, 0] =
    table[tok]`` (the greedy inject with the argmax replaced by the
    host's draw)."""
    cache, x = carry
    emb = jnp.take(table, tok.astype(jnp.int32), axis=0)
    return (cache,
            jnp.where((jnp.arange(x.shape[0]) == slot)[:, None, None],
                      emb[None, None, :].astype(x.dtype), x))


def _verify_attention(q: jax.Array, k_flat: jax.Array, v_flat: jax.Array,
                      valid: jax.Array) -> jax.Array:
    """Offset-causal length-masked attention for one verify step.

    q: ``[B, n, G, d]`` (G = gamma+1 verify positions per slot);
    k_flat/v_flat: ``[B, S_max, kvh, d]``; valid: ``[B, G, S_max]`` bool
    — query ``i`` of slot ``b`` reaches keys ``j <= lengths[b] + i``
    (the per-slot offset-causal mask, ``_chunk_attention``'s static mask
    made per-slot dynamic).  Same math as ``_cached_attention`` (fp32
    softmax, 1/sqrt(d), grouped-query broadcasting), of which it is the
    G>1 generalisation."""
    b, n, g, d = q.shape
    kvh = k_flat.shape[2]
    q32 = q.astype(jnp.float32)
    k32 = k_flat.transpose(0, 2, 1, 3).astype(jnp.float32)  # [B, kvh, S, d]
    v32 = v_flat.transpose(0, 2, 1, 3).astype(jnp.float32)
    if kvh != n:
        q32 = q32.reshape(b, kvh, n // kvh, g, d)
        logits = jnp.einsum("bhgqd,bhkd->bhgqk", q32, k32) / math.sqrt(d)
        logits = jnp.where(valid[:, None, None, :, :], logits, -jnp.inf)
        probs = jax.nn.softmax(logits, axis=-1)
        out = jnp.einsum("bhgqk,bhkd->bhgqd", probs, v32)
        out = out.reshape(b, n, g, d)
    else:
        logits = jnp.einsum("bnqd,bnkd->bnqk", q32, k32) / math.sqrt(d)
        logits = jnp.where(valid[:, None, :, :], logits, -jnp.inf)
        probs = jax.nn.softmax(logits, axis=-1)
        out = jnp.einsum("bnqk,bnkd->bnqd", probs, v32)
    return out.astype(k_flat.dtype)


def build_decode_token_step(config: ModelConfig, mesh: Mesh):
    """Jitted token-feedback decode step: the per-step decode math
    (verbatim ``_decode_step_math``) followed by the greedy token
    quantisation — ``tok = argmax(y)``, next input ``table[tok]``.
    Returns ``(carry, tok [B])``; the token ids are the committed
    output (device argmax, never a host float transfer).  This is the
    speculative modes' pinned per-step oracle."""

    def decode_token_step(carry, params, table, active):
        (cache, y), _ = _decode_step_math(carry, params, active, config)
        tok = jnp.argmax(y[:, 0, :], axis=-1).astype(jnp.int32)
        x2 = jnp.take(table, tok, axis=0)[:, None, :].astype(y.dtype)
        return (cache, x2), tok

    x_sh = NamedSharding(mesh, decode_batch_spec(mesh))
    dp_ax = decode_batch_spec(mesh)[0]
    return jax.jit(
        decode_token_step,
        donate_argnums=(0,),
        out_shardings=((cache_shardings(mesh), x_sh),
                       NamedSharding(mesh, P(dp_ax))),
    )


def build_decode_fused_token(config: ModelConfig, mesh: Mesh, k: int):
    """The fused K-step scan in token-feedback mode: identical trip
    structure to ``build_decode_fused`` (lengths recomputed per trip
    from the replicated inputs — same dp-reshard hazard, same fix) with
    the greedy token quantisation between trips.  Returns ``(carry,
    toks [k, B])``."""

    def decode_fused_token(carry, params, table, active, remaining):
        cache0, x0 = carry
        lengths0 = cache0.lengths
        act_i32 = active.astype(jnp.int32)

        def step(c, _):
            k_c, v_c, x, i = c
            step_active = active & (i < remaining)
            lengths_i = lengths0 + act_i32 * jnp.minimum(i, remaining)
            (cache, _x2), y = _decode_step_math(
                (KVCache(k_c, v_c, lengths_i), x), params, step_active,
                config)
            tok = jnp.argmax(y[:, 0, :], axis=-1).astype(jnp.int32)
            x2 = jnp.take(table, tok, axis=0)[:, None, :].astype(x.dtype)
            return (cache.k, cache.v, x2, i + 1), tok

        (k_c, v_c, x, _i), toks = jax.lax.scan(
            step, (cache0.k, cache0.v, x0, jnp.int32(0)), None, length=k)
        lengths_f = lengths0 + act_i32 * jnp.minimum(jnp.int32(k),
                                                     remaining)
        return (KVCache(k_c, v_c, lengths_f), x), toks

    x_sh = NamedSharding(mesh, decode_batch_spec(mesh))
    dp_ax = decode_batch_spec(mesh)[0]
    return jax.jit(
        decode_fused_token,
        donate_argnums=(0,),
        out_shardings=((cache_shardings(mesh), x_sh),
                       NamedSharding(mesh, P(None, dp_ax))),
    )


def build_verify_step(config: ModelConfig, mesh: Mesh, gamma: int):
    """Jitted draft-and-verify target forward: the γ proposed tokens of
    every slot run through ONE batched ``[max_batch, γ+1, H]``
    ``_serve_block`` stack under the per-slot offset-causal mask
    (``_verify_attention``) — one fused forward per verify unit, zero
    per-draft-token dispatches or collectives (audited:
    ``verify_step_expectation``).

    Inputs: the donated ``(cache, x)`` carry, the token table, the
    drafters' ``draft_ids [B, γ]``, ``active`` and ``remaining`` (each
    slot's output-token budget).  Per layer, all γ+1 positions append
    K/V at ``lengths + i`` via one-hot masked writes (the decode-step
    append, γ+1 times), exactly as γ+1 sequential decode steps would.

    Greedy acceptance: ``tok = argmax(y)`` gives the target's true
    token at every position; the accepted prefix length is the run of
    leading draft/target matches, and ``commits = min(accepted+1,
    remaining)`` (the +1 is the verify's own bonus token — the target
    output at the first mismatch position, whose input was still a
    verified token).  New lengths advance by ``commits``; the rejected
    suffix's cache entries are DEAD BY CONSTRUCTION — attention is
    length-masked, and the next unit's writes land at the committed
    lengths, overwriting every rejected position before any later
    query's mask can reach it (asserted by the token-identity tests,
    never copied or zeroed).  ``x'`` is the last committed token's
    embedding, so the carry protocol is unchanged.

    Returns ``(carry, tok [B, γ+1], commits [B])``; tok/commits stay
    dp-sharded (no boundary gather — the host reads them at the unit's
    sync)."""
    n, d, kvh = config.num_heads, config.head_dim, config.kv_heads
    g1 = gamma + 1

    def verify_step(carry, params, table, draft_ids, active, remaining):
        cache, x = carry
        b_dim, s_max = cache.max_batch, cache.max_seq
        nb, bs = cache.num_blocks, cache.block_size
        lengths = cache.lengths
        d_emb = jnp.take(table, draft_ids, axis=0).astype(x.dtype)
        h0 = jnp.concatenate([x, d_emb], axis=1)        # [B, γ+1, H]
        pos = jnp.arange(s_max)[None, :]                # [1, S]
        offs = lengths[:, None] + jnp.arange(g1)[None, :]   # [B, γ+1]
        valid = pos[:, None, :] <= offs[:, :, None]     # [B, γ+1, S]

        def attention_step(q, k, v, cache_state):
            k_l, v_l = cache_state
            qh = _heads(q, n, d)                        # [B, n, γ+1, d]
            k_new = k.reshape(b_dim, g1, kvh, d)
            v_new = v.reshape(b_dim, g1, kvh, d)
            k_flat = k_l.reshape(b_dim, s_max, kvh, d)
            v_flat = v_l.reshape(b_dim, s_max, kvh, d)
            # γ+1 one-hot appends at each slot's own running length —
            # the decode-step masked write, unrolled over the verify
            # positions (static γ, so this stays collective-free
            # elementwise selects)
            for i in range(g1):
                m = ((pos == lengths[:, None] + i)
                     & active[:, None])[..., None, None]
                k_flat = jnp.where(m, k_new[:, i][:, None], k_flat)
                v_flat = jnp.where(m, v_new[:, i][:, None], v_flat)
            attn = _verify_attention(qh, k_flat, v_flat, valid)
            return (attn.transpose(0, 2, 1, 3).reshape(b_dim, g1, n * d),
                    (k_flat.reshape(b_dim, nb, bs, kvh, d),
                     v_flat.reshape(b_dim, nb, bs, kvh, d)))

        def body(h, layer_and_cache):
            layer, k_l, v_l = layer_and_cache
            return _serve_block(h, layer, config, attention_step,
                                (k_l, v_l))

        h, (k_new, v_new) = jax.lax.scan(
            body, h0, (params["layers"], cache.k, cache.v)
        )
        y = _layernorm(h, params["ln_f"]["scale"], params["ln_f"]["bias"])
        tok = jnp.argmax(y, axis=-1).astype(jnp.int32)  # [B, γ+1]
        match = (tok[:, :gamma] == draft_ids).astype(jnp.int32)
        accepted = jnp.sum(jnp.cumprod(match, axis=1), axis=1)  # [B]
        commits = jnp.where(active,
                            jnp.minimum(accepted + 1, remaining),
                            0).astype(jnp.int32)
        lengths_f = (lengths + commits).astype(jnp.int32)
        last = jnp.take_along_axis(
            tok, jnp.maximum(commits - 1, 0)[:, None], axis=1)[:, 0]
        x_new = jnp.take(table, last, axis=0)[:, None, :].astype(x.dtype)
        x_f = jnp.where(active[:, None, None], x_new, x)
        return (KVCache(k_new, v_new, lengths_f), x_f), tok, commits

    x_sh = NamedSharding(mesh, decode_batch_spec(mesh))
    dp_ax = decode_batch_spec(mesh)[0]
    return jax.jit(
        verify_step,
        donate_argnums=(0,),
        out_shardings=((cache_shardings(mesh), x_sh),
                       NamedSharding(mesh, P(dp_ax, None)),
                       NamedSharding(mesh, P(dp_ax))),
    )


def build_verify_probs(config: ModelConfig, mesh: Mesh, gamma: int):
    """The SAMPLED verify's device half: ``build_verify_step``'s exact
    batched γ+1-position forward (same one-hot K/V appends at
    ``lengths + i``, same offset-causal mask), but acceptance moves to
    the HOST — the program returns the raw verify logits ``y [B, γ+1,
    H]`` and commits NOTHING: lengths and ``x`` come back unchanged,
    so the appended-but-uncommitted cache positions sit past every
    slot's length (dead by the usual mask construction) until the
    host's residual-sampling pass decides the true commits and the
    tiny ``build_spec_commit`` program advances the carry.  Re-running
    the program on the returned carry is therefore idempotent — the
    retry ladder's contract.

    ``gamma=0`` degenerates to a plain decode step that returns its
    softmax-able logits without committing — the sampled path's
    cold-drafter fallback unit (one sampled token per trip)."""
    g1 = gamma + 1

    def verify_probs(carry, params, table, draft_ids, active):
        cache, x = carry
        b_dim, s_max = cache.max_batch, cache.max_seq
        nb, bs = cache.num_blocks, cache.block_size
        n, d, kvh = config.num_heads, config.head_dim, config.kv_heads
        lengths = cache.lengths
        d_emb = jnp.take(table, draft_ids, axis=0).astype(x.dtype)
        h0 = jnp.concatenate([x, d_emb], axis=1)        # [B, γ+1, H]
        pos = jnp.arange(s_max)[None, :]                # [1, S]
        offs = lengths[:, None] + jnp.arange(g1)[None, :]   # [B, γ+1]
        valid = pos[:, None, :] <= offs[:, :, None]     # [B, γ+1, S]

        def attention_step(q, k, v, cache_state):
            k_l, v_l = cache_state
            qh = _heads(q, n, d)
            k_new = k.reshape(b_dim, g1, kvh, d)
            v_new = v.reshape(b_dim, g1, kvh, d)
            k_flat = k_l.reshape(b_dim, s_max, kvh, d)
            v_flat = v_l.reshape(b_dim, s_max, kvh, d)
            for i in range(g1):
                m = ((pos == lengths[:, None] + i)
                     & active[:, None])[..., None, None]
                k_flat = jnp.where(m, k_new[:, i][:, None], k_flat)
                v_flat = jnp.where(m, v_new[:, i][:, None], v_flat)
            attn = _verify_attention(qh, k_flat, v_flat, valid)
            return (attn.transpose(0, 2, 1, 3).reshape(b_dim, g1, n * d),
                    (k_flat.reshape(b_dim, nb, bs, kvh, d),
                     v_flat.reshape(b_dim, nb, bs, kvh, d)))

        def body(h, layer_and_cache):
            layer, k_l, v_l = layer_and_cache
            return _serve_block(h, layer, config, attention_step,
                                (k_l, v_l))

        h, (k_new, v_new) = jax.lax.scan(
            body, h0, (params["layers"], cache.k, cache.v)
        )
        y = _layernorm(h, params["ln_f"]["scale"], params["ln_f"]["bias"])
        return (KVCache(k_new, v_new, lengths), x), y

    x_sh = NamedSharding(mesh, decode_batch_spec(mesh))
    dp_ax = decode_batch_spec(mesh)[0]
    return jax.jit(
        verify_probs,
        donate_argnums=(0,),
        out_shardings=((cache_shardings(mesh), x_sh),
                       NamedSharding(mesh, P(dp_ax, None, None))),
    )


def build_spec_commit(config: ModelConfig, mesh: Mesh):
    """The sampled verify's commit half: the host's residual-sampling
    pass decided ``commits`` (per-slot committed window length) and
    ``next_ids`` (each slot's LAST committed token — the next unit's
    input); this tiny program advances lengths by the commits and
    re-embeds ``x`` from the token table, completing exactly the carry
    protocol ``build_verify_step`` applies on device for the greedy
    law.  The rejected suffix needs no cleanup — same dead-by-
    construction argument as the greedy verify."""

    def spec_commit(carry, table, next_ids, commits, active):
        cache, x = carry
        lengths_f = (cache.lengths + commits).astype(jnp.int32)
        emb = jnp.take(table, next_ids, axis=0)[:, None, :].astype(x.dtype)
        x_f = jnp.where(active[:, None, None], emb, x)
        return (KVCache(cache.k, cache.v, lengths_f), x_f)

    x_sh = NamedSharding(mesh, decode_batch_spec(mesh))
    return jax.jit(
        spec_commit,
        donate_argnums=(0,),
        out_shardings=(cache_shardings(mesh), x_sh),
    )


def build_draft_scan(config: ModelConfig, mesh: Mesh, gamma: int):
    """Jitted draft-model proposal scan: γ greedy token-feedback decode
    steps of the SHALLOW draft transformer over its own donated paged
    cache plane — ``draft_scan(cache, params, table, x, lengths,
    active) -> (cache, draft_ids [B, γ])``.

    ``x`` is the TARGET's current carry input (the draft shares the
    target's hidden size and token table, so the committed-token
    embedding is the right draft input); ``lengths`` are the HOST'S
    committed lengths, passed explicitly — this IS the draft plane's
    rejection rollback: the cache's own lengths leaf (advanced by γ
    last unit) is simply overridden, and entries past the committed
    lengths are dead by the same length-mask construction as the
    target's.  The ids stay on device (dp-sharded) and flow straight
    into the verify step — no host round-trip in the draft-verify
    chain."""

    def draft_scan(cache, params, table, x, lengths, active):
        act_i32 = active.astype(jnp.int32)

        def step(c, _):
            k_c, v_c, x_c, i = c
            lengths_i = lengths + act_i32 * i
            (cache_i, _x2), y = _decode_step_math(
                (KVCache(k_c, v_c, lengths_i), x_c), params, active,
                config)
            tok = jnp.argmax(y[:, 0, :], axis=-1).astype(jnp.int32)
            x2 = jnp.take(table, tok, axis=0)[:, None, :].astype(x_c.dtype)
            return (cache_i.k, cache_i.v, x2, i + 1), tok

        (k_c, v_c, _x, _i), toks = jax.lax.scan(
            step, (cache.k, cache.v, x, jnp.int32(0)), None, length=gamma)
        lengths_f = lengths + act_i32 * gamma
        return KVCache(k_c, v_c, lengths_f), toks.T    # ids [B, γ]

    dp_ax = decode_batch_spec(mesh)[0]
    return jax.jit(
        draft_scan,
        donate_argnums=(0,),
        out_shardings=(cache_shardings(mesh),
                       NamedSharding(mesh, P(dp_ax, None))),
    )


def _ngram_propose(hist: list, gamma: int,
                   max_ngram: int = 3) -> Optional[list]:
    """Prompt-lookup / n-gram drafting (Saxena 2023): find the most
    recent earlier occurrence of the history's trailing n-gram (n from
    ``max_ngram`` down to 1) in ``hist`` (= the request's prompt token
    ids + every committed token) and propose the γ ids that followed
    it.  When the match sits d < γ positions back, the continuation
    runs off the end of the history after d tokens — but a trailing
    match at distance d means the history is locally d-periodic, so
    the proposal extends CYCLICALLY through that period rather than
    flat-padding (greedy feedback through a fixed table falls into
    short cycles, and cyclic extension is what lets a γ≫d proposal
    stay correct for the whole window).  Pure, deterministic function
    of the history — drafter determinism from trace seeds is a test
    invariant.  None = cold (no occurrence of even the last token):
    the scheduler falls back to a plain decode unit."""
    ln = len(hist)
    for n in range(min(max_ngram, ln - 1), 0, -1):
        key = hist[ln - n:]
        for start in range(ln - n - 1, -1, -1):
            if hist[start:start + n] == key:
                cont = list(hist[start + n:start + n + gamma])
                if len(cont) < gamma:
                    d = len(cont)  # == distance back to the match
                    cont += [cont[i % d] for i in range(d, gamma)]
                return cont
    return None


def softmax_np(logits: np.ndarray, temperature: float) -> np.ndarray:
    """Host-side temperature softmax (float64, max-subtracted) — the
    sampled path's target law ``p``.  The device never softmaxes: the
    verify logits come to host raw and every probability the sampler
    consumes is computed here, so the sampled law is exactly
    reproducible from the journal'd seeds."""
    z = np.asarray(logits, np.float64) / float(temperature)
    z = z - z.max(axis=-1, keepdims=True)
    e = np.exp(z)
    return e / e.sum(axis=-1, keepdims=True)


def residual_distribution(p_target: np.ndarray,
                          q_draft: np.ndarray) -> np.ndarray:
    """The rejection-correction distribution of speculative SAMPLING
    (Leviathan et al. 2023): ``norm(max(p - q, 0))``.  Degenerates to
    ``p`` when ``q`` dominates it everywhere (rejection then has zero
    probability, so the branch is never taken)."""
    resid = np.maximum(np.asarray(p_target, np.float64)
                       - np.asarray(q_draft, np.float64), 0.0)
    z = resid.sum()
    if z <= 0.0:
        return np.asarray(p_target, np.float64)
    return resid / z


def speculative_sample(p_target: np.ndarray, q_draft: np.ndarray,
                       draft_id: int,
                       rng: np.random.Generator) -> tuple[int, bool]:
    """One position of the residual-sampling correction — HOW the
    equivalence gate weakens for sampled (temperature > 0) decode:
    accept the drafted token with probability ``min(1, p/q)``; on
    rejection, sample from ``residual_distribution(p, q)``.  The
    composite law is exactly ``p`` (distribution-identity, pinned by
    ``tests/test_speculative.py``), so sampled speculative decode is
    distribution-identical — not token-identical — to the sequential
    sampler.  The engine's default serving path is greedy (argmax),
    which this correction degenerates to as temperature -> 0; with
    ``serving.temperature > 0`` the scheduler's verify units run this
    helper position-by-position over the host-side verify softmax
    (``q`` = the deterministic drafter's one-hot, so acceptance is
    ``p[draft]`` and the residual is ``p`` with the draft's mass
    removed — docs/serving.md)."""
    p = float(p_target[draft_id])
    q = float(q_draft[draft_id])
    accept_p = 1.0 if q <= 0.0 and p > 0.0 else (
        min(1.0, p / q) if q > 0.0 else 0.0)
    if rng.uniform() < accept_p:
        return int(draft_id), True
    resid = residual_distribution(p_target, q_draft)
    return int(rng.choice(len(resid), p=resid)), False


def _with_deadline(fn, deadline: Optional[float], label: str,
                   phase: str) -> Any:
    """Run ``fn()`` under the serving dispatch watchdog (the PR-5
    daemon-thread pattern, ``bench/runner._call_with_deadline``).

    With no deadline this is a direct call — zero threads, zero
    overhead.  With one, ``fn`` runs on a daemon thread joined for
    ``deadline`` seconds; an overrun ABANDONS the thread (it may be
    wedged inside the runtime and cannot be killed) and raises
    :class:`DeadlineExceeded` — the engine then fails the unit's
    requests closed and continues on a fresh carry, so the zombie's
    eventual outputs (if any) are never consumed."""
    if deadline is None:
        return fn()
    box: dict[str, Any] = {}

    def target() -> None:
        try:
            box["value"] = fn()
        except BaseException as e:  # noqa: BLE001 — marshalled to caller
            box["error"] = e

    t = threading.Thread(target=target, daemon=True,
                         name=f"dlbb-serve-{phase}-{label}")
    t.start()
    t.join(deadline)
    if t.is_alive():
        raise DeadlineExceeded(label, deadline, phase=phase)
    if "error" in box:
        raise box["error"]
    return box["value"]


# ---------------------------------------------------------------------------
# the engine
# ---------------------------------------------------------------------------


@dataclass
class _SlotState:
    req: Request
    tokens_done: int = 0
    admitted_s: float = 0.0
    first_token_s: float = 0.0
    # adaptive speculation: this request's current verify γ (a ladder
    # bucket) and its acceptance-rate EMA (-1 = no verify observed yet)
    gamma_eff: int = 0
    accept_ema: float = -1.0


@dataclass
class _RunStats:
    ttft_s: list[float] = field(default_factory=list)
    per_token_s: list[float] = field(default_factory=list)
    prefill_s: list[float] = field(default_factory=list)
    decode_step_s: list[float] = field(default_factory=list)
    e2e_latency_s: list[float] = field(default_factory=list)
    completed_output_tokens: int = 0
    generated_tokens: int = 0
    decode_steps: int = 0       # decode steps executed (fused trips count)
    decode_units: int = 0       # host dispatches (a fused scan is ONE)
    fused_scans: int = 0
    fused_steps: int = 0
    single_steps: int = 0
    prefill_chunks: int = 0
    compacted_scans: int = 0
    # resilience accounting (docs/resilience.md, serving-faults section)
    retries: int = 0
    hung_dispatches: int = 0
    failed_requests: int = 0
    preempted_requests: int = 0
    deadline_shed: int = 0
    completed_past_deadline: int = 0
    # speculative decoding (docs/serving.md, "Speculative decoding")
    spec_verify_units: int = 0      # draft-and-verify dispatches
    spec_fallback_units: int = 0    # cold-drafter plain-decode fallbacks
    spec_proposed_tokens: int = 0   # γ per resident slot per verify
    spec_accepted_tokens: int = 0   # drafts the target verify accepted
    spec_commit_tokens: int = 0     # committed incl. the bonus token
    spec_slot_verifies: int = 0     # slot-level verifies (for mean len)
    spec_draft_s: float = 0.0       # host drafting / draft-scan wall
    # shared-prefix cache (docs/serving.md, "Prefix cache & quantized KV")
    prefix_hits: int = 0            # admissions that attached to the trie
    prefix_tokens_reused: int = 0   # prompt tokens served from shared blocks
    prefix_cow_blocks: int = 0      # blocks rewritten privately (CoW)


class ServingEngine:
    """Trace-driven continuous-batching engine (see module docstring).

    One engine serves many traces: each :meth:`run_trace` starts from a
    fresh cache.  The journal (``resilience.journal.SweepJournal``) and
    metrics registry are optional — the bench harness wires both."""

    def __init__(
        self,
        config: ModelConfig,
        serving: ServingConfig,
        mesh: Mesh,
        params: Any = None,
        journal: Any = None,
        registry: Optional[MetricsRegistry] = None,
        seed: int = 0,
        verbose: bool = True,
        capture_tokens: bool = False,
    ) -> None:
        axes = mesh.axis_names
        self.dp = mesh.shape["dp"] if "dp" in axes else 1
        self.tp = mesh.shape["tp"] if "tp" in axes else 1
        serving.validate(config, dp=self.dp, tp=self.tp)
        self.config = config
        self.serving = serving
        self.mesh = mesh
        self.verbose = verbose
        # the equivalence gate: argmax "token ids" of every generated
        # output recorded per request (syncs each unit — leave off for
        # perf runs)
        self.capture_tokens = capture_tokens
        # public and reassignable: the bench wires one journal per run
        # directory; tests swap it between run_trace calls
        self.journal = journal
        # fleet-replica control plane for the CURRENT run (run_trace's
        # ``control=``); None outside a fleet
        self._control: Any = None
        self.registry = registry if registry is not None else MetricsRegistry()
        self._requests = self.registry.labeled_counter(
            "serve_requests", "outcome",
            initial=("arrived", "admitted", "rejected", "completed",
                     "failed", "preempted", "canceled"),
            help="request lifecycle outcomes",
        )
        self._rejections = self.registry.labeled_counter(
            "serve_rejections", "reason",
            initial=("queue-full", "infeasible", "deadline"),
            help="requests shed, by rejection reason",
        )
        self._retry_counter = self.registry.labeled_counter(
            "serve_request_retries", "phase",
            initial=("prefill", "decode", "bookkeeping"),
            help="transient dispatch/bookkeeping retries, by phase",
        )
        self._deadline_counter = self.registry.labeled_counter(
            "serve_deadline_exceeded", "reason",
            initial=("shed-queued", "completed-late"),
            help="per-request SLO deadline misses, by how they surfaced",
        )
        for name, hlp in (
            ("serve_decode_steps",
             "decode steps executed (each fused-scan trip counts once)"),
            ("serve_fused_scan_steps",
             "decode steps executed inside fused lax.scan dispatches"),
            ("serve_prefill_chunks", "prefill chunks processed"),
            ("serve_hung_dispatches",
             "decode units abandoned by the dispatch watchdog"),
        ):
            self.registry.inc(name, 0, help=hlp)
        self._quantized = serving.kv_quantization == "int8"
        if serving.prefix_caching:
            for name, hlp in (
                ("serve_prefix_hits",
                 "admissions that attached to shared prefix blocks"),
                ("serve_prefix_tokens_reused",
                 "prompt tokens served from shared blocks (prefill "
                 "skipped)"),
            ):
                self.registry.inc(name, 0, help=hlp)
        self._dtype = _dtype_of(config.dtype)
        self.params = (params if params is not None
                       else init_params_sharded(config, jax.random.key(seed),
                                                mesh))
        self._prefill = build_prefill(config, mesh,
                                      quantized=self._quantized)
        self._decode = build_decode_step(config, mesh,
                                         quantized=self._quantized)
        self._fused_ks = serving.fused_horizons
        self._decode_fused = {
            k: build_decode_fused(config, mesh, k,
                                  quantized=self._quantized)
            for k in self._fused_ks
        }
        self._prefill_chunk_jits: dict[int, Any] = {}
        self._attach_jits: dict[int, Any] = {}
        self._compact_gather_fn = None
        self._compact_scatter_fn = None
        if serving.compact_threshold is not None:
            self._compact_gather_fn = build_compact_gather(mesh)
            self._compact_scatter_fn = build_compact_scatter(mesh)
        self._fast = (serving.decode_horizon > 1
                      or serving.inflight_window > 1
                      or serving.prefill_chunk is not None
                      or serving.compact_threshold is not None)
        self._inject = jax.jit(_inject_token, donate_argnums=(0,))
        self._x_sharding = NamedSharding(mesh, decode_batch_spec(mesh))
        self._active_sharding = NamedSharding(mesh, P())
        # -- speculative decoding (docs/serving.md) --
        # token-feedback modes quantise decode through the greedy token
        # table; the legacy jits above stay built (jax.jit is lazy, so
        # an unused ladder costs nothing) and the "off" path is
        # bit-for-bit untouched
        self._token_mode = serving.speculation != "off"
        # non-adaptive runs verify at exactly spec_gamma; adaptive runs
        # need the whole back-off ladder compiled
        self._spec_gammas: tuple[int, ...] = (
            serving.spec_gammas if serving.spec_adaptive
            else ((serving.spec_gamma,) if serving.spec_drafting else ()))
        self._table: Optional[jax.Array] = None
        self._decode_token = None
        self._decode_fused_token: dict[int, Any] = {}
        self._verify: dict[int, Any] = {}
        self._draft_config: Optional[ModelConfig] = None
        self._draft_params: Any = None
        self._draft_prefill = None
        self._draft_scan: dict[int, Any] = {}
        if self._token_mode:
            self._table = jax.device_put(
                token_embedding_table(config.hidden_size, self._dtype),
                NamedSharding(mesh, P()))
            self._decode_token = build_decode_token_step(config, mesh)
            self._decode_fused_token = {
                k: build_decode_fused_token(config, mesh, k)
                for k in self._fused_ks
            }
            self._inject_greedy = jax.jit(_inject_token_greedy,
                                          donate_argnums=(0,))
            dp_ax = decode_batch_spec(mesh)[0]
            self._ids_sharding = NamedSharding(mesh, P(dp_ax, None))
        # sampled (temperature > 0) decode: host residual sampling over
        # the verify logits — verify_probs/spec_commit replace the
        # greedy on-device verify, and the cold-drafter fallback is the
        # γ=0 probs program (one sampled token per trip), so a sampled
        # run NEVER dispatches a greedy token program after prefill
        self._sampled = serving.temperature > 0
        self._verify_probs: dict[int, Any] = {}
        self._spec_commit = None
        self._inject_sampled = None
        if self._sampled:
            probs_gammas = set(self._spec_gammas)
            if serving.speculation == "ngram":
                probs_gammas.add(0)     # the cold-drafter fallback unit
            self._verify_probs = {g: build_verify_probs(config, mesh, g)
                                  for g in sorted(probs_gammas)}
            self._spec_commit = build_spec_commit(config, mesh)
            self._inject_sampled = jax.jit(_inject_token_sampled,
                                           donate_argnums=(0,))
            self.registry.inc(
                "serve_sampled_tokens", 0,
                help="tokens committed by the sampled (temperature > 0) "
                     "residual-sampling path")
        if serving.spec_drafting:
            self._verify = {g: build_verify_step(config, mesh, g)
                            for g in self._spec_gammas}
            self._spec_proposed = self.registry.labeled_counter(
                "serve_spec_proposed_total", "drafter",
                initial=("ngram", "draft-model"),
                help="draft tokens proposed to the verify step, by drafter",
            )
            self._spec_accepted = self.registry.labeled_counter(
                "serve_spec_accepted_total", "drafter",
                initial=("ngram", "draft-model"),
                help="draft tokens the target verify accepted, by drafter",
            )
        if serving.speculation == "draft-model":
            self._draft_config = serving.draft_model_config(config)
            # the draft model is the ENGINE's (never caller-supplied):
            # derived deterministically from the seed so replays draft
            # identically; sharded by the same ParallelismPlan
            self._draft_params = init_params_sharded(
                self._draft_config, jax.random.key(seed + 1), mesh)
            self._draft_prefill = build_prefill(self._draft_config, mesh)
            self._draft_scan = {
                g: build_draft_scan(self._draft_config, mesh, g)
                for g in self._spec_gammas
            }
        self._t0 = time.perf_counter()

    # -- clock (monotonic, run-relative) -----------------------------------

    def _now(self) -> float:
        return time.perf_counter() - self._t0

    # -- setup -------------------------------------------------------------

    def _fresh_carry(self):
        create = (create_quant_kv_cache if self._quantized
                  else create_kv_cache)
        cache = create(
            self.config, self.serving.max_batch, self.serving.num_blocks,
            self.serving.block_size, mesh=self.mesh,
        )
        x = jax.device_put(
            jnp.zeros((self.serving.max_batch, 1, self.config.hidden_size),
                      self._dtype),
            self._x_sharding,
        )
        return (cache, x)

    def _fresh_draft_cache(self) -> Optional[KVCache]:
        """The draft model's own paged KV plane (same slot/block
        geometry as the target's — both planes cover max_seq tokens per
        slot — at the draft config's layer/kv-head dims).  None when no
        draft model is configured, so every carry-reset site can assign
        unconditionally."""
        if self._draft_config is None:
            return None
        return create_kv_cache(
            self._draft_config, self.serving.max_batch,
            self.serving.num_blocks, self.serving.block_size,
            mesh=self.mesh,
        )

    def capture_device_traces(self, trace_root: Any) -> list[dict]:
        """Serving capture parity with the sweep engine's gated capture
        (docs/observability.md): ONE dedicated prefill and ONE decode
        scan (fused when the fast path is configured) captured through
        ``obs/capture.py`` on FRESH state, strictly outside every timed
        region — the bench calls this after ``run_trace`` has returned,
        so no capture overhead can touch TTFT/goodput.  Each returned
        meta carries its ``phase`` so the devtrace report renders
        per-phase rows; failures are contained in the metas exactly as
        sweep captures are."""
        from dlbb_tpu.obs import capture as obs_capture

        cfg = self.serving
        bucket = cfg.prefill_buckets[0]

        def prefill_payload():
            carry = self._fresh_carry()
            x = request_embeddings(0, bucket, self.config.hidden_size,
                                   dtype=self._dtype, pad_to=bucket)
            return (carry[0], x)

        def prefill_fn(t):
            return self._prefill(t[0], self.params, t[1], np.int32(0),
                                 np.int32(bucket))

        metas = [obs_capture.capture_device_trace(
            prefill_fn, prefill_payload, trace_root,
            label=f"serve_prefill_b{bucket}")]
        metas[0]["phase"] = "prefill"

        if self._fast and self._fused_ks:
            k = min(self._fused_ks)
            fused = (self._decode_fused_token[k] if self._token_mode
                     else self._decode_fused[k])

            if self._token_mode:
                def decode_fn(t):
                    return fused(t[0], self.params, self._table, t[1],
                                 t[2])
            else:
                def decode_fn(t):
                    return fused(t[0], self.params, t[1], t[2])

            def decode_payload():
                return (self._fresh_carry(), self._zero_active(),
                        self._zero_remaining())

            label = (f"serve_decode_fused_token_k{k}" if self._token_mode
                     else f"serve_decode_fused_k{k}")
        else:
            if self._token_mode:
                def decode_fn(t):
                    return self._decode_token(t[0], self.params,
                                              self._table, t[1])
            else:
                def decode_fn(t):
                    return self._decode(t[0], self.params, t[1])

            def decode_payload():
                return (self._fresh_carry(), self._zero_active())

            label = ("serve_decode_token_step" if self._token_mode
                     else "serve_decode_step")
        meta = obs_capture.capture_device_trace(
            decode_fn, decode_payload, trace_root, label=label)
        meta["phase"] = "decode"
        # token steps the captured program executes per dispatch — the
        # run's scans vary k, so downstream device-time accounting must
        # normalise per STEP, never per dispatch
        meta["decode_steps_per_scan"] = (min(self._fused_ks)
                                         if self._fast and self._fused_ks
                                         else 1)
        metas.append(meta)
        return metas

    def _zero_active(self) -> jax.Array:
        return jax.device_put(
            jnp.zeros((self.serving.max_batch,), bool),
            self._active_sharding)

    def _zero_remaining(self) -> jax.Array:
        return jax.device_put(
            jnp.zeros((self.serving.max_batch,), jnp.int32),
            self._active_sharding)

    def _infeasible_reason(self, r: Request) -> Optional[str]:
        """Why the envelope can never serve ``r`` (None = feasible)."""
        max_bucket = self.serving.prefill_buckets[-1]
        if r.output_len < 1:
            return f"output_len must be >= 1 (got {r.output_len})"
        if r.prompt_len < 1 or r.prompt_len > max_bucket:
            return (f"prompt_len={r.prompt_len} outside (0, {max_bucket}] "
                    "(largest prefill bucket)")
        if r.total_tokens > self.serving.max_seq:
            return (f"prompt+output={r.total_tokens} exceeds "
                    f"serving.max_seq={self.serving.max_seq} "
                    "(per-slot cache capacity)")
        need = max(1, math.ceil(r.total_tokens / self.serving.block_size))
        if need > self.serving.total_blocks:
            return (f"needs {need} cache blocks, budget is "
                    f"{self.serving.total_blocks} (serving.blocks_budget)")
        return None

    def _validate_trace(self, trace: TrafficTrace) -> None:
        """Fail BEFORE the run on any request the config cannot serve —
        an infeasible request rejected mid-trace would read as load.
        (``serving.reject_infeasible`` flips this into per-request
        runtime rejection, journaled with reason="infeasible".)"""
        for r in trace:
            reason = self._infeasible_reason(r)
            if reason is not None:
                raise ValueError(f"request {r.rid}: {reason}")

    def _chunk_jit(self, chunk_index: int):
        """The chunked-prefill jit for static chunk offset
        ``chunk_index * prefill_chunk`` (one retrace per offset — the
        bucketed chunk ladder; built lazily, warmed by ``_compile``)."""
        jit = self._prefill_chunk_jits.get(chunk_index)
        if jit is None:
            chunk = self.serving.prefill_chunk
            jit = build_prefill_chunk(self.config, self.mesh, chunk,
                                      chunk_index * chunk,
                                      quantized=self._quantized)
            self._prefill_chunk_jits[chunk_index] = jit
        return jit

    def _attach_jit(self, m_chunks: int):
        """The prefix-attach jit for ``m_chunks`` matched chunks (one
        retrace per matched chunk count — the same bucketing as the
        chunk-jit ladder; built lazily, warmed by ``_compile``)."""
        jit = self._attach_jits.get(m_chunks)
        if jit is None:
            chunk = self.serving.prefill_chunk
            jit = build_prefix_attach(self.config, self.mesh,
                                      m_chunks * chunk,
                                      self.serving.block_size,
                                      quantized=self._quantized)
            self._attach_jits[m_chunks] = jit
        return jit

    def _compile(self, buckets: list[int], max_chunks: int = 0) -> None:
        """Warm every jit the trace will hit (prefill per bucket or per
        chunk offset, decode + the fused-scan ladder, compaction,
        inject) on scratch state, so compile time never lands in TTFT."""
        carry = self._fresh_carry()
        cfg = self.serving
        active = jax.device_put(
            jnp.zeros((cfg.max_batch,), bool), self._active_sharding,
        )
        y_last = None
        for b in buckets:
            dummy = request_embeddings(0, b, self.config.hidden_size,
                                       dtype=self._dtype, pad_to=b)
            cache, y_last = self._prefill(
                carry[0], self.params, dummy, np.int32(0), np.int32(b))
            carry = (cache, carry[1])
        if max_chunks:
            chunk = cfg.prefill_chunk
            total = max_chunks * chunk
            dummy = request_embeddings(0, total, self.config.hidden_size,
                                       dtype=self._dtype, pad_to=total)
            prefix = create_prefix(self.config, self.mesh)
            cache = carry[0]
            for ci in range(max_chunks):
                cache, prefix, y_last = self._chunk_jit(ci)(
                    cache, prefix, self.params,
                    dummy[:, ci * chunk:(ci + 1) * chunk],
                    np.int32(0), np.int32(total))
            if cfg.prefix_caching:
                # the attach ladder: one jit per possible matched chunk
                # count (a full prompt always keeps >= 1 unmatched
                # chunk, so the ladder stops at max_chunks - 1)
                for m in range(1, max_chunks):
                    cache, _prefix = self._attach_jit(m)(
                        cache, np.int32(0), np.int32(0))
            carry = (cache, carry[1])
        remaining = jax.device_put(
            jnp.zeros((cfg.max_batch,), jnp.int32), self._active_sharding)
        if self._token_mode:
            # token-feedback warms: the legacy inject/decode/fused jits
            # are never dispatched in a token-mode run, so warming them
            # would only burn compile time — and a SAMPLED run likewise
            # never dispatches the greedy inject/decode/verify programs
            # (its entire decode surface is verify_probs + spec_commit)
            if self._sampled:
                carry = self._inject_sampled(carry, np.int32(0),
                                             np.int32(0), self._table)
                zeros_i = jax.device_put(
                    jnp.zeros((cfg.max_batch,), jnp.int32),
                    self._active_sharding)
                for g in sorted(self._verify_probs):
                    ids = jax.device_put(
                        jnp.zeros((cfg.max_batch, g), jnp.int32),
                        self._ids_sharding)
                    carry, _y = self._verify_probs[g](
                        carry, self.params, self._table, ids, active)
                carry = self._spec_commit(carry, self._table, zeros_i,
                                          remaining, active)
            else:
                carry, _tok = self._inject_greedy(carry, np.int32(0),
                                                  y_last, self._table)
                carry, _tok = self._decode_token(carry, self.params,
                                                 self._table, active)
                for k in self._fused_ks:
                    carry, _toks = self._decode_fused_token[k](
                        carry, self.params, self._table, active,
                        remaining)
                for g in self._spec_gammas:
                    ids = jax.device_put(
                        jnp.zeros((cfg.max_batch, g), jnp.int32),
                        self._ids_sharding)
                    carry, _tok, _commits = self._verify[g](
                        carry, self.params, self._table, ids, active,
                        remaining)
            if self._draft_config is not None:
                dcache = self._fresh_draft_cache()
                for b in buckets:
                    dummy = request_embeddings(
                        0, b, self.config.hidden_size,
                        dtype=self._dtype, pad_to=b)
                    dcache, _dy = self._draft_prefill(
                        dcache, self._draft_params, dummy, np.int32(0),
                        np.int32(b))
                dlen = jax.device_put(
                    jnp.zeros((cfg.max_batch,), jnp.int32),
                    self._active_sharding)
                for g in self._spec_gammas:
                    dcache, _ids = self._draft_scan[g](
                        dcache, self._draft_params, self._table,
                        carry[1], dlen, active)
                jax.block_until_ready(dcache.lengths)
            jax.block_until_ready(carry[1])
            return
        carry = self._inject(carry, np.int32(0), y_last)
        carry, _y = self._decode(carry, self.params, active)
        for k in self._fused_ks:
            carry, _ys = self._decode_fused[k](carry, self.params, active,
                                               remaining)
        if self._compact_gather_fn is not None:
            bucket = cfg.max_batch // 2
            idx = jax.device_put(jnp.arange(bucket, dtype=jnp.int32),
                                 self._active_sharding)
            s_active = jax.device_put(jnp.zeros((bucket,), bool),
                                      self._active_sharding)
            s_rem = jax.device_put(jnp.zeros((bucket,), jnp.int32),
                                   self._active_sharding)
            small = self._compact_gather_fn(carry, idx)
            for k in self._fused_ks:
                small, _ys = self._decode_fused[k](small, self.params,
                                                   s_active, s_rem)
            carry = self._compact_scatter_fn(carry, small, idx)
        # block on the live carry, not an intermediate output: earlier
        # outputs may share buffers with a carry a later warm call donated
        jax.block_until_ready(carry[1])

    def _event(self, event: str, rid: int, **extra: Any) -> None:
        if self.journal is not None:
            self.journal.event(event, config=f"request-{rid}", **extra)
        ctl = self._control
        if ctl is not None and getattr(ctl, "on_event", None) is not None:
            # live lifecycle feed to the fleet supervisor (terminal
            # accounting, hedge winner detection); a sink failure must
            # never take the replica down — the journal line above is
            # already durable
            try:
                ctl.on_event(rid, event, dict(extra))
            except Exception:  # noqa: BLE001 — contained by contract
                pass

    # -- the run -----------------------------------------------------------

    def run_trace(self, trace: TrafficTrace,
                  guard: Optional[PreemptionGuard] = None,
                  collect_raw: bool = False,
                  feed: Any = None,
                  control: Any = None) -> dict[str, Any]:
        """Serve ``trace`` to completion (or to a graceful preemption
        drain); returns the report dict (``docs/serving.md`` documents
        every field).  Pure compute + host scheduling — writing
        artifacts is ``serve/bench.py``'s job.

        ``guard``: an installed :class:`PreemptionGuard` (the bench
        harness passes its own); None installs one for the run when
        possible (main thread).  On SIGTERM the engine stops admission,
        drains the in-flight window, journals still-resident requests
        ``request-preempted``, and returns a report with
        ``preempted=True`` + ``remaining_rids`` — the snapshot
        ``cli serve --resume`` replays.  ``collect_raw`` adds the raw
        latency sample lists to the report (``raw_samples``; always
        present on a preempted report so resume can merge honestly).

        ``feed``/``control`` are the fleet-replica hooks
        (``serve/fleet.py``): ``feed`` replaces the static arrival
        deque with a supervisor-fed :class:`~dlbb_tpu.serve.fleet.
        RequestFeed` (``trace`` is still used for compile planning and
        feasibility), and ``control`` is the replica control plane —
        heartbeat, kill/hang fault sites, hedge cancels, degradation
        overrides, and the fleet-shared clock origin — checked strictly
        at the scheduler-loop boundary."""
        if guard is None:
            with PreemptionGuard() as own:
                return self._serve_trace(trace, own, collect_raw,
                                         feed, control)
        return self._serve_trace(trace, guard, collect_raw, feed, control)

    def _serve_trace(self, trace: TrafficTrace, guard: PreemptionGuard,
                     collect_raw: bool, feed: Any = None,
                     control: Any = None) -> dict[str, Any]:
        self._control = control
        if not len(trace):
            raise ValueError("cannot serve an empty trace")
        cfg = self.serving
        if cfg.reject_infeasible:
            feasible = [r for r in trace
                        if self._infeasible_reason(r) is None]
            if not feasible:
                raise ValueError(
                    "every request in the trace is infeasible for this "
                    "serving envelope — nothing to serve"
                )
        else:
            self._validate_trace(trace)
            feasible = list(trace)
        if cfg.prefill_chunk is not None:
            buckets: list[int] = []
            max_chunks = max(-(-r.prompt_len // cfg.prefill_chunk)
                             for r in feasible)
        else:
            buckets = sorted({cfg.bucket_for(r.prompt_len)
                              for r in feasible})
            max_chunks = 0
        with Timer() as t_compile:
            self._compile(buckets, max_chunks)
        compile_time = t_compile.elapsed

        ledger = BlockLedger(cfg.total_blocks, cfg.block_size,
                             prefix_caching=cfg.prefix_caching)
        # registry counters are cumulative across an engine's lifetime
        # (Prometheus semantics); the report carries THIS run's deltas
        counts_base = {k: self._requests[k] for k in self._requests}
        shed_base = self._rejections["queue-full"]
        # a fleet supervisor feeds arrivals dynamically (and re-feeds
        # failovers at queue head); a standalone run serves the static
        # trace in arrival order
        pending = (feed if feed is not None
                   else deque(sorted(trace,
                                     key=lambda r: (r.arrival_s, r.rid))))
        queue: deque[Request] = deque()
        slots: dict[int, _SlotState] = {}
        free_slots = list(range(cfg.max_batch))
        stats = _RunStats()
        series: dict[str, list] = {
            "t_s": [], "queue_depth": [], "active_slots": [],
            "blocks_in_use": [], "blocks_reserved": [],
        }
        if cfg.prefix_caching:
            series["shared_blocks"] = []
        carry = self._fresh_carry()
        active_np = np.zeros((cfg.max_batch,), bool)
        active_dev = jax.device_put(jnp.asarray(active_np),
                                    self._active_sharding)
        rejected_detail: list[dict[str, Any]] = []
        tokens_by_rid: dict[int, list[int]] = {}
        # -- speculative decoding state (docs/serving.md) --
        token_mode = self._token_mode
        spec_on = cfg.spec_drafting
        # per-rid committed token history (prompt ids + every committed
        # token): the n-gram drafter's lookup context
        hist: dict[int, list[int]] = {}
        # sampled decode's host RNG: seeded from the config knob so a
        # (trace, config) pair replays token-for-token — the journal'd
        # runs stay deterministic even though the law is a distribution
        sample_rng = (np.random.default_rng(cfg.sample_seed)
                      if self._sampled else None)
        # the draft model's KV plane rides in a one-slot holder (the
        # closures below rebind it at every dispatch / carry reset);
        # its ledger mirrors the target's accounting — the draft plane
        # has the same slot/block geometry, and its COMMITTED content
        # tracks the target's exactly (draft writes past the committed
        # length are dead by the length-mask construction)
        draft_cache: list[Optional[KVCache]] = [self._fresh_draft_cache()]
        draft_ledger = (BlockLedger(cfg.total_blocks, cfg.block_size)
                        if draft_cache[0] is not None else None)
        # run-level acceptance EMA (the metrics.prom gauge)
        accept_ema_run = [-1.0]
        # per-request final outcome map (rid -> "completed" /
        # "rejected[reason]" / "failed[reason]" / "preempted") — the
        # thing kill-mid-trace ≡ uninterrupted equivalence is pinned on
        outcomes: dict[int, str] = {}
        # permanent-failure records: full exception chains, never a
        # silent skip (the serving twin of the sweep quarantine)
        failed_detail: list[dict[str, Any]] = []
        # bounded in-flight window: decode units dispatched but not yet
        # synced (cfg.inflight_window == 1 syncs every unit — the
        # legacy cadence); last_sync anchors the per-unit interval so
        # back-to-back units never double-count queued device time
        inflight: deque[dict[str, Any]] = deque()
        last_sync = [0.0]
        # host-side active_np mutations are staged; the device mask is
        # re-uploaded lazily, and ALWAYS before a decode dispatch — a
        # decode interleaved into the admission loop (chunked prefill)
        # must see slots admitted earlier in the same loop
        active_dirty = [False]

        def refresh_active() -> None:
            nonlocal active_dev
            if active_dirty[0]:
                active_dev = jax.device_put(jnp.asarray(active_np),
                                            self._active_sharding)
                active_dirty[0] = False

        def release(slot: int) -> _SlotState:
            """Host scan-exit: free a completed slot's blocks + slot so
            the next admission can reuse them (device order is safe —
            the scan already masked the slot inactive)."""
            st = slots.pop(slot)
            ledger.free(slot)
            if draft_ledger is not None:
                draft_ledger.free(slot)
            active_np[slot] = False
            active_dirty[0] = True
            free_slots.append(slot)
            free_slots.sort()
            return st

        def finish(st: _SlotState, done_at: float) -> None:
            """Completion stats + journal at the unit's SYNC point (the
            honest timestamp — the device work is provably done)."""
            lat = done_at - st.req.arrival_s
            stats.e2e_latency_s.append(lat)
            stats.completed_output_tokens += st.req.output_len
            self._requests["completed"] += 1
            outcomes[st.req.rid] = "completed"
            extra: dict[str, Any] = {}
            if st.req.deadline_s is not None and lat > st.req.deadline_s:
                # served, but past its SLO — a first-class count, not a
                # rejection (the tokens were delivered)
                stats.completed_past_deadline += 1
                self._deadline_counter["completed-late"] += 1
                extra["past_deadline"] = True
            if self.capture_tokens:
                # tokens ride the completion event so a fleet supervisor
                # keeps them even when this replica dies right after
                # (its report — the usual carrier — dies with it)
                extra["tokens"] = [int(t) for t in
                                   tokens_by_rid.get(st.req.rid, [])]
            self._event("request-completed", st.req.rid,
                        output_tokens=st.req.output_len,
                        latency_s=round(lat, 6), **extra)

        def take_snapshot() -> dict[str, Any]:
            """Pre-dispatch rollback point: the host ledger/slot/
            admission bookkeeping (tiny, host-only copies).  The device
            carry needs no snapshot because every fault site fires
            BEFORE the jit consumes it — a restored host state always
            matches the on-device state (docs/resilience.md)."""
            return {
                "ledger": ledger.snapshot(),
                "draft_ledger": (draft_ledger.snapshot()
                                 if draft_ledger is not None else None),
                "slots": {s: (st, st.tokens_done)
                          for s, st in slots.items()},
                "free_slots": list(free_slots),
                "active": active_np.copy(),
                "generated": stats.generated_tokens,
            }

        def restore_snapshot(snap: dict[str, Any]) -> None:
            ledger.restore(snap["ledger"])
            if draft_ledger is not None:
                draft_ledger.restore(snap["draft_ledger"])
            slots.clear()
            for s, (st, td) in snap["slots"].items():
                st.tokens_done = td
                slots[s] = st
            free_slots[:] = snap["free_slots"]
            active_np[:] = snap["active"]
            active_dirty[0] = True
            stats.generated_tokens = snap["generated"]

        def fail_requests(states: list[_SlotState], exc: BaseException,
                          reason: str) -> None:
            """Fail requests CLOSED: journaled ``request-failed`` with
            the full exception chain, outcome recorded, counters bumped
            — never a silent skip, and never the whole run."""
            rec = exception_chain(exc)
            rids = []
            for st in states:
                rids.append(st.req.rid)
                outcomes[st.req.rid] = f"failed[{reason}]"
                stats.failed_requests += 1
                self._requests["failed"] += 1
                self._event("request-failed", st.req.rid, reason=reason,
                            error=rec["error"],
                            tokens_done=st.tokens_done)
            failed_detail.append({"reason": reason, "rids": rids, **rec})

        def fail_resident(exc: BaseException, reason: str) -> None:
            """Fail every currently-resident request (the affected set
            of a permanently-failed or hung decode unit — decode covers
            the whole resident batch), freeing their slots + blocks."""
            fail_requests([release(s) for s in sorted(list(slots))],
                          exc, reason)

        def cancel_request(rid: int, reason: str) -> None:
            """Supervisor-requested cancel (serve/fleet.py: the losing
            hedge duplicate).  Resident: the in-flight window settles
            first so the release happens at a sync point, then the
            slot's blocks are freed.  Queued / not-yet-fed: the request
            is simply dropped.  An unknown rid is a benign race — the
            request completed between the cancel decision and this loop
            boundary — and a no-op by design (the tokens are identical
            on both replicas, so a double completion is harmless)."""
            slot = next((s for s, st in slots.items()
                         if st.req.rid == rid), None)
            if slot is not None:
                drain()
                st_now = slots.get(slot)
                if st_now is None or st_now.req.rid != rid:
                    return  # completed (or failed) at the drain sync
                st = release(slot)
                hist.pop(rid, None)
                outcomes[rid] = f"canceled[{reason}]"
                self._requests["canceled"] += 1
                self._event("request-canceled", rid, reason=reason,
                            tokens_done=st.tokens_done)
                return
            for r in list(queue):
                if r.rid == rid:
                    queue.remove(r)
                    outcomes[rid] = f"canceled[{reason}]"
                    self._requests["canceled"] += 1
                    self._event("request-canceled", rid, reason=reason,
                                tokens_done=0)
                    return
            if feed is not None and feed.discard(rid):
                outcomes[rid] = f"canceled[{reason}]"
                self._requests["canceled"] += 1
                self._event("request-canceled", rid, reason=reason,
                            tokens_done=0)

        # EMA of the observed per-step interval: the horizon policy uses
        # it to convert "next arrival in X seconds" into a step budget,
        # and the dispatch watchdog scales its deadline from it
        step_ema = [0.0]
        # bumped at every catastrophic carry replacement (hung/failed
        # dispatch, abandoned window): the chunked-prefill interleave
        # checks it — chunks already written to the OLD cache are gone
        # with it, so a mid-prefill reset must restart the prefill
        # rather than keep chunking into the fresh empty cache
        carry_resets = [0]

        def unit_deadline(k: int) -> Optional[float]:
            """Watchdog deadline for a k-step unit: EMA-scaled with a
            floor while the EMA is cold; None = watchdog off."""
            f = cfg.dispatch_deadline_factor
            if f is None:
                return None
            return max(cfg.dispatch_deadline_min_s, f * k * step_ema[0])

        def abandon_window(first_unit: dict[str, Any],
                           exc: BaseException) -> None:
            """A unit's sync blew its deadline: every un-synced unit
            chains off the same donated carry, so the whole window is
            abandoned — its requests (including completions that were
            never confirmed at a sync point) fail closed, and the
            engine continues on a fresh carry."""
            nonlocal carry
            stats.hung_dispatches += 1
            self.registry.inc("serve_hung_dispatches")
            hung = [first_unit] + list(inflight)
            inflight.clear()
            last_sync[0] = time.perf_counter()
            unconfirmed = [st for u in hung for st in u["completions"]]
            fail_requests(unconfirmed, exc, "hung-dispatch")
            fail_resident(exc, "hung-dispatch")
            carry = self._fresh_carry()
            draft_cache[0] = self._fresh_draft_cache()
            carry_resets[0] += 1

        def sync_one() -> None:
            unit = inflight.popleft()
            try:
                _with_deadline(
                    lambda: jax.block_until_ready(unit["ys"]),
                    unit_deadline(unit["k_exec"]),
                    f"decode[k={unit['k_exec']}]", "serve-sync")
            except DeadlineExceeded as e:
                abandon_window(unit, e)
                return
            t_ready = time.perf_counter()
            dt = t_ready - max(unit["t0"], last_sync[0])
            last_sync[0] = t_ready
            stats.decode_step_s.append(dt)
            per_step = dt / unit["k_exec"]
            step_ema[0] = (per_step if step_ema[0] == 0.0
                           else 0.5 * step_ema[0] + 0.5 * per_step)
            for _row, _slot, _rid, steps in unit["rows"]:
                for _ in range(steps):
                    stats.per_token_s.append(dt / unit["k_exec"])
            done_at = self._now()
            if unit.get("tokens"):
                # token-feedback unit: ys are the committed token ids
                # themselves ([B] per-step, [k, B] fused) — the n-gram
                # history extends from them even when capture is off
                if cfg.speculation == "ngram" or self.capture_tokens:
                    toks_np = np.asarray(unit["ys"])
                    if toks_np.ndim == 1:   # per-step unit: [B]
                        toks_np = toks_np[None]
                    for row, _slot, rid, steps in unit["rows"]:
                        ids = [int(t) for t in toks_np[:steps, row]]
                        if cfg.speculation == "ngram" and rid in hist:
                            hist[rid].extend(ids)
                        if self.capture_tokens:
                            tokens_by_rid.setdefault(rid, []).extend(ids)
            elif self.capture_tokens:
                ys_np = np.asarray(unit["ys"], np.float32)
                if ys_np.ndim == 3:        # per-step unit: [B, 1, H]
                    ys_np = ys_np[None]
                for row, _slot, rid, steps in unit["rows"]:
                    for i in range(steps):
                        tokens_by_rid.setdefault(rid, []).append(
                            int(np.argmax(ys_np[i, row, 0])))
            # finish AFTER the unit's token capture: the completion
            # event carries the request's full committed token list
            for st in unit["completions"]:
                finish(st, done_at)

        def drain() -> None:
            while inflight:
                sync_one()

        def decode_unit(k: int, steps: dict[int, int], compact: bool,
                        snap: dict[str, Any]) -> None:
            """One decode unit, committed: the device dispatch (under
            the watchdog when armed), torn-protected host bookkeeping,
            and the in-flight window push + boundary sync.  Transient
            bookkeeping faults roll themselves back and replay (pure
            host recomputation — the device result is already in hand,
            so NEVER a re-dispatch); everything else raises out to
            ``dispatch_decode``'s recovery loop with nothing committed."""
            nonlocal carry
            rows: list[tuple[int, int, int, int]] = []
            deadline = unit_deadline(k)
            t0 = time.perf_counter()
            # ONE span per dispatched unit, covering dispatch AND the
            # boundary sync below — in the per-step/window=1 cadence
            # the span therefore spans the real step wall (as PR-9's
            # did); under a deeper window the synced device time
            # belongs to an older unit and per-unit device attribution
            # lives in decode_step_s/per_token_s instead
            span_args = dict(active=len(slots), steps=k)
            if compact:
                span_args["compacted"] = True
            with spans.span("serve-decode", **span_args):
                if inject.fire("serve-decode-fail"):
                    # fires BEFORE the jit is invoked: the donated carry
                    # was never consumed, so a retry re-dispatches from
                    # unchanged device state
                    raise TransientFault(
                        "injected serve-decode-fail at the decode "
                        "dispatch boundary")

                def dispatch(fn):
                    def run():
                        if inject.fire("serve-decode-hang"):
                            # a wedged dispatch: the sleep sits on the
                            # watchdog's daemon thread, never on the
                            # engine's scheduler thread
                            time.sleep(inject.param("hang_seconds"))
                        return fn()
                    return _with_deadline(run, deadline,
                                          f"decode[k={k}]",
                                          "serve-dispatch")

                if k == 1:
                    if token_mode:
                        carry, ys = dispatch(
                            lambda: self._decode_token(
                                carry, self.params, self._table,
                                active_dev))
                    else:
                        carry, ys = dispatch(
                            lambda: self._decode(carry, self.params,
                                                 active_dev))
                    stats.single_steps += 1
                    for s in sorted(steps):
                        rows.append((s, s, slots[s].req.rid, 1))
                elif compact:
                    bucket = cfg.max_batch // 2
                    act = sorted(slots)
                    idx_np = np.asarray(
                        act + free_slots[:bucket - len(act)], np.int32)
                    idx = jax.device_put(jnp.asarray(idx_np),
                                         self._active_sharding)
                    s_act_np = np.zeros((bucket,), bool)
                    s_act_np[:len(act)] = True
                    s_rem_np = np.zeros((bucket,), np.int32)
                    for i, s in enumerate(act):
                        s_rem_np[i] = steps[s]
                    s_act = jax.device_put(jnp.asarray(s_act_np),
                                           self._active_sharding)
                    s_rem = jax.device_put(jnp.asarray(s_rem_np),
                                           self._active_sharding)

                    def compact_unit():
                        small = self._compact_gather_fn(carry, idx)
                        small, ys = self._decode_fused[k](
                            small, self.params, s_act, s_rem)
                        return (self._compact_scatter_fn(carry, small,
                                                         idx), ys)

                    carry, ys = dispatch(compact_unit)
                    stats.fused_scans += 1
                    stats.fused_steps += k
                    stats.compacted_scans += 1
                    self.registry.inc("serve_fused_scan_steps", k)
                    for i, s in enumerate(act):
                        rows.append((i, s, slots[s].req.rid, steps[s]))
                else:
                    rem_np = np.zeros((cfg.max_batch,), np.int32)
                    for s, m in steps.items():
                        rem_np[s] = m
                    rem_dev = jax.device_put(jnp.asarray(rem_np),
                                             self._active_sharding)
                    if token_mode:
                        carry, ys = dispatch(
                            lambda: self._decode_fused_token[k](
                                carry, self.params, self._table,
                                active_dev, rem_dev))
                    else:
                        carry, ys = dispatch(
                            lambda: self._decode_fused[k](
                                carry, self.params, active_dev, rem_dev))
                    stats.fused_scans += 1
                    stats.fused_steps += k
                    self.registry.inc("serve_fused_scan_steps", k)
                    for s in sorted(steps):
                        rows.append((s, s, slots[s].req.rid, steps[s]))
                # host bookkeeping at scan exit: the ledger's known
                # lengths make every step's outcome deterministic at
                # dispatch time.  A torn half-applied update
                # (serve-cache-torn) restores the pre-dispatch snapshot
                # and REPLAYS the accounting — the device result is
                # already in hand, so this is pure host recomputation,
                # never a re-dispatch
                book_attempt = 0
                while True:
                    completions: list[int] = []
                    try:
                        for s, m in sorted(steps.items()):
                            st = slots[s]
                            st.tokens_done += m
                            if inject.fire("serve-cache-torn"):
                                raise TransientFault(
                                    "injected serve-cache-torn: ledger/"
                                    "slot bookkeeping torn mid-unit")
                            ledger.append(s, m)
                            if draft_ledger is not None:
                                draft_ledger.append(s, m)
                            stats.generated_tokens += m
                            if st.tokens_done >= st.req.output_len:
                                completions.append(s)
                        break
                    except (TransientFault, CorruptStats) as e:
                        restore_snapshot(snap)
                        if book_attempt >= cfg.max_dispatch_retries:
                            raise RuntimeError(
                                "ledger/slot bookkeeping kept failing "
                                "after the decode unit completed on "
                                "device"
                            ) from e
                        book_attempt += 1
                        stats.retries += 1
                        self._retry_counter["bookkeeping"] += 1
                        if self.journal is not None:
                            self.journal.event(
                                "dispatch-retry", phase="bookkeeping",
                                attempt=book_attempt, error=str(e))
                        time.sleep(cfg.retry_backoff_s
                                   * (2 ** (book_attempt - 1)))
                stats.decode_steps += k
                stats.decode_units += 1
                self.registry.inc("serve_decode_steps", k)
                done_states = [release(s) for s in completions]
                if completions:
                    refresh_active()
                inflight.append({"t0": t0, "ys": ys, "k_exec": k,
                                 "rows": rows, "tokens": token_mode,
                                 "completions": done_states})
                # a k==1 unit's y is the SAME logical value as the
                # carry's x (decode_step returns ((cache, y), y)); on
                # donation-honoring backends the duplicate outputs may
                # alias one buffer, and the next dispatch donating the
                # carry would invalidate the held ys — so per-step
                # units never stay in flight (a fused scan's stacked
                # ys is its own buffer and may)
                window = 1 if k == 1 else cfg.inflight_window
                while len(inflight) >= window:
                    sync_one()

        def spec_unit(g: int, drafts_np: np.ndarray,
                      snap: dict[str, Any]) -> None:
            """One draft-and-verify unit, committed: draft (host match
            already in ``drafts_np`` for ngram; the draft-model scan
            dispatches here), ONE batched target verify over the whole
            resident batch, a synchronous commit read, and the
            rollback-disciplined host bookkeeping.

            A verify unit never rides the in-flight window: its host
            accounting depends on the device's acceptance result, so it
            syncs at its own boundary (the window was drained before
            drafting — history and bookkeeping must be current).
            Bookkeeping is optimistic-then-rollback: every slot is
            first accounted the full γ+1 window (the fused-scan
            discipline — outcomes known at dispatch time), and the
            synced commits roll any shortfall back to the pre-dispatch
            snapshot (PR-11's ledger snapshot/restore as the
            rejection-rollback primitive) and replay the true counts.
            The rejected suffix needs NO device cleanup: appended-but-
            rejected cache positions sit past the committed lengths,
            attention is length-masked, and the next unit's writes land
            at the committed lengths — dead by construction (asserted
            by the token-identity tests, never copied or zeroed)."""
            nonlocal carry
            refresh_active()
            rows = [(s, slots[s].req.rid) for s in sorted(slots)]
            rem_map = {s: slots[s].req.output_len - slots[s].tokens_done
                       for s, _ in rows}
            deadline = unit_deadline(g + 1)
            t0 = time.perf_counter()
            with spans.span("serve-verify", active=len(slots), gamma=g,
                            drafter=cfg.speculation):
                if inject.fire("serve-decode-fail"):
                    # fires BEFORE any jit consumes the carry — a retry
                    # re-dispatches from unchanged device state (same
                    # contract as the decode unit's site)
                    raise TransientFault(
                        "injected serve-decode-fail at the verify "
                        "dispatch boundary")

                def dispatch(fn):
                    def run():
                        if inject.fire("serve-decode-hang"):
                            time.sleep(inject.param("hang_seconds"))
                        return fn()
                    return _with_deadline(run, deadline,
                                          f"verify[gamma={g}]",
                                          "serve-dispatch")

                rem_np = np.zeros((cfg.max_batch,), np.int32)
                for s, _ in rows:
                    rem_np[s] = rem_map[s]
                rem_dev = jax.device_put(jnp.asarray(rem_np),
                                         self._active_sharding)
                if cfg.speculation == "draft-model":
                    # the draft plane's rejection rollback IS this
                    # lengths vector: the host's committed lengths
                    # override the plane's own (advanced-by-γ) leaf,
                    # and entries past them are dead by the same
                    # length-mask construction as the target's
                    lengths_np = np.zeros((cfg.max_batch,), np.int32)
                    for s, _ in rows:
                        st = slots[s]
                        lengths_np[s] = (st.req.prompt_len
                                         + st.tokens_done - 1)
                    dlen = jax.device_put(jnp.asarray(lengths_np),
                                          self._active_sharding)
                    t_d = time.perf_counter()
                    dcache, ids = dispatch(
                        lambda: self._draft_scan[g](
                            draft_cache[0], self._draft_params,
                            self._table, carry[1], dlen, active_dev))
                    draft_cache[0] = dcache
                    # host dispatch wall only — the proposals stay on
                    # device and flow straight into the verify
                    stats.spec_draft_s += time.perf_counter() - t_d
                else:
                    ids = jax.device_put(jnp.asarray(drafts_np),
                                         self._ids_sharding)
                committed_ids: Optional[dict[int, list[int]]] = None
                if self._sampled:
                    # sampled verify: the device computes the γ+1
                    # verify logits WITHOUT committing (lengths/x come
                    # back unchanged — retry-idempotent); acceptance is
                    # the host's residual-sampling pass (the literal
                    # ``speculative_sample`` helper, q = the
                    # deterministic drafter's one-hot), and the tiny
                    # spec_commit program applies the decided commits
                    carry, y = dispatch(
                        lambda: self._verify_probs[g](
                            carry, self.params, self._table, ids,
                            active_dev))
                    y_np = _with_deadline(
                        lambda: np.asarray(y), deadline,
                        f"verify[gamma={g}]", "serve-sync")
                    ids_np = (np.asarray(ids)
                              if cfg.speculation == "draft-model"
                              else drafts_np)
                    vocab = y_np.shape[-1]
                    commits_np = np.zeros((cfg.max_batch,), np.int32)
                    next_np = np.zeros((cfg.max_batch,), np.int32)
                    committed_ids = {}
                    for s, _rid in rows:
                        p_rows = softmax_np(y_np[s], cfg.temperature)
                        toks: list[int] = []
                        for j in range(g):
                            d_id = int(ids_np[s, j])
                            q = np.zeros((vocab,), np.float64)
                            q[d_id] = 1.0
                            t, ok = speculative_sample(
                                p_rows[j], q, d_id, sample_rng)
                            toks.append(t)
                            if not ok:
                                break
                        else:
                            # every draft accepted: the window's +1
                            # bonus is a free draw from the last
                            # position's target distribution
                            toks.append(int(sample_rng.choice(
                                vocab, p=p_rows[g])))
                        m = min(len(toks), rem_map[s])
                        commits_np[s] = m
                        next_np[s] = toks[m - 1]
                        committed_ids[s] = toks[:m]
                    next_dev = jax.device_put(jnp.asarray(next_np),
                                              self._active_sharding)
                    com_dev = jax.device_put(jnp.asarray(commits_np),
                                             self._active_sharding)
                    carry = dispatch(
                        lambda: self._spec_commit(
                            carry, self._table, next_dev, com_dev,
                            active_dev))
                    self.registry.inc("serve_sampled_tokens",
                                      int(commits_np.sum()))
                else:
                    carry, tok, commits = dispatch(
                        lambda: self._verify[g](
                            carry, self.params, self._table, ids,
                            active_dev, rem_dev))
                    commits_np = _with_deadline(
                        lambda: np.asarray(commits), deadline,
                        f"verify[gamma={g}]", "serve-sync")
                t_ready = time.perf_counter()
                dt = t_ready - max(t0, last_sync[0])
                last_sync[0] = t_ready
                # torn-protected bookkeeping (the decode unit's replay
                # discipline): the device result is in hand, so every
                # replay is pure host recomputation, never a re-dispatch
                book_attempt = 0
                while True:
                    completions: list[int] = []
                    try:
                        for s, _rid in rows:
                            st = slots[s]
                            opt = min(g + 1, rem_map[s])
                            st.tokens_done += opt
                            ledger.append(s, opt)
                            if draft_ledger is not None:
                                draft_ledger.append(s, opt)
                            stats.generated_tokens += opt
                        if inject.fire("serve-cache-torn"):
                            raise TransientFault(
                                "injected serve-cache-torn: ledger/slot "
                                "bookkeeping torn mid-verify")
                        if any(int(commits_np[s]) != min(g + 1, rem_map[s])
                               for s, _ in rows):
                            # rejection rollback: restore the
                            # pre-dispatch snapshot, replay TRUE commits
                            restore_snapshot(snap)
                            for s, _rid in rows:
                                st = slots[s]
                                m = int(commits_np[s])
                                st.tokens_done += m
                                ledger.append(s, m)
                                if draft_ledger is not None:
                                    draft_ledger.append(s, m)
                                stats.generated_tokens += m
                        for s, _rid in rows:
                            if (slots[s].tokens_done
                                    >= slots[s].req.output_len):
                                completions.append(s)
                        break
                    except (TransientFault, CorruptStats) as e:
                        restore_snapshot(snap)
                        if book_attempt >= cfg.max_dispatch_retries:
                            raise RuntimeError(
                                "ledger/slot bookkeeping kept failing "
                                "after the verify unit completed on "
                                "device"
                            ) from e
                        book_attempt += 1
                        stats.retries += 1
                        self._retry_counter["bookkeeping"] += 1
                        if self.journal is not None:
                            self.journal.event(
                                "dispatch-retry", phase="bookkeeping",
                                attempt=book_attempt, error=str(e))
                        time.sleep(cfg.retry_backoff_s
                                   * (2 ** (book_attempt - 1)))
                # committed: per-slot acceptance stats, adaptive γ,
                # history/capture, then completions at THIS sync point
                stats.decode_steps += 1
                stats.decode_units += 1
                stats.spec_verify_units += 1
                self.registry.inc("serve_decode_steps", 1)
                stats.decode_step_s.append(dt)
                step_ema[0] = (dt if step_ema[0] == 0.0
                               else 0.5 * step_ema[0] + 0.5 * dt)
                drafter = cfg.speculation
                ladder = self._spec_gammas
                unit_acc = 0
                tok_np = (np.asarray(tok)
                          if (committed_ids is None
                              and (drafter == "ngram"
                                   or self.capture_tokens))
                          else None)
                for s, rid in rows:
                    m = int(commits_np[s])
                    acc = max(m - 1, 0)
                    unit_acc += acc
                    stats.spec_slot_verifies += 1
                    stats.spec_proposed_tokens += g
                    stats.spec_accepted_tokens += acc
                    stats.spec_commit_tokens += m
                    self._spec_proposed[drafter] += g
                    self._spec_accepted[drafter] += acc
                    for _ in range(m):
                        stats.per_token_s.append(dt / m)
                    self._event("spec-verify", rid, gamma=g,
                                accepted=acc, committed=m)
                    st = slots[s]
                    if cfg.spec_adaptive:
                        rate = acc / g if g else 0.0
                        st.accept_ema = (rate if st.accept_ema < 0
                                         else 0.5 * st.accept_ema
                                         + 0.5 * rate)
                        pos = (ladder.index(st.gamma_eff)
                               if st.gamma_eff in ladder
                               else len(ladder) - 1)
                        if st.accept_ema < 0.25 and pos > 0:
                            st.gamma_eff = ladder[pos - 1]
                        elif (st.accept_ema > 0.75
                              and pos < len(ladder) - 1):
                            st.gamma_eff = ladder[pos + 1]
                    if tok_np is not None or committed_ids is not None:
                        ids_host = (committed_ids[s]
                                    if committed_ids is not None
                                    else [int(t) for t in tok_np[s, :m]])
                        if drafter == "ngram" and rid in hist:
                            hist[rid].extend(ids_host)
                        if self.capture_tokens:
                            tokens_by_rid.setdefault(rid, []).extend(
                                ids_host)
                unit_rate = (unit_acc / (g * len(rows))
                             if (rows and g) else 0.0)
                accept_ema_run[0] = (
                    unit_rate if accept_ema_run[0] < 0
                    else 0.5 * accept_ema_run[0] + 0.5 * unit_rate)
                self.registry.set_gauge(
                    "serve_spec_acceptance_ema", accept_ema_run[0],
                    help="EMA of per-verify-unit draft acceptance rate")
                done_states = [release(s) for s in completions]
                if completions:
                    refresh_active()
                done_at = self._now()
                for st in done_states:
                    finish(st, done_at)

        def dispatch_spec() -> bool:
            """One draft-and-verify unit over the resident batch, with
            the decode path's full recovery ladder.  Returns False when
            the drafter is cold (no n-gram hit for ANY resident slot) —
            the caller falls back to the plain token decode unit, so
            speculation COMPOSES with decode_horizon/inflight_window
            instead of replacing them."""
            nonlocal carry
            # history and host bookkeeping must be current before
            # drafting (fallback token units may still be in flight)
            drain()
            if not slots:
                return True     # the drain's completions emptied the batch
            ladder = self._spec_gammas
            if cfg.spec_adaptive:
                g_want = max(st.gamma_eff for st in slots.values())
            else:
                g_want = cfg.spec_gamma
            g = ladder[0]
            for cand in ladder:
                if cand <= g_want:
                    g = cand
            drafts_np = np.zeros((cfg.max_batch, g), np.int32)
            if cfg.speculation == "ngram":
                t_d = time.perf_counter()
                any_hit = False
                for s in sorted(slots):
                    prop = _ngram_propose(hist.get(slots[s].req.rid, []),
                                          g)
                    if prop is not None:
                        drafts_np[s] = prop
                        any_hit = True
                stats.spec_draft_s += time.perf_counter() - t_d
                if not any_hit:
                    stats.spec_fallback_units += 1
                    if not self._sampled:
                        return False
                    # sampled cold fallback: the plain token decode
                    # unit is a greedy program, so a cold drafter
                    # degenerates to the γ=0 verify — one host-sampled
                    # token per trip, never a silent greedy token
                    g = 0
                    drafts_np = np.zeros((cfg.max_batch, 0), np.int32)
            snap = take_snapshot()
            attempt = 0
            while True:
                try:
                    spec_unit(g, drafts_np, snap)
                    return True
                except (TransientFault, CorruptStats) as e:
                    restore_snapshot(snap)
                    if attempt >= cfg.max_dispatch_retries:
                        fail_resident(e, "dispatch-failed")
                        return True
                    attempt += 1
                    stats.retries += 1
                    self._retry_counter["decode"] += 1
                    if self.journal is not None:
                        self.journal.event("dispatch-retry",
                                           phase="decode",
                                           attempt=attempt,
                                           error=str(e))
                    time.sleep(cfg.retry_backoff_s * (2 ** (attempt - 1)))
                except DeadlineExceeded as e:
                    restore_snapshot(snap)
                    stats.hung_dispatches += 1
                    self.registry.inc("serve_hung_dispatches")
                    drain()
                    fail_resident(e, "hung-dispatch")
                    carry = self._fresh_carry()
                    draft_cache[0] = self._fresh_draft_cache()
                    carry_resets[0] += 1
                    return True
                except Exception as e:  # noqa: BLE001 — fail closed
                    restore_snapshot(snap)
                    try:
                        drain()
                    except Exception:  # noqa: BLE001
                        inflight.clear()
                    fail_resident(e, "dispatch-failed")
                    carry = self._fresh_carry()
                    draft_cache[0] = self._fresh_draft_cache()
                    carry_resets[0] += 1
                    return True

        def dispatch_decode(max_k: Optional[int] = None) -> None:
            """One decode unit over the resident batch: a single step,
            or — when no scheduling event needs an earlier boundary — a
            fused K-step scan (largest power-of-two bucket <= the
            event horizon), optionally on a compacted half batch.
            ``max_k`` caps the horizon (the chunked-prefill interleave
            passes 1: the mid-admission request is itself a waiter, and
            a full fused scan between chunks would re-create the
            head-of-line blocking the interleave exists to remove).

            Hardened (docs/resilience.md, serving faults): a
            transiently-failed dispatch rolls the host ledger/slot
            state back to the pre-dispatch snapshot and re-issues with
            exponential backoff; exhaustion — or a real dispatch error
            — fails only the resident requests (full exception chains,
            journaled ``request-failed``), never the run; a dispatch
            exceeding the EMA-scaled watchdog deadline is abandoned on
            its daemon thread and the engine continues on a fresh
            carry."""
            nonlocal carry
            refresh_active()
            if (spec_on and max_k is None
                    and (control is None or control.spec_enabled)):
                # draft-and-verify first; a cold n-gram drafter falls
                # through to a plain token decode unit below (the
                # chunked-prefill interleave's max_k=1 also bypasses
                # drafting — a verify's γ+1 commit window would re-create
                # the head-of-line blocking the interleave removes)
                if dispatch_spec():
                    return
                refresh_active()
            rem = {s: slots[s].req.output_len - slots[s].tokens_done
                   for s in sorted(slots)}
            # next event: the earliest completion while anything is (or
            # may soon be) waiting for a slot; a quiescent batch fuses
            # through its full drain
            horizon = (min(rem.values()) if (queue or pending)
                       else max(rem.values()))
            horizon = min(cfg.decode_horizon, horizon)
            if control is not None and control.horizon_cap is not None:
                # degradation ladder (serve/fleet.py): a shrunk horizon
                # trades fused-scan throughput for scheduling latency
                # under overload — never silently (each transition is
                # journaled ``degrade-transition``)
                horizon = min(horizon, max(1, control.horizon_cap))
            if pending:
                # a known arrival is a scheduling event too: bound the
                # scan so admission happens near the arrival instead of
                # up to decode_horizon steps late (steps estimated from
                # the observed per-step interval; before the first
                # sample exists, stay per-step — one unit bootstraps
                # the EMA)
                if step_ema[0] > 0.0:
                    gap = pending[0].arrival_s - self._now()
                    steps_to_arrival = (max(1, int(gap / step_ema[0]))
                                        if gap > 0 else 1)
                    horizon = min(horizon, steps_to_arrival)
                else:
                    horizon = 1
            if max_k is not None:
                horizon = min(horizon, max_k)
            k = 1
            for cand in self._fused_ks:
                if cand <= horizon:
                    k = cand
            steps = {s: min(k, r) for s, r in rem.items()}
            compact = (
                self._compact_gather_fn is not None and k > 1
                and len(slots) <= cfg.compact_threshold * cfg.max_batch
                and len(slots) <= cfg.max_batch // 2
            )
            snap = take_snapshot()
            attempt = 0
            while True:
                try:
                    decode_unit(k, steps, compact, snap)
                    return
                except (TransientFault, CorruptStats) as e:
                    # fired BEFORE the jit consumed the carry (the
                    # injection contract): restore the host snapshot
                    # and re-issue the same unit
                    restore_snapshot(snap)
                    if attempt >= cfg.max_dispatch_retries:
                        fail_resident(e, "dispatch-failed")
                        return
                    attempt += 1
                    stats.retries += 1
                    self._retry_counter["decode"] += 1
                    if self.journal is not None:
                        self.journal.event("dispatch-retry",
                                           phase="decode",
                                           attempt=attempt,
                                           error=str(e))
                    time.sleep(cfg.retry_backoff_s * (2 ** (attempt - 1)))
                except DeadlineExceeded as e:
                    # hung dispatch: the zombie daemon thread still
                    # holds the donated carry — settle the valid
                    # in-flight tail, fail the resident batch closed,
                    # continue on a fresh carry
                    restore_snapshot(snap)
                    stats.hung_dispatches += 1
                    self.registry.inc("serve_hung_dispatches")
                    drain()
                    fail_resident(e, "hung-dispatch")
                    carry = self._fresh_carry()
                    draft_cache[0] = self._fresh_draft_cache()
                    carry_resets[0] += 1
                    return
                except Exception as e:  # noqa: BLE001 — fail closed
                    # a real (non-injected) dispatch failure: the
                    # donated carry must be presumed consumed — fail
                    # the resident batch closed with the exception
                    # chain and continue on a fresh carry
                    restore_snapshot(snap)
                    try:
                        drain()
                    except Exception:  # noqa: BLE001
                        inflight.clear()
                    fail_resident(e, "dispatch-failed")
                    carry = self._fresh_carry()
                    draft_cache[0] = self._fresh_draft_cache()
                    carry_resets[0] += 1
                    return

        def attach_plan(req: Request) -> Optional[dict[str, Any]]:
            """Host-side prefix match for one admission: the prompt's
            full-block token-id chain (pure numpy, the same
            admission-time id view the n-gram drafter uses — the trie
            never touches the device), the trie's longest indexed
            match, and the chunk-floored attach point.  The attach is
            capped at whole CHUNKS (the suffix prefill resumes at a
            static chunk-jit offset) and always leaves >= 1 chunk to
            compute (the final chunk owns ``y_last`` and the slot
            length); blocks the trie matched past that cap are
            recomputed privately — the copy-on-write tail, counted via
            ``note_cow``.  ``resets`` pins the carry generation: an
            attach copies DEVICE blocks, so a plan from before a carry
            reset degrades to a full prefill (the slot then physically
            holds every block it refs, keeping the trie true)."""
            bs = cfg.block_size
            chunk = cfg.prefill_chunk
            full_blocks = req.prompt_len // bs
            plan = {"chain": [], "attach_blocks": 0, "attach_tokens": 0,
                    "donor": None, "cow_blocks": 0,
                    "resets": carry_resets[0], "attached_tokens": 0}
            if full_blocks == 0:
                return plan
            ids = prompt_token_ids(
                req.seed, req.prompt_len, self.config.hidden_size,
                prefix_len=req.prefix_len, prefix_seed=req.prefix_seed)
            chain = [tuple(ids[i * bs:(i + 1) * bs])
                     for i in range(full_blocks)]
            plan["chain"] = chain
            depth, donor = ledger.match_prefix(chain)
            cap = ((req.prompt_len - 1) // chunk) * chunk
            attach_tokens = min(depth * bs, cap) // chunk * chunk
            if donor is None or attach_tokens <= 0:
                return plan
            plan.update(attach_blocks=attach_tokens // bs,
                        attach_tokens=attach_tokens, donor=donor,
                        cow_blocks=depth - attach_tokens // bs)
            return plan

        def prefill_once(req: Request, slot: int,
                         plan: Optional[dict[str, Any]] = None):
            """The prefill dispatch for one admitted request (chunked or
            monolithic) — returns ``(bucket, y_last, dt)``.  Raised
            through by the retry wrapper below; idempotent on retry:
            chunk writes are deterministic masked selects of identical
            values, and interleaved decode units commit independently.
            With a prefix-attach ``plan``, the matched chunks' prefills
            are replaced by ONE donor-block copy (``build_prefix_attach``)
            and only the suffix chunks run; a carry reset since planning
            degrades to the full prefill (a retry after a reset finds
            zeroed donor blocks, so copying would serve garbage)."""
            nonlocal carry
            if inject.fire("serve-prefill-fail"):
                # fires BEFORE any jit is invoked — see serve-decode-fail
                raise TransientFault(
                    "injected serve-prefill-fail at the prefill "
                    "dispatch boundary")
            if cfg.prefill_chunk is not None:
                chunk = cfg.prefill_chunk
                n_chunks = -(-req.prompt_len // chunk)
                bucket = n_chunks * chunk
                m_chunks = 0
                if plan is not None and plan["attach_blocks"]:
                    plan["attached_tokens"] = 0
                    if carry_resets[0] == plan["resets"]:
                        m_chunks = plan["attach_tokens"] // chunk
                x_prompt = request_embeddings(
                    req.seed, req.prompt_len,
                    self.config.hidden_size,
                    dtype=self._dtype, pad_to=bucket,
                    prefix_len=req.prefix_len,
                    prefix_seed=req.prefix_seed,
                )
                with spans.span("serve-prefill", rid=req.rid,
                                bucket=bucket, slot=slot,
                                chunks=n_chunks - m_chunks):
                    t0 = time.perf_counter()
                    decode_spent = 0.0
                    cache = carry[0]
                    if m_chunks:
                        # copy-on-attach: one masked-select copy of the
                        # donor's matched blocks stands in for the
                        # matched chunks' prefill dispatches (the TTFT
                        # win), and its returned fp prefix carry is
                        # exactly what those chunks would have produced
                        with spans.span("serve-prefix-attach",
                                        rid=req.rid, slot=slot,
                                        donor=plan["donor"],
                                        blocks=plan["attach_blocks"]):
                            cache, prefix = self._attach_jit(m_chunks)(
                                cache, np.int32(plan["donor"]),
                                np.int32(slot))
                        plan["attached_tokens"] = m_chunks * chunk
                    else:
                        prefix = create_prefix(self.config, self.mesh)
                    for ci in range(m_chunks, n_chunks):
                        with spans.span("serve-prefill-chunk",
                                        rid=req.rid, chunk=ci):
                            cache, prefix, y_last = \
                                self._chunk_jit(ci)(
                                    cache, prefix,
                                    self.params,
                                    x_prompt[:, ci * chunk:
                                             (ci + 1) * chunk],
                                    np.int32(slot),
                                    np.int32(req.prompt_len))
                        stats.prefill_chunks += 1
                        self.registry.inc("serve_prefill_chunks")
                        if ci < n_chunks - 1 and slots:
                            # interleave: the resident batch decodes
                            # between chunks instead of head-of-line
                            # blocking
                            carry = (cache, carry[1])
                            td = time.perf_counter()
                            resets = carry_resets[0]
                            dispatch_decode(max_k=1)
                            decode_spent += time.perf_counter() - td
                            if carry_resets[0] != resets:
                                # the resident batch failed and took the
                                # carry with it — this request's chunks
                                # 0..ci died in the old cache; restart
                                # the prefill on the fresh carry (chunk
                                # writes are deterministic, so a replay
                                # is exact) via the retry wrapper
                                raise TransientFault(
                                    "carry reset during the chunked-"
                                    "prefill interleave (resident batch "
                                    "failed closed)")
                            cache = carry[0]
                    carry = (cache, carry[1])
                    jax.block_until_ready(y_last)
                    # the interleaved units' dispatch+sync time is
                    # already billed to decode_step_s/per_token_s —
                    # keep prefill_s a PREFILL cost
                    dt = time.perf_counter() - t0 - decode_spent
            else:
                bucket = cfg.bucket_for(req.prompt_len)
                x_prompt = request_embeddings(
                    req.seed, req.prompt_len,
                    self.config.hidden_size,
                    dtype=self._dtype, pad_to=bucket,
                )
                with spans.span("serve-prefill", rid=req.rid,
                                bucket=bucket, slot=slot):
                    t0 = time.perf_counter()
                    cache, y_last = self._prefill(
                        carry[0], self.params, x_prompt,
                        np.int32(slot), np.int32(req.prompt_len))
                    if self._draft_prefill is not None:
                        # the draft plane is prefilled at admission from
                        # the SAME prompt embeddings (idempotent masked
                        # writes, so the retry wrapper covers it); its
                        # cost is billed as prefill — the admission
                        # price of the draft model
                        dcache, _dy = self._draft_prefill(
                            draft_cache[0], self._draft_params, x_prompt,
                            np.int32(slot), np.int32(req.prompt_len))
                        draft_cache[0] = dcache
                    jax.block_until_ready(y_last)
                    dt = time.perf_counter() - t0
                carry = (cache, carry[1])
            return bucket, y_last, dt

        def prefill_dispatch(req: Request, slot: int,
                             plan: Optional[dict[str, Any]] = None):
            """Bounded-retry wrapper around :func:`prefill_once` —
            transient dispatch failures back off and re-issue (chunk
            counters rolled back so a retried prefill never
            double-counts); exhaustion raises to the admission loop's
            fail-closed path.  The prefix-attach ``plan`` rides through
            unchanged: each attempt re-checks the carry generation
            itself, so a retry after a mid-prefill carry reset degrades
            to the full prefill instead of copying zeroed donor blocks."""
            attempt = 0
            while True:
                chunks_base = stats.prefill_chunks
                try:
                    return prefill_once(req, slot, plan)
                except (TransientFault, CorruptStats) as e:
                    stats.prefill_chunks = chunks_base
                    if attempt >= cfg.max_dispatch_retries:
                        raise
                    attempt += 1
                    stats.retries += 1
                    self._retry_counter["prefill"] += 1
                    if self.journal is not None:
                        self.journal.event("dispatch-retry",
                                           phase="prefill", rid=req.rid,
                                           attempt=attempt,
                                           error=str(e))
                    time.sleep(cfg.retry_backoff_s * (2 ** (attempt - 1)))

        def fail_admission(req: Request, slot: int,
                           exc: BaseException) -> None:
            """A permanently-failed prefill fails ONLY the admitting
            request: reservation undone, journaled with the chain.  A
            real (non-injected) failure also consumed the donated
            cache, so the resident batch fails closed too and the
            engine continues on a fresh carry."""
            nonlocal carry
            ledger.free(slot)
            if draft_ledger is not None:
                draft_ledger.free(slot)
            free_slots.append(slot)
            free_slots.sort()
            fail_requests([_SlotState(req=req, tokens_done=0)], exc,
                          "dispatch-failed")
            if not isinstance(exc, InjectedFault):
                fail_resident(exc, "dispatch-failed")
                carry = self._fresh_carry()
                draft_cache[0] = self._fresh_draft_cache()

        # a fleet run shares one clock origin across every replica (the
        # supervisor's barrier sets it after ALL replicas have compiled,
        # so per-replica compile skew never distorts arrival/deadline
        # accounting); a standalone run starts its own
        self._t0 = (control.sync_start() if control is not None
                    else time.perf_counter())
        last_sync[0] = self._t0
        preempted = False
        while pending or queue or slots:
            if control is not None:
                # replica control plane (serve/fleet.py), strictly at
                # the loop boundary so a fence can never tear a
                # half-applied dispatch: heartbeat, injected replica
                # kill/hang, supervisor cancels (losing hedges)
                control.beat()
                control.check()
                for c_rid, c_reason in control.take_cancels():
                    cancel_request(c_rid, c_reason)
            if inject.fire("serve-preempt"):
                # chaos harness: deliver a real SIGTERM to ourselves —
                # the PreemptionGuard turns it into the drain flag below
                # (inert-flag fallback off the main thread)
                if guard.installed:
                    os.kill(os.getpid(), signal.SIGTERM)
                else:
                    guard.request()
            if guard.requested:
                # graceful drain: stop admission at this boundary; the
                # in-flight window settles below and still-resident
                # requests are journaled ``request-preempted``
                preempted = True
                break
            now = self._now()
            # 1. arrivals -> admission control (bounded queue)
            while pending and pending[0].arrival_s <= now:
                req = pending.popleft()
                self._requests["arrived"] += 1
                self._event("request-arrived", req.rid,
                            prompt=req.prompt_len, output=req.output_len)
                reason = (self._infeasible_reason(req)
                          if cfg.reject_infeasible else None)
                if reason is not None:
                    self._requests["rejected"] += 1
                    self._rejections["infeasible"] += 1
                    outcomes[req.rid] = "rejected[infeasible]"
                    rejected_detail.append({
                        "rid": req.rid, "reason": "infeasible",
                        "queue_depth": len(queue), "queue_wait_s": 0.0,
                        "detail": reason,
                    })
                    # distinct journal event from the load-shed path:
                    # infeasible is a config/trace mismatch, never load
                    self._event("request-infeasible", req.rid,
                                reason="infeasible", detail=reason)
                elif len(queue) >= cfg.queue_capacity:
                    head_wait = (now - queue[0].arrival_s if queue
                                 else 0.0)
                    self._requests["rejected"] += 1
                    self._rejections["queue-full"] += 1
                    outcomes[req.rid] = "rejected[queue-full]"
                    rejected_detail.append({
                        "rid": req.rid, "reason": "queue-full",
                        "queue_depth": len(queue),
                        "queue_wait_s": round(head_wait, 6),
                    })
                    self._event("request-rejected", req.rid,
                                reason="queue-full",
                                queue_depth=len(queue),
                                queue_wait_s=round(head_wait, 6))
                else:
                    queue.append(req)
                    self._requests["admitted"] += 1
                    self._event("request-admitted", req.rid,
                                queue_depth=len(queue))
            # 2. step-boundary scheduling: grant slots + block
            #    reservations, prefill each granted request.  First,
            #    per-request SLO shedding: a queue head whose wait has
            #    already blown its deadline is shed
            #    (``request-rejected[reason=deadline]`` — DISTINCT from
            #    queue-full: this is latency, not capacity) rather than
            #    served into a guaranteed SLO miss
            while (queue and queue[0].deadline_s is not None
                    and now - queue[0].arrival_s > queue[0].deadline_s):
                req = queue.popleft()
                wait = now - req.arrival_s
                self._requests["rejected"] += 1
                self._rejections["deadline"] += 1
                self._deadline_counter["shed-queued"] += 1
                stats.deadline_shed += 1
                outcomes[req.rid] = "rejected[deadline]"
                rejected_detail.append({
                    "rid": req.rid, "reason": "deadline",
                    "queue_depth": len(queue),
                    "queue_wait_s": round(wait, 6),
                    "deadline_s": req.deadline_s,
                })
                self._event("request-rejected", req.rid,
                            reason="deadline",
                            queue_wait_s=round(wait, 6),
                            deadline_s=req.deadline_s)
            scheduled = False
            if queue and free_slots:
                # scan boundary: settle in-flight decode before the
                # prefill blocks, so its sync cost lands in decode
                # timing and TTFT stays honest
                drain()
                with spans.span("serve-admission", queue=len(queue),
                                free_slots=len(free_slots)):
                    while queue and free_slots:
                        # prefix admission: blocks the trie already
                        # holds are counted ONCE fleet-wide, so a
                        # request whose private suffix fits is
                        # admittable even when its full footprint
                        # would not be — the int8/prefix capacity win
                        plan = (attach_plan(queue[0])
                                if cfg.prefix_caching else None)
                        attach_blocks = (plan["attach_blocks"]
                                         if plan else 0)
                        if not ledger.can_reserve(
                                queue[0].total_tokens,
                                shared_blocks=attach_blocks):
                            break
                        req = queue.popleft()
                        slot = free_slots.pop(0)
                        ledger.reserve(
                            slot, req.total_tokens,
                            chain=(plan["chain"] if plan else None),
                            attach_blocks=attach_blocks)
                        if draft_ledger is not None:
                            draft_ledger.reserve(slot, req.total_tokens)
                        try:
                            bucket, y_last, dt = prefill_dispatch(
                                req, slot, plan)
                        except Exception as e:  # noqa: BLE001 — closed
                            fail_admission(req, slot, e)
                            continue
                        first_id = -1
                        if token_mode and self._sampled:
                            # sampled inject: position 0 obeys the same
                            # temperature law as every later token —
                            # the prefill's last logits come to host
                            # (one [H] vector per admission), the first
                            # token is drawn from their softmax, and
                            # the device only embeds the committed id
                            # (once per ADMISSION, not per token)
                            # comm-lint: disable=host-transfer-in-loop
                            p0 = softmax_np(np.asarray(y_last),
                                            cfg.temperature)
                            first_id = int(sample_rng.choice(
                                p0.shape[-1], p=p0))
                            carry = self._inject_sampled(
                                carry, np.int32(slot),
                                np.int32(first_id), self._table)
                        elif token_mode:
                            # greedy token inject: argmax on device, a
                            # 4-byte id to host — the history seed AND
                            # the equivalence capture in one transfer
                            carry, first_tok = self._inject_greedy(
                                carry, np.int32(slot), y_last,
                                self._table)
                            first_id = int(first_tok)
                        else:
                            carry = self._inject(carry, np.int32(slot),
                                                 y_last)
                        ledger.append(slot, req.prompt_len)
                        if draft_ledger is not None:
                            draft_ledger.append(slot, req.prompt_len)
                        if cfg.prefix_caching and plan is not None:
                            reused = plan["attached_tokens"]
                            if reused:
                                stats.prefix_hits += 1
                                stats.prefix_tokens_reused += reused
                                self.registry.inc("serve_prefix_hits")
                                self.registry.inc(
                                    "serve_prefix_tokens_reused", reused)
                                self._event(
                                    "prefix-attach", req.rid, slot=slot,
                                    donor=plan["donor"], tokens=reused,
                                    blocks=reused // cfg.block_size)
                                if plan["cow_blocks"]:
                                    # matched deeper than the attach cap:
                                    # the tail blocks were recomputed
                                    # privately — the copy-on-write edge
                                    ledger.note_cow(plan["cow_blocks"])
                                    stats.prefix_cow_blocks += (
                                        plan["cow_blocks"])
                                    self._event(
                                        "prefix-cow", req.rid, slot=slot,
                                        blocks=plan["cow_blocks"])
                            # index this slot's full-block chain: the
                            # prefill (attached or full) made the slot
                            # a physical holder of every block it refs,
                            # and dedup against already-shared blocks
                            # refunds the private reservation
                            ledger.register(slot, plan["chain"])
                        t_first = self._now()
                        st = _SlotState(req=req, tokens_done=1,
                                        admitted_s=now,
                                        first_token_s=t_first,
                                        gamma_eff=cfg.spec_gamma)
                        if cfg.speculation == "ngram":
                            # prompt-lookup context: the prompt's own
                            # token-id view (pure numpy, admission-time)
                            # plus the prefill's first committed token
                            hist[req.rid] = prompt_token_ids(
                                req.seed, req.prompt_len,
                                self.config.hidden_size,
                                period=req.prompt_period,
                                prefix_len=req.prefix_len,
                                prefix_seed=req.prefix_seed) + [first_id]
                        slots[slot] = st
                        active_np[slot] = True
                        active_dirty[0] = True
                        stats.ttft_s.append(t_first - req.arrival_s)
                        stats.prefill_s.append(dt)
                        stats.generated_tokens += 1
                        scheduled = True
                        if self.capture_tokens:
                            # device-side argmax: a 4-byte scalar comes
                            # to host per admission, never the whole
                            # hidden state (host-transfer-in-loop)
                            tokens_by_rid.setdefault(req.rid, []).append(
                                first_id if token_mode
                                else int(jnp.argmax(y_last)))
                        self._event("request-prefill", req.rid, slot=slot,
                                    bucket=bucket,
                                    ttft_s=round(t_first - req.arrival_s, 6))
                        if st.tokens_done >= req.output_len:
                            finish(release(slot), self._now())
                if scheduled:
                    refresh_active()
            # 3. a decode unit over every resident request: one step, or
            #    a fused K-step scan on the fast path
            if slots:
                dispatch_decode()
            elif pending and not queue:
                # idle until the next arrival (nothing resident, nothing
                # admittable); settle any in-flight tail first
                drain()
                wait = pending[0].arrival_s - self._now()
                if wait > 0:
                    time.sleep(min(wait, 0.05))
            # 4. timeseries sample at the step boundary
            series["t_s"].append(round(self._now(), 6))
            series["queue_depth"].append(len(queue))
            series["active_slots"].append(len(slots))
            series["blocks_in_use"].append(ledger.blocks_in_use)
            series["blocks_reserved"].append(ledger.blocks_reserved)
            self.registry.set_gauge("serve_queue_depth", len(queue),
                                    help="bounded admission queue depth")
            self.registry.set_gauge("serve_active_slots", len(slots),
                                    help="decode slots in use")
            self.registry.set_gauge(
                "serve_decode_batch_occupancy",
                len(slots) / cfg.max_batch,
                help="resident fraction of the decode batch")
            self.registry.set_gauge("serve_cache_blocks_in_use",
                                    ledger.blocks_in_use,
                                    help="cache blocks holding tokens")
            if cfg.prefix_caching:
                series["shared_blocks"].append(ledger.shared_blocks)
                self.registry.set_gauge(
                    "serve_cache_shared_blocks", ledger.shared_blocks,
                    help="trie-indexed blocks counted once fleet-wide")
                self.registry.set_gauge(
                    "serve_cache_prefix_refs", ledger.trie.total_refs(),
                    help="slot references across all shared blocks")
        drain()
        remaining_rids: list[int] = []
        if preempted:
            # graceful drain: the in-flight window settled above;
            # still-resident requests are preempted — journaled, freed,
            # and replayed by ``cli serve --resume`` (serve/bench.py
            # writes the ledger/queue/trace-cursor snapshot)
            for s in sorted(list(slots)):
                st = release(s)
                outcomes[st.req.rid] = "preempted"
                stats.preempted_requests += 1
                self._requests["preempted"] += 1
                remaining_rids.append(st.req.rid)
                self._event("request-preempted", st.req.rid,
                            tokens_done=st.tokens_done,
                            output_len=st.req.output_len)
            remaining_rids += [r.rid for r in queue]
            remaining_rids += [r.rid for r in pending]
            if self.journal is not None:
                self.journal.event("preempted",
                                   signal=guard.signal_received,
                                   remaining=len(remaining_rids))
            if self.verbose:
                print(f"[serve] SIGTERM received — drained the in-flight "
                      f"window, {len(remaining_rids)} request(s) remain "
                      "for --resume")
        wall = self._now()

        self.registry.set_gauge("serve_queue_depth_peak",
                                max(series["queue_depth"], default=0))
        self.registry.set_gauge("serve_cache_blocks_peak",
                                ledger.peak_in_use)
        goodput = (stats.completed_output_tokens / wall) if wall > 0 else 0.0
        arrived = self._requests["arrived"] - counts_base["arrived"]
        # shed rate counts LOAD shedding only (queue-full) — an
        # infeasible rejection is a config/trace mismatch, and folding
        # it in would misread as pressure and prompt a pointless
        # queue_capacity tune
        shed = self._rejections["queue-full"] - shed_base
        report = {
            "schema": SERVING_REPORT_SCHEMA,
            "model": {
                "hidden_size": self.config.hidden_size,
                "num_layers": self.config.num_layers,
                "num_heads": self.config.num_heads,
                "kv_heads": self.config.kv_heads,
                "attention": self.config.attention,
                "dtype": self.config.dtype,
            },
            "mesh": {"dp": self.dp, "tp": self.tp},
            "serving": cfg.to_dict(),
            "trace": {
                "kind": trace.kind,
                "seed": trace.seed,
                "num_requests": len(trace),
                "params": dict(trace.params),
                "horizon_s": trace.horizon_s,
            },
            "requests": {
                **{k: self._requests[k] - counts_base[k]
                   for k in ("arrived", "admitted", "rejected",
                             "completed", "failed", "preempted",
                             "canceled")},
                "rejected_rids": [d["rid"] for d in rejected_detail],
                "rejected_detail": rejected_detail,
                "shed_rate": (shed / arrived) if arrived else 0.0,
                "deadline_shed": stats.deadline_shed,
                "completed_past_deadline": stats.completed_past_deadline,
                # rid -> final outcome: the per-request ground truth the
                # kill-mid-trace ≡ uninterrupted chaos gate compares
                "outcomes": {str(rid): o
                             for rid, o in sorted(outcomes.items())},
            },
            "goodput_tokens_per_s": goodput,
            "throughput_tokens_per_s": (
                stats.generated_tokens / wall if wall > 0 else 0.0
            ),
            "completed_output_tokens": stats.completed_output_tokens,
            "generated_tokens": stats.generated_tokens,
            "decode_steps": stats.decode_steps,
            "decode_units": stats.decode_units,
            "fast_path": {
                "enabled": self._fast,
                "decode_horizon": cfg.decode_horizon,
                "inflight_window": cfg.inflight_window,
                "prefill_chunk": cfg.prefill_chunk,
                "compact_threshold": cfg.compact_threshold,
                "fused_scans": stats.fused_scans,
                "fused_steps": stats.fused_steps,
                "single_steps": stats.single_steps,
                "prefill_chunks": stats.prefill_chunks,
                "compacted_scans": stats.compacted_scans,
            },
            "speculation": {
                "mode": cfg.speculation,
                "gamma": cfg.spec_gamma,
                "adaptive": cfg.spec_adaptive,
                "temperature": cfg.temperature,
                "sampled": self._sampled,
                "sample_seed": cfg.sample_seed,
                "verify_units": stats.spec_verify_units,
                "fallback_units": stats.spec_fallback_units,
                "proposed_tokens": stats.spec_proposed_tokens,
                "accepted_tokens": stats.spec_accepted_tokens,
                "acceptance_rate": (
                    stats.spec_accepted_tokens
                    / stats.spec_proposed_tokens
                    if stats.spec_proposed_tokens else 0.0),
                "mean_accepted_len": (
                    stats.spec_commit_tokens / stats.spec_slot_verifies
                    if stats.spec_slot_verifies else 0.0),
                "draft_overhead_s": stats.spec_draft_s,
            },
            "resilience": {
                "retries": stats.retries,
                "hung_dispatches": stats.hung_dispatches,
                "failed_requests": stats.failed_requests,
                "failed": failed_detail,
            },
            "preempted": preempted,
            "remaining_rids": sorted(remaining_rids),
            "prefix": {
                "enabled": cfg.prefix_caching,
                "kv_quantization": cfg.kv_quantization,
                "hits": stats.prefix_hits,
                "tokens_reused": stats.prefix_tokens_reused,
                "cow_blocks": stats.prefix_cow_blocks,
                "hit_rate": (stats.prefix_hits / len(stats.prefill_s)
                             if stats.prefill_s else 0.0),
            },
            "ttft": summarize(stats.ttft_s),
            "per_token_latency": summarize(stats.per_token_s),
            "e2e_latency": summarize(stats.e2e_latency_s),
            "prefill_time": summarize(stats.prefill_s),
            "decode_step_time": summarize(stats.decode_step_s),
            "cache": ledger.stats(),
            "timeseries": series,
            "compile_time_s": compile_time,
            "wall_seconds": wall,
        }
        if collect_raw or preempted:
            # the raw sample lists: a preempted session's checkpoint
            # carries them so the --resume merge can re-summarize over
            # BOTH sessions instead of faking a merged percentile
            report["raw_samples"] = {
                "ttft_s": list(stats.ttft_s),
                "per_token_s": list(stats.per_token_s),
                "prefill_s": list(stats.prefill_s),
                "decode_step_s": list(stats.decode_step_s),
                "e2e_latency_s": list(stats.e2e_latency_s),
            }
        if self.capture_tokens:
            report["completed_tokens"] = {
                str(rid): toks for rid, toks in sorted(tokens_by_rid.items())
            }
        if self.verbose:
            ttft = report["ttft"]
            ptl = report["per_token_latency"]
            print(
                f"[serve] {trace.kind} x{len(trace)}: "
                f"{report['requests']['completed']} completed / "
                f"{report['requests']['rejected']} rejected, "
                f"goodput {goodput:.0f} tok/s, "
                f"ttft p50 {ttft['median'] * 1e3:.1f} ms "
                f"p99 {ttft['p99'] * 1e3:.1f} ms, "
                f"per-token p50 {ptl['median'] * 1e3:.2f} ms"
            )
        return report
