"""Continuous-batching inference engine over the paged KV-cache.

Two jitted device programs, fixed shapes for the whole run:

- **prefill** (one compile per sequence-length *bucket*): runs the full
  transformer stack over one request's ``[1, bucket, H]`` prompt with
  ordinary causal attention, writes its K/V into the request's cache
  slot (block-aligned masked select — see ``serve/kvcache.py``), sets
  the slot length, and returns the last real token's output — the
  request's FIRST generated token (TTFT stops here).
- **decode_step** (one compile, ``[max_batch, 1, H]``): appends each
  active slot's pending token to the cache at its own length, attends
  over the slot's valid prefix (length-masked, GQA-grouped at
  ``kv_heads`` width), and produces every active slot's next token.
  The output hidden state IS the next step's input embedding (the model
  is its own next-token function — same convention as the chained
  timing loop), so the decode carry ``(cache, x)`` feeds back without
  any host round-trip, and both leaves are donated.

Around them, a host-side continuous-batching scheduler (Orca-style
iteration-level scheduling): arrivals from a ``TrafficTrace`` pass
admission control (bounded queue — overflow is a *rejected* request),
waiting requests are granted slots + worst-case block reservations at
step boundaries, completed requests free both immediately, and the next
decode step runs with whatever mix of old and new requests is resident.
Per-phase obs spans (``serve-admission`` / ``serve-prefill`` /
``serve-decode``), request-lifecycle events into the resilience journal,
and live MetricsRegistry counters/gauges come for free from the
machinery the sweep engine already has.

Communication contract (audited — ``analysis/hlo_audit.py`` decode and
prefill targets, ``plan_expected_kinds(decode=True)``): a decode step
may contain only the tiny per-token TP collectives (row-parallel psums
of ``[max_batch, 1, H]`` + QKV realignment permutes); the cache never
crosses the wire.  A byte ceiling of activation size proves no step
accidentally re-gathers the KV-cache.
"""

from __future__ import annotations

import math
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dlbb_tpu.data.synthetic import request_embeddings
from dlbb_tpu.models.configs import ModelConfig, validate_serving
from dlbb_tpu.models.attention import dense_attention
from dlbb_tpu.models.transformer import (
    _dtype_of,
    _layernorm,
    init_params_sharded,
)
from dlbb_tpu.obs import spans
from dlbb_tpu.obs.export import MetricsRegistry
from dlbb_tpu.serve.kvcache import (
    BlockLedger,
    KVCache,
    cache_shardings,
    create_kv_cache,
)
from dlbb_tpu.serve.traffic import Request, TrafficTrace
from dlbb_tpu.utils.metrics import Timer, summarize

SERVING_REPORT_SCHEMA = "dlbb_serving_report_v1"


# ---------------------------------------------------------------------------
# configuration
# ---------------------------------------------------------------------------


def _default_buckets(block_size: int, max_seq: int) -> tuple[int, ...]:
    """Doubling bucket ladder: block_size, 2x, 4x, ... up to max_seq."""
    buckets = []
    b = block_size
    while b < max_seq:
        buckets.append(b)
        b *= 2
    buckets.append(max_seq)
    return tuple(buckets)


@dataclass(frozen=True)
class ServingConfig:
    """The serving envelope (YAML ``serving:`` section).

    max_batch:       decode slots (the fixed decode batch dim).
    block_size:      tokens per cache block.
    max_seq:         per-slot capacity (prompt + output ceiling); must be
                     a block multiple — ``num_blocks = max_seq/block_size``.
    prefill_buckets: sequence-length buckets prefill compiles at
                     (block-multiples; default: doubling ladder up to
                     max_seq).  A prompt pads to the smallest bucket >= it.
    queue_capacity:  admission-control bound; an arrival finding the
                     queue full is REJECTED (counted, journaled).
    blocks_budget:   global cache-block budget the ledger enforces
                     (default: the physical pool, max_batch x num_blocks;
                     set lower to model cache pressure).
    hbm_budget_gb:   per-device HBM budget the build-time footprint gate
                     (``models.configs.validate_serving``) checks the
                     KV-cache against; None disables the gate.
    """

    max_batch: int = 8
    block_size: int = 16
    max_seq: int = 256
    prefill_buckets: tuple[int, ...] = ()
    queue_capacity: int = 64
    blocks_budget: Optional[int] = None
    hbm_budget_gb: Optional[float] = 12.0

    def __post_init__(self) -> None:
        if not self.prefill_buckets:
            object.__setattr__(
                self, "prefill_buckets",
                _default_buckets(self.block_size, self.max_seq),
            )
        else:
            # normalise: bucket_for's first-match walk and every
            # "buckets[-1] is the largest" consumer assume ascending
            # unique buckets
            object.__setattr__(
                self, "prefill_buckets",
                tuple(sorted(set(self.prefill_buckets))),
            )

    @property
    def num_blocks(self) -> int:
        return self.max_seq // self.block_size

    @property
    def total_blocks(self) -> int:
        return (self.blocks_budget if self.blocks_budget is not None
                else self.max_batch * self.num_blocks)

    def validate(self, config: ModelConfig, dp: int = 1,
                 tp: int = 1) -> None:
        budget = (None if self.hbm_budget_gb is None
                  else int(self.hbm_budget_gb * 2**30))
        validate_serving(config, self.max_batch, self.max_seq,
                         self.block_size, dp=dp, tp=tp,
                         hbm_budget_bytes=budget)
        for b in self.prefill_buckets:
            if b % self.block_size != 0 or not 0 < b <= self.max_seq:
                raise ValueError(
                    f"prefill bucket {b} must be a block_size="
                    f"{self.block_size} multiple in (0, {self.max_seq}]"
                )
        if self.queue_capacity < 1:
            raise ValueError(
                f"serving.queue_capacity must be >= 1, got "
                f"{self.queue_capacity}"
            )
        if self.total_blocks < 1:
            raise ValueError(
                f"serving.blocks_budget must be >= 1, got "
                f"{self.total_blocks}"
            )

    def bucket_for(self, prompt_len: int) -> int:
        for b in self.prefill_buckets:
            if prompt_len <= b:
                return b
        raise ValueError(
            f"prompt_len={prompt_len} exceeds the largest prefill bucket "
            f"{self.prefill_buckets[-1]} (serving.max_seq={self.max_seq})"
        )

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "ServingConfig":
        fields = {}
        for k in ("max_batch", "block_size", "max_seq", "queue_capacity",
                  "blocks_budget", "hbm_budget_gb"):
            if k in d:
                fields[k] = d[k]
        if "prefill_buckets" in d:
            fields["prefill_buckets"] = tuple(d["prefill_buckets"])
        return cls(**fields)

    def to_dict(self) -> dict[str, Any]:
        return {
            "max_batch": self.max_batch,
            "block_size": self.block_size,
            "max_seq": self.max_seq,
            "num_blocks": self.num_blocks,
            "prefill_buckets": list(self.prefill_buckets),
            "queue_capacity": self.queue_capacity,
            "blocks_budget": self.total_blocks,
            "hbm_budget_gb": self.hbm_budget_gb,
        }


# ---------------------------------------------------------------------------
# device programs
# ---------------------------------------------------------------------------


def _split_qkv(qkv: jax.Array, config: ModelConfig):
    """[..., qkv_width] -> q [..., H], k/v [..., kv_heads * head_dim]."""
    h, kvd = config.hidden_size, config.kv_heads * config.head_dim
    return qkv[..., :h], qkv[..., h:h + kvd], qkv[..., h + kvd:]


def _serve_block(h, layer, config: ModelConfig, attention_step,
                 k_l, v_l):
    """One transformer block with a pluggable attention step — the ONE
    copy of the ln1/qkv/out/ln2/ffn structure both serving programs
    share (the serving twin of ``transformer._block``, whose math the
    equivalence tests pin it against).  ``attention_step(q, k, v, k_l,
    v_l) -> (attn [B, S, n*d], k_l, v_l)`` owns everything that differs
    between prefill (dense causal + block write) and decode (cached
    append + length-masked read)."""
    y = _layernorm(h, layer["ln1"]["scale"], layer["ln1"]["bias"])
    qkv = y @ layer["qkv"]["kernel"] + layer["qkv"]["bias"]
    q, k, v = _split_qkv(qkv, config)
    attn, k_l, v_l = attention_step(q, k, v, k_l, v_l)
    h = attn @ layer["out"]["kernel"] + layer["out"]["bias"] + h
    residual = h
    y2 = _layernorm(h, layer["ln2"]["scale"], layer["ln2"]["bias"])
    y2 = y2 @ layer["ffn_up"]["kernel"] + layer["ffn_up"]["bias"]
    y2 = jax.nn.gelu(y2)
    h = (y2 @ layer["ffn_down"]["kernel"]
         + layer["ffn_down"]["bias"] + residual)
    return h, (k_l, v_l)


def _heads(t: jax.Array, nh: int, d: int) -> jax.Array:
    """[B, S, nh*d] -> [B, nh, S, d]."""
    b, s, _ = t.shape
    return t.reshape(b, s, nh, d).transpose(0, 2, 1, 3)


def _cached_attention(q: jax.Array, k_flat: jax.Array, v_flat: jax.Array,
                      valid: jax.Array) -> jax.Array:
    """Length-masked decode attention over the flattened cache.

    q: ``[B, n, 1, d]``; k_flat/v_flat: ``[B, S_max, kvh, d]``;
    valid: ``[B, S_max]`` bool.  Same math as
    ``models.attention.dense_attention`` (fp32 softmax, 1/sqrt(d),
    grouped-query einsum broadcasting) with the causal mask replaced by
    the per-slot validity mask — positions past a slot's length
    contribute exactly zero (softmax of -inf)."""
    b, n, _, d = q.shape
    kvh = k_flat.shape[2]
    q32 = q.astype(jnp.float32)
    k32 = k_flat.transpose(0, 2, 1, 3).astype(jnp.float32)  # [B, kvh, S, d]
    v32 = v_flat.transpose(0, 2, 1, 3).astype(jnp.float32)
    if kvh != n:
        q32 = q32.reshape(b, kvh, n // kvh, 1, d)
        logits = jnp.einsum("bhgqd,bhkd->bhgqk", q32, k32) / math.sqrt(d)
        logits = jnp.where(valid[:, None, None, None, :], logits, -jnp.inf)
        probs = jax.nn.softmax(logits, axis=-1)
        out = jnp.einsum("bhgqk,bhkd->bhgqd", probs, v32)
        out = out.reshape(b, n, 1, d)
    else:
        logits = jnp.einsum("bnqd,bnkd->bnqk", q32, k32) / math.sqrt(d)
        logits = jnp.where(valid[:, None, None, :], logits, -jnp.inf)
        probs = jax.nn.softmax(logits, axis=-1)
        out = jnp.einsum("bnqk,bnkd->bnqd", probs, v32)
    return out.astype(k_flat.dtype)


def _write_prompt_blocks(cache_layer: jax.Array, update: jax.Array,
                         slot: jax.Array) -> jax.Array:
    """Masked-select write of a prefill bucket into one slot's blocks.

    cache_layer: ``[B, nb, bs, kvh, d]``; update: ``[wb, bs, kvh, d]``
    (``wb`` = bucket/block_size, static).  One-hot over the slot dim and
    a static block mask — pure elementwise, so GSPMD keeps the write
    local to the shard owning the slot (no collective, no regather)."""
    b_dim, nb = cache_layer.shape[:2]
    wb = update.shape[0]
    padded = jnp.pad(update, ((0, nb - wb), (0, 0), (0, 0), (0, 0)))
    slot_mask = (jnp.arange(b_dim) == slot)[:, None, None, None, None]
    blk_mask = (jnp.arange(nb) < wb)[None, :, None, None, None]
    return jnp.where(slot_mask & blk_mask, padded[None], cache_layer)


def build_prefill(config: ModelConfig, mesh: Mesh):
    """Jitted ``prefill(cache, params, x, slot, length) -> (cache,
    y_last)`` — retraces once per prompt bucket (x's static shape).  The
    cache is donated (argnum 0), so the carried protocol matches the
    train-step convention the audit and calibration understand."""
    n, d, kvh = config.num_heads, config.head_dim, config.kv_heads

    def prefill(cache: KVCache, params, x, slot, length):
        bs = cache.block_size
        s_bucket = x.shape[1]
        wb = s_bucket // bs

        def attention_step(q, k, v, k_l, v_l):
            qh, kh, vh = (_heads(q, n, d), _heads(k, kvh, d),
                          _heads(v, kvh, d))
            attn = dense_attention(qh, kh, vh, causal=config.causal)
            # write this layer's K/V blocks into the slot ([S, kvh, d]
            # token-major, re-tiled to whole blocks)
            k_blocks = kh.transpose(0, 2, 1, 3)[0].reshape(wb, bs, kvh, d)
            v_blocks = vh.transpose(0, 2, 1, 3)[0].reshape(wb, bs, kvh, d)
            k_l = _write_prompt_blocks(k_l, k_blocks, slot)
            v_l = _write_prompt_blocks(v_l, v_blocks, slot)
            return (attn.transpose(0, 2, 1, 3).reshape(1, s_bucket, n * d),
                    k_l, v_l)

        def body(h, layer_and_cache):
            layer, k_l, v_l = layer_and_cache
            return _serve_block(h, layer, config, attention_step,
                                k_l, v_l)

        h, (k_new, v_new) = jax.lax.scan(
            body, x, (params["layers"], cache.k, cache.v)
        )
        y = _layernorm(h, params["ln_f"]["scale"], params["ln_f"]["bias"])
        y_last = jax.lax.dynamic_slice(
            y, (0, length - 1, 0), (1, 1, y.shape[-1])
        )[0, 0]
        lengths = jnp.where(jnp.arange(cache.max_batch) == slot,
                            length, cache.lengths).astype(jnp.int32)
        return KVCache(k_new, v_new, lengths), y_last

    return jax.jit(
        prefill,
        donate_argnums=(0,),
        out_shardings=(cache_shardings(mesh), NamedSharding(mesh, P())),
    )


def decode_batch_spec(mesh: Mesh) -> P:
    """Decode activations ``[max_batch, 1, H]``: slots over dp."""
    axes = getattr(mesh, "axis_names", ())
    dp = "dp" if "dp" in axes and mesh.shape["dp"] > 1 else None
    return P(dp, None, None)


def build_decode_step(config: ModelConfig, mesh: Mesh):
    """Jitted ``decode_step(carry, params, active) -> (carry, y)`` with
    ``carry = (cache, x)`` — ONE fixed-shape compile for the whole run.
    The carry is donated; its returned ``x`` is this step's output, so
    the engine (and the calibration harness's carry protocol) feeds
    ``out[0]`` straight back in."""
    n, d, kvh = config.num_heads, config.head_dim, config.kv_heads

    def decode_step(carry, params, active):
        cache, x = carry
        b_dim, s_max = cache.max_batch, cache.max_seq
        nb, bs = cache.num_blocks, cache.block_size
        lengths = cache.lengths
        pos = jnp.arange(s_max)[None, :]
        write_mask = (pos == lengths[:, None]) & active[:, None]
        valid = pos <= lengths[:, None]

        def attention_step(q, k, v, k_l, v_l):
            qh = _heads(q, n, d)                        # [B, n, 1, d]
            k_new = k[:, 0].reshape(b_dim, kvh, d)
            v_new = v[:, 0].reshape(b_dim, kvh, d)
            # append at each active slot's own length (masked select —
            # elementwise, shard-local; see serve/kvcache.py)
            k_flat = k_l.reshape(b_dim, s_max, kvh, d)
            v_flat = v_l.reshape(b_dim, s_max, kvh, d)
            k_flat = jnp.where(write_mask[..., None, None],
                               k_new[:, None], k_flat)
            v_flat = jnp.where(write_mask[..., None, None],
                               v_new[:, None], v_flat)
            attn = _cached_attention(qh, k_flat, v_flat, valid)
            return (attn.transpose(0, 2, 1, 3).reshape(b_dim, 1, n * d),
                    k_flat.reshape(b_dim, nb, bs, kvh, d),
                    v_flat.reshape(b_dim, nb, bs, kvh, d))

        def body(h, layer_and_cache):
            layer, k_l, v_l = layer_and_cache
            return _serve_block(h, layer, config, attention_step,
                                k_l, v_l)

        h, (k_new, v_new) = jax.lax.scan(
            body, x, (params["layers"], cache.k, cache.v)
        )
        y = _layernorm(h, params["ln_f"]["scale"], params["ln_f"]["bias"])
        lengths = lengths + active.astype(jnp.int32)
        new_cache = KVCache(k_new, v_new, lengths)
        return (new_cache, y), y

    x_sh = NamedSharding(mesh, decode_batch_spec(mesh))
    return jax.jit(
        decode_step,
        donate_argnums=(0,),
        out_shardings=((cache_shardings(mesh), x_sh), x_sh),
    )


def _inject_token(carry, slot, vec):
    """Place a freshly-prefilled request's first token into the decode
    input buffer: ``x[slot, 0] = vec``."""
    cache, x = carry
    mask = (jnp.arange(x.shape[0]) == slot)[:, None, None]
    return cache, jnp.where(mask, vec[None, None, :].astype(x.dtype), x)


# ---------------------------------------------------------------------------
# the engine
# ---------------------------------------------------------------------------


@dataclass
class _SlotState:
    req: Request
    tokens_done: int = 0
    admitted_s: float = 0.0
    first_token_s: float = 0.0


@dataclass
class _RunStats:
    ttft_s: list[float] = field(default_factory=list)
    per_token_s: list[float] = field(default_factory=list)
    prefill_s: list[float] = field(default_factory=list)
    decode_step_s: list[float] = field(default_factory=list)
    e2e_latency_s: list[float] = field(default_factory=list)
    completed_output_tokens: int = 0
    generated_tokens: int = 0
    decode_steps: int = 0


class ServingEngine:
    """Trace-driven continuous-batching engine (see module docstring).

    One engine serves many traces: each :meth:`run_trace` starts from a
    fresh cache.  The journal (``resilience.journal.SweepJournal``) and
    metrics registry are optional — the bench harness wires both."""

    def __init__(
        self,
        config: ModelConfig,
        serving: ServingConfig,
        mesh: Mesh,
        params: Any = None,
        journal: Any = None,
        registry: Optional[MetricsRegistry] = None,
        seed: int = 0,
        verbose: bool = True,
    ) -> None:
        axes = mesh.axis_names
        self.dp = mesh.shape["dp"] if "dp" in axes else 1
        self.tp = mesh.shape["tp"] if "tp" in axes else 1
        serving.validate(config, dp=self.dp, tp=self.tp)
        self.config = config
        self.serving = serving
        self.mesh = mesh
        self.verbose = verbose
        # public and reassignable: the bench wires one journal per run
        # directory; tests swap it between run_trace calls
        self.journal = journal
        self.registry = registry if registry is not None else MetricsRegistry()
        self._requests = self.registry.labeled_counter(
            "serve_requests", "outcome",
            initial=("arrived", "admitted", "rejected", "completed"),
            help="request lifecycle outcomes",
        )
        self._dtype = _dtype_of(config.dtype)
        self.params = (params if params is not None
                       else init_params_sharded(config, jax.random.key(seed),
                                                mesh))
        self._prefill = build_prefill(config, mesh)
        self._decode = build_decode_step(config, mesh)
        self._inject = jax.jit(_inject_token, donate_argnums=(0,))
        self._x_sharding = NamedSharding(mesh, decode_batch_spec(mesh))
        self._active_sharding = NamedSharding(mesh, P())
        self._t0 = time.perf_counter()

    # -- clock (monotonic, run-relative) -----------------------------------

    def _now(self) -> float:
        return time.perf_counter() - self._t0

    # -- setup -------------------------------------------------------------

    def _fresh_carry(self) -> tuple[KVCache, jax.Array]:
        cache = create_kv_cache(
            self.config, self.serving.max_batch, self.serving.num_blocks,
            self.serving.block_size, mesh=self.mesh,
        )
        x = jax.device_put(
            jnp.zeros((self.serving.max_batch, 1, self.config.hidden_size),
                      self._dtype),
            self._x_sharding,
        )
        return (cache, x)

    def _validate_trace(self, trace: TrafficTrace) -> None:
        """Fail BEFORE the run on any request the config cannot serve —
        an infeasible request rejected mid-trace would read as load."""
        max_bucket = self.serving.prefill_buckets[-1]
        ledger_cap = self.serving.total_blocks
        for r in trace:
            if r.output_len < 1:
                raise ValueError(
                    f"request {r.rid}: output_len must be >= 1 "
                    f"(got {r.output_len})"
                )
            if r.prompt_len < 1 or r.prompt_len > max_bucket:
                raise ValueError(
                    f"request {r.rid}: prompt_len={r.prompt_len} outside "
                    f"(0, {max_bucket}] (largest prefill bucket)"
                )
            if r.total_tokens > self.serving.max_seq:
                raise ValueError(
                    f"request {r.rid}: prompt+output={r.total_tokens} "
                    f"exceeds serving.max_seq={self.serving.max_seq} "
                    "(per-slot cache capacity)"
                )
            need = max(1, math.ceil(r.total_tokens
                                    / self.serving.block_size))
            if need > ledger_cap:
                raise ValueError(
                    f"request {r.rid}: needs {need} cache blocks, budget "
                    f"is {ledger_cap} (serving.blocks_budget)"
                )

    def _compile(self, buckets: list[int]) -> None:
        """Warm every jit the trace will hit (prefill per bucket, decode,
        inject) on scratch state, so compile time never lands in TTFT."""
        carry = self._fresh_carry()
        active = jax.device_put(
            jnp.zeros((self.serving.max_batch,), bool),
            self._active_sharding,
        )
        for b in buckets:
            dummy = request_embeddings(0, b, self.config.hidden_size,
                                       dtype=self._dtype, pad_to=b)
            cache, y_last = self._prefill(
                carry[0], self.params, dummy, np.int32(0), np.int32(b))
            carry = (cache, carry[1])
        carry = self._inject(carry, np.int32(0), y_last)
        carry, y = self._decode(carry, self.params, active)
        jax.block_until_ready(y)

    def _event(self, event: str, rid: int, **extra: Any) -> None:
        if self.journal is not None:
            self.journal.event(event, config=f"request-{rid}", **extra)

    # -- the run -----------------------------------------------------------

    def run_trace(self, trace: TrafficTrace) -> dict[str, Any]:
        """Serve ``trace`` to completion; returns the report dict
        (``docs/serving.md`` documents every field).  Pure compute + host
        scheduling — writing artifacts is ``serve/bench.py``'s job."""
        if not len(trace):
            raise ValueError("cannot serve an empty trace")
        self._validate_trace(trace)
        cfg = self.serving
        buckets = sorted({cfg.bucket_for(r.prompt_len) for r in trace})
        with Timer() as t_compile:
            self._compile(buckets)
        compile_time = t_compile.elapsed

        ledger = BlockLedger(cfg.total_blocks, cfg.block_size)
        # registry counters are cumulative across an engine's lifetime
        # (Prometheus semantics); the report carries THIS run's deltas
        counts_base = {k: self._requests[k] for k in self._requests}
        pending = deque(sorted(trace, key=lambda r: (r.arrival_s, r.rid)))
        queue: deque[Request] = deque()
        slots: dict[int, _SlotState] = {}
        free_slots = list(range(cfg.max_batch))
        stats = _RunStats()
        series: dict[str, list] = {
            "t_s": [], "queue_depth": [], "active_slots": [],
            "blocks_in_use": [], "blocks_reserved": [],
        }
        carry = self._fresh_carry()
        active_np = np.zeros((cfg.max_batch,), bool)
        active_dev = jax.device_put(jnp.asarray(active_np),
                                    self._active_sharding)
        rejected_detail: list[int] = []

        def refresh_active() -> None:
            nonlocal active_dev
            active_dev = jax.device_put(jnp.asarray(active_np),
                                        self._active_sharding)

        def complete(slot: int) -> None:
            st = slots.pop(slot)
            ledger.free(slot)
            active_np[slot] = False
            free_slots.append(slot)
            free_slots.sort()
            done_at = self._now()
            stats.e2e_latency_s.append(done_at - st.req.arrival_s)
            stats.completed_output_tokens += st.req.output_len
            self._requests["completed"] += 1
            self._event("request-completed", st.req.rid,
                        output_tokens=st.req.output_len,
                        latency_s=round(done_at - st.req.arrival_s, 6))

        self._t0 = time.perf_counter()
        while pending or queue or slots:
            now = self._now()
            # 1. arrivals -> admission control (bounded queue)
            while pending and pending[0].arrival_s <= now:
                req = pending.popleft()
                self._requests["arrived"] += 1
                self._event("request-arrived", req.rid,
                            prompt=req.prompt_len, output=req.output_len)
                if len(queue) >= cfg.queue_capacity:
                    self._requests["rejected"] += 1
                    rejected_detail.append(req.rid)
                    self._event("request-rejected", req.rid,
                                reason="queue-full",
                                queue_depth=len(queue))
                else:
                    queue.append(req)
                    self._requests["admitted"] += 1
                    self._event("request-admitted", req.rid,
                                queue_depth=len(queue))
            # 2. step-boundary scheduling: grant slots + block
            #    reservations, prefill each granted request
            scheduled = False
            if queue and free_slots:
                with spans.span("serve-admission", queue=len(queue),
                                free_slots=len(free_slots)):
                    while (queue and free_slots
                            and ledger.can_reserve(queue[0].total_tokens)):
                        req = queue.popleft()
                        slot = free_slots.pop(0)
                        ledger.reserve(slot, req.total_tokens)
                        bucket = cfg.bucket_for(req.prompt_len)
                        x_prompt = request_embeddings(
                            req.seed, req.prompt_len,
                            self.config.hidden_size, dtype=self._dtype,
                            pad_to=bucket,
                        )
                        with spans.span("serve-prefill", rid=req.rid,
                                        bucket=bucket, slot=slot):
                            t0 = time.perf_counter()
                            cache, y_last = self._prefill(
                                carry[0], self.params, x_prompt,
                                np.int32(slot), np.int32(req.prompt_len))
                            jax.block_until_ready(y_last)
                            dt = time.perf_counter() - t0
                        carry = self._inject((cache, carry[1]),
                                             np.int32(slot), y_last)
                        ledger.append(slot, req.prompt_len)
                        t_first = self._now()
                        st = _SlotState(req=req, tokens_done=1,
                                        admitted_s=now,
                                        first_token_s=t_first)
                        slots[slot] = st
                        active_np[slot] = True
                        stats.ttft_s.append(t_first - req.arrival_s)
                        stats.prefill_s.append(dt)
                        stats.generated_tokens += 1
                        scheduled = True
                        self._event("request-prefill", req.rid, slot=slot,
                                    bucket=bucket,
                                    ttft_s=round(t_first - req.arrival_s, 6))
                        if st.tokens_done >= req.output_len:
                            complete(slot)
                if scheduled:
                    refresh_active()
            # 3. one continuous-batching decode step over every resident
            #    request
            if slots:
                with spans.span("serve-decode", active=len(slots)):
                    t0 = time.perf_counter()
                    carry, y = self._decode(carry, self.params, active_dev)
                    jax.block_until_ready(y)
                    dt = time.perf_counter() - t0
                stats.decode_step_s.append(dt)
                stats.decode_steps += 1
                finished = []
                for slot in sorted(slots):
                    st = slots[slot]
                    st.tokens_done += 1
                    ledger.append(slot, 1)
                    stats.per_token_s.append(dt)
                    stats.generated_tokens += 1
                    if st.tokens_done >= st.req.output_len:
                        finished.append(slot)
                for slot in finished:
                    complete(slot)
                if finished:
                    refresh_active()
            elif pending and not queue:
                # idle until the next arrival (nothing resident, nothing
                # admittable)
                wait = pending[0].arrival_s - self._now()
                if wait > 0:
                    time.sleep(min(wait, 0.05))
            # 4. timeseries sample at the step boundary
            series["t_s"].append(round(self._now(), 6))
            series["queue_depth"].append(len(queue))
            series["active_slots"].append(len(slots))
            series["blocks_in_use"].append(ledger.blocks_in_use)
            series["blocks_reserved"].append(ledger.blocks_reserved)
            self.registry.set_gauge("serve_queue_depth", len(queue),
                                    help="bounded admission queue depth")
            self.registry.set_gauge("serve_active_slots", len(slots),
                                    help="decode slots in use")
            self.registry.set_gauge("serve_cache_blocks_in_use",
                                    ledger.blocks_in_use,
                                    help="cache blocks holding tokens")
        wall = self._now()

        self.registry.set_gauge("serve_queue_depth_peak",
                                max(series["queue_depth"], default=0))
        self.registry.set_gauge("serve_cache_blocks_peak",
                                ledger.peak_in_use)
        goodput = (stats.completed_output_tokens / wall) if wall > 0 else 0.0
        report = {
            "schema": SERVING_REPORT_SCHEMA,
            "model": {
                "hidden_size": self.config.hidden_size,
                "num_layers": self.config.num_layers,
                "num_heads": self.config.num_heads,
                "kv_heads": self.config.kv_heads,
                "attention": self.config.attention,
                "dtype": self.config.dtype,
            },
            "mesh": {"dp": self.dp, "tp": self.tp},
            "serving": cfg.to_dict(),
            "trace": {
                "kind": trace.kind,
                "seed": trace.seed,
                "num_requests": len(trace),
                "params": dict(trace.params),
                "horizon_s": trace.horizon_s,
            },
            "requests": {
                **{k: self._requests[k] - counts_base[k]
                   for k in ("arrived", "admitted", "rejected",
                             "completed")},
                "rejected_rids": rejected_detail,
            },
            "goodput_tokens_per_s": goodput,
            "throughput_tokens_per_s": (
                stats.generated_tokens / wall if wall > 0 else 0.0
            ),
            "completed_output_tokens": stats.completed_output_tokens,
            "generated_tokens": stats.generated_tokens,
            "decode_steps": stats.decode_steps,
            "ttft": summarize(stats.ttft_s),
            "per_token_latency": summarize(stats.per_token_s),
            "e2e_latency": summarize(stats.e2e_latency_s),
            "prefill_time": summarize(stats.prefill_s),
            "decode_step_time": summarize(stats.decode_step_s),
            "cache": ledger.stats(),
            "timeseries": series,
            "compile_time_s": compile_time,
            "wall_seconds": wall,
        }
        if self.verbose:
            ttft = report["ttft"]
            ptl = report["per_token_latency"]
            print(
                f"[serve] {trace.kind} x{len(trace)}: "
                f"{report['requests']['completed']} completed / "
                f"{report['requests']['rejected']} rejected, "
                f"goodput {goodput:.0f} tok/s, "
                f"ttft p50 {ttft['median'] * 1e3:.1f} ms "
                f"p99 {ttft['p99'] * 1e3:.1f} ms, "
                f"per-token p50 {ptl['median'] * 1e3:.2f} ms"
            )
        return report
