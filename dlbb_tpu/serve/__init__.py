"""Serving subsystem: continuous-batching inference over a paged,
mesh-sharded KV-cache, driven by synthetic traffic traces.

- ``kvcache.py`` — the cache pytree (slot dim over dp, kv-head dim over
  tp, GQA-aware) + host block ledger (alloc/free/append accounting);
- ``engine.py``  — bucketed prefill / fixed-shape decode jits and the
  continuous-batching scheduler (admission control, bounded queue,
  step-boundary insert/evict);
- ``traffic.py`` — seeded, replayable arrival processes (Poisson /
  bursty MMPP / diurnal) with sampled prompt/output lengths;
- ``bench.py``   — the trace-driven harness behind ``cli serve``
  (atomic report JSON + manifest + metrics.prom + journal).

See ``docs/serving.md`` for the architecture, cache sharding contract,
trace schema, and report fields.
"""

from dlbb_tpu.serve.engine import (  # noqa: F401
    ServingConfig,
    ServingEngine,
    build_decode_step,
    build_prefill,
)
from dlbb_tpu.serve.kvcache import (  # noqa: F401
    BlockLedger,
    CacheOverflow,
    KVCache,
    create_kv_cache,
)
from dlbb_tpu.serve.traffic import (  # noqa: F401
    Request,
    TrafficTrace,
    generate_trace,
)

__all__ = [
    "BlockLedger",
    "CacheOverflow",
    "KVCache",
    "Request",
    "ServingConfig",
    "ServingEngine",
    "TrafficTrace",
    "build_decode_step",
    "build_prefill",
    "create_kv_cache",
    "generate_trace",
]
