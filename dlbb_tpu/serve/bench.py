"""Trace-driven serving benchmark harness (``cli serve``).

Composes the serving level out of the machinery every other level
already uses: the :class:`~dlbb_tpu.parallel.plan.ParallelismPlan`
resolves and validates the mesh, the resilience journal records request
lifecycle events (fsync'd, reconstructable into a Perfetto timeline via
``cli obs trace``), obs spans wrap the admission/prefill/decode phases,
and every artifact is an atomic write:

- ``serving_<name>.json``   — the full report (``docs/serving.md``);
- ``trace_<name>.json``     — the exact trace served, replayable;
- ``serving_manifest.json`` — run summary + topology fingerprint;
- ``metrics.prom``          — Prometheus textfile
  (``obs.export.serving_metrics``);
- ``sweep_journal.jsonl``   — request lifecycle audit trail.

Graceful drain + deterministic resume (docs/resilience.md): a SIGTERM
mid-trace stops admission, drains the in-flight window, and writes
``serving_resume.json`` — the queue/trace-cursor checkpoint (remaining
rids + the partial report with raw latency samples) next to the full
replayable trace.  ``cli serve --resume`` replays the remaining
requests (arrivals rebased, original gaps preserved) and MERGES the two
sessions into the final artifact set, so it matches an uninterrupted
run: same artifact names, same report schema, and the same per-request
outcomes for every non-preempted request — the invariant
``cli chaos --plan serve`` pins.  The checkpoint is deleted once the
merged artifacts land; an incomplete session never writes
``serving_<name>.json``.
"""

from __future__ import annotations

import json
import time
from dataclasses import replace
from pathlib import Path
from typing import Any, Optional, Sequence

from dlbb_tpu.models.configs import ModelConfig
from dlbb_tpu.serve.engine import ServingConfig, ServingEngine
from dlbb_tpu.serve.traffic import TRACE_KINDS, TrafficTrace, generate_trace

SERVING_MANIFEST_SCHEMA = "dlbb_serving_manifest_v1"
SERVING_RESUME_SCHEMA = "dlbb_serving_resume_v1"
RESUME_CHECKPOINT = "serving_resume.json"

# The CLI's default model when no --config YAML is given: small enough
# that a 100-request trace serves in seconds on the CPU-simulated mesh,
# GQA (kv_heads < num_heads) so the grouped cache path is always the one
# exercised, exact attention as serving requires.
DEFAULT_SERVE_MODEL = dict(
    hidden_size=128, num_layers=4, num_heads=8, num_kv_heads=4,
    ffn_intermediate=256, dtype="float32", attention="full",
)


def _hbm_record(model_cfg: ModelConfig, serving_cfg: ServingConfig,
                plan) -> dict:
    """The HBM envelope a run was admitted under: the analytic
    per-device cache footprint ``validate_serving`` priced (the number
    the static memory audit pins against the compiled decode carry —
    docs/memory_audit.md) next to the configured budget, recorded in
    both the result report and the serving manifest (fresh runs and
    resumed merges alike)."""
    from dlbb_tpu.models.configs import kv_cache_bytes_per_device

    cache_dev = kv_cache_bytes_per_device(
        model_cfg, serving_cfg.max_batch, serving_cfg.max_seq,
        dp=plan.dp, tp=plan.tp,
        kv_quantization=serving_cfg.kv_quantization,
        block_size=serving_cfg.block_size)
    budget = (None if serving_cfg.hbm_budget_gb is None
              else int(serving_cfg.hbm_budget_gb * 2**30))
    return {
        "kv_cache_bytes_per_device": cache_dev,
        "budget_bytes": budget,
        "headroom_bytes": (None if budget is None
                           else budget - cache_dev),
    }


def default_parallelism(n_devices: int, kv_heads: int,
                        max_batch: int) -> tuple[int, int]:
    """Auto (dp, tp) for ``n_devices``: the largest tp in {4, 2, 1} that
    divides the device count AND the kv-head count, then the largest dp
    that divides ``max_batch`` within the remaining devices — both
    serving cache axes populated whenever the mesh allows it, and an
    awkward max_batch costs dp width, never the whole tp axis."""
    for tp in (4, 2, 1):
        if n_devices % tp or kv_heads % tp:
            continue
        for dp in range(n_devices // tp, 0, -1):
            if max_batch % dp == 0:
                return dp, tp
    return 1, 1


def resolve_trace(
    trace: str,
    num_requests: int = 100,
    seed: int = 42,
    rate: Optional[float] = None,
    serving: Optional[ServingConfig] = None,
    deadline_s: Optional[float] = None,
    **params: Any,
) -> TrafficTrace:
    """``--trace`` semantics: a known kind generates a seeded trace
    (lengths bounded to fit the serving envelope); anything else is a
    path to a saved trace JSON."""
    if trace not in TRACE_KINDS:
        return TrafficTrace.load(trace)
    kw: dict[str, Any] = dict(params)
    if rate is not None:
        kw["rate"] = rate
    if deadline_s is not None:
        kw["deadline_s"] = deadline_s
    if serving is not None and "prompt_range" not in kw:
        # bound sampled lengths so every request fits the envelope:
        # prompt within the largest bucket, and max_prompt + max_out <=
        # max_seq BY CONSTRUCTION (max_out is the exact remainder), so
        # the engine's pre-run validation can never reject a generated
        # trace
        max_prompt = min(serving.prefill_buckets[-1],
                         max(1, serving.max_seq // 2))
        max_out = serving.max_seq - max_prompt
        if max_out < 1:
            raise ValueError(
                f"serving.max_seq={serving.max_seq} leaves no room for "
                "output tokens; raise max_seq or pass explicit "
                "prompt_range/output_range"
            )
        kw["prompt_range"] = (min(8, max_prompt), max_prompt)
        kw["output_range"] = (min(4, max_out), min(48, max_out))
    return generate_trace(trace, num_requests, seed=seed, **kw)


def run_serving(
    config: dict[str, Any],
    trace: TrafficTrace,
    output_dir: Optional[str] = None,
    devices: Optional[Sequence] = None,
    journal: bool = True,
    verbose: bool = True,
    fault_plan: Optional[str] = None,
    collect_raw: bool = False,
    device_trace: Optional[str] = None,
    capture_tokens: bool = False,
) -> dict[str, Any]:
    """Run one trace-driven serving benchmark.

    ``config`` follows the experiment-YAML schema with a ``serving:``
    section next to ``model:`` and ``parallelism:`` (world_size = tp,
    data_parallel = dp).  Returns the report dict; when ``output_dir``
    is set, writes the artifact set listed in the module docstring.

    ``fault_plan`` activates the chaos harness for the run (an
    explicit plan wins; else an already-active plan is left alone;
    else ``DLBB_FAULT_PLAN`` — the sweep engine's contract).  A
    SIGTERM mid-trace (or the ``serve-preempt`` site) drains
    gracefully and writes the ``serving_resume.json`` checkpoint
    instead of the result artifact — see :func:`resume_serving`.

    ``device_trace`` (``--device-trace`` / ``DLBB_DEVICE_TRACE``)
    routes through the same ``obs/capture`` gate as sweeps: one
    captured prefill + one captured decode scan per run, AFTER the
    trace has been served (strictly outside timed regions), contained
    failures counted in ``obs_device_capture_failures_total``."""
    import os

    from dlbb_tpu.obs import capture as obs_capture
    from dlbb_tpu.obs import spans
    from dlbb_tpu.obs.export import serving_metrics
    from dlbb_tpu.parallel.plan import ParallelismPlan
    from dlbb_tpu.resilience import inject
    from dlbb_tpu.resilience.journal import SweepJournal
    from dlbb_tpu.resilience.preempt import PreemptionGuard
    from dlbb_tpu.utils.config import save_json
    from dlbb_tpu.utils.simulate import topology_record
    from dlbb_tpu.utils.sysinfo import collect_system_info

    model_cfg = ModelConfig.from_dict(config.get("model",
                                                 DEFAULT_SERVE_MODEL))
    serving_cfg = ServingConfig.from_dict(config.get("serving", {}))
    plan = ParallelismPlan.from_config(config, model_cfg, devices)
    if plan.sp > 1 or plan.pp > 1 or plan.ep > 1:
        raise ValueError(
            f"serving supports (dp, tp) meshes only (got sp={plan.sp}, "
            f"pp={plan.pp}, ep={plan.ep}); the decode step's length-1 "
            "sequence cannot shard over sp/pp, and MoE is outside the "
            "serving envelope"
        )

    # chaos-harness activation (mirrors bench/runner.py): explicit arg
    # wins; else an already-active plan is left alone; else the env
    fault_spec = fault_plan
    if fault_spec is None and inject.active() is None:
        fault_spec = os.environ.get(inject.ENV_VAR, "").strip() or None

    name = config.get("experiment", {}).get("name") or (
        f"{trace.kind}_{len(trace)}req_seed{trace.seed}"
    )
    out = Path(output_dir) if output_dir is not None else None
    jrn = None
    if out is not None and journal:
        jrn = SweepJournal(
            out,
            meta={"mode": "serve", "name": name, "trace_kind": trace.kind,
                  "num_requests": len(trace), "fault_plan": fault_spec},
            sink=spans.journal_sink,
        )
    topology = topology_record()
    try:
        with inject.plan_scope(fault_spec), PreemptionGuard() as guard:
            engine = ServingEngine(
                model_cfg, serving_cfg, plan.mesh,
                journal=jrn,
                seed=config.get("input", {}).get("seed", 0),
                verbose=verbose,
                capture_tokens=capture_tokens,
            )
            # degraded-probe fallbacks are first-class events (ROADMAP
            # standing chore): journaled AND counted, not just a field
            # buried in the topology record
            if jrn is not None:
                jrn.event("topology", **topology)
            engine.registry.inc(
                "serve_degraded", 1 if topology["degraded"] else 0,
                help="runs on a degraded (fallback) backend",
            )
            if topology["degraded"]:
                reason = topology.get("degraded_reason")
                if jrn is not None:
                    jrn.event("degraded", reason=reason)
                if verbose:
                    print(f"[topology] DEGRADED backend: {reason}")
            report = engine.run_trace(trace, guard=guard,
                                      collect_raw=collect_raw)
    finally:
        if jrn is not None:
            jrn.close()

    report["experiment"] = config.get("experiment", {})
    report["backend"] = "xla_tpu"
    report["mesh"] = plan.mesh_dict()
    report["system_info"] = collect_system_info()
    report["timestamp"] = time.time()
    report["hbm"] = _hbm_record(model_cfg, serving_cfg, plan)

    # serving capture parity (docs/observability.md): the gated device
    # capture runs AFTER the trace has been served — never inside a
    # timed region — on fresh state, one prefill + one decode scan
    capture_dir = device_trace or obs_capture.default_capture_dir()
    if capture_dir and not report.get("preempted"):
        with spans.span("device-capture", cat="capture", label="serve"):
            metas = engine.capture_device_traces(capture_dir)
        for m in metas:
            if "error" in m:
                engine.registry.inc(
                    "obs_device_capture_failures",
                    reason=m.get("error_kind", "unknown"),
                    help="contained device-capture failures "
                         "(error recorded in the capture metadata)",
                )
        report["observability"] = {
            "device_trace_dir": str(capture_dir),
            "device_captures": metas,
        }
        if verbose:
            ok = sum(1 for m in metas if "error" not in m)
            print(f"[serve] device capture: {ok}/{len(metas)} phase "
                  f"capture(s) under {capture_dir}")

    if out is not None:
        trace_path = trace.save(out / f"trace_{name}.json")
        if report["preempted"]:
            # graceful-drain checkpoint: the full replayable trace is
            # on disk, this records the cursor (remaining rids) + the
            # partial report with raw samples for the resume merge.
            # The result artifact is NOT written — an incomplete
            # session must never masquerade as a run
            ckpt = {
                "schema": SERVING_RESUME_SCHEMA,
                "name": name,
                "trace_file": trace_path.name,
                "config": config,
                "remaining_rids": report["remaining_rids"],
                "partial": report,
            }
            save_json(ckpt, out / RESUME_CHECKPOINT)
            if verbose:
                print(f"[serve] preempted — checkpoint written to "
                      f"{out / RESUME_CHECKPOINT}; finish with "
                      "`cli serve --resume --output "
                      f"{out}`")
            return report
        result_path = save_json(report, out / f"serving_{name}.json")
        registry = serving_metrics(report, registry=engine.registry)
        prom_path = registry.write_textfile(out / "metrics.prom")
        manifest = {
            "schema": SERVING_MANIFEST_SCHEMA,
            "name": name,
            "result": result_path.name,
            "trace_file": trace_path.name,
            "metrics": prom_path.name,
            "requests": report["requests"],
            "goodput_tokens_per_s": report["goodput_tokens_per_s"],
            "wall_seconds": report["wall_seconds"],
            "compile_time_s": report["compile_time_s"],
            "decode_steps": report["decode_steps"],
            "mesh": report["mesh"],
            "hbm": report["hbm"],
            "topology": topology,
            # replica id -> device ids for fleet runs (serve/fleet.py
            # writes its own manifest); None marks a single-replica run
            # so overlays never silently aggregate across the two
            "fault_domains": topology.get("fault_domains"),
            "journal": (None if jrn is None else jrn.path.name),
        }
        save_json(manifest, out / "serving_manifest.json")
        if verbose:
            print(f"[serve] report written to {result_path}")
    return report


def merge_reports(partial: dict[str, Any],
                  resumed: dict[str, Any]) -> dict[str, Any]:
    """Merge a preempted session's partial report with its resumed
    session into one report equivalent (names + schema + per-request
    outcomes for non-preempted requests) to an uninterrupted run.

    Counters sum across sessions (a preempted-then-replayed request
    therefore counts in both — ``requests.sessions`` records how many
    sessions merged); latency summaries are re-summarized over BOTH
    sessions' raw samples, never faked from two percentile sets; the
    resumed session's outcome for a rid overrides the partial one (a
    ``preempted`` marker resolves to its replayed outcome)."""
    from dlbb_tpu.utils.metrics import summarize

    merged = dict(resumed)
    merged["trace"] = partial["trace"]  # the FULL trace identity
    req_a = partial["requests"]
    req_b = resumed["requests"]
    req: dict[str, Any] = {
        k: req_a.get(k, 0) + req_b.get(k, 0)
        for k in ("arrived", "admitted", "rejected", "completed",
                  "failed", "preempted", "canceled", "deadline_shed",
                  "completed_past_deadline")
    }
    req["rejected_detail"] = (list(req_a.get("rejected_detail", []))
                              + list(req_b.get("rejected_detail", [])))
    req["rejected_rids"] = [d["rid"] for d in req["rejected_detail"]]
    outcomes = dict(req_a.get("outcomes", {}))
    outcomes.update(req_b.get("outcomes", {}))
    req["outcomes"] = {k: outcomes[k]
                       for k in sorted(outcomes, key=int)}
    arrived = req["arrived"]
    queue_full = sum(1 for d in req["rejected_detail"]
                     if d.get("reason") == "queue-full")
    req["shed_rate"] = (queue_full / arrived) if arrived else 0.0
    req["sessions"] = req_a.get("sessions", 1) + req_b.get("sessions", 1)
    merged["requests"] = req

    raw: dict[str, list] = {}
    for key in ("ttft_s", "per_token_s", "prefill_s", "decode_step_s",
                "e2e_latency_s"):
        raw[key] = (list(partial.get("raw_samples", {}).get(key, []))
                    + list(resumed.get("raw_samples", {}).get(key, [])))
    merged["ttft"] = summarize(raw["ttft_s"])
    merged["per_token_latency"] = summarize(raw["per_token_s"])
    merged["e2e_latency"] = summarize(raw["e2e_latency_s"])
    merged["prefill_time"] = summarize(raw["prefill_s"])
    merged["decode_step_time"] = summarize(raw["decode_step_s"])

    for key in ("completed_output_tokens", "generated_tokens",
                "decode_steps", "decode_units", "wall_seconds",
                "compile_time_s"):
        merged[key] = partial.get(key, 0) + resumed.get(key, 0)
    wall = merged["wall_seconds"]
    merged["goodput_tokens_per_s"] = (
        merged["completed_output_tokens"] / wall if wall > 0 else 0.0)
    merged["throughput_tokens_per_s"] = (
        merged["generated_tokens"] / wall if wall > 0 else 0.0)

    fast = dict(resumed.get("fast_path", {}))
    for key in ("fused_scans", "fused_steps", "single_steps",
                "prefill_chunks", "compacted_scans"):
        fast[key] = (partial.get("fast_path", {}).get(key, 0)
                     + resumed.get("fast_path", {}).get(key, 0))
    merged["fast_path"] = fast

    res_a = partial.get("resilience", {})
    res_b = resumed.get("resilience", {})
    merged["resilience"] = {
        "retries": res_a.get("retries", 0) + res_b.get("retries", 0),
        "hung_dispatches": (res_a.get("hung_dispatches", 0)
                            + res_b.get("hung_dispatches", 0)),
        "failed_requests": (res_a.get("failed_requests", 0)
                            + res_b.get("failed_requests", 0)),
        "failed": (list(res_a.get("failed", []))
                   + list(res_b.get("failed", []))),
    }

    cache = dict(resumed.get("cache", {}))
    for key in ("peak_blocks_reserved", "peak_blocks_in_use",
                "peak_shared_blocks"):
        cache[key] = max(partial.get("cache", {}).get(key, 0),
                         resumed.get("cache", {}).get(key, 0))
    cache["cow_blocks"] = (partial.get("cache", {}).get("cow_blocks", 0)
                           + resumed.get("cache", {}).get("cow_blocks", 0))
    merged["cache"] = cache

    if "prefix" in partial or "prefix" in resumed:
        pre_a = partial.get("prefix", {})
        pre_b = resumed.get("prefix", {})
        prefix = dict(pre_b) or dict(pre_a)
        for key in ("hits", "tokens_reused", "cow_blocks"):
            prefix[key] = pre_a.get(key, 0) + pre_b.get(key, 0)
        prefills = len(raw["prefill_s"])
        prefix["hit_rate"] = (prefix.get("hits", 0) / prefills
                              if prefills else 0.0)
        merged["prefix"] = prefix

    # timeseries: the resumed session re-anchored its clock, so its
    # samples are offset by the partial session's wall
    offset = partial.get("wall_seconds", 0.0)
    series_a = partial.get("timeseries", {})
    series_b = resumed.get("timeseries", {})
    series = {}
    for key in series_a:
        vals_b = series_b.get(key, [])
        if key == "t_s":
            vals_b = [round(t + offset, 6) for t in vals_b]
        series[key] = list(series_a.get(key, [])) + list(vals_b)
    merged["timeseries"] = series

    # a resumed session preempted AGAIN keeps its raw samples so the
    # next resume can merge honestly; a completed merge drops them
    if resumed.get("preempted"):
        merged["raw_samples"] = raw
    else:
        merged.pop("raw_samples", None)
    if "completed_tokens" in partial or "completed_tokens" in resumed:
        toks = dict(partial.get("completed_tokens", {}))
        toks.update(resumed.get("completed_tokens", {}))
        merged["completed_tokens"] = toks
    return merged


def resume_serving(
    output_dir: str,
    devices: Optional[Sequence] = None,
    verbose: bool = True,
) -> dict[str, Any]:
    """Finish a preempted serving run (``cli serve --resume``).

    Loads ``serving_resume.json`` + the saved full trace, replays the
    remaining requests (arrivals rebased to the resume instant with
    their original gaps preserved), merges both sessions, and writes
    the final artifact set — identical names + schema (and per-request
    outcomes for non-preempted requests) to an uninterrupted run.  The
    checkpoint is deleted on success; a session preempted AGAIN
    rewrites it with the merged partial instead."""
    from dlbb_tpu.utils.config import save_json

    out = Path(output_dir)
    ckpt_path = out / RESUME_CHECKPOINT
    if not ckpt_path.exists():
        raise FileNotFoundError(
            f"nothing to resume: no {RESUME_CHECKPOINT} under {out} "
            "(either the run completed, or it was never preempted)"
        )
    ckpt = json.loads(ckpt_path.read_text())
    if ckpt.get("schema") != SERVING_RESUME_SCHEMA:
        raise ValueError(
            f"{ckpt_path} is not a serving resume checkpoint "
            f"(schema={ckpt.get('schema')!r})"
        )
    full = TrafficTrace.load(out / ckpt["trace_file"])
    remaining = set(ckpt["remaining_rids"])
    reqs = [r for r in full if r.rid in remaining]
    if not reqs:
        raise ValueError(
            f"checkpoint names no servable remaining requests "
            f"({len(remaining)} rids, none found in {ckpt['trace_file']})"
        )
    # rebase arrivals to the resume instant, preserving the original
    # inter-arrival gaps so the replayed load keeps its shape
    t0 = min(r.arrival_s for r in reqs)
    sub = TrafficTrace(
        kind=full.kind, seed=full.seed,
        params={**full.params, "resumed_from": ckpt["name"]},
        requests=tuple(replace(r, arrival_s=r.arrival_s - t0)
                       for r in sorted(reqs, key=lambda r: (r.arrival_s,
                                                            r.rid))),
    )
    if verbose:
        print(f"[serve] resuming {ckpt['name']}: {len(sub)} remaining "
              f"request(s) of {len(full)}")

    from dlbb_tpu.obs import spans
    from dlbb_tpu.obs.export import serving_metrics
    from dlbb_tpu.parallel.plan import ParallelismPlan
    from dlbb_tpu.resilience.journal import SweepJournal
    from dlbb_tpu.resilience.preempt import PreemptionGuard
    from dlbb_tpu.utils.simulate import topology_record
    from dlbb_tpu.utils.sysinfo import collect_system_info

    config = ckpt["config"]
    name = ckpt["name"]
    model_cfg = ModelConfig.from_dict(config.get("model",
                                                 DEFAULT_SERVE_MODEL))
    serving_cfg = ServingConfig.from_dict(config.get("serving", {}))
    plan = ParallelismPlan.from_config(config, model_cfg, devices)
    # the journal is append-only across sessions: the resume appends a
    # new session marker + its own lifecycle after the preempted one's
    jrn = SweepJournal(
        out,
        meta={"mode": "serve", "name": name, "resume": True,
              "remaining": len(sub)},
        sink=spans.journal_sink,
    )
    try:
        with PreemptionGuard() as guard:
            engine = ServingEngine(
                model_cfg, serving_cfg, plan.mesh, journal=jrn,
                seed=config.get("input", {}).get("seed", 0),
                verbose=verbose,
            )
            resumed = engine.run_trace(sub, guard=guard,
                                       collect_raw=True)
    finally:
        jrn.close()
    resumed["experiment"] = config.get("experiment", {})
    resumed["backend"] = "xla_tpu"
    resumed["mesh"] = plan.mesh_dict()
    resumed["system_info"] = collect_system_info()
    resumed["timestamp"] = time.time()
    resumed["hbm"] = _hbm_record(model_cfg, serving_cfg, plan)

    merged = merge_reports(ckpt["partial"], resumed)
    if merged.get("preempted"):
        # preempted AGAIN mid-resume: refresh the checkpoint with the
        # merged partial; the final artifacts wait for the next resume
        save_json({
            "schema": SERVING_RESUME_SCHEMA,
            "name": name,
            "trace_file": ckpt["trace_file"],
            "config": config,
            "remaining_rids": merged["remaining_rids"],
            "partial": merged,
        }, ckpt_path)
        if verbose:
            print("[serve] preempted again mid-resume — checkpoint "
                  "refreshed")
        return merged
    result_path = save_json(merged, out / f"serving_{name}.json")
    registry = serving_metrics(merged, registry=engine.registry)
    prom_path = registry.write_textfile(out / "metrics.prom")
    manifest = {
        "schema": SERVING_MANIFEST_SCHEMA,
        "name": name,
        "result": result_path.name,
        "trace_file": ckpt["trace_file"],
        "metrics": prom_path.name,
        "requests": merged["requests"],
        "goodput_tokens_per_s": merged["goodput_tokens_per_s"],
        "wall_seconds": merged["wall_seconds"],
        "compile_time_s": merged["compile_time_s"],
        "decode_steps": merged["decode_steps"],
        "mesh": merged["mesh"],
        "hbm": merged.get("hbm"),
        "topology": topology_record(),
        "journal": jrn.path.name,
    }
    save_json(manifest, out / "serving_manifest.json")
    ckpt_path.unlink()
    if verbose:
        print(f"[serve] resumed run merged into {result_path}")
    return merged


def run_serve_from_config(
    config_path: Optional[str],
    trace: str = "poisson",
    num_requests: int = 100,
    seed: int = 42,
    rate: Optional[float] = None,
    output_dir: Optional[str] = None,
    overrides: Optional[dict[str, Any]] = None,
    devices: Optional[Sequence] = None,
    verbose: bool = True,
    resume: bool = False,
    fault_plan: Optional[str] = None,
    slo: Optional[float] = None,
    device_trace: Optional[str] = None,
    prefix_groups: Optional[int] = None,
    prefix_len: Optional[int] = None,
    replicas: Optional[int] = None,
) -> dict[str, Any]:
    """CLI entry: optional experiment YAML + flag overrides (including
    the decode fast-path knobs — decode_horizon / inflight_window /
    prefill_chunk / compact_threshold — and the resilience knobs,
    docs/serving.md).  ``--resume`` finishes a preempted run from its
    ``serving_resume.json`` checkpoint; ``--slo SEC`` stamps generated
    requests with a per-request deadline; ``--fault-plan`` activates
    the chaos harness; ``--prefix-groups``/``--prefix-len`` generate a
    shared-prefix trace (docs/serving.md, "Prefix cache & quantized
    KV") — the traffic shape the ``prefix_caching`` engine exploits.

    Without ``--config`` the default small GQA model serves on an
    auto-planned (dp, tp) mesh over the available devices.

    ``--replicas N`` (or a ``fleet:`` config section) routes the trace
    through the replica-level fleet supervisor instead — N failure
    domains, each its own engine, with health-fencing / failover /
    hedging / the degradation ladder (docs/fleet.md); the
    ``parallelism:`` section then describes ONE replica's mesh."""
    import jax

    from dlbb_tpu.utils.config import load_config

    if resume:
        out = output_dir or "results/serving"
        return resume_serving(out, devices=devices, verbose=verbose)
    if config_path is not None:
        config = load_config(config_path)
    else:
        config = {"model": dict(DEFAULT_SERVE_MODEL)}
    config.setdefault("serving", {})
    if overrides:
        for key, value in sorted(overrides.items()):
            if value is not None:
                config["serving"][key] = value
    serving_cfg = ServingConfig.from_dict(config["serving"])
    if replicas is not None and replicas > 1:
        config.setdefault("fleet", {})["replicas"] = replicas
    fleet = bool(config.get("fleet"))
    if "parallelism" not in config:
        model_cfg = ModelConfig.from_dict(config.get("model",
                                                     DEFAULT_SERVE_MODEL))
        n = len(devices) if devices is not None else len(jax.devices())
        if fleet:
            # fleet parallelism is PER REPLICA: auto-plan within one
            # failure domain's device share
            n //= max(1, int(config["fleet"].get("replicas", 2)))
        dp, tp = default_parallelism(n, model_cfg.kv_heads,
                                     serving_cfg.max_batch)
        config["parallelism"] = {"data_parallel": dp, "world_size": tp}
    trace_kw: dict[str, Any] = {}
    if prefix_groups is not None:
        trace_kw["prefix_groups"] = prefix_groups
    if prefix_len is not None:
        trace_kw["prefix_len"] = prefix_len
    resolved = resolve_trace(trace, num_requests=num_requests, seed=seed,
                             rate=rate, serving=serving_cfg,
                             deadline_s=slo, **trace_kw)
    out = output_dir or config.get("experiment", {}).get(
        "output_dir", "results/serving")
    if fleet:
        from dlbb_tpu.serve.fleet import run_fleet

        return run_fleet(config, resolved, output_dir=out,
                         devices=devices, verbose=verbose,
                         fault_plan=fault_plan)
    return run_serving(config, resolved, output_dir=out, devices=devices,
                       verbose=verbose, fault_plan=fault_plan,
                       device_trace=device_trace)
