"""Trace-driven serving benchmark harness (``cli serve``).

Composes the serving level out of the machinery every other level
already uses: the :class:`~dlbb_tpu.parallel.plan.ParallelismPlan`
resolves and validates the mesh, the resilience journal records request
lifecycle events (fsync'd, reconstructable into a Perfetto timeline via
``cli obs trace``), obs spans wrap the admission/prefill/decode phases,
and every artifact is an atomic write:

- ``serving_<name>.json``   — the full report (``docs/serving.md``);
- ``trace_<name>.json``     — the exact trace served, replayable;
- ``serving_manifest.json`` — run summary + topology fingerprint;
- ``metrics.prom``          — Prometheus textfile
  (``obs.export.serving_metrics``);
- ``sweep_journal.jsonl``   — request lifecycle audit trail.
"""

from __future__ import annotations

import time
from pathlib import Path
from typing import Any, Optional, Sequence

from dlbb_tpu.models.configs import ModelConfig
from dlbb_tpu.serve.engine import ServingConfig, ServingEngine
from dlbb_tpu.serve.traffic import TRACE_KINDS, TrafficTrace, generate_trace

SERVING_MANIFEST_SCHEMA = "dlbb_serving_manifest_v1"

# The CLI's default model when no --config YAML is given: small enough
# that a 100-request trace serves in seconds on the CPU-simulated mesh,
# GQA (kv_heads < num_heads) so the grouped cache path is always the one
# exercised, exact attention as serving requires.
DEFAULT_SERVE_MODEL = dict(
    hidden_size=128, num_layers=4, num_heads=8, num_kv_heads=4,
    ffn_intermediate=256, dtype="float32", attention="full",
)


def default_parallelism(n_devices: int, kv_heads: int,
                        max_batch: int) -> tuple[int, int]:
    """Auto (dp, tp) for ``n_devices``: the largest tp in {4, 2, 1} that
    divides the device count AND the kv-head count, then the largest dp
    that divides ``max_batch`` within the remaining devices — both
    serving cache axes populated whenever the mesh allows it, and an
    awkward max_batch costs dp width, never the whole tp axis."""
    for tp in (4, 2, 1):
        if n_devices % tp or kv_heads % tp:
            continue
        for dp in range(n_devices // tp, 0, -1):
            if max_batch % dp == 0:
                return dp, tp
    return 1, 1


def resolve_trace(
    trace: str,
    num_requests: int = 100,
    seed: int = 42,
    rate: Optional[float] = None,
    serving: Optional[ServingConfig] = None,
    **params: Any,
) -> TrafficTrace:
    """``--trace`` semantics: a known kind generates a seeded trace
    (lengths bounded to fit the serving envelope); anything else is a
    path to a saved trace JSON."""
    if trace not in TRACE_KINDS:
        return TrafficTrace.load(trace)
    kw: dict[str, Any] = dict(params)
    if rate is not None:
        kw["rate"] = rate
    if serving is not None and "prompt_range" not in kw:
        # bound sampled lengths so every request fits the envelope:
        # prompt within the largest bucket, and max_prompt + max_out <=
        # max_seq BY CONSTRUCTION (max_out is the exact remainder), so
        # the engine's pre-run validation can never reject a generated
        # trace
        max_prompt = min(serving.prefill_buckets[-1],
                         max(1, serving.max_seq // 2))
        max_out = serving.max_seq - max_prompt
        if max_out < 1:
            raise ValueError(
                f"serving.max_seq={serving.max_seq} leaves no room for "
                "output tokens; raise max_seq or pass explicit "
                "prompt_range/output_range"
            )
        kw["prompt_range"] = (min(8, max_prompt), max_prompt)
        kw["output_range"] = (min(4, max_out), min(48, max_out))
    return generate_trace(trace, num_requests, seed=seed, **kw)


def run_serving(
    config: dict[str, Any],
    trace: TrafficTrace,
    output_dir: Optional[str] = None,
    devices: Optional[Sequence] = None,
    journal: bool = True,
    verbose: bool = True,
) -> dict[str, Any]:
    """Run one trace-driven serving benchmark.

    ``config`` follows the experiment-YAML schema with a ``serving:``
    section next to ``model:`` and ``parallelism:`` (world_size = tp,
    data_parallel = dp).  Returns the report dict; when ``output_dir``
    is set, writes the artifact set listed in the module docstring."""
    from dlbb_tpu.obs import spans
    from dlbb_tpu.obs.export import serving_metrics
    from dlbb_tpu.parallel.plan import ParallelismPlan
    from dlbb_tpu.resilience.journal import SweepJournal
    from dlbb_tpu.utils.config import save_json
    from dlbb_tpu.utils.simulate import topology_record
    from dlbb_tpu.utils.sysinfo import collect_system_info

    model_cfg = ModelConfig.from_dict(config.get("model",
                                                 DEFAULT_SERVE_MODEL))
    serving_cfg = ServingConfig.from_dict(config.get("serving", {}))
    plan = ParallelismPlan.from_config(config, model_cfg, devices)
    if plan.sp > 1 or plan.pp > 1 or plan.ep > 1:
        raise ValueError(
            f"serving supports (dp, tp) meshes only (got sp={plan.sp}, "
            f"pp={plan.pp}, ep={plan.ep}); the decode step's length-1 "
            "sequence cannot shard over sp/pp, and MoE is outside the "
            "serving envelope"
        )

    name = config.get("experiment", {}).get("name") or (
        f"{trace.kind}_{len(trace)}req_seed{trace.seed}"
    )
    out = Path(output_dir) if output_dir is not None else None
    jrn = None
    if out is not None and journal:
        jrn = SweepJournal(
            out,
            meta={"mode": "serve", "name": name, "trace_kind": trace.kind,
                  "num_requests": len(trace)},
            sink=spans.journal_sink,
        )
    try:
        engine = ServingEngine(
            model_cfg, serving_cfg, plan.mesh,
            journal=jrn,
            seed=config.get("input", {}).get("seed", 0),
            verbose=verbose,
        )
        report = engine.run_trace(trace)
    finally:
        if jrn is not None:
            jrn.close()

    report["experiment"] = config.get("experiment", {})
    report["backend"] = "xla_tpu"
    report["mesh"] = plan.mesh_dict()
    report["system_info"] = collect_system_info()
    report["timestamp"] = time.time()

    if out is not None:
        result_path = save_json(report, out / f"serving_{name}.json")
        trace_path = trace.save(out / f"trace_{name}.json")
        registry = serving_metrics(report, registry=engine.registry)
        prom_path = registry.write_textfile(out / "metrics.prom")
        manifest = {
            "schema": SERVING_MANIFEST_SCHEMA,
            "name": name,
            "result": result_path.name,
            "trace_file": trace_path.name,
            "metrics": prom_path.name,
            "requests": report["requests"],
            "goodput_tokens_per_s": report["goodput_tokens_per_s"],
            "wall_seconds": report["wall_seconds"],
            "compile_time_s": report["compile_time_s"],
            "decode_steps": report["decode_steps"],
            "mesh": report["mesh"],
            "topology": topology_record(),
            "journal": (None if jrn is None else jrn.path.name),
        }
        save_json(manifest, out / "serving_manifest.json")
        if verbose:
            print(f"[serve] report written to {result_path}")
    return report


def run_serve_from_config(
    config_path: Optional[str],
    trace: str = "poisson",
    num_requests: int = 100,
    seed: int = 42,
    rate: Optional[float] = None,
    output_dir: Optional[str] = None,
    overrides: Optional[dict[str, Any]] = None,
    devices: Optional[Sequence] = None,
    verbose: bool = True,
) -> dict[str, Any]:
    """CLI entry: optional experiment YAML + flag overrides (including
    the decode fast-path knobs — decode_horizon / inflight_window /
    prefill_chunk / compact_threshold, docs/serving.md).

    Without ``--config`` the default small GQA model serves on an
    auto-planned (dp, tp) mesh over the available devices."""
    import jax

    from dlbb_tpu.utils.config import load_config

    if config_path is not None:
        config = load_config(config_path)
    else:
        config = {"model": dict(DEFAULT_SERVE_MODEL)}
    config.setdefault("serving", {})
    if overrides:
        for key, value in sorted(overrides.items()):
            if value is not None:
                config["serving"][key] = value
    serving_cfg = ServingConfig.from_dict(config["serving"])
    if "parallelism" not in config:
        model_cfg = ModelConfig.from_dict(config.get("model",
                                                     DEFAULT_SERVE_MODEL))
        n = len(devices) if devices is not None else len(jax.devices())
        dp, tp = default_parallelism(n, model_cfg.kv_heads,
                                     serving_cfg.max_batch)
        config["parallelism"] = {"data_parallel": dp, "world_size": tp}
    resolved = resolve_trace(trace, num_requests=num_requests, seed=seed,
                             rate=rate, serving=serving_cfg)
    out = output_dir or config.get("experiment", {}).get(
        "output_dir", "results/serving")
    return run_serving(config, resolved, output_dir=out, devices=devices,
                       verbose=verbose)
