"""Replica-level fault tolerance: the serving fleet supervisor.

PR-11 made ONE engine survive its own faults (transient dispatches,
torn bookkeeping, graceful preemption).  This module makes the engine
itself a replaceable unit: the device mesh is partitioned into N
independent replica sub-meshes (``comm/mesh.partition_devices`` —
contiguous, disjoint *failure domains*), each running its own
:class:`~dlbb_tpu.serve.engine.ServingEngine` (own ``BlockLedger``, own
KV planes, own journal track), under a host-side supervisor that:

- **routes** admissions least-loaded with prefix affinity: a request
  carrying a ``prefix_seed`` goes back to the replica whose
  ``PrefixTrie`` already holds that prefix (the re-prefill there is a
  cheap attach), falling back to the replica with the fewest resident
  blocks;
- **health-checks** replicas through a per-replica heartbeat — the
  PR-11 dispatch-EMA watchdog generalised one level up.  A replica that
  dies (``serve-replica-kill``), hangs past its heartbeat deadline
  (``serve-replica-hang``), or crashes is **fenced**: no new
  admissions, its kill flag set (a hung replica that later wakes raises
  :class:`ReplicaKilled` at its next loop boundary — it can never
  double-serve), and every resident request **failed over**: re-enqueued
  at the head of a survivor's feed and re-prefilled there, original
  ``arrival_s`` (and therefore ``deadline_s`` accounting) preserved;
- **hedges** stragglers when ``serving.hedge_factor`` is set: a request
  resident past p99 x factor is duplicated onto a second replica,
  first completion wins, the loser is cancelled and its blocks freed —
  greedy decode depends only on (params, request), and every replica
  initialises from the same seed, so the tokens are pinned identical
  either way;
- **degrades** explicitly under overload or shrinking capacity through
  a monotonic ladder (:data:`DEGRADE_LEVELS`): full service -> disable
  speculation -> cap the decode horizon at 1 -> shed best-effort (no
  ``deadline_s``) arrivals.  Every transition is journaled and counted
  (``serve_degrade_transitions_total``); nothing degrades silently.

Failover is transactional: the routing mutation runs against a
snapshot, the ``serve-failover-torn`` site fires after the mutation and
BEFORE any feed push, and a torn attempt restores the snapshot and
retries — a request is never double-routed and a shared prefix block is
never double-freed (the chaos class ``cli chaos --plan fleet`` pins
this, plus token-identity vs an unfaulted single-replica run).

Everything here is strictly host-side: threads, deques and dicts.  No
function in this module is ever traced or jitted, and the static
zero-injection AST pin from PR-11 extends over this file
(``tests/test_fleet.py``) — the jitted prefill/decode programs are
byte-identical with or without a fleet or a fault plan.

See ``docs/fleet.md`` for the supervisor state machine, the failover
contract, hedging semantics and the degradation-ladder table.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from pathlib import Path
from typing import Any, Callable, Optional, Sequence

import numpy as np

from dlbb_tpu.comm.mesh import (available_devices, fault_domain_record,
                                partition_devices)
from dlbb_tpu.models.configs import ModelConfig
from dlbb_tpu.obs.export import MetricsRegistry
from dlbb_tpu.resilience import inject
from dlbb_tpu.resilience.errors import (DeadlineExceeded, InjectedFault,
                                        TornWrite, exception_chain)
from dlbb_tpu.serve.engine import ServingConfig, ServingEngine
from dlbb_tpu.serve.traffic import Request, TrafficTrace

FLEET_REPORT_SCHEMA = "dlbb_fleet_report_v1"

# The degradation ladder, in escalation order.  Transitions are
# monotonic within a run: the supervisor only ever climbs (recovering
# capacity mid-trace would un-shed nobody and make the journal
# ambiguous about which requests saw which service level).
DEGRADE_LEVELS = ("full", "no-speculation", "short-horizon",
                  "shed-best-effort")

# Feed-empty sentinel arrival.  Deliberately NOT float("inf"): the
# engine's admission planner computes ``int(gap / step_ema)`` on the
# next arrival gap, and int(inf) raises.  1e12 seconds is ~31k years —
# far enough.
_FAR_FUTURE_S = 1.0e12

_FENCE_REASONS = ("replica-killed", "replica-hung", "replica-crashed")


class ReplicaKilled(InjectedFault):
    """A replica was killed (the ``serve-replica-kill`` site, or the
    supervisor's kill flag after fencing).  Simulated SIGKILL: it
    propagates straight out of the engine — no cleanup, no report —
    and the supervisor fails the residents over."""


class _FeedHorizon:
    """What an open-but-empty feed shows at index 0: a pseudo-arrival in
    the far future, so the engine's arrival-gap planner keeps decoding
    at full horizon instead of seeing IndexError or int(inf)."""

    __slots__ = ()
    arrival_s = _FAR_FUTURE_S
    rid = -1


_HORIZON = _FeedHorizon()


class RequestFeed:
    """Thread-safe arrival feed a fleet supervisor pushes into and one
    engine drains (``run_trace(..., feed=)``).

    Mimics the deque the engine otherwise builds from the static trace:
    truthiness means "more work may come" (items present OR still
    open), ``[0]`` peeks the next arrival (a far-future sentinel while
    empty-but-open, so the engine idles instead of exiting), and
    ``popleft``/``discard`` mutate from the engine side only.  The
    supervisor closes the feed once every request is fleet-terminal —
    only then does the engine's main loop condition go false."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._items: deque[Request] = deque()
        self._closed = False

    def push(self, req: Request) -> None:
        with self._lock:
            if self._closed:
                raise RuntimeError("push into a closed feed")
            self._items.append(req)

    def push_front(self, req: Request) -> None:
        """Failover re-admission: the moved request jumps the line (it
        already waited its queue time on the dead replica)."""
        with self._lock:
            if self._closed:
                raise RuntimeError("push into a closed feed")
            self._items.appendleft(req)

    def popleft(self) -> Request:
        with self._lock:
            return self._items.popleft()

    def discard(self, rid: int) -> bool:
        """Drop a not-yet-admitted request (hedge-loser cancel)."""
        with self._lock:
            for i, req in enumerate(self._items):
                if req.rid == rid:
                    del self._items[i]
                    return True
        return False

    def close(self) -> None:
        with self._lock:
            self._closed = True

    @property
    def closed(self) -> bool:
        return self._closed

    def __bool__(self) -> bool:
        with self._lock:
            return bool(self._items) or not self._closed

    def __len__(self) -> int:
        with self._lock:
            return len(self._items)

    def __iter__(self):
        with self._lock:
            return iter(list(self._items))

    def __getitem__(self, idx: int) -> Any:
        if idx != 0:
            raise IndexError("feeds only expose the head")
        with self._lock:
            if self._items:
                return self._items[0]
            if not self._closed:
                return _HORIZON
            raise IndexError("feed drained and closed")


class _StartGate:
    """Fleet-shared clock origin.  Every replica compiles, then parks in
    :meth:`arrive`; the supervisor releases the gate once all live
    replicas arrived (or gave up on the dead ones) and the SAME
    ``t0`` becomes every engine's clock origin — arrival offsets and
    ``deadline_s`` accounting agree across the fleet, un-skewed by
    per-replica compile time."""

    def __init__(self, timeout_s: float) -> None:
        self._event = threading.Event()
        self._lock = threading.Lock()
        self._timeout_s = timeout_s
        self.arrived: set[int] = set()
        self.t0: Optional[float] = None

    def arrive(self, replica: int) -> float:
        with self._lock:
            self.arrived.add(replica)
        self._event.wait(self._timeout_s)
        with self._lock:
            if self.t0 is None:
                # gate timed out (supervisor gone?) — fail open with a
                # local origin rather than hanging the replica forever
                self.t0 = time.perf_counter()
            return self.t0

    def release(self) -> float:
        with self._lock:
            if self.t0 is None:
                self.t0 = time.perf_counter()
        self._event.set()
        return self.t0


class ReplicaControl:
    """Per-replica control plane the engine consults strictly at its
    scheduler-loop boundary (``run_trace(..., control=)``): heartbeat
    out, kill/cancel/degradation in.  Everything here is host-side; the
    fault sites fire in :meth:`check`, never inside a jit."""

    def __init__(self, replica: int, gate: _StartGate) -> None:
        self.replica = replica
        self._gate = gate
        self._lock = threading.Lock()
        self._cancels: deque[tuple[int, str]] = deque()
        self._kill_reason: Optional[str] = None
        # degradation knobs the engine reads per loop iteration
        self.spec_enabled = True
        self.horizon_cap: Optional[int] = None
        # lifecycle sink the supervisor installs (engine._event feeds it)
        self.on_event: Optional[Callable[[int, str, dict], None]] = None
        # heartbeat state (supervisor-read)
        self.started = False
        self.last_beat = time.monotonic()
        self.beat_ema: Optional[float] = None
        self.beats = 0

    # -- engine side -------------------------------------------------------

    def sync_start(self) -> float:
        return self._gate.arrive(self.replica)

    def beat(self) -> None:
        now = time.monotonic()
        if self.started:
            dt = now - self.last_beat
            self.beat_ema = (dt if self.beat_ema is None
                             else 0.9 * self.beat_ema + 0.1 * dt)
        self.last_beat = now
        self.started = True
        self.beats += 1

    def check(self) -> None:
        """Loop-boundary fault + kill-flag check.  The hang site sleeps
        (the heartbeat watchdog must fence us meanwhile); the kill site
        — or a fence that already set the flag — raises, so a fenced
        replica can never dispatch again, even one waking from a hang
        after its residents were failed over."""
        if inject.fire("serve-replica-hang"):
            time.sleep(inject.param("hang_seconds"))
        if self._kill_reason is None and inject.fire("serve-replica-kill"):
            with self._lock:
                if self._kill_reason is None:
                    self._kill_reason = "serve-replica-kill"
        if self._kill_reason is not None:
            raise ReplicaKilled(
                f"replica {self.replica} killed ({self._kill_reason})"
            )

    def take_cancels(self) -> list[tuple[int, str]]:
        with self._lock:
            if not self._cancels:
                return []
            out = list(self._cancels)
            self._cancels.clear()
            return out

    # -- supervisor side ---------------------------------------------------

    def request_kill(self, reason: str) -> None:
        with self._lock:
            if self._kill_reason is None:
                self._kill_reason = reason

    @property
    def kill_reason(self) -> Optional[str]:
        return self._kill_reason

    def cancel(self, rid: int, reason: str) -> None:
        with self._lock:
            self._cancels.append((rid, reason))


class _ReplicaJournal:
    """A replica's view of the ONE shared fleet journal: every line
    gains ``replica=N`` (the per-replica Perfetto track key —
    ``obs/spans.journal_to_trace``) and writes serialise through a
    shared lock (``SweepJournal`` is single-writer by design)."""

    def __init__(self, journal: Any, replica: int,
                 lock: threading.Lock) -> None:
        self._journal = journal
        self._lock = lock
        self.replica = replica

    def event(self, event: str, config: Optional[str] = None,
              **extra: Any) -> None:
        if self._journal is None:
            return
        extra.setdefault("replica", self.replica)
        with self._lock:
            self._journal.event(event, config=config, **extra)


class FleetConfig:
    """Fleet-level knobs (the ``fleet:`` config section).

    replicas             independent failure domains to partition the
                         device mesh into
    heartbeat_factor     fence a replica silent for factor x its own
                         loop-period EMA ...
    heartbeat_min_s      ... but never sooner than this floor (compile
                         stalls and idle sleeps are legal silences)
    start_timeout_s      cap on waiting for every replica to compile
                         and reach the shared clock gate
    stall_timeout_s      fleet-level fail-closed: no routing/terminal
                         progress for this long ends the run with every
                         outstanding request failed, never a hang
    degrade              enable the automatic overload ladder
    degrade_high_water   escalate one level when resident requests
                         exceed this multiple of live slot capacity
    degrade_interval_s   minimum spacing between automatic escalations
    hedge_min_completions completions needed before the p99 estimate is
                         trusted enough to hedge on
    tick_s               supervisor loop period
    """

    _FIELDS = ("replicas", "heartbeat_factor", "heartbeat_min_s",
               "start_timeout_s", "stall_timeout_s", "degrade",
               "degrade_high_water", "degrade_interval_s",
               "hedge_min_completions", "tick_s")

    def __init__(self, replicas: int = 2, heartbeat_factor: float = 32.0,
                 heartbeat_min_s: float = 1.5,
                 start_timeout_s: float = 120.0,
                 stall_timeout_s: float = 120.0, degrade: bool = True,
                 degrade_high_water: float = 2.0,
                 degrade_interval_s: float = 0.25,
                 hedge_min_completions: int = 8,
                 tick_s: float = 0.005) -> None:
        self.replicas = int(replicas)
        self.heartbeat_factor = float(heartbeat_factor)
        self.heartbeat_min_s = float(heartbeat_min_s)
        self.start_timeout_s = float(start_timeout_s)
        self.stall_timeout_s = float(stall_timeout_s)
        self.degrade = bool(degrade)
        self.degrade_high_water = float(degrade_high_water)
        self.degrade_interval_s = float(degrade_interval_s)
        self.hedge_min_completions = int(hedge_min_completions)
        self.tick_s = float(tick_s)

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "FleetConfig":
        unknown = set(d) - set(cls._FIELDS)
        if unknown:
            raise ValueError(
                f"unknown fleet config key(s) {sorted(unknown)} "
                f"(known: {list(cls._FIELDS)})"
            )
        return cls(**{k: d[k] for k in cls._FIELDS if k in d})

    def to_dict(self) -> dict[str, Any]:
        return {k: getattr(self, k) for k in self._FIELDS}

    def validate(self) -> None:
        if self.replicas < 1:
            raise ValueError(f"fleet.replicas={self.replicas} must be >= 1")
        if self.heartbeat_factor < 1.0:
            raise ValueError(
                f"fleet.heartbeat_factor={self.heartbeat_factor} must be "
                ">= 1 (a sub-EMA deadline fences healthy replicas)"
            )
        for knob in ("heartbeat_min_s", "start_timeout_s",
                     "stall_timeout_s", "degrade_high_water",
                     "degrade_interval_s", "tick_s"):
            if getattr(self, knob) <= 0:
                raise ValueError(f"fleet.{knob} must be > 0")
        if self.hedge_min_completions < 1:
            raise ValueError("fleet.hedge_min_completions must be >= 1")


def validate_fleet(config: dict[str, Any], model_cfg: ModelConfig,
                   serving_cfg: ServingConfig, fleet_cfg: FleetConfig,
                   n_devices: int) -> tuple[int, int]:
    """The fleet admission ladder — every rung rejects BEFORE any
    replica builds, with the reason, never as a mid-run OOM or a
    lopsided fleet:

    1. the fleet knobs themselves are sane;
    2. the device count partitions into ``replicas`` equal failure
       domains;
    3. the per-replica (dp, tp) plan fits inside one domain;
    4. the per-replica serving envelope (incl. the HBM budget — each
       replica carries its OWN full KV planes) passes the engine's own
       ``ServingConfig.validate``.

    Returns the per-replica ``(dp, tp)``."""
    fleet_cfg.validate()
    par = dict(config.get("parallelism", {}))
    tp = int(par.get("world_size", 1))
    dp = int(par.get("data_parallel", 1))
    for axis in ("sequence_parallel", "pipeline_parallel",
                 "expert_parallel"):
        if int(par.get(axis, 1)) > 1:
            raise ValueError(
                f"serving fleets support (dp, tp) replicas only "
                f"(got {axis}={par[axis]})"
            )
    if n_devices % fleet_cfg.replicas != 0:
        raise ValueError(
            f"{n_devices} device(s) do not partition into "
            f"{fleet_cfg.replicas} equal failure domains"
        )
    per_domain = n_devices // fleet_cfg.replicas
    if dp * tp > per_domain:
        raise ValueError(
            f"per-replica plan dp={dp} x tp={tp} needs {dp * tp} "
            f"devices but each of the {fleet_cfg.replicas} failure "
            f"domains has only {per_domain} "
            f"({n_devices} devices total)"
        )
    serving_cfg.validate(model_cfg, dp=dp, tp=tp)
    return dp, tp


# engine terminal lifecycle events -> fleet outcome kind
_TERMINAL_EVENTS = {
    "request-completed": "completed",
    "request-failed": "failed",
    "request-rejected": "rejected",
    "request-infeasible": "rejected",
    "request-canceled": "canceled",
}


class FleetSupervisor:
    """Host-side control plane over N replica engines (module
    docstring).  One instance serves one trace; all shared state is
    owned by the supervisor thread — replica threads communicate only
    through the event deque (lifecycle sink), their control objects,
    and their feeds."""

    def __init__(self, model_cfg: ModelConfig, serving_cfg: ServingConfig,
                 fleet_cfg: FleetConfig, meshes: Sequence,
                 fault_domains: Optional[dict[str, list[int]]] = None,
                 seed: int = 0, journal: Any = None,
                 registry: Optional[MetricsRegistry] = None,
                 verbose: bool = False,
                 capture_tokens: bool = True) -> None:
        if not meshes:
            raise ValueError("a fleet needs at least one replica mesh")
        self.model = model_cfg
        self.serving = serving_cfg
        self.fleet = fleet_cfg
        self.meshes = list(meshes)
        self.fault_domains = dict(fault_domains or {})
        self.seed = seed
        self.journal = journal
        self.verbose = verbose
        self.capture_tokens = capture_tokens
        self.registry = registry if registry is not None else MetricsRegistry()
        self._failover_counter = self.registry.labeled_counter(
            "serve_failovers", "reason", initial=_FENCE_REASONS,
            help="requests failed over off a fenced replica, by fence "
                 "reason")
        self._hedge_counter = self.registry.labeled_counter(
            "serve_hedges", "outcome", initial=("issued", "won", "lost"),
            help="hedged requests: issued duplicates, and whether the "
                 "hedge (won) or the primary (lost) completed first")
        self._degrade_counter = self.registry.labeled_counter(
            "serve_degrade_transitions", "level",
            initial=DEGRADE_LEVELS[1:],
            help="degradation-ladder escalations, by level entered")

        R = len(self.meshes)
        self._gate = _StartGate(fleet_cfg.start_timeout_s)
        self._jlock = threading.Lock()
        self.controls = [ReplicaControl(i, self._gate) for i in range(R)]
        self.feeds = [RequestFeed() for _ in range(R)]
        self.engines: list[Optional[ServingEngine]] = [None] * R
        self.reports: list[Optional[dict]] = [None] * R
        self.death: list[Optional[dict]] = [None] * R
        self._threads: list[Optional[threading.Thread]] = [None] * R
        self._done = [False] * R
        self._fenced = [False] * R
        self._fence_reason: list[Optional[str]] = [None] * R

        # routing state (supervisor thread only)
        self._events: deque[tuple[int, int, str, dict]] = deque()
        self._elock = threading.Lock()
        self._req_by_rid: dict[int, Request] = {}
        self._assign: dict[int, int] = {}      # rid -> primary replica
        self._hedged: dict[int, int] = {}      # rid -> hedge replica
        self._hedge_resolved: set[int] = set()
        self._terminal: dict[int, str] = {}    # rid -> fleet outcome
        self._routed_at: dict[int, float] = {}
        self._copy_blocks: dict[tuple[int, int], int] = {}
        self._blocks = [0] * R                 # resident-block estimate
        self._routed_count = [0] * R
        self._affinity: dict[tuple, int] = {}
        self._affinity_hits = 0
        self._affinity_misses = 0
        self._shed = 0
        self._e2e: list[float] = []
        self._ttft: dict[int, float] = {}
        self._tokens: dict[int, list[int]] = {}
        self._completed_by: dict[int, int] = {}
        self._failover_rids: set[int] = set()
        self._failover_log: list[dict[str, Any]] = []
        self._level = 0
        self._degrade_log: list[dict[str, Any]] = []
        self._last_degrade = -1.0e9
        self._t0: Optional[float] = None

    # -- journal -----------------------------------------------------------

    def _jevent(self, event: str, config: Optional[str] = None,
                **extra: Any) -> None:
        if self.journal is None:
            return
        with self._jlock:
            self.journal.event(event, config=config, **extra)

    # -- replica workers ---------------------------------------------------

    def _sink(self, replica: int) -> Callable[[int, str, dict], None]:
        def on_event(rid: int, event: str, extra: dict) -> None:
            with self._elock:
                self._events.append((replica, rid, event, extra))
        return on_event

    def _worker(self, idx: int, trace: TrafficTrace) -> None:
        ctl = self.controls[idx]
        try:
            engine = ServingEngine(
                self.model, self.serving, self.meshes[idx],
                journal=_ReplicaJournal(self.journal, idx, self._jlock),
                seed=self.seed, verbose=False,
                capture_tokens=self.capture_tokens,
            )
            self.engines[idx] = engine
            ctl.on_event = self._sink(idx)
            self._jevent("replica-up", replica=idx,
                         devices=self.fault_domains.get(str(idx)))
            self.reports[idx] = engine.run_trace(
                trace, feed=self.feeds[idx], control=ctl)
        except ReplicaKilled as e:
            self.death[idx] = {"reason": "replica-killed",
                               **exception_chain(e)}
            self._jevent("replica-failed", replica=idx,
                         reason="replica-killed", **exception_chain(e))
        except BaseException as e:  # noqa: BLE001 — fail closed, never hang
            self.death[idx] = {"reason": "replica-crashed",
                               **exception_chain(e)}
            self._jevent("replica-failed", replica=idx,
                         reason="replica-crashed", **exception_chain(e))
        finally:
            self._done[idx] = True

    # -- clock -------------------------------------------------------------

    def _now(self) -> float:
        return time.perf_counter() - (self._t0 or time.perf_counter())

    # -- routing -----------------------------------------------------------

    def _blocks_for(self, req: Request) -> int:
        total = req.prompt_len + req.output_len
        return -(-total // self.serving.block_size)

    def _admittable(self) -> list[int]:
        return [i for i in range(len(self.meshes))
                if not self._fenced[i] and not self._done[i]]

    def _pick(self, req: Request,
              exclude: frozenset = frozenset()) -> Optional[int]:
        alive = [i for i in self._admittable() if i not in exclude]
        if not alive:
            return None
        key = None
        if req.prefix_seed is not None:
            key = (req.prefix_seed, req.prefix_len)
            aff = self._affinity.get(key)
            if aff is not None and aff in alive:
                self._affinity_hits += 1
                return aff
        tgt = min(alive, key=lambda i: (self._blocks[i], i))
        if key is not None:
            self._affinity[key] = tgt
            self._affinity_misses += 1
        return tgt

    def _push(self, rid: int, req: Request, tgt: int,
              front: bool = False) -> None:
        self._assign[rid] = tgt
        nb = self._blocks_for(req)
        self._copy_blocks[(rid, tgt)] = nb
        self._blocks[tgt] += nb
        (self.feeds[tgt].push_front if front
         else self.feeds[tgt].push)(req)
        self._routed_count[tgt] += 1

    def _route(self, req: Request) -> None:
        rid = req.rid
        self._req_by_rid.setdefault(rid, req)
        if self._level >= 3 and req.deadline_s is None:
            # shed-best-effort: requests without an SLO class are
            # rejected at the door while the fleet is at ladder level 3
            self._terminal[rid] = "rejected[degraded-shed]"
            self._shed += 1
            self._jevent("request-rejected", config=f"request-{rid}",
                         reason="degraded-shed", level=self._level)
            return
        tgt = self._pick(req)
        if tgt is None:
            self._terminal[rid] = "failed[no-replica]"
            self._jevent("request-failed", config=f"request-{rid}",
                         reason="no-replica")
            return
        self._routed_at[rid] = self._now()
        self._push(rid, req, tgt)

    # -- lifecycle events --------------------------------------------------

    def _drain_events(self) -> int:
        with self._elock:
            batch = list(self._events)
            self._events.clear()
        for replica, rid, event, extra in batch:
            self._handle_event(replica, rid, event, extra)
        return len(batch)

    def _handle_event(self, rep: int, rid: int, event: str,
                      extra: dict) -> None:
        if event == "request-prefill":
            ttft = extra.get("ttft_s")
            if ttft is not None:
                # last write wins: a failed-over request's re-prefill
                # overwrites the dead replica's number — THAT is the
                # TTFT the client observed
                self._ttft[rid] = float(ttft)
            return
        kind = _TERMINAL_EVENTS.get(event)
        if kind is None:
            return
        nb = self._copy_blocks.pop((rid, rep), None)
        if nb:
            self._blocks[rep] = max(0, self._blocks[rep] - nb)
        reason = extra.get("reason")
        out = ("completed" if kind == "completed"
               else f"{kind}[{reason}]" if reason else kind)
        prev = self._terminal.get(rid)
        # precedence: a completion anywhere beats any other copy's fate
        # (hedge loser cancels, fence-time failures); first-terminal
        # wins otherwise
        if prev is None or (kind == "completed"
                            and not prev.startswith("completed")):
            self._terminal[rid] = out
        if kind == "completed":
            lat = extra.get("latency_s")
            if prev is None or not prev.startswith("completed"):
                if lat is not None:
                    self._e2e.append(float(lat))
                self._completed_by[rid] = rep
                toks = extra.get("tokens")
                if toks is not None:
                    self._tokens[rid] = [int(t) for t in toks]
            hedge = self._hedged.get(rid)
            if hedge is not None and rid not in self._hedge_resolved:
                self._hedge_resolved.add(rid)
                won = rep == hedge
                self._hedge_counter["won" if won else "lost"] += 1
                loser = self._assign.get(rid) if won else hedge
                if (loser is not None and loser != rep
                        and not self._fenced[loser]
                        and not self._done[loser]):
                    self.controls[loser].cancel(rid, "hedge-lost")

    # -- fencing & failover ------------------------------------------------

    def _routing_snapshot(self) -> dict[str, Any]:
        return {
            "assign": dict(self._assign),
            "blocks": list(self._blocks),
            "copy_blocks": dict(self._copy_blocks),
            "affinity": dict(self._affinity),
            "hedged": dict(self._hedged),
            "routed_count": list(self._routed_count),
        }

    def _restore_routing(self, snap: dict[str, Any]) -> None:
        self._assign = dict(snap["assign"])
        self._blocks = list(snap["blocks"])
        self._copy_blocks = dict(snap["copy_blocks"])
        self._affinity = dict(snap["affinity"])
        self._hedged = dict(snap["hedged"])
        self._routed_count = list(snap["routed_count"])

    def _fence(self, idx: int, reason: str,
               chain: Optional[dict] = None) -> None:
        """Fence ``idx`` (kill flag + closed feed + purged affinity) and
        fail its residents over.  The routing mutation is transactional:
        built against a snapshot, ``serve-failover-torn`` fires after
        the mutation and before any feed push, and a torn attempt rolls
        back and retries — never a double-routed request or a leaked
        block estimate."""
        if self._fenced[idx]:
            return
        self._fenced[idx] = True
        self._fence_reason[idx] = reason
        self.controls[idx].request_kill(reason)
        self.feeds[idx].close()
        self._jevent("replica-fenced", replica=idx, reason=reason,
                     **(chain or {}))
        if self.verbose:
            print(f"[fleet] replica {idx} FENCED ({reason})")
        # the dead replica's block estimates and prefix homes are moot
        self._blocks[idx] = 0
        for key in [k for k in self._copy_blocks if k[1] == idx]:
            del self._copy_blocks[key]
        self._affinity = {k: v for k, v in self._affinity.items()
                          if v != idx}
        # hedge copies touching the dead replica resolve to the survivor
        for rid, hedge in list(self._hedged.items()):
            if hedge == idx:
                del self._hedged[rid]
            elif self._assign.get(rid) == idx:
                self._assign[rid] = hedge
                del self._hedged[rid]
        residents = [rid for rid, rep in self._assign.items()
                     if rep == idx and rid not in self._terminal]
        pushes: list[tuple[int, Request, int]] = []
        orphans: list[int] = []
        for attempt in (1, 2):
            snap = self._routing_snapshot()
            pushes, orphans = [], []
            try:
                for rid in residents:
                    req = self._req_by_rid[rid]
                    tgt = self._pick(req, exclude=frozenset({idx}))
                    if tgt is None:
                        orphans.append(rid)
                        continue
                    self._assign[rid] = tgt
                    nb = self._blocks_for(req)
                    self._copy_blocks[(rid, tgt)] = nb
                    self._blocks[tgt] += nb
                    self._routed_count[tgt] += 1
                    pushes.append((rid, req, tgt))
                if pushes and inject.fire("serve-failover-torn"):
                    raise TornWrite(
                        "fleet routing table torn mid-failover")
                break
            except TornWrite as e:
                self._restore_routing(snap)
                self._jevent("failover-torn", replica=idx,
                             attempt=attempt, **exception_chain(e))
                if attempt == 2:
                    raise
        # COMMIT — only a committed routing table touches the feeds,
        # so a torn attempt above never half-delivered a request
        for rid, req, tgt in pushes:
            self.feeds[tgt].push_front(req)
            self._failover_counter[reason] += 1
            self._failover_rids.add(rid)
            rec = {"rid": rid, "from": idx, "to": tgt, "reason": reason}
            self._failover_log.append(rec)
            self._jevent("request-failover", config=f"request-{rid}",
                         from_replica=idx, to_replica=tgt, reason=reason,
                         **(chain or {}))
        for rid in orphans:
            self._terminal[rid] = "failed[replica-lost]"
            self._jevent("request-failed", config=f"request-{rid}",
                         reason="replica-lost", replica=idx,
                         **(chain or {}))

    def _health(self) -> None:
        for idx in range(len(self.meshes)):
            if self._fenced[idx]:
                continue
            if self._done[idx]:
                if self.death[idx] is not None:
                    self._fence(idx, self.death[idx]["reason"],
                                chain={k: v
                                       for k, v in self.death[idx].items()
                                       if k != "reason"})
                continue
            ctl = self.controls[idx]
            if not ctl.started:
                continue  # still compiling — the start gate owns this
            ema = ctl.beat_ema if ctl.beat_ema else 0.05
            deadline = max(self.fleet.heartbeat_min_s,
                           self.fleet.heartbeat_factor * ema)
            if time.monotonic() - ctl.last_beat > deadline:
                exc = DeadlineExceeded(f"replica-{idx} heartbeat",
                                       deadline, phase="heartbeat")
                self._fence(idx, "replica-hung",
                            chain=exception_chain(exc))

    # -- hedging -----------------------------------------------------------

    def _maybe_hedge(self, now: float) -> None:
        factor = self.serving.hedge_factor
        if factor is None:
            return
        if len(self._e2e) < self.fleet.hedge_min_completions:
            return
        threshold = factor * float(np.quantile(self._e2e, 0.99))
        for rid, routed_at in list(self._routed_at.items()):
            if (rid in self._terminal or rid in self._hedged
                    or now - routed_at <= threshold):
                continue
            primary = self._assign.get(rid)
            if primary is None:
                continue
            req = self._req_by_rid[rid]
            alt = self._pick(req, exclude=frozenset({primary}))
            if alt is None:
                continue
            self._hedged[rid] = alt
            nb = self._blocks_for(req)
            self._copy_blocks[(rid, alt)] = nb
            self._blocks[alt] += nb
            self._routed_count[alt] += 1
            self.feeds[alt].push_front(req)
            self._hedge_counter["issued"] += 1
            self._jevent("request-hedged", config=f"request-{rid}",
                         primary=primary, hedge=alt,
                         threshold_s=round(threshold, 6))

    # -- degradation ladder ------------------------------------------------

    def degrade_to(self, level: int, reason: str) -> None:
        """Climb the ladder to ``level`` (monotonic: requests to a
        level at or below the current one are no-ops — the fleet never
        silently recovers service classes mid-run).  Each level entered
        is applied to every live replica, journaled, and counted."""
        level = int(level)
        if level <= self._level:
            return
        if level >= len(DEGRADE_LEVELS):
            raise ValueError(
                f"degrade level {level} out of range "
                f"(max {len(DEGRADE_LEVELS) - 1})"
            )
        while self._level < level:
            self._level += 1
            name = DEGRADE_LEVELS[self._level]
            if self._level == 1:
                for ctl in self.controls:
                    ctl.spec_enabled = False
            elif self._level == 2:
                for ctl in self.controls:
                    ctl.horizon_cap = 1
            # level 3 (shed-best-effort) acts at routing time
            self._degrade_counter[name] += 1
            rec = {"level": self._level, "name": name, "reason": reason,
                   "t_s": round(self._now(), 6)}
            self._degrade_log.append(rec)
            self._jevent("degrade-transition", level=self._level,
                         name=name, reason=reason)
            if self.verbose:
                print(f"[fleet] DEGRADE -> {name} ({reason})")

    def _maybe_degrade(self, now: float) -> None:
        if (not self.fleet.degrade or self._level >= 3
                or now - self._last_degrade
                < self.fleet.degrade_interval_s):
            return
        alive = self._admittable()
        if not alive:
            return
        capacity = len(alive) * self.serving.max_batch
        resident = sum(1 for rid in self._assign
                       if rid not in self._terminal)
        pressure = resident / max(1, capacity)
        if pressure > self.fleet.degrade_high_water:
            self._last_degrade = now
            self.degrade_to(
                self._level + 1,
                f"overload: {resident} resident requests over "
                f"{capacity} live slots (pressure {pressure:.2f})")

    # -- gauges ------------------------------------------------------------

    def _export_gauges(self) -> None:
        resident: dict[int, int] = {i: 0 for i in range(len(self.meshes))}
        for rid, rep in self._assign.items():
            if rid not in self._terminal:
                resident[rep] += 1
        for rid, rep in self._hedged.items():
            if rid not in self._terminal:
                resident[rep] += 1
        for i, n in resident.items():
            self.registry.set_gauge(
                "serve_replica_resident_requests", n, replica=str(i),
                help="requests resident (routed, not terminal) per "
                     "replica")
        self.registry.set_gauge(
            "serve_fleet_degrade_level", self._level,
            help="current degradation-ladder level (0 = full service)")
        self.registry.set_gauge(
            "serve_fleet_live_replicas", len(self._admittable()),
            help="replicas admitting new requests")

    # -- the run -----------------------------------------------------------

    def serve(self, trace: TrafficTrace) -> dict[str, Any]:
        """Serve ``trace`` across the fleet; returns the aggregated
        fleet report (schema :data:`FLEET_REPORT_SCHEMA`)."""
        R = len(self.meshes)
        reqs = sorted(trace, key=lambda r: (r.arrival_s, r.rid))
        if not reqs:
            raise ValueError("cannot serve an empty trace")
        self._req_by_rid = {r.rid: r for r in reqs}
        for i in range(R):
            t = threading.Thread(target=self._worker, args=(i, trace),
                                 name=f"fleet-replica-{i}", daemon=True)
            self._threads[i] = t
            t.start()
        # hold the gate until every replica that is still alive has
        # compiled and parked — the shared t0 keeps arrival offsets and
        # deadline_s accounting identical across the fleet
        gate_deadline = time.monotonic() + self.fleet.start_timeout_s
        while time.monotonic() < gate_deadline:
            with self._gate._lock:
                arrived = set(self._gate.arrived)
            if all(self._done[i] or i in arrived for i in range(R)):
                break
            time.sleep(0.01)
        self._t0 = self._gate.release()
        wall_start = time.perf_counter()

        i = 0
        last_progress = time.monotonic()
        while True:
            now = self._now()
            progressed = 0
            while i < len(reqs) and reqs[i].arrival_s <= now:
                self._route(reqs[i])
                i += 1
                progressed += 1
            progressed += self._drain_events()
            self._health()
            self._maybe_hedge(now)
            self._maybe_degrade(now)
            self._export_gauges()
            outstanding = [rid for rid in self._assign
                           if rid not in self._terminal]
            if progressed:
                last_progress = time.monotonic()
            if i >= len(reqs) and not outstanding:
                break
            if not self._admittable():
                # the whole fleet is gone: fail closed, loudly — every
                # unserved request gets a terminal outcome and the run
                # ends instead of hanging
                for j in range(i, len(reqs)):
                    rid = reqs[j].rid
                    self._terminal[rid] = "failed[no-replica]"
                    self._jevent("request-failed",
                                 config=f"request-{rid}",
                                 reason="no-replica")
                i = len(reqs)
                for rid in outstanding:
                    if rid not in self._terminal:
                        self._terminal[rid] = "failed[replica-lost]"
                        self._jevent("request-failed",
                                     config=f"request-{rid}",
                                     reason="replica-lost")
                break
            if (time.monotonic() - last_progress
                    > self.fleet.stall_timeout_s):
                self._jevent("fleet-stall",
                             outstanding=sorted(outstanding),
                             timeout_s=self.fleet.stall_timeout_s)
                for rid in outstanding:
                    self._terminal[rid] = "failed[fleet-stall]"
                    self._jevent("request-failed",
                                 config=f"request-{rid}",
                                 reason="fleet-stall")
                for idx in self._admittable():
                    self._fence(idx, "replica-hung",
                                chain={"error": "fleet stall timeout"})
                break
            time.sleep(self.fleet.tick_s)

        for feed in self.feeds:
            feed.close()
        for i, t in enumerate(self._threads):
            if t is None:
                continue
            # a fenced replica may still be inside an injected hang; its
            # thread is a daemon and will observe the kill flag on wake —
            # don't let shutdown block on it
            t.join(timeout=2.0 if self._fenced[i] else 60.0)
        self._drain_events()
        self._export_gauges()
        wall = time.perf_counter() - wall_start
        return self._build_report(trace, wall)

    # -- the report --------------------------------------------------------

    def _build_report(self, trace: TrafficTrace,
                      wall: float) -> dict[str, Any]:
        from dlbb_tpu.utils.metrics import summarize

        R = len(self.meshes)
        outcomes = {rid: self._terminal.get(rid, "failed[unresolved]")
                    for rid in self._req_by_rid}
        counts = {"completed": 0, "failed": 0, "rejected": 0,
                  "canceled": 0, "preempted": 0}
        for out in outcomes.values():
            for k in counts:
                if out.startswith(k):
                    counts[k] += 1
                    break
        replicas = []
        for i in range(R):
            rep = self.reports[i]
            if rep is not None:
                # the fleet artifact carries the aggregate; strip the
                # per-replica bulk (fleet-level tokens/series are the
                # authoritative copies)
                rep = {k: v for k, v in rep.items()
                       if k not in ("timeseries", "completed_tokens")}
            status = ("fenced" if self._fenced[i]
                      else "failed" if self.death[i] is not None
                      else "ok")
            replicas.append({
                "replica": i,
                "devices": self.fault_domains.get(str(i)),
                "status": status,
                "fence_reason": self._fence_reason[i],
                "routed": self._routed_count[i],
                "death": self.death[i],
                "report": rep,
            })
        clean_ttft = [v for rid, v in self._ttft.items()
                      if rid not in self._failover_rids]
        fo_ttft = [v for rid, v in self._ttft.items()
                   if rid in self._failover_rids]
        penalty = (float(np.mean(fo_ttft) - np.mean(clean_ttft))
                   if fo_ttft and clean_ttft else None)
        completed_tokens = sum(
            self._req_by_rid[rid].output_len
            for rid, out in outcomes.items() if out == "completed")
        report: dict[str, Any] = {
            "schema": FLEET_REPORT_SCHEMA,
            "model": {
                "hidden_size": self.model.hidden_size,
                "num_layers": self.model.num_layers,
                "num_heads": self.model.num_heads,
                "kv_heads": self.model.kv_heads,
                "attention": self.model.attention,
                "dtype": self.model.dtype,
            },
            "serving": self.serving.to_dict(),
            "fleet": {**self.fleet.to_dict(),
                      "fault_domains": self.fault_domains},
            "trace": {"kind": trace.kind, "seed": trace.seed,
                      "num_requests": len(trace)},
            "requests": {
                "arrived": len(trace),
                "shed": self._shed,
                "outcomes": {str(r): o
                             for r, o in sorted(outcomes.items())},
                **counts,
            },
            "routing": {
                "per_replica": {str(i): self._routed_count[i]
                                for i in range(R)},
                "prefix_affinity_hits": self._affinity_hits,
                "prefix_affinity_misses": self._affinity_misses,
            },
            "replicas": replicas,
            "failovers": {
                "total": len(self._failover_log),
                "by_reason": {r: int(self._failover_counter[r])
                              for r in _FENCE_REASONS},
                "requests": self._failover_log,
            },
            "hedges": {k: int(self._hedge_counter[k])
                       for k in ("issued", "won", "lost")},
            "degrade": {"level": self._level,
                        "name": DEGRADE_LEVELS[self._level],
                        "transitions": self._degrade_log},
            "ttft": summarize(sorted(self._ttft.values())),
            "ttft_failover": summarize(sorted(fo_ttft)),
            "failover_ttft_penalty_s": penalty,
            "e2e_latency": summarize(sorted(self._e2e)),
            "goodput_tokens_per_s": (completed_tokens / wall
                                     if wall > 0 else 0.0),
            "wall_seconds": wall,
        }
        if self.capture_tokens:
            report["completed_tokens"] = {
                str(rid): toks
                for rid, toks in sorted(self._tokens.items())
            }
        return report


def run_fleet(
    config: dict[str, Any],
    trace: TrafficTrace,
    output_dir: Optional[str] = None,
    devices: Optional[Sequence] = None,
    journal: bool = True,
    verbose: bool = True,
    fault_plan: Optional[str] = None,
    capture_tokens: bool = True,
) -> dict[str, Any]:
    """Run one trace across a replica fleet (the ``cli serve
    --replicas N`` entry point).

    ``config`` follows the experiment-YAML schema with ``fleet:`` next
    to ``serving:``/``model:``/``parallelism:`` (the parallelism plan is
    PER REPLICA).  Writes the serving artifact family under
    ``output_dir``: ``fleet_<name>.json`` (schema
    ``dlbb_fleet_report_v1``), the shared journal with per-replica
    tracks, ``metrics.prom``, and ``serving_manifest.json`` whose
    ``fault_domains`` field marks the run as a fleet so report overlays
    never aggregate it with single-replica numbers."""
    import os

    from dlbb_tpu.obs import spans
    from dlbb_tpu.obs.export import fleet_metrics
    from dlbb_tpu.parallel.plan import ParallelismPlan
    from dlbb_tpu.resilience.journal import SweepJournal
    from dlbb_tpu.serve.bench import (DEFAULT_SERVE_MODEL,
                                      SERVING_MANIFEST_SCHEMA, _hbm_record)
    from dlbb_tpu.utils.config import save_json
    from dlbb_tpu.utils.simulate import topology_record
    from dlbb_tpu.utils.sysinfo import collect_system_info

    model_cfg = ModelConfig.from_dict(config.get("model",
                                                 DEFAULT_SERVE_MODEL))
    serving_cfg = ServingConfig.from_dict(config.get("serving", {}))
    fleet_cfg = FleetConfig.from_dict(config.get("fleet", {}))
    devs = list(devices) if devices is not None else available_devices()
    validate_fleet(config, model_cfg, serving_cfg, fleet_cfg, len(devs))
    groups = partition_devices(devs, fleet_cfg.replicas)
    plans = [ParallelismPlan.from_config(config, model_cfg, devices=g)
             for g in groups]
    meshes = [p.mesh for p in plans]
    domains = fault_domain_record(groups)

    fault_spec = fault_plan
    if fault_spec is None and inject.active() is None:
        fault_spec = os.environ.get(inject.ENV_VAR, "").strip() or None

    name = config.get("experiment", {}).get("name") or (
        f"fleet{fleet_cfg.replicas}_{trace.kind}_{len(trace)}req_"
        f"seed{trace.seed}"
    )
    out = Path(output_dir) if output_dir is not None else None
    jrn = None
    if out is not None and journal:
        jrn = SweepJournal(
            out,
            meta={"mode": "fleet", "name": name,
                  "replicas": fleet_cfg.replicas,
                  "trace_kind": trace.kind, "num_requests": len(trace),
                  "fault_plan": fault_spec},
            sink=spans.journal_sink,
        )
    topology = topology_record(fault_domains=domains)
    try:
        with inject.plan_scope(fault_spec):
            sup = FleetSupervisor(
                model_cfg, serving_cfg, fleet_cfg, meshes,
                fault_domains=domains, journal=jrn,
                seed=config.get("input", {}).get("seed", 0),
                verbose=verbose, capture_tokens=capture_tokens,
            )
            if jrn is not None:
                jrn.event("topology", **topology)
            sup.registry.inc(
                "serve_degraded", 1 if topology["degraded"] else 0,
                help="runs on a degraded (fallback) backend",
            )
            report = sup.serve(trace)
    finally:
        if jrn is not None:
            jrn.close()

    report["experiment"] = config.get("experiment", {})
    report["backend"] = "xla_tpu"
    report["mesh"] = plans[0].mesh_dict()  # ONE replica's mesh
    report["topology"] = topology
    report["hbm"] = _hbm_record(model_cfg, serving_cfg, plans[0])
    report["system_info"] = collect_system_info()
    report["timestamp"] = time.time()

    if out is not None:
        trace_path = trace.save(out / f"trace_{name}.json")
        result_path = save_json(report, out / f"fleet_{name}.json")
        registry = fleet_metrics(report, registry=sup.registry)
        prom_path = registry.write_textfile(out / "metrics.prom")
        manifest = {
            "schema": SERVING_MANIFEST_SCHEMA,
            "name": name,
            "kind": "fleet",
            "result": result_path.name,
            "trace_file": trace_path.name,
            "metrics": prom_path.name,
            "requests": report["requests"],
            "goodput_tokens_per_s": report["goodput_tokens_per_s"],
            "wall_seconds": report["wall_seconds"],
            "mesh": plans[0].mesh_dict(),
            "hbm": report["hbm"],
            "topology": topology,
            "fault_domains": domains,
            "failovers": report["failovers"]["total"],
            "hedges": report["hedges"],
            "degrade_level": report["degrade"]["level"],
            "journal": (None if jrn is None else jrn.path.name),
        }
        save_json(manifest, out / "serving_manifest.json")
        if verbose:
            print(f"[fleet] report written to {result_path}")
    return report
